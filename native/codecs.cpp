// Native columnar codecs: the host-side transcoding engine.
//
// Byte-compatible with the reference column formats
// (/root/reference/backend/encoding.js): LEB128 varints, RLE columns with
// repetition/literal/null-run records, Delta columns (RLE over successive
// differences) and Boolean run-length columns. These are the hot host-side
// paths when transcoding binary changes/documents into the dense op tensors
// consumed by the TPU engine, and when re-encoding op tables into the binary
// document format.
//
// Exposed as a C ABI for ctypes binding (no pybind11 in this environment).
// Null values are represented by a caller-chosen int64 sentinel.

#include <cstdint>
#include <cstring>
#include <vector>

namespace {

constexpr int64_t ERR_TRUNCATED = -1;
constexpr int64_t ERR_OVERFLOW = -2;
constexpr int64_t ERR_INVALID = -3;

struct Reader {
  const uint8_t* buf;
  size_t len;
  size_t pos = 0;

  bool done() const { return pos >= len; }

  // Reads an unsigned LEB128 (up to 64 bits). Returns false on truncation.
  bool read_uleb(uint64_t* out) {
    uint64_t result = 0;
    int shift = 0;
    while (pos < len && shift < 70) {
      uint8_t byte = buf[pos++];
      result |= static_cast<uint64_t>(byte & 0x7f) << shift;
      if (!(byte & 0x80)) {
        *out = result;
        return true;
      }
      shift += 7;
    }
    return false;
  }

  // Reads a signed LEB128 (up to 64 bits).
  bool read_sleb(int64_t* out) {
    uint64_t result = 0;
    int shift = 0;
    while (pos < len && shift < 70) {
      uint8_t byte = buf[pos++];
      result |= static_cast<uint64_t>(byte & 0x7f) << shift;
      shift += 7;
      if (!(byte & 0x80)) {
        if ((byte & 0x40) && shift < 64) {
          result |= ~uint64_t{0} << shift;
        }
        *out = static_cast<int64_t>(result);
        return true;
      }
    }
    return false;
  }
};

struct Writer {
  uint8_t* buf;
  size_t cap;
  size_t pos = 0;

  bool write_uleb(uint64_t value) {
    do {
      if (pos >= cap) return false;
      uint8_t byte = value & 0x7f;
      value >>= 7;
      buf[pos++] = byte | (value ? 0x80 : 0x00);
    } while (value);
    return true;
  }

  bool write_sleb(int64_t value) {
    while (true) {
      if (pos >= cap) return false;
      uint8_t byte = value & 0x7f;
      value >>= 7;  // arithmetic shift
      if ((value == 0 && !(byte & 0x40)) || (value == -1 && (byte & 0x40))) {
        buf[pos++] = byte;
        return true;
      }
      buf[pos++] = byte | 0x80;
    }
  }
};

}  // namespace

extern "C" {

// ---- RLE int/uint columns -------------------------------------------------

// Decodes an RLE column of (u)ints into out[0..cap). Nulls become
// `null_sentinel`. Returns the number of values, or a negative error code.
int64_t am_rle_decode(const uint8_t* buf, size_t len, int is_signed,
                      int64_t null_sentinel, int64_t* out, size_t cap) {
  Reader r{buf, len};
  size_t n = 0;
  while (!r.done()) {
    int64_t count;
    if (!r.read_sleb(&count)) return ERR_TRUNCATED;
    if (count > 0) {
      int64_t value;
      if (is_signed) {
        if (!r.read_sleb(&value)) return ERR_TRUNCATED;
      } else {
        uint64_t uv;
        if (!r.read_uleb(&uv)) return ERR_TRUNCATED;
        value = static_cast<int64_t>(uv);
      }
      if (n + count > cap) return ERR_OVERFLOW;
      for (int64_t i = 0; i < count; i++) out[n++] = value;
    } else if (count < 0) {
      for (int64_t i = 0; i < -count; i++) {
        int64_t value;
        if (is_signed) {
          if (!r.read_sleb(&value)) return ERR_TRUNCATED;
        } else {
          uint64_t uv;
          if (!r.read_uleb(&uv)) return ERR_TRUNCATED;
          value = static_cast<int64_t>(uv);
        }
        if (n >= cap) return ERR_OVERFLOW;
        out[n++] = value;
      }
    } else {
      uint64_t nulls;
      if (!r.read_uleb(&nulls)) return ERR_TRUNCATED;
      if (nulls == 0) return ERR_INVALID;
      if (n + nulls > cap) return ERR_OVERFLOW;
      for (uint64_t i = 0; i < nulls; i++) out[n++] = null_sentinel;
    }
  }
  return static_cast<int64_t>(n);
}

// Encodes values[0..n) as an RLE column (reference state machine:
// repetition / literal / null runs, encoding.js:558). Returns byte length or
// a negative error code.
int64_t am_rle_encode(const int64_t* values, size_t n, int is_signed,
                      int64_t null_sentinel, uint8_t* out, size_t cap) {
  Writer w{out, cap};
  size_t i = 0;
  // Leading all-null column: encodes to nothing only if ALL values are null
  // (encoding.js finish(): trailing nulls after data are kept)
  bool wrote_any = false;
  while (i < n) {
    if (values[i] == null_sentinel) {
      size_t j = i;
      while (j < n && values[j] == null_sentinel) j++;
      if (j == n && !wrote_any) return static_cast<int64_t>(w.pos);  // skip pure trailing nulls at start
      if (!w.write_sleb(0) || !w.write_uleb(j - i)) return ERR_OVERFLOW;
      wrote_any = true;
      i = j;
      continue;
    }
    // find run of equal values
    size_t j = i;
    while (j < n && values[j] == values[i]) j++;
    size_t run = j - i;
    if (run >= 2) {
      if (!w.write_sleb(static_cast<int64_t>(run))) return ERR_OVERFLOW;
      if (is_signed ? !w.write_sleb(values[i])
                    : !w.write_uleb(static_cast<uint64_t>(values[i])))
        return ERR_OVERFLOW;
      wrote_any = true;
      i = j;
    } else {
      // literal run: values until the next repetition (>=2 equal) or null
      size_t k = i + 1;
      while (k < n && values[k] != null_sentinel) {
        if (k + 1 < n && values[k + 1] == values[k]) break;
        k++;
      }
      size_t lit = k - i;
      if (!w.write_sleb(-static_cast<int64_t>(lit))) return ERR_OVERFLOW;
      for (size_t t = i; t < k; t++) {
        if (is_signed ? !w.write_sleb(values[t])
                      : !w.write_uleb(static_cast<uint64_t>(values[t])))
          return ERR_OVERFLOW;
      }
      wrote_any = true;
      i = k;
    }
  }
  return static_cast<int64_t>(w.pos);
}

// ---- Delta columns --------------------------------------------------------

int64_t am_delta_decode(const uint8_t* buf, size_t len, int64_t null_sentinel,
                        int64_t* out, size_t cap) {
  int64_t n = am_rle_decode(buf, len, 1, null_sentinel, out, cap);
  if (n < 0) return n;
  int64_t absolute = 0;
  for (int64_t i = 0; i < n; i++) {
    if (out[i] != null_sentinel) {
      absolute += out[i];
      out[i] = absolute;
    }
  }
  return n;
}

int64_t am_delta_encode(const int64_t* values, size_t n, int64_t null_sentinel,
                        uint8_t* out, size_t cap) {
  std::vector<int64_t> deltas(n);
  int64_t absolute = 0;
  for (size_t i = 0; i < n; i++) {
    if (values[i] == null_sentinel) {
      deltas[i] = null_sentinel;
    } else {
      deltas[i] = values[i] - absolute;
      absolute = values[i];
    }
  }
  return am_rle_encode(deltas.data(), n, 1, null_sentinel, out, cap);
}

// ---- Boolean columns ------------------------------------------------------

int64_t am_bool_decode(const uint8_t* buf, size_t len, uint8_t* out, size_t cap) {
  Reader r{buf, len};
  size_t n = 0;
  uint8_t value = 1;  // negated before the first run
  bool first = true;
  while (!r.done()) {
    uint64_t count;
    if (!r.read_uleb(&count)) return ERR_TRUNCATED;
    value = !value;
    if (count == 0 && !first) return ERR_INVALID;
    first = false;
    if (n + count > cap) return ERR_OVERFLOW;
    for (uint64_t i = 0; i < count; i++) out[n++] = value;
  }
  return static_cast<int64_t>(n);
}

int64_t am_bool_encode(const uint8_t* values, size_t n, uint8_t* out, size_t cap) {
  Writer w{out, cap};
  uint8_t last = 0;  // runs start with false
  size_t count = 0;
  for (size_t i = 0; i < n; i++) {
    uint8_t v = values[i] ? 1 : 0;
    if (v == last) {
      count++;
    } else {
      if (!w.write_uleb(count)) return ERR_OVERFLOW;
      last = v;
      count = 1;
    }
  }
  if (count > 0 && !w.write_uleb(count)) return ERR_OVERFLOW;
  return static_cast<int64_t>(w.pos);
}

// ---- String RLE columns ---------------------------------------------------

// Decodes a string-RLE column (RLE records whose values are length-prefixed
// UTF-8 strings). Output: `blob` receives the string bytes; offs[2*i] and
// offs[2*i+1] are the [start, end) range of row i's string in blob, or -1/-1
// for null. Repeated runs share one blob range. Returns the number of rows,
// or a negative error code.
int64_t am_strrle_decode(const uint8_t* buf, size_t len,
                         uint8_t* blob, size_t blob_cap,
                         int64_t* offs, size_t cap) {
  Reader r{buf, len};
  size_t n = 0;
  size_t blob_pos = 0;
  while (!r.done()) {
    int64_t count;
    if (!r.read_sleb(&count)) return ERR_TRUNCATED;
    if (count > 0) {
      uint64_t slen;
      if (!r.read_uleb(&slen)) return ERR_TRUNCATED;
      if (r.pos + slen > r.len) return ERR_TRUNCATED;
      if (blob_pos + slen > blob_cap) return ERR_OVERFLOW;
      std::memcpy(blob + blob_pos, r.buf + r.pos, slen);
      r.pos += slen;
      int64_t start = static_cast<int64_t>(blob_pos);
      int64_t end = static_cast<int64_t>(blob_pos + slen);
      blob_pos += slen;
      if (n + count > cap) return ERR_OVERFLOW;
      for (int64_t i = 0; i < count; i++) {
        offs[2 * n] = start;
        offs[2 * n + 1] = end;
        n++;
      }
    } else if (count < 0) {
      for (int64_t i = 0; i < -count; i++) {
        uint64_t slen;
        if (!r.read_uleb(&slen)) return ERR_TRUNCATED;
        if (r.pos + slen > r.len) return ERR_TRUNCATED;
        if (blob_pos + slen > blob_cap) return ERR_OVERFLOW;
        if (n >= cap) return ERR_OVERFLOW;
        std::memcpy(blob + blob_pos, r.buf + r.pos, slen);
        r.pos += slen;
        offs[2 * n] = static_cast<int64_t>(blob_pos);
        offs[2 * n + 1] = static_cast<int64_t>(blob_pos + slen);
        blob_pos += slen;
        n++;
      }
    } else {
      uint64_t nulls;
      if (!r.read_uleb(&nulls)) return ERR_TRUNCATED;
      if (nulls == 0) return ERR_INVALID;
      if (n + nulls > cap) return ERR_OVERFLOW;
      for (uint64_t i = 0; i < nulls; i++) {
        offs[2 * n] = -1;
        offs[2 * n + 1] = -1;
        n++;
      }
    }
  }
  return static_cast<int64_t>(n);
}

// ---- LEB128 batch helpers -------------------------------------------------

int64_t am_uleb_decode_batch(const uint8_t* buf, size_t len, int64_t* out, size_t cap) {
  Reader r{buf, len};
  size_t n = 0;
  while (!r.done()) {
    uint64_t v;
    if (!r.read_uleb(&v)) return ERR_TRUNCATED;
    if (n >= cap) return ERR_OVERFLOW;
    out[n++] = static_cast<int64_t>(v);
  }
  return static_cast<int64_t>(n);
}

}  // extern "C"
