# Repo-level developer targets. The analyzer and tests force
# JAX_PLATFORMS=cpu so they run on any host (no TPU required); amlint
# itself is stdlib-only and never initialises jax.

PY ?= python

.PHONY: lint test native obs-report faults bench-smoke gate-bench chaos serve decode mesh mesh-workers mesh-shm prof store sync2

lint:
	JAX_PLATFORMS=cpu $(PY) -m automerge_tpu.analysis automerge_tpu

# incremental lint: files changed vs REF (default HEAD) plus their
# transitive importers; falls back to the full scan when a rule-scoped
# module (workers/meshfarm/serve) imports a changed one
REF ?= HEAD
lint-changed:
	JAX_PLATFORMS=cpu $(PY) -m automerge_tpu.analysis --changed $(REF) automerge_tpu

test:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m 'not slow'

# the fault-corpus suite: per-doc isolation, quarantine lifecycle, device
# bisect/fallback, sync survival (tests/test_faults.py). A degradation
# curve with N% poison docs: `python bench.py --faults N`.
faults:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_faults.py -q

# the chaos soak suite (incl. slow sweeps): supervised sync convergence
# under seeded loss/dup/reorder/corruption, peer restarts, partitions
# (tests/test_chaos_sync.py + the session unit suite). Goodput vs loss:
# `python bench.py --chaos P`.
chaos:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_chaos_sync.py tests/test_sync_session.py -q

# host perf gate: fails when the visibility+patch_assembly share of
# end-to-end time regresses above BENCH_SMOKE_MAX_TAIL_SHARE (README
# "Performance"); also runs as a tier-1 test (tests/test_bench_smoke.py)
bench-smoke:
	JAX_PLATFORMS=cpu $(PY) bench.py --quick

# gate-phase microbench: the same delivery stream through the columnar
# causal gate and the scalar oracle chain (gate_mode="oracle"); gates on
# canonical patch parity and the columnar gate phases beating the scalar
# chain (README "Performance")
gate-bench:
	JAX_PLATFORMS=cpu $(PY) bench.py --gate

# columnar decode microbench (cold/warm MB/s, scalar vs vectorized vs
# native) + mixed-size page-packing report; gates on the vectorized path
# beating the scalar oracle and >= 80% slab occupancy (README
# "Performance")
decode:
	JAX_PLATFORMS=cpu $(PY) bench.py --decode

# serving front-door demo (README "Serving"): 192 simulated clients over
# the chaos transport in simulated time through the session multiplexer +
# dynamic batcher; gates on convergence, batch occupancy, zero
# unexplained sheds, a populated amscope phase breakdown with a p99
# exemplar trace, and bounded observability overhead vs the metrics-only
# baseline. The full-scale harness (10^4+ clients):
# `python bench.py --serve`; also a tier-1 test (tests/test_serve_smoke.py)
serve:
	JAX_PLATFORMS=cpu $(PY) bench.py --serve --quick

# multi-chip mesh smoke (README "Multi-chip"): the doc-sharded MeshFarm
# on 8 forced virtual CPU host devices — fan-out, mid-run page-granular
# migration, actor-table reconcile convergence, ownership audit; gates
# are machine-independent. The full MULTICHIP record run (8192 docs,
# real devices when present): `python bench.py --mesh`; also a tier-1
# test (tests/test_mesh_smoke.py)
mesh:
	$(PY) bench.py --mesh --quick

# process-worker mesh smoke (README "Process workers"): the same quick
# gates with every shard in its own spawned worker process, pinned to
# the pickle-pipe ORACLE transport — pickled column fan-out, migration
# over the pipe, clean worker shutdown. The full MULTICHIP_r08 record
# run: `python bench.py --mesh --backend process --transport pickle`;
# byte parity + crash recovery are tier-1
# (tests/test_mesh_workers_smoke.py, tests/test_mesh_workers.py)
mesh-workers:
	$(PY) bench.py --mesh --quick --backend process --transport pickle

# shared-memory mesh smoke (README "Process workers"): the same quick
# gates over the zero-copy column rings — bulk bytes ride the shm
# segments and the pipe collapses to control frames, gated at
# BENCH_MESH_SHM_PIPE_BYTES_PER_ROUND (default 4096 bytes/round/shard).
# The full MULTICHIP_r09 record run (shm + pickle-oracle delta):
# `python bench.py --mesh --backend process --transport shm`
mesh-shm:
	$(PY) bench.py --mesh --quick --backend process --transport shm

# persistence-tier smoke (README "Persistence"): WAL-attached merge
# round-trip, then both cold-start paths rebuilt from the on-disk log —
# gates byte parity with the writer, a clean recovery report, and full
# change accounting. The full STORE_r01 record run (batched hydration
# >= 5x the per-doc load loop): `python bench.py --store`; the same
# quick gates are tier-1 as tests/test_store_smoke.py
store:
	$(PY) bench.py --store --quick

# sync v2 smoke (README "Resilient sync"): Bloom (v1) vs range
# reconciliation (v2) — deterministic round-trip bound, the poisoned
# sentHashes stall that only v1's watchdog can break, byte-for-byte
# v1<->v2 interop, and the one-dispatch-per-sweep farm fingerprint pin.
# The full SYNC_r01 record run (1e5-change divergence):
# `python bench.py --sync2`
sync2:
	JAX_PLATFORMS=cpu $(PY) bench.py --sync2 --quick

# amprof ledger smoke (README "Observability"): run the quick bench with
# per-program compile/dispatch attribution + memory sampling, append the
# normalized record to PROF_LEDGER, then render the perf trajectory. Diff
# the last two comparable runs:
# `python -m automerge_tpu.obs --ledger ledger.jsonl --diff -2 -1`
PROF_LEDGER ?= ledger.jsonl
prof:
	JAX_PLATFORMS=cpu AM_LEDGER=$(PROF_LEDGER) $(PY) bench.py --quick
	$(PY) -m automerge_tpu.obs --ledger $(PROF_LEDGER)

native:
	$(MAKE) -C native

# span tree + metrics table for a small canned farm merge + sync
# round-trip (automerge_tpu/obs; see README "Observability"). The CLI
# contract — including the --flight timeline and --watch telemetry
# renderers — is pinned in tier-1 by tests/test_obs_cli.py, so this
# target cannot rot silently.
obs-report:
	JAX_PLATFORMS=cpu $(PY) -m automerge_tpu.obs --docs 4 --rounds 2 --ops 8
