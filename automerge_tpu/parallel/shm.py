"""Zero-copy mesh data plane: shared-memory column rings.

The process-backend mesh used to ship every delivery as a pickled column
batch over the worker pipe (~33-37KB/round/shard, `mesh.pipe.<s>.bytes_out`).
The columns are flat bytes on both ends, so that serialization is pure
waste. This module is the shared-memory replacement: a pair of bounded
single-producer/single-consumer rings per shard —

- the **send ring** (controller produces, worker consumes) carries the
  per-delivery column batches (``[(local_doc, change_buffers...)]``),
- the **result ring** (worker produces, controller consumes) carries the
  apply result frame (patch blob + struct-encoded outcome tuples),

and the pipe carries only compact control frames: op, a :class:`SlotRef`
(slot id + generation + length), metric deltas and flight tails. Pickle
stays available as the byte-for-byte parity oracle (``mesh_transport=
"pickle"``) and the automatic fallback when POSIX shared memory is not
available (:func:`shm_available`).

Ring anatomy (one ``multiprocessing.shared_memory`` segment per ring):
an int64 header — magic, slot count, slot capacity, then four words per
slot ``(state, generation, used_bytes, reserved)`` — followed by the slot
data region. Slot lifecycle is an explicit three-state handshake::

    FREE --acquire (producer, bumps generation)--> PRODUCER_HELD
         --accept  (consumer, checks generation)--> CONSUMER_HELD
         --release (consumer)--------------------> FREE

The pipe provides ordering (a SlotRef is only ever read after its control
frame arrives), so the header words need no cross-process atomics beyond
aligned int64 stores. Bounded capacity gives natural backpressure: a
producer that finds no FREE slot spins with a short sleep (the caller
meters the stall) or gives up after ``timeout`` and falls back to the
inline pickle path — the rings can degrade, never deadlock.

The generation counter is the crash story: a worker killed while a slot
is PRODUCER_HELD leaves the header intact, so the controller reclaims
exactly the held slots (:meth:`ColumnRing.reclaim`) and a stale SlotRef
from before the crash can never alias a reused slot — ``accept`` checks
the generation and refuses. Respawned workers re-attach to the same
segments by name; clean shutdown unlinks every segment so nothing leaks
in ``/dev/shm`` (pinned by tests/test_mesh_workers.py).

Worker-import discipline: this module is imported by the worker process
(`parallel/workers.py`), so it is stdlib-only and touches no controller
state, no metrics registry and no jax — callers on both sides do their
own metering. Payload encoding in here is ``struct``, never pickle: the
amlint AM504 rule (`# amlint: mesh-data-plane` scope) pins that bulk
column payloads do not regrow a pickle dependency on this path.
"""
# amlint: mesh-data-plane
from __future__ import annotations

import os
import secrets
import struct
import time
from multiprocessing import shared_memory

from ..errors import DecodeError, DeviceFaultError

__all__ = [
    "SlotRef",
    "ColumnRing",
    "RingStall",
    "shm_available",
    "create_ring",
    "attach_ring",
    "encode_columns",
    "decode_columns",
    "encode_result",
    "decode_result",
    "DEFAULT_SLOTS",
    "DEFAULT_SLOT_BYTES",
]

_MAGIC = 0x414D5348  # "AMSH"

#: slot states — the explicit acquire/accept/release handshake
FREE, PRODUCER_HELD, CONSUMER_HELD = 0, 1, 2

#: header layout: 3 ring words + 4 words per slot, then 64B-aligned data
_RING_WORDS = 3
_SLOT_WORDS = 4
_W_STATE, _W_GEN, _W_USED, _W_RESERVED = 0, 1, 2, 3

DEFAULT_SLOTS = 8
DEFAULT_SLOT_BYTES = 256 * 1024


class RingStall(DeviceFaultError):
    """Producer could not acquire a slot before ``timeout`` — the ring is
    full (consumer is behind). Callers catch this and take the inline
    pickle fallback; it never propagates past the transport layer."""

    kind = "device_fault"


def ring_sizes() -> tuple[int, int]:
    """(slots, slot_bytes) from env knobs, with bounds sanity."""
    slots = max(2, int(os.environ.get("AM_MESH_SHM_SLOTS", str(DEFAULT_SLOTS))))
    slot_bytes = max(
        4096, int(os.environ.get("AM_MESH_SHM_SLOT_BYTES", str(DEFAULT_SLOT_BYTES)))
    )
    return slots, slot_bytes


_AVAILABLE: bool | None = None


def shm_available() -> bool:
    """True when POSIX shared memory actually works on this host (probed
    once with a tiny create/attach/unlink round trip, then cached)."""
    global _AVAILABLE
    if _AVAILABLE is None:
        try:
            seg = shared_memory.SharedMemory(create=True, size=4096)
            try:
                seg.buf[0] = 7
                # attach-side register is a dedup no-op in the tracker's
                # name set — creator and attacher are the same process
                peer = shared_memory.SharedMemory(name=seg.name)
                ok = peer.buf[0] == 7
                peer.close()
            finally:
                seg.close()
                seg.unlink()
            _AVAILABLE = bool(ok)
        except (OSError, ValueError, FileNotFoundError):
            _AVAILABLE = False
    return _AVAILABLE


def ring_name(tag: str) -> str:
    """A fresh, collision-safe segment name (``am-<pid>-<nonce>-<tag>``)."""
    return f"am-{os.getpid()}-{secrets.token_hex(4)}-{tag}"


class SlotRef:
    """Picklable control-frame handle to one published slot: what crosses
    the pipe instead of the payload. All fields are plain ``int`` at
    construction so flight events and JSONL dumps never see np.int64
    (the PR 14 stringification bug class)."""

    __slots__ = ("slot", "generation", "nbytes")

    def __init__(self, slot, generation, nbytes):
        self.slot = int(slot)
        self.generation = int(generation)
        self.nbytes = int(nbytes)

    def __getstate__(self):
        return (self.slot, self.generation, self.nbytes)

    def __setstate__(self, state):
        self.slot, self.generation, self.nbytes = state

    def __repr__(self):
        return (
            f"SlotRef(slot={self.slot}, generation={self.generation}, "
            f"nbytes={self.nbytes})"
        )


class ColumnRing:
    """One bounded SPSC ring over one shared-memory segment.

    Exactly one process produces (``acquire``/``publish``) and exactly one
    consumes (``accept``/``release``); the mesh runs one send ring and one
    result ring per shard, so each ring has a fixed producer and consumer.
    The creating side owns the segment lifetime (``unlink``); attachers
    only map it.
    """

    def __init__(self, seg: shared_memory.SharedMemory, nslots: int,
                 slot_bytes: int, owner: bool):
        self._seg = seg
        self.nslots = int(nslots)
        self.slot_bytes = int(slot_bytes)
        self.owner = owner
        self.closed = False
        self.stalls = 0  # producer-side acquire waits (caller meters)
        header_words = _RING_WORDS + _SLOT_WORDS * self.nslots
        self._data_off = ((header_words * 8 + 63) // 64) * 64

    # -- construction -------------------------------------------------- #

    @classmethod
    def create(cls, tag: str, nslots: int, slot_bytes: int) -> "ColumnRing":
        header_words = _RING_WORDS + _SLOT_WORDS * nslots
        data_off = ((header_words * 8 + 63) // 64) * 64
        size = data_off + nslots * slot_bytes
        seg = shared_memory.SharedMemory(
            name=ring_name(tag), create=True, size=size
        )
        ring = cls(seg, nslots, slot_bytes, owner=True)
        hdr = ring._header()
        hdr[0] = _MAGIC
        hdr[1] = nslots
        hdr[2] = slot_bytes
        for s in range(nslots):
            base = _RING_WORDS + _SLOT_WORDS * s
            hdr[base + _W_STATE] = FREE
            hdr[base + _W_GEN] = 0
            hdr[base + _W_USED] = 0
            hdr[base + _W_RESERVED] = 0
        return ring

    @classmethod
    def attach(cls, name: str) -> "ColumnRing":
        # Attaching registers the name with the resource_tracker (a 3.10
        # stdlib wart) — but mesh workers are POSIX-spawn children, which
        # inherit the controller's tracker fd, so the register is a set
        # dedup no-op and the owner's unlink unregisters exactly once.
        # Un-registering here instead would clobber the owner's entry in
        # the shared set and make that unlink a tracker KeyError.
        seg = shared_memory.SharedMemory(name=name)
        hdr = seg.buf.cast("q")
        magic, nslots, slot_bytes = hdr[0], hdr[1], hdr[2]
        del hdr
        if magic != _MAGIC:
            seg.close()
            raise DecodeError(f"shm segment {name!r} is not a column ring")
        return cls(seg, nslots, slot_bytes, owner=False)

    @property
    def name(self) -> str:
        return self._seg.name

    def _header(self):
        return self._seg.buf.cast("q")

    def _slot_base(self, slot: int) -> int:
        return _RING_WORDS + _SLOT_WORDS * slot

    # -- producer side ------------------------------------------------- #

    def acquire(self, timeout: float = 0.5,
                poll_s: float = 0.0005) -> tuple[int, int]:
        """Claims a FREE slot, bumping its generation: returns
        ``(slot, generation)``. Waits up to ``timeout`` for the consumer
        to free one (counted in ``self.stalls``), then raises
        :class:`RingStall` so the caller can fall back inline."""
        hdr = self._header()
        try:
            deadline = None
            stalled = False
            while True:
                for s in range(self.nslots):
                    base = self._slot_base(s)
                    if hdr[base + _W_STATE] == FREE:
                        gen = int(hdr[base + _W_GEN]) + 1
                        hdr[base + _W_GEN] = gen
                        hdr[base + _W_USED] = 0
                        hdr[base + _W_STATE] = PRODUCER_HELD
                        return s, gen
                if deadline is None:
                    deadline = time.monotonic() + timeout
                if not stalled:
                    stalled = True
                    self.stalls += 1
                if time.monotonic() >= deadline:
                    raise RingStall(
                        f"ring {self.name}: no free slot after {timeout}s "
                        f"({self.nslots} slots, consumer behind)"
                    )
                time.sleep(poll_s)
        finally:
            del hdr

    def slot_view(self, slot: int) -> memoryview:
        """The writable data region of one slot (full capacity)."""
        off = self._data_off + slot * self.slot_bytes
        return self._seg.buf[off:off + self.slot_bytes]

    def publish(self, slot: int, generation: int, nbytes: int) -> SlotRef:
        """Seals an acquired slot at ``nbytes`` and returns the control
        frame to ship over the pipe."""
        hdr = self._header()
        try:
            base = self._slot_base(slot)
            hdr[base + _W_USED] = nbytes
        finally:
            del hdr
        return SlotRef(slot, generation, nbytes)

    def abandon(self, slot: int) -> None:
        """Producer backs out of an acquired slot (e.g. payload turned
        out oversize): straight back to FREE, generation already burned."""
        hdr = self._header()
        try:
            hdr[self._slot_base(slot) + _W_STATE] = FREE
        finally:
            del hdr

    # -- consumer side ------------------------------------------------- #

    def accept(self, ref: SlotRef) -> memoryview:
        """Validates a control frame against the header (held by the
        producer, generation matches — a stale ref from before a crash
        reclaim refuses here) and takes consumer ownership. Returns a
        view of the published bytes; pair with :meth:`release`."""
        if ref.slot < 0 or ref.slot >= self.nslots:
            raise DecodeError(f"ring {self.name}: slot {ref.slot} out of range")
        hdr = self._header()
        try:
            base = self._slot_base(ref.slot)
            state = int(hdr[base + _W_STATE])
            gen = int(hdr[base + _W_GEN])
            used = int(hdr[base + _W_USED])
            if state != PRODUCER_HELD or gen != ref.generation:
                raise DeviceFaultError(
                    f"ring {self.name}: stale slot ref (slot {ref.slot} "
                    f"state={state} gen={gen}, ref gen={ref.generation})"
                )
            if used != ref.nbytes or used > self.slot_bytes:
                raise DecodeError(
                    f"ring {self.name}: slot {ref.slot} length mismatch "
                    f"(header {used}, ref {ref.nbytes})"
                )
            hdr[base + _W_STATE] = CONSUMER_HELD
        finally:
            del hdr
        off = self._data_off + ref.slot * self.slot_bytes
        return self._seg.buf[off:off + used]

    def release(self, slot: int) -> None:
        """Consumer is done with the payload: slot returns to FREE."""
        if self.closed:
            return
        hdr = self._header()
        try:
            hdr[self._slot_base(slot) + _W_STATE] = FREE
        finally:
            del hdr

    # -- supervision --------------------------------------------------- #

    def reclaim(self, held_by_producer_only: bool = False) -> int:
        """Frees slots after a peer crash; returns how many. With
        ``held_by_producer_only`` (the result ring after a worker crash)
        only PRODUCER_HELD slots free — CONSUMER_HELD ones belong to live
        controller-side lazy patches and stay valid across the respawn."""
        freed = 0
        hdr = self._header()
        try:
            for s in range(self.nslots):
                base = self._slot_base(s)
                state = hdr[base + _W_STATE]
                if state == FREE:
                    continue
                if held_by_producer_only and state == CONSUMER_HELD:
                    continue
                hdr[base + _W_STATE] = FREE
                freed += 1
        finally:
            del hdr
        return freed

    def slots_in_use(self) -> int:
        hdr = self._header()
        try:
            return sum(
                1 for s in range(self.nslots)
                if hdr[self._slot_base(s) + _W_STATE] != FREE
            )
        finally:
            del hdr

    def close(self, unlink: bool | None = None) -> None:
        """Drops the mapping; the owning side also unlinks the segment so
        nothing is left behind in /dev/shm."""
        if self.closed:
            return
        self.closed = True
        try:
            self._seg.close()
        except BufferError:
            return  # an exported view still pins the mapping; owner retries
        if unlink if unlink is not None else self.owner:
            try:
                self._seg.unlink()
            except FileNotFoundError:
                pass


def create_ring(tag: str) -> ColumnRing:
    slots, slot_bytes = ring_sizes()
    return ColumnRing.create(tag, slots, slot_bytes)


def attach_ring(name: str) -> ColumnRing:
    return ColumnRing.attach(name)


# ---------------------------------------------------------------------- #
# payload codecs — struct, never pickle (AM504): the column batches are
# flat bytes already, so framing is counts + lengths + raw concatenation.

_U32 = struct.Struct("<I")
_U64 = struct.Struct("<Q")


def measure_columns(groups) -> int:
    """Encoded size of one ``[(local_doc, (change_buf, ...)), ...]``
    delivery batch — checked against slot capacity before acquiring."""
    n = 8  # group count
    for _loc, bufs in groups:
        n += 16 + 8 * len(bufs)  # loc + nbufs + per-buffer lengths
        for b in bufs:
            n += len(b)
    return n


def encode_columns_into(view: memoryview, groups) -> int:
    """Writes the batch straight into a mapped slot; returns bytes used."""
    _U64.pack_into(view, 0, len(groups))
    off = 8
    for loc, bufs in groups:
        _U64.pack_into(view, off, loc)
        _U64.pack_into(view, off + 8, len(bufs))
        off += 16
        for b in bufs:
            _U64.pack_into(view, off, len(b))
            off += 8
        for b in bufs:
            view[off:off + len(b)] = b
            off += len(b)
    return off


def encode_columns(groups) -> bytes:
    buf = bytearray(measure_columns(groups))
    encode_columns_into(memoryview(buf), groups)
    return bytes(buf)


def decode_columns(view) -> list:
    """Inverse of :func:`encode_columns_into`; copies the buffers out of
    the slot (the slot is released right after, the farm keeps bytes)."""
    view = memoryview(view)
    (ngroups,) = _U64.unpack_from(view, 0)
    off = 8
    groups = []
    for _ in range(ngroups):
        loc, nbufs = _U64.unpack_from(view, off)[0], _U64.unpack_from(view, off + 8)[0]
        off += 16
        lengths = [_U64.unpack_from(view, off + 8 * i)[0] for i in range(nbufs)]
        off += 8 * nbufs
        bufs = []
        for ln in lengths:
            bufs.append(bytes(view[off:off + ln]))
            off += ln
        groups.append((int(loc), tuple(bufs)))
    return groups


# result frame: u64 patch-blob length | patch blob | u32 outcome count |
# outcome records. Outcomes are the farm's 5-tuple wire form
# ``(status, exc_blob, error_kind, offending_hashes, fallback)``,
# struct-framed with a flags byte (the overwhelmingly common
# ``("applied", None, None, (), False)`` costs 8 bytes).

_F_FALLBACK, _F_BLOB, _F_KIND = 1, 2, 4


def _put_str(out: bytearray, s: str) -> None:
    b = s.encode("utf-8")
    out += _U32.pack(len(b))
    out += b


def _get_str(view, off: int) -> tuple[str, int]:
    (n,) = _U32.unpack_from(view, off)
    off += 4
    return str(view[off:off + n], "utf-8"), off + n


def encode_result(patches_blob: bytes, outcome_wires) -> bytes:
    out = bytearray(_U64.pack(len(patches_blob)))
    out += patches_blob
    out += _U32.pack(len(outcome_wires))
    for status, blob, kind, offending, fallback in outcome_wires:
        flags = (_F_FALLBACK if fallback else 0) \
            | (_F_BLOB if blob is not None else 0) \
            | (_F_KIND if kind is not None else 0)
        out.append(flags)
        _put_str(out, status)
        if blob is not None:
            out += _U64.pack(len(blob))
            out += blob
        if kind is not None:
            _put_str(out, kind)
        out += _U32.pack(len(offending))
        for h in offending:
            hb = h.encode("utf-8") if isinstance(h, str) else bytes(h)
            out.append(0 if isinstance(h, str) else 1)
            out += _U32.pack(len(hb))
            out += hb
    return bytes(out)


def decode_result(view) -> tuple[tuple[int, int], list]:
    """Returns ``((patches_off, patches_len), outcome_wires)`` — the
    patch blob is described by offsets, not copied, so the caller can
    hold the slot and unpickle straight from the mapped segment."""
    view = memoryview(view)
    (blob_len,) = _U64.unpack_from(view, 0)
    patches = (8, int(blob_len))
    off = 8 + int(blob_len)
    (count,) = _U32.unpack_from(view, off)
    off += 4
    wires = []
    for _ in range(count):
        flags = view[off]
        off += 1
        status, off = _get_str(view, off)
        blob = None
        if flags & _F_BLOB:
            (n,) = _U64.unpack_from(view, off)
            off += 8
            blob = bytes(view[off:off + n])
            off += n
        kind = None
        if flags & _F_KIND:
            kind, off = _get_str(view, off)
        (noff,) = _U32.unpack_from(view, off)
        off += 4
        offending = []
        for _h in range(noff):
            tag = view[off]
            off += 1
            (n,) = _U32.unpack_from(view, off)
            off += 4
            raw = bytes(view[off:off + n])
            off += n
            offending.append(str(raw, "utf-8") if tag == 0 else raw)
        wires.append((status, blob, kind, tuple(offending),
                      bool(flags & _F_FALLBACK)))
    return patches, wires
