"""Mesh construction for the multi-chip merge farm.

The batch-of-documents axis is embarrassingly parallel (each document's
state is self-contained, SURVEY.md §2.5), so the production distribution
strategy is doc sharding over `dp` — meshfarm.py routes whole documents
to shard-local farms. The op-capacity axis can additionally be sharded
over `sp` (sequence parallelism) for documents with very long op logs;
XLA inserts the collectives needed by the sort and the segmented
reductions across `sp` shards.

The stale dense-``BatchedDocState`` sharding helpers that predated the
paged slab (state_sharding / changes_sharding / shard_batch /
sharded_apply_ops / sharded_visible_state) are gone — the paged engine
owns placement per shard farm via ``jax.default_device`` (meshfarm.py).
``_apply_ops_impl`` stays: it is the donation-free vmapped merge step the
compile-contract entry check exercises.
"""
from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh

from ..tpu.engine import BatchedDocState, ChangeOpsBatch


def make_mesh(devices=None, sp: int = 1) -> Mesh:
    """Builds a ('dp', 'sp') mesh over the given (or all) devices.

    `sp` must divide the device count exactly — a remainder would have to
    silently fall back to (n, 1), handing the caller a mesh with a
    different data-parallel degree than the one their shardings assume."""
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if sp < 1:
        raise ValueError(f"sp must be >= 1, got {sp}")
    if n % sp != 0:
        raise ValueError(
            f"sp={sp} does not divide the device count {n}: an uneven "
            "sequence-parallel split cannot be laid out as a ('dp', 'sp') "
            "mesh (pass an sp that divides len(devices))"
        )
    dev_array = np.array(devices, dtype=object).reshape((n // sp, sp))
    return Mesh(dev_array, ("dp", "sp"))


def _apply_ops_impl(state: BatchedDocState, changes: ChangeOpsBatch) -> BatchedDocState:
    # Re-implementation without donation so shardings can be attached by the
    # caller's jit.
    from ..tpu.engine import _merge_one_doc

    key, op, action, value, pred, over, num = jax.vmap(_merge_one_doc)(
        state.key, state.op, state.action, state.value, state.pred,
        state.overwritten, state.num_ops,
        changes.key, changes.op, changes.action, changes.value, changes.pred,
    )
    return BatchedDocState(key, op, action, value, pred, over, num)
