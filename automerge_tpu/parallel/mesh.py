"""Mesh construction and sharding for the batched merge engine.

The batch-of-documents axis is embarrassingly parallel (each document's
state is self-contained, SURVEY.md §2.5), so the primary distribution
strategy is data parallelism over `dp`. The op-capacity axis can
additionally be sharded over `sp` (sequence parallelism) for documents with
very long op logs; XLA inserts the collectives needed by the sort and the
segmented reductions across `sp` shards.
"""
from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..tpu.engine import BatchedDocState, ChangeOpsBatch


def make_mesh(devices=None, sp: int = 1) -> Mesh:
    """Builds a ('dp', 'sp') mesh over the given (or all) devices."""
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if sp > 1 and n % sp == 0:
        shape = (n // sp, sp)
    else:
        shape = (n, 1)
    dev_array = np.array(devices, dtype=object).reshape(shape)
    return Mesh(dev_array, ("dp", "sp"))


def state_sharding(mesh: Mesh) -> BatchedDocState:
    row = NamedSharding(mesh, P("dp", "sp"))
    vec = NamedSharding(mesh, P("dp"))
    return BatchedDocState(key=row, op=row, action=row, value=row,
                           pred=row, overwritten=row, num_ops=vec)


def changes_sharding(mesh: Mesh) -> ChangeOpsBatch:
    row = NamedSharding(mesh, P("dp", "sp"))
    return ChangeOpsBatch(key=row, op=row, action=row, value=row, pred=row)


def shard_batch(tree, shardings):
    """Places a pytree of arrays onto the mesh with the given shardings."""
    return jax.tree.map(jax.device_put, tree, shardings)


def _apply_ops_impl(state: BatchedDocState, changes: ChangeOpsBatch) -> BatchedDocState:
    # Re-implementation without donation so shardings can be attached by the
    # caller's jit.
    from ..tpu.engine import _merge_one_doc

    key, op, action, value, pred, over, num = jax.vmap(_merge_one_doc)(
        state.key, state.op, state.action, state.value, state.pred,
        state.overwritten, state.num_ops,
        changes.key, changes.op, changes.action, changes.value, changes.pred,
    )
    return BatchedDocState(key, op, action, value, pred, over, num)


def sharded_apply_ops(mesh: Mesh):
    """Returns a jitted applyChanges step whose inputs/outputs are sharded
    over the mesh: documents over `dp`, the op axis over `sp`."""
    s_shard = state_sharding(mesh)
    c_shard = changes_sharding(mesh)
    return jax.jit(
        _apply_ops_impl,
        in_shardings=(s_shard, c_shard),
        out_shardings=s_shard,
    )


def _visible_state_impl(state: BatchedDocState, cmp):
    from ..tpu.engine import _visible_state_one_doc

    return jax.vmap(_visible_state_one_doc)(
        state.key, state.op, state.action, state.value, state.pred,
        state.overwritten, cmp,
    )


def sharded_visible_state(mesh: Mesh):
    """Returns a jitted (state, actor_rank) -> per-row visibility function.

    `actor_rank` (int32[A], replicated) remaps counter-tied conflicts onto
    lexicographic actor order, matching the engine path's tie-break
    (engine.batched_visible_state); pass an identity table (arange) to keep
    intern-order ties.
    """
    from ..tpu.engine import remap_opid_actors

    s_shard = state_sharding(mesh)
    row = NamedSharding(mesh, P("dp", "sp"))
    rep = NamedSharding(mesh, P())
    out = (row, row, row, row, row)

    def impl(state, actor_rank):
        return _visible_state_impl(state, remap_opid_actors(state.op, actor_rank))

    return jax.jit(impl, in_shardings=(s_shard, rep), out_shardings=out)
