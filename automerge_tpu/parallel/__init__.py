"""Multi-chip execution: doc-sharded shard-local farms (meshfarm.py)
behind one controller, plus ('dp', 'sp') mesh construction (mesh.py) and
the process-worker runtime (workers.py).

Exports resolve lazily (PEP 562): a spawned mesh worker child imports
``automerge_tpu.parallel.workers`` through this package, and an eager
``from .meshfarm import MeshFarm`` here would drag the controller — and
jax — into every child before the spawn env overrides apply (pinned by
tests/test_mesh_workers_smoke.py::test_workers_module_imports_without_jax).
"""
__all__ = ["MeshFarm", "make_mesh"]


def __getattr__(name):
    if name == "MeshFarm":
        from .meshfarm import MeshFarm
        return MeshFarm
    if name == "make_mesh":
        from .mesh import make_mesh
        return make_mesh
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
