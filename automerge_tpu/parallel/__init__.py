"""Multi-chip execution: doc-sharded shard-local farms (meshfarm.py)
behind one controller, plus ('dp', 'sp') mesh construction (mesh.py)."""
from .mesh import make_mesh
from .meshfarm import MeshFarm

__all__ = ["MeshFarm", "make_mesh"]
