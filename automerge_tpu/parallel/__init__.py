"""Multi-chip execution: document-batch sharding over a jax.sharding.Mesh."""
from .mesh import make_mesh, shard_batch, sharded_apply_ops, sharded_visible_state
