"""MeshFarm: a doc-sharded multi-chip merge farm.

One controller front over N shard-local ``TpuDocFarm``s. Each shard owns
its documents outright — interners, page slab, host mirrors, quarantine
set — so shards share NO mutable state and each one can live on its own
device (``devices=[...]`` pins shard ``s``'s dispatches under
``jax.default_device``). The controller:

- **routes** every document to a shard by a stable doc-id hash
  (splitmix64 of the global index — the placement is a pure function of
  ``(num_docs, num_shards)``, so a restarted controller recovers the
  same routing without any persisted table);
- **fans out** one ``apply_changes`` delivery into per-shard
  ``apply_changes(isolation="doc")`` sub-dispatches (only shards with
  active docs dispatch; ``AM_MESH_CONCURRENCY`` > 1 runs them on a
  thread pool — on real multi-chip hosts the per-shard XLA dispatches
  overlap, on a single CPU they serialize harmlessly) and **merges** the
  per-shard ``FarmApplyResult``s back into one global-index result;
- **reconciles** the shard-local actor interner tables every
  ``reconcile_interval`` applies: shards intern actors independently, so
  a reconcile pass exchanges the table deltas (the union is interned
  into every shard) to keep actor-rank-dependent readbacks and sync
  filters globally consistent. Convergence is testable: a second pass
  immediately after a first syncs zero entries;
- **rebalances** hot/overfull documents between shards with
  page-granular migration (``farm.export_doc`` → id translation →
  ``engine.adopt_rows`` whole-page scatter → source ``evict_doc``),
  driven by per-shard slab page occupancy and the controller's per-doc
  dispatch histogram.

The facade exposes the exact ``TpuDocFarm`` surface the serving stack
consumes (``num_docs``, ``quarantine``, ``apply_changes``, ``get_*``,
``release_quarantine``), all in GLOBAL doc indexes, so ``SyncFarm`` and
``DynamicBatcher`` run unmodified over a mesh.

Decode-cache ownership: the columnar decode caches are process-global
and SHARED by every shard on purpose — cached entries hold actor
*strings* and immutable op lists, never interner ids, and each shard
interns at transcode time into its own tables. Sharing parses is safe;
sharing interner state would not be, and there is none to share (pinned
by tests/test_mesh_parity.py).
"""
from __future__ import annotations

import contextlib
import contextvars
import os
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from ..errors import PackingLimitError
from ..obs.flight import get_flight
from ..obs.metrics import get_metrics
from ..obs.scope import current_exemplar
from ..tpu.farm import _APPLIED, FarmApplyResult, TpuDocFarm

_METRICS = get_metrics()
_M_SHARDS = _METRICS.gauge("mesh.shards", "shards in the mesh farm")
_M_APPLY = _METRICS.counter(
    "mesh.apply.calls", "deliveries fanned out through the mesh front"
)
_M_MIGRATED = _METRICS.counter(
    "mesh.docs.migrated",
    "documents moved between shards by page-granular migration",
)
_M_RECONCILE_RUNS = _METRICS.counter(
    "mesh.reconcile.runs", "cross-shard actor-table reconcile passes"
)
_M_RECONCILE_SYNCED = _METRICS.counter(
    "mesh.reconcile.actors_synced",
    "actor table entries copied between shard interners by reconcile",
)
_M_REBALANCE = _METRICS.counter(
    "mesh.rebalance.moves",
    "documents migrated by the occupancy-driven rebalancer",
)
_FLIGHT = get_flight()

# per-shard instrument families, registered lazily on first touch (the
# farm.quarantine.causes.<kind> idiom): full-literal-prefix names so the
# README catalog's <s> placeholder rows match them
_SHARD_DISPATCH_MS: dict[int, object] = {}
_SHARD_DOCS: dict[int, object] = {}


def _shard_dispatch_ms(s: int):
    h = _SHARD_DISPATCH_MS.get(s)
    if h is None:
        h = _METRICS.histogram(
            f"mesh.shard.{s}.dispatch_ms",
            f"wall time of shard {s}'s apply_changes sub-dispatches",
        )
        _SHARD_DISPATCH_MS[s] = h
    return h


def _shard_docs(s: int):
    c = _SHARD_DOCS.get(s)
    if c is None:
        c = _METRICS.counter(
            f"mesh.shard.{s}.docs",
            f"active documents dispatched to shard {s}",
        )
        _SHARD_DOCS[s] = c
    return c


def _route(num_docs: int, num_shards: int) -> np.ndarray:
    """Stable doc-id -> shard map: splitmix64 of the global index mod the
    shard count. Pure and stateless — rebalancing overrides individual
    entries at runtime, but the BASE placement needs no persisted table."""
    x = np.arange(num_docs, dtype=np.uint64)
    z = x + np.uint64(0x9E3779B97F4A7C15)
    z = (z ^ (z >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
    z = (z ^ (z >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
    z = z ^ (z >> np.uint64(31))
    return (z % np.uint64(num_shards)).astype(np.int64)


class MeshFarm:
    """N shard-local TpuDocFarms behind one controller. See module
    docstring.

    `num_shards` defaults to the visible device count when `devices` is
    given, else 1. `spare_slots` sizes each shard's migration headroom
    (empty doc slots a rebalance can adopt into)."""

    def __init__(self, num_docs: int, num_shards: int | None = None,
                 capacity: int = 1024, quarantine_threshold: int | None = 3,
                 page_size: int | None = None, devices=None,
                 reconcile_interval: int | None = 64,
                 spare_slots: int | None = None):
        if num_shards is None:
            num_shards = len(devices) if devices else 1
        if num_shards < 1 or num_docs < num_shards:
            # amlint: disable=AM401 — API-usage validation, not a
            # data-plane fault (nothing was decoded or dispatched)
            raise ValueError(
                f"need 1 <= num_shards <= num_docs, got "
                f"num_shards={num_shards} num_docs={num_docs}"
            )
        self.num_docs = num_docs
        self.num_shards = num_shards
        self.reconcile_interval = reconcile_interval
        self._devices = list(devices) if devices else None
        self._shard_of = _route(num_docs, num_shards)
        self._local_of = np.zeros(num_docs, np.int64)
        if spare_slots is None:
            spare_slots = max(2, (num_docs // num_shards) // 8)
        self._owners: list[list] = []
        self._free: list[list] = []
        self.shards: list[TpuDocFarm] = []
        for s in range(num_shards):
            mine = np.nonzero(self._shard_of == s)[0]
            self._local_of[mine] = np.arange(len(mine), dtype=np.int64)
            self._owners.append(mine.tolist() + [None] * spare_slots)
            self._free.append(
                list(range(len(mine) + spare_slots - 1, len(mine) - 1, -1))
            )
            with self._device_ctx(s):
                self.shards.append(TpuDocFarm(
                    len(mine) + spare_slots, capacity=capacity,
                    quarantine_threshold=quarantine_threshold,
                    page_size=page_size,
                ))
        self._calls = 0
        self._doc_dispatches = np.zeros(num_docs, np.int64)
        workers = int(os.environ.get("AM_MESH_CONCURRENCY", "1"))
        self._executor = (
            ThreadPoolExecutor(max_workers=min(workers, num_shards))
            if workers > 1 and num_shards > 1 else None
        )
        _M_SHARDS.set(num_shards)

    # ------------------------------------------------------------------ #
    # routing

    def _device_ctx(self, s: int):
        if self._devices is None:
            return contextlib.nullcontext()
        import jax

        return jax.default_device(self._devices[s % len(self._devices)])

    def shard_of(self, d: int) -> int:
        """Current owning shard of global doc `d` (base routing overridden
        by migrations). The serve batcher uses this for its per-shard
        flush accounting."""
        return int(self._shard_of[d])

    def _local(self, d: int) -> tuple[TpuDocFarm, int]:
        s = self._shard_of[d]
        return self.shards[s], self._local_of[d]

    # ------------------------------------------------------------------ #
    # the fan-out data plane

    def apply_changes(self, per_doc_buffers, is_local: bool = False,
                      isolation: str = "doc"):
        """Routes one global delivery into per-shard sub-deliveries,
        dispatches each shard's farm, and merges the per-shard results
        into one global-index FarmApplyResult. Shards with no active docs
        are not dispatched; their docs report the same no-op patch an
        empty delivery produces."""
        if isolation != "doc":
            # amlint: disable=AM401 — API-usage validation: batch-wide
            # rollback cannot span shard-local fault domains
            raise ValueError(
                "MeshFarm supports isolation='doc' only (shards are "
                "independent fault domains)"
            )
        assert len(per_doc_buffers) == self.num_docs
        self._calls += 1
        _M_APPLY.inc()
        shard_of, local_of = self._shard_of, self._local_of
        active = [d for d, bufs in enumerate(per_doc_buffers) if bufs]
        subs = [
            [[] for _ in range(f.num_docs)] for f in self.shards
        ]
        for d in active:
            subs[shard_of[d]][local_of[d]] = list(per_doc_buffers[d])
        np.add.at(self._doc_dispatches, active, 1)
        touched = sorted({shard_of[d] for d in active})
        counts = {
            s: sum(1 for d in active if shard_of[d] == s) for s in touched
        }

        def run_shard(s):
            t0 = time.perf_counter()
            with self._device_ctx(s):
                result = self.shards[s].apply_changes(
                    subs[s], is_local=is_local, isolation="doc"
                )
            if _METRICS.enabled:
                _shard_dispatch_ms(s).observe(
                    (time.perf_counter() - t0) * 1000.0,
                    exemplar=current_exemplar(),
                )
                _shard_docs(s).inc(counts[s])
            return result

        results = self._dispatch_shards(touched, run_shard)
        patches = [
            results[shard_of[g]][local_of[g]]
            if shard_of[g] in results
            else self.shards[shard_of[g]]._noop_patch(local_of[g])
            for g in range(self.num_docs)
        ]
        outcomes = [
            results[shard_of[g]].outcomes[local_of[g]]
            if shard_of[g] in results
            else _APPLIED
            for g in range(self.num_docs)
        ]
        if self.reconcile_interval and (
            self._calls % self.reconcile_interval == 0
        ):
            self.reconcile_actors()
        return FarmApplyResult(patches, outcomes)

    def _dispatch_shards(self, touched, fn):
        """Runs `fn(s)` for every touched shard; concurrently when the
        pool is enabled (context propagated so ambient profile/scope
        state follows each sub-dispatch), serially otherwise. Results
        come back keyed by shard id either way."""
        if self._executor is not None and len(touched) > 1:
            futures = {
                s: self._executor.submit(
                    contextvars.copy_context().run, fn, s
                )
                for s in touched
            }
            return {s: futures[s].result() for s in touched}
        return {s: fn(s) for s in touched}

    # ------------------------------------------------------------------ #
    # cross-shard actor reconcile

    def reconcile_actors(self) -> int:
        """Exchanges actor-table deltas between shards: the union of every
        shard's actor strings is interned into every shard (append-only,
        first-seen order, so the pass is deterministic). Returns the
        number of entries copied; a converged mesh returns 0."""
        union: list[str] = []
        seen: set[str] = set()
        for f in self.shards:
            for a in f.actors.table:
                if a not in seen:
                    seen.add(a)
                    union.append(a)
        synced = 0
        for f in self.shards:
            missing = [a for a in union if f.actors.find(a) is None]
            for a in missing:
                f.actors.intern(a)
            synced += len(missing)
        _M_RECONCILE_RUNS.inc()
        _M_RECONCILE_SYNCED.inc(synced)
        if _FLIGHT.enabled:
            _FLIGHT.record(
                "mesh.reconcile", actors=len(union), synced=synced
            )
        return synced

    # ------------------------------------------------------------------ #
    # page-granular migration + the rebalancer

    def migrate_doc(self, d: int, dest_shard: int) -> None:
        """Moves global doc `d` onto `dest_shard` by whole pages: export
        (dense page readback + host state), id translation into the
        destination farm's interners, one adopt-scatter into freshly
        allocated pages, then the source slot is evicted and freed."""
        src_shard = int(self._shard_of[d])
        if src_shard == dest_shard:
            return
        if not self._free[dest_shard]:
            raise PackingLimitError(
                f"shard {dest_shard} has no free doc slots for migration"
            )
        src, dst = self.shards[src_shard], self.shards[dest_shard]
        l_src = int(self._local_of[d])
        l_dst = self._free[dest_shard].pop()
        export = src.export_doc(l_src)
        with self._device_ctx(dest_shard):
            dst.adopt_doc(l_dst, export)
        src.evict_doc(l_src)
        self._owners[src_shard][l_src] = None
        self._free[src_shard].append(l_src)
        self._owners[dest_shard][l_dst] = d
        self._shard_of[d] = dest_shard
        self._local_of[d] = l_dst
        _M_MIGRATED.inc()
        if _FLIGHT.enabled:
            _FLIGHT.record(
                "mesh.migrate", doc=d, src=src_shard, dest=dest_shard,
                rows=int(export["rows"]["key"].shape[0]),
            )

    def rebalance(self, max_moves: int = 1, min_gain_pages: int = 2):
        """Migrates the hottest doc off the most page-loaded shard onto
        the least-loaded one, up to `max_moves` times, while the page-load
        spread exceeds `min_gain_pages`. Heat = the controller's per-doc
        dispatch counts, tie-broken by row count. Returns the moves as
        (doc, src_shard, dest_shard) triples."""
        moves = []
        for _ in range(max_moves):
            loads = np.fromiter(
                (f.engine.pages.allocated for f in self.shards),
                np.int64, count=self.num_shards,
            )
            src_shard = int(np.argmax(loads))
            dest_shard = int(np.argmin(loads))
            if (
                src_shard == dest_shard
                or loads[src_shard] - loads[dest_shard] < min_gain_pages
                or not self._free[dest_shard]
            ):
                break
            candidates = [
                g for g in self._owners[src_shard] if g is not None
            ]
            if not candidates:
                break
            src = self.shards[src_shard]
            hot = max(
                candidates,
                key=lambda g: (
                    self._doc_dispatches[g],
                    src.engine.lengths[self._local_of[g]],
                ),
            )
            self.migrate_doc(hot, dest_shard)
            moves.append((hot, src_shard, dest_shard))
            _M_REBALANCE.inc()
        if moves and _FLIGHT.enabled:
            _FLIGHT.record("mesh.rebalance", moves=len(moves))
        return moves

    def audit(self) -> None:
        """Cross-shard ownership invariants: every global doc is owned by
        exactly one shard slot, routing arrays agree with the owner
        tables, and free lists cover exactly the unowned slots. Raises
        AssertionError on any leak."""
        seen: dict[int, tuple[int, int]] = {}
        for s, owners in enumerate(self._owners):
            assert len(owners) == self.shards[s].num_docs
            frees = set(self._free[s])
            for loc, g in enumerate(owners):
                if g is None:
                    assert loc in frees, (s, loc)
                    continue
                assert loc not in frees, (s, loc)
                assert g not in seen, f"doc {g} owned twice: {seen[g]}, {(s, loc)}"
                seen[g] = (s, loc)
                assert int(self._shard_of[g]) == s
                assert int(self._local_of[g]) == loc
        assert len(seen) == self.num_docs, "docs lost across shards"

    # ------------------------------------------------------------------ #
    # TpuDocFarm facade (global doc indexes) — the surface SyncFarm and
    # the serve stack consume

    @property
    def quarantine(self):
        """{global doc: last failure} across every shard."""
        out = {}
        for s, f in enumerate(self.shards):
            owners = self._owners[s]
            for loc, exc in f.quarantine.items():
                out[owners[loc]] = exc
        return out

    def release_quarantine(self, doc: int | None = None):
        if doc is not None:
            f, loc = self._local(doc)
            return [doc] if f.release_quarantine(loc) else []
        released = []
        for s, f in enumerate(self.shards):
            owners = self._owners[s]
            released.extend(owners[loc] for loc in f.release_quarantine())
        return released

    def get_patch(self, d: int):
        f, loc = self._local(d)
        return f.get_patch(loc)

    def get_heads(self, d: int):
        f, loc = self._local(d)
        return f.get_heads(loc)

    def get_all_changes(self, d: int):
        f, loc = self._local(d)
        return f.get_all_changes(loc)

    def get_changes(self, d: int, have_deps):
        f, loc = self._local(d)
        return f.get_changes(loc, have_deps)

    def get_change_by_hash(self, d: int, hash_):
        f, loc = self._local(d)
        return f.get_change_by_hash(loc, hash_)

    def get_missing_deps(self, d: int, heads=()):
        f, loc = self._local(d)
        return f.get_missing_deps(loc, heads)
