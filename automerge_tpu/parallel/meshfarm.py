"""MeshFarm: a doc-sharded multi-chip merge farm.

One controller front over N shard-local ``TpuDocFarm``s. Each shard owns
its documents outright — interners, page slab, host mirrors, quarantine
set — so shards share NO mutable state and each one can live on its own
device (``devices=[...]`` pins shard ``s``'s dispatches under
``jax.default_device``). The controller:

- **routes** every document to a shard by a stable doc-id hash
  (splitmix64 of the global index — the placement is a pure function of
  ``(num_docs, num_shards)``, so a restarted controller recovers the
  same routing without any persisted table);
- **fans out** one ``apply_changes`` delivery into per-shard
  ``apply_changes(isolation="doc")`` sub-dispatches and **merges** the
  per-shard ``FarmApplyResult``s back into one global-index result;
- **reconciles** the shard-local actor interner tables every
  ``reconcile_interval`` applies: shards intern actors independently, so
  a reconcile pass exchanges the table deltas (the union is interned
  into every shard) to keep actor-rank-dependent readbacks and sync
  filters globally consistent. Convergence is testable: a second pass
  immediately after a first syncs zero entries;
- **rebalances** hot/overfull documents between shards with
  page-granular migration (``farm.export_doc`` → id translation →
  ``engine.adopt_rows`` whole-page scatter → source ``evict_doc``),
  driven by per-shard slab page occupancy and the controller's per-doc
  dispatch histogram — explicitly via ``rebalance()``, or as a
  controller *policy* that runs every ``rebalance_interval`` applies.

Two execution backends share every code path above through a uniform
per-shard handle interface (``mesh_backend=`` ctor arg / the
``AM_MESH_BACKEND`` env knob):

- ``"inline"`` (default, the parity oracle): shards are in-process
  ``TpuDocFarm``s exactly as before; ``AM_MESH_CONCURRENCY`` > 1 runs
  sub-dispatches on a thread pool — device dispatches overlap, but
  every shard's HOST work still serializes under one GIL;
- ``"process"``: each shard's farm lives in its own worker process
  (``parallel/workers.py``, spawn-context, one JAX client per worker).
  Deliveries fan out as per-shard column batches over a two-transport
  data plane (``mesh_transport=`` / ``AM_MESH_TRANSPORT``): the default
  ``"shm"`` transport writes each batch into a per-shard shared-memory
  send ring and ships only a ``SlotRef`` control frame over the pipe,
  with results struct-encoded into the worker's result ring the same
  way (``parallel/shm.py``); ``"pickle"`` keeps the batch in the pipe
  frame and stays the byte-for-byte parity oracle (and the automatic
  fallback when POSIX shared memory is unavailable). Either way results
  come back as compact outcome/patch frames (patches stay pickled until
  someone indexes the result — under shm straight out of the mapped
  segment), and the controller additionally keeps
  three tiny mirrors so untouched shards need zero round trips: a
  quarantine mirror (the serve batcher reads ``mesh.quarantine`` on
  every submit), a no-op-patch mirror (clock/heads/maxOp/pending per
  doc) for docs whose shard was not dispatched, and a per-doc
  committed-delivery log that re-hydrates a respawned worker after a
  crash. Worker supervision — heartbeat, crash detection, respawn with
  re-hydration or quarantine of in-flight docs (``WorkerCrashError``) —
  is the controller's job; see ``heartbeat`` and ``_recover_worker``.

The facade exposes the exact ``TpuDocFarm`` surface the serving stack
consumes (``num_docs``, ``quarantine``, ``apply_changes``, ``get_*``,
``release_quarantine``), all in GLOBAL doc indexes, so ``SyncFarm`` and
``DynamicBatcher`` run unmodified over a mesh — with either backend.

Decode-cache ownership: the columnar decode caches are process-global
and SHARED by every inline shard on purpose — cached entries hold actor
*strings* and immutable op lists, never interner ids, and each shard
interns at transcode time into its own tables. Sharing parses is safe;
sharing interner state would not be, and there is none to share (pinned
by tests/test_mesh_parity.py). Under the process backend each worker
simply has its own cache with identical behavior (same env knobs travel
to the worker at spawn).
"""
# amlint: mesh-data-plane
from __future__ import annotations

import contextlib
import contextvars
import os
import pickle
import tempfile
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from ..errors import PackingLimitError, WorkerCrashError, error_kind
from ..obs.flight import get_flight, read_blackbox
from ..obs.metrics import get_metrics
from ..obs.prof import get_observatory
from ..obs.scope import current_exemplar
from ..profiling import get_profile
from ..tpu.farm import (
    _APPLIED,
    DocOutcome,
    FarmApplyResult,
    TpuDocFarm,
    _empty_object_patch,
    exc_from_blob,
    outcome_from_wire,
)
from . import shm as _shm
from .workers import WorkerHandle

_METRICS = get_metrics()
_M_SHARDS = _METRICS.gauge("mesh.shards", "shards in the mesh farm")
_M_APPLY = _METRICS.counter(
    "mesh.apply.calls", "deliveries fanned out through the mesh front"
)
_M_MIGRATED = _METRICS.counter(
    "mesh.docs.migrated",
    "documents moved between shards by page-granular migration",
)
_M_RECONCILE_RUNS = _METRICS.counter(
    "mesh.reconcile.runs", "cross-shard actor-table reconcile passes"
)
_M_RECONCILE_SYNCED = _METRICS.counter(
    "mesh.reconcile.actors_synced",
    "actor table entries copied between shard interners by reconcile",
)
_M_REBALANCE = _METRICS.counter(
    "mesh.rebalance.moves",
    "documents migrated by the occupancy-driven rebalancer",
)
_M_W_SPAWNS = _METRICS.counter(
    "mesh.worker.spawns", "mesh worker processes started (incl. respawns)"
)
_M_W_CRASHES = _METRICS.counter(
    "mesh.worker.crashes",
    "mesh worker deaths detected (pipe EOF, exit, timeout)",
)
_M_W_RESPAWNS = _METRICS.counter(
    "mesh.worker.respawns", "crashed mesh workers brought back up"
)
_M_W_RPCS = _METRICS.counter(
    "mesh.worker.rpcs", "controller->worker round trips"
)
_M_W_REHYDRATED = _METRICS.counter(
    "mesh.worker.rehydrated_docs",
    "documents replayed into a respawned worker from the delivery log",
)
_M_W_LOST = _METRICS.counter(
    "mesh.worker.lost_docs",
    "in-flight documents quarantined because their worker crashed",
)
_M_TELEMETRY_EVENTS = _METRICS.counter(
    "mesh.telemetry.events",
    "worker flight events absorbed into the controller timeline",
)
_M_TELEMETRY_RECOVERED = _METRICS.counter(
    "mesh.telemetry.blackbox.recovered",
    "dead-worker black-box files recovered into crash dumps",
)
_M_SHM_SEGMENTS = _METRICS.gauge(
    "mesh.shm.segments",
    "live shared-memory ring segments owned by this controller",
)
_M_SHM_REMAPS = _METRICS.counter(
    "mesh.shm.remaps",
    "worker respawns that reclaimed + re-attached existing shm rings",
)
_FLIGHT = get_flight()
_OBSERVATORY = get_observatory()


#: monotonic suffix for black-box paths (parallel meshes in one process)
_BB_SEQ = 0


def _absorb_worker_events(events) -> None:
    """The controller end of the flight telemetry channel: shipped worker
    event tails merge into the controller's unified timeline with fresh
    controller seqs (origin keys preserved). Injected into every
    ``WorkerHandle`` as ``on_flight``."""
    _M_TELEMETRY_EVENTS.inc(len(events))
    _FLIGHT.absorb(events)

# per-shard instrument families, registered lazily on first touch (the
# farm.quarantine.causes.<kind> idiom): full-literal-prefix names so the
# README catalog's <s> placeholder rows match them
_SHARD_DISPATCH_MS: dict[int, object] = {}
_SHARD_DOCS: dict[int, object] = {}


def _shard_dispatch_ms(s: int):
    h = _SHARD_DISPATCH_MS.get(s)
    if h is None:
        h = _METRICS.histogram(
            f"mesh.shard.{s}.dispatch_ms",
            f"wall time of shard {s}'s apply_changes sub-dispatches",
        )
        _SHARD_DISPATCH_MS[s] = h
    return h


def _shard_docs(s: int):
    c = _SHARD_DOCS.get(s)
    if c is None:
        c = _METRICS.counter(
            f"mesh.shard.{s}.docs",
            f"active documents dispatched to shard {s}",
        )
        _SHARD_DOCS[s] = c
    return c


# the mesh pickle tax, measured (ROADMAP item 2b): every frame the
# controller moves over a shard's pipe records its pickled size and
# serialize/deserialize wall time under mesh.pipe.<s>.* — the family the
# shared-memory transport PR will be judged against
_PIPE_INSTRUMENTS: dict[int, tuple] = {}


def _pipe_instruments(s: int) -> tuple:
    m = _PIPE_INSTRUMENTS.get(s)
    if m is None:
        m = (
            _METRICS.counter(
                f"mesh.pipe.{s}.bytes_out",
                f"pickled bytes sent to shard {s}'s worker",
            ),
            _METRICS.counter(
                f"mesh.pipe.{s}.bytes_in",
                f"pickled bytes received from shard {s}'s worker",
            ),
            _METRICS.counter(
                f"mesh.pipe.{s}.frames_out",
                f"frames sent to shard {s}'s worker",
            ),
            _METRICS.counter(
                f"mesh.pipe.{s}.frames_in",
                f"frames received from shard {s}'s worker",
            ),
            _METRICS.histogram(
                f"mesh.pipe.{s}.serialize_ms",
                f"controller-side pickle time per frame to shard {s}",
            ),
            _METRICS.histogram(
                f"mesh.pipe.{s}.deserialize_ms",
                f"controller-side unpickle time per frame from shard {s}",
            ),
            _METRICS.histogram(
                f"mesh.pipe.{s}.payload_ms",
                f"pickle/unpickle time per COLUMN-PAYLOAD frame on shard "
                f"{s}'s pipe (inline batches + inline patch blobs)",
            ),
            _METRICS.histogram(
                f"mesh.pipe.{s}.control_ms",
                f"pickle/unpickle time per CONTROL frame on shard {s}'s "
                f"pipe (ops, SlotRefs, acks, telemetry)",
            ),
            _METRICS.counter(
                f"mesh.pipe.{s}.payload_bytes",
                f"pipe bytes in COLUMN-PAYLOAD frames for shard {s}, both "
                f"directions (zero when the shm rings carry the columns)",
            ),
            _METRICS.counter(
                f"mesh.pipe.{s}.control_bytes",
                f"pipe bytes in CONTROL frames for shard {s}, both "
                f"directions (ops, SlotRefs, acks, telemetry deltas)",
            ),
        )
        _PIPE_INSTRUMENTS[s] = m
    return m


def _pipe_recorder(s: int):
    """The ``on_pipe`` callback for shard ``s``'s WorkerHandle: cheap
    no-op while metrics are disabled, full accounting otherwise. The
    ``kind`` leg splits column-payload frames from control frames so
    ``serialize_ms``'s aggregate has an attributable breakdown — under
    the shm transport the payload histograms go silent and the whole
    pickle tax is visibly control-frame noise."""

    def on_pipe(direction: str, nbytes: int, pickle_s: float,
                kind: str = "payload") -> None:
        if not _METRICS.enabled:
            return
        (b_out, b_in, f_out, f_in, ser_ms, deser_ms,
         payload_ms, control_ms,
         payload_bytes, control_bytes) = _pipe_instruments(s)
        if direction == "out":
            b_out.inc(nbytes)
            f_out.inc()
            ser_ms.observe(pickle_s * 1000.0)
        else:
            b_in.inc(nbytes)
            f_in.inc()
            deser_ms.observe(pickle_s * 1000.0)
        if kind == "payload":
            payload_ms.observe(pickle_s * 1000.0)
            payload_bytes.inc(nbytes)
        else:
            control_ms.observe(pickle_s * 1000.0)
            control_bytes.inc(nbytes)

    return on_pipe


# the shm transport's accounting twin: bytes that moved through the
# rings instead of the pipe, ring occupancy, and the stall/fallback
# count the backpressure design trades deadlocks for
_SHM_INSTRUMENTS: dict[int, tuple] = {}


def _shm_instruments(s: int) -> tuple:
    m = _SHM_INSTRUMENTS.get(s)
    if m is None:
        m = (
            _METRICS.counter(
                f"mesh.shm.{s}.bytes_out",
                f"column-batch bytes written to shard {s}'s send ring",
            ),
            _METRICS.counter(
                f"mesh.shm.{s}.bytes_in",
                f"result-frame bytes read from shard {s}'s result ring",
            ),
            _METRICS.gauge(
                f"mesh.shm.{s}.slots_in_use",
                f"shard {s} result-ring slots held (worker-side writes + "
                f"controller-side lazy patches)",
            ),
            _METRICS.counter(
                f"mesh.shm.{s}.stalls",
                f"shard {s} shm stalls: ring-full waits, oversize batches "
                f"and responses degraded to the inline pickle path",
            ),
        )
        _SHM_INSTRUMENTS[s] = m
    return m


def _route(num_docs: int, num_shards: int) -> np.ndarray:
    """Stable doc-id -> shard map: splitmix64 of the global index mod the
    shard count. Pure and stateless — rebalancing overrides individual
    entries at runtime, but the BASE placement needs no persisted table."""
    x = np.arange(num_docs, dtype=np.uint64)
    z = x + np.uint64(0x9E3779B97F4A7C15)
    z = (z ^ (z >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
    z = (z ^ (z >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
    z = z ^ (z >> np.uint64(31))
    return (z % np.uint64(num_shards)).astype(np.int64)


class _InlineShard:
    """The in-process twin of ``workers.WorkerHandle``: same per-shard
    facade over a directly owned ``TpuDocFarm``, so every controller
    path above the apply fan-out is backend-agnostic."""

    __slots__ = ("farm",)

    def __init__(self, farm: TpuDocFarm):
        self.farm = farm

    def get_patch(self, loc):
        return self.farm.get_patch(loc)

    def get_heads(self, loc):
        return self.farm.get_heads(loc)

    def get_all_changes(self, loc):
        return self.farm.get_all_changes(loc)

    def get_changes(self, loc, have_deps):
        return self.farm.get_changes(loc, have_deps)

    def get_change_by_hash(self, loc, hash_):
        return self.farm.get_change_by_hash(loc, hash_)

    def get_missing_deps(self, loc, heads=()):
        return self.farm.get_missing_deps(loc, heads)

    def release_quarantine(self, loc=None):
        return self.farm.release_quarantine(loc)

    def quarantine_map(self):
        return dict(self.farm.quarantine)

    def force_quarantine(self, loc, exc):
        self.farm.quarantine[loc] = exc

    def actor_table(self):
        return list(self.farm.actors.table)

    def intern_actors(self, actors):
        missing = [a for a in actors if self.farm.actors.find(a) is None]
        for a in missing:
            self.farm.actors.intern(a)
        return len(missing)

    def export_doc(self, loc):
        return self.farm.export_doc(loc)

    def adopt_doc(self, loc, export):
        self.farm.adopt_doc(loc, export)

    def evict_doc(self, loc):
        self.farm.evict_doc(loc)

    def pages_allocated(self):
        return int(self.farm.engine.pages.allocated)

    def doc_lengths(self):
        return self.farm.engine.lengths.tolist()

    def ping(self, timeout=None):
        return True

    def close(self):
        pass


def _raise_first_shard_error(errors: dict):
    """Re-raises the FIRST failing shard's exception (lowest shard id)
    with the shard attached: ``exc.shard`` plus a ``[shard N]`` message
    prefix. Callers collect errors from EVERY dispatched shard first, so
    a mid-dispatch failure never abandons other shards' results (pinned
    by tests/test_mesh_workers.py)."""
    s = min(errors)
    exc = errors[s]
    exc.shard = s
    if exc.args and isinstance(exc.args[0], str):
        exc.args = (f"[shard {s}] {exc.args[0]}",) + exc.args[1:]
    else:
        exc.args = (f"[shard {s}]",) + tuple(exc.args)
    raise exc


#: placeholder for a patch that still lives inside a shard's pickled frame
_PENDING = object()


class _LazyPatches:
    """One shard's double-pickled patch column: unpickles on first index."""

    __slots__ = ("_blob", "_patches")

    def __init__(self, blob: bytes):
        self._blob = blob
        self._patches = None

    def get(self) -> list:
        if self._patches is None:
            self._patches = pickle.loads(self._blob)
            self._blob = None
        return self._patches

    def __getstate__(self):  # keep result objects picklable either way
        return {"blob": self._blob, "patches": self._patches}

    def __setstate__(self, state):
        self._blob = state["blob"]
        self._patches = state["patches"]


class _ShmPatches(_LazyPatches):
    """One shard's patch column still sitting in its result-ring slot:
    the slot stays CONSUMER_HELD until someone indexes the result, then
    the blob unpickles straight out of the mapped segment (no
    controller-side copy) and the slot frees for the worker's next
    response. Dropping the result without touching it frees the slot
    too (``__del__``); a farm ``close()`` before that is also fine —
    ``release`` is a no-op on a closed ring, the patches are just gone
    with the segment."""

    __slots__ = ("_ring", "_slot", "_off", "_len")

    def __init__(self, ring, slot: int, off: int, length: int):
        super().__init__(None)
        self._ring = ring
        self._slot = int(slot)
        self._off = int(off)
        self._len = int(length)

    def get(self) -> list:
        if self._patches is None:
            view = self._ring.slot_view(self._slot)
            blob = view[self._off:self._off + self._len]
            try:
                self._patches = pickle.loads(blob)
            finally:
                del blob, view
            self._ring.release(self._slot)
            self._ring = None
        return self._patches

    def __getstate__(self):  # materialize before leaving the process
        return {"blob": None, "patches": self.get()}

    def __del__(self):
        ring = getattr(self, "_ring", None)
        if ring is not None:
            ring.release(self._slot)


class _MeshApplyResult(FarmApplyResult):
    """``FarmApplyResult`` whose patches materialize lazily out of the
    per-shard pickled frames. Indexing (and iteration, which routes
    through indexing) unpickles the owning shard's frame once and caches
    the materialized patch in place; callers that only look at
    ``outcomes`` (the serve batcher's accounting path) never pay the
    patch unpickle at all. NOTE: the underlying raw list holds
    ``_PENDING`` placeholders until touched, so serialize via
    ``list(result)``/iteration, never the raw list object."""

    def __init__(self, patches, outcomes, lazy: dict):
        super().__init__(patches, outcomes)
        self._lazy = lazy

    def _materialize(self, i: int):
        frame, loc = self._lazy.pop(i)
        patch = frame.get()[loc]
        list.__setitem__(self, i, patch)
        return patch

    def __getitem__(self, i):
        if isinstance(i, slice):
            return [self[j] for j in range(*i.indices(len(self)))]
        if i < 0:
            i += len(self)
        v = list.__getitem__(self, i)
        return self._materialize(i) if v is _PENDING else v

    def __iter__(self):
        return (self[i] for i in range(len(self)))


class MeshFarm:
    """N shard-local TpuDocFarms behind one controller. See module
    docstring.

    `num_shards` defaults to the visible device count when `devices` is
    given, else 1. `spare_slots` sizes each shard's migration headroom
    (empty doc slots a rebalance can adopt into). `mesh_backend` picks
    "inline" (default; env ``AM_MESH_BACKEND``) or "process" workers;
    `rebalance_interval` arms `rebalance_policy` ("page_load" or a
    callable taking the mesh) every that many applies. `warm_changes`
    (process backend) pre-compiles each worker's jit caches against a
    throwaway farm before the readiness barrier lifts.

    `mesh_transport` picks the process backend's data plane: "shm"
    (shared-memory column rings, pipe carries control frames only),
    "pickle" (batches ride the pipe frames — the parity oracle), or
    None/"auto" (env ``AM_MESH_TRANSPORT``, else shm when the host
    supports it). Explicitly requesting "shm" on a host without POSIX
    shared memory degrades to "pickle" rather than failing — the
    transports are byte-for-byte interchangeable. Inline backends have
    no transport; the resolved value is always "pickle" there.

    `store_dir` turns on the crash-consistent persistence tier
    (automerge_tpu/store): each shard owns ``<store_dir>/shard-NNN`` —
    workers (or inline shards) recover + hydrate from it on open, commit
    every delivery through its WAL before acking, and a
    ``_recover_worker`` respawn re-hydrates from disk instead of relying
    only on the controller's in-memory delivery log. Store directories
    deliberately survive ``close()`` — they ARE the durability story.
    Controller-side mirrors (no-op patch clocks for never-touched docs)
    reflect only deliveries this controller observed."""

    def __init__(self, num_docs: int, num_shards: int | None = None,
                 capacity: int = 1024, quarantine_threshold: int | None = 3,
                 page_size: int | None = None, devices=None,
                 reconcile_interval: int | None = 64,
                 spare_slots: int | None = None,
                 mesh_backend: str | None = None,
                 mesh_transport: str | None = None,
                 rebalance_policy="page_load",
                 rebalance_interval: int | None = None,
                 worker_timeout: float | None = None,
                 warm_changes=None, store_dir: str | None = None):
        if mesh_backend is None:
            mesh_backend = os.environ.get("AM_MESH_BACKEND", "inline")
        if mesh_backend not in ("inline", "process"):
            # amlint: disable=AM401 — API-usage validation, not a
            # data-plane fault (nothing was decoded or dispatched)
            raise ValueError(
                f"mesh_backend must be 'inline' or 'process', "
                f"got {mesh_backend!r}"
            )
        if mesh_transport is None:
            mesh_transport = os.environ.get("AM_MESH_TRANSPORT", "auto")
        if mesh_transport not in ("auto", "pickle", "shm"):
            # amlint: disable=AM401 — API-usage validation, not a
            # data-plane fault (nothing was decoded or dispatched)
            raise ValueError(
                f"mesh_transport must be 'auto', 'pickle' or 'shm', "
                f"got {mesh_transport!r}"
            )
        if mesh_backend != "process":
            mesh_transport = "pickle"  # no pipe to take off the data path
        elif mesh_transport != "pickle":
            # auto resolves to shm; an explicit shm ask degrades to the
            # pickle oracle when the host has no working POSIX shm
            mesh_transport = "shm" if _shm.shm_available() else "pickle"
        if store_dir is not None and rebalance_interval:
            # amlint: disable=AM401 — API-usage validation, not a
            # data-plane fault (nothing was decoded or dispatched)
            raise ValueError(
                "store_dir with automatic rebalancing is unsupported: the "
                "per-shard WAL is keyed by worker-local slots, which "
                "migration re-assigns"
            )
        if num_shards is None:
            num_shards = len(devices) if devices else 1
        if num_shards < 1 or num_docs < num_shards:
            # amlint: disable=AM401 — API-usage validation, not a
            # data-plane fault (nothing was decoded or dispatched)
            raise ValueError(
                f"need 1 <= num_shards <= num_docs, got "
                f"num_shards={num_shards} num_docs={num_docs}"
            )
        self.num_docs = num_docs
        self.num_shards = num_shards
        self.backend = mesh_backend
        self.transport = mesh_transport
        self.reconcile_interval = reconcile_interval
        self.rebalance_policy = rebalance_policy
        self.rebalance_interval = rebalance_interval
        self._devices = list(devices) if devices else None
        self._shard_of = _route(num_docs, num_shards)
        self._local_of = np.zeros(num_docs, np.int64)
        if spare_slots is None:
            spare_slots = max(2, (num_docs // num_shards) // 8)
        self._owners: list[list] = []
        self._free: list[list] = []
        self._slots: list[int] = []
        self.shards: list[TpuDocFarm] = []
        self._handles: list = []
        specs = []
        for s in range(num_shards):
            mine = np.nonzero(self._shard_of == s)[0]
            self._local_of[mine] = np.arange(len(mine), dtype=np.int64)
            self._owners.append(mine.tolist() + [None] * spare_slots)
            self._free.append(
                list(range(len(mine) + spare_slots - 1, len(mine) - 1, -1))
            )
            self._slots.append(len(mine) + spare_slots)
            specs.append(dict(
                shard=s, num_docs=len(mine) + spare_slots,
                capacity=capacity, quarantine_threshold=quarantine_threshold,
                page_size=page_size, env=(), epoch=0,
                blackbox_path=self._blackbox_path(s),
                warm_buffers=tuple(warm_changes) if warm_changes else None,
                store_dir=self._shard_store_dir(store_dir, s),
            ))
        # shm transport: the controller owns one send ring + one result
        # ring per shard; workers attach by name (spec["shm"]) at spawn
        # and RE-attach to the same segments on respawn
        self._rings: list[tuple] = []
        if mesh_backend == "process" and mesh_transport == "shm":
            for spec in specs:
                s = spec["shard"]
                send = _shm.create_ring(f"s{s}-tx")
                result = _shm.create_ring(f"s{s}-rx")
                self._rings.append((send, result))
                spec["shm"] = {"send": send.name, "result": result.name}
            _M_SHM_SEGMENTS.set(2 * num_shards)
        if mesh_backend == "process":
            # start every worker before awaiting any readiness message,
            # so farm construction + jit warmup overlap across workers
            self._handles = [
                WorkerHandle(
                    spec, timeout=worker_timeout, defer_ready=True,
                    on_delta=_METRICS.merge_frame, on_rpc=_M_W_RPCS.inc,
                    on_flight=_absorb_worker_events,
                    on_pipe=_pipe_recorder(spec["shard"]),
                )
                for spec in specs
            ]
            ready = [h.ensure_ready() for h in self._handles]
            _M_W_SPAWNS.inc(num_shards)
            if _FLIGHT.enabled:
                for s, pid in enumerate(ready):
                    _FLIGHT.record("mesh.worker.spawn", shard=s, pid=pid)
        else:
            for s, slots in enumerate(self._slots):
                with self._device_ctx(s):
                    farm = TpuDocFarm(
                        slots, capacity=capacity,
                        quarantine_threshold=quarantine_threshold,
                        page_size=page_size,
                    )
                    if specs[s]["store_dir"] is not None:
                        from ..store import ShardStore, hydrate_farm

                        shard_store = ShardStore(specs[s]["store_dir"])
                        hydrate_farm(farm, shard_store)
                        farm.attach_store(shard_store)
                    self.shards.append(farm)
            self._handles = [_InlineShard(f) for f in self.shards]
        # process-backend controller mirrors (see module docstring):
        # quarantine cache, per-doc no-op-patch state, committed-delivery
        # log for crash re-hydration
        self._qcache: dict[int, BaseException] = {}
        self._noop_state: list = [(0, {}, [], 0) for _ in range(num_docs)]
        self._doc_log: dict[int, list] = {}
        self._calls = 0
        self._doc_dispatches = np.zeros(num_docs, np.int64)
        workers = int(os.environ.get("AM_MESH_CONCURRENCY", "1"))
        self._executor = (
            ThreadPoolExecutor(max_workers=min(workers, num_shards))
            if workers > 1 and num_shards > 1 and mesh_backend == "inline"
            else None
        )
        _M_SHARDS.set(num_shards)

    # ------------------------------------------------------------------ #
    # routing

    @staticmethod
    def _shard_store_dir(root: str | None, s: int) -> str | None:
        """Shard ``s``'s store directory under the mesh ``store_dir`` (None
        when persistence is off). Deterministic — a new controller over the
        same root re-adopts every shard's history."""
        return None if root is None else os.path.join(root, f"shard-{s:03d}")

    @staticmethod
    def _blackbox_path(s: int) -> str:
        """Where shard ``s``'s worker persists its black box: the flight
        dump dir when one is configured (crash forensics land next to the
        crash dumps), the system temp dir otherwise. Unique per
        controller pid + spec so parallel meshes never collide; stable
        across respawns so recovery always knows where to look."""
        global _BB_SEQ
        _BB_SEQ += 1
        base = _FLIGHT.dump_dir or tempfile.gettempdir()
        return os.path.join(
            base, f"am-blackbox-{os.getpid()}-{_BB_SEQ:04d}-s{s}.json"
        )

    def _device_ctx(self, s: int):
        if self._devices is None or self.backend == "process":
            return contextlib.nullcontext()
        import jax

        return jax.default_device(self._devices[s % len(self._devices)])

    def shard_of(self, d: int) -> int:
        """Current owning shard of global doc `d` (base routing overridden
        by migrations). The serve batcher uses this for its per-shard
        flush accounting."""
        return int(self._shard_of[d])

    def _local(self, d: int):
        s = self._shard_of[d]
        return self._handles[s], self._local_of[d]

    # ------------------------------------------------------------------ #
    # lifecycle (process backend; inline no-ops)

    def close(self) -> None:
        """Shuts every worker down cleanly (ack'd shutdown, join,
        terminate stragglers), removes the workers' black-box files and
        releases the dispatch pool. Idempotent; leaves zero child
        processes behind."""
        for h in self._handles:
            h.close()
            if isinstance(h, _InlineShard):
                # final durability barrier; the store DIRECTORY persists
                if h.farm.store is not None:
                    h.farm.store.close()
                continue
            path = getattr(h, "spec", {}).get("blackbox_path")
            if path:
                with contextlib.suppress(OSError):
                    os.remove(path)
        if self._rings:
            # workers are down; unlink every segment so /dev/shm is clean
            # (pinned by tests/test_mesh_workers.py)
            for rings in self._rings:
                for ring in rings:
                    ring.close()
            self._rings = []
            _M_SHM_SEGMENTS.set(0)
        if self._executor is not None:
            self._executor.shutdown(wait=False)
            self._executor = None

    def __enter__(self):
        return self

    def __exit__(self, *exc_info):
        self.close()

    def heartbeat(self):
        """Pings every shard; a dead worker is detected here even between
        deliveries, respawned and re-hydrated (in-flight docs: none —
        nothing was in flight). Returns {shard: "ok" | "respawned"}."""
        status = {}
        for s, h in enumerate(self._handles):
            try:
                h.ping()
                status[s] = "ok"
            except WorkerCrashError as exc:
                self._recover_worker(s, in_flight=(), cause=exc,
                                     phase="heartbeat")
                status[s] = "respawned"
        return status

    def inject_worker_fault(self, shard: int, when: str = "next_apply"):
        """Test/chaos hook (process backend only): make `shard`'s worker
        SIGKILL itself — immediately (`when="now"`, fire-and-forget) or
        at its next apply (`"next_apply"`, i.e. mid-delivery from the
        controller's point of view)."""
        if self.backend != "process":
            # amlint: disable=AM401 — API-usage validation, not a
            # data-plane fault (nothing was decoded or dispatched)
            raise ValueError("worker fault injection needs the process "
                             "backend")
        h = self._handles[shard]
        if when == "now":
            h.request("_debug_die_now")
        else:
            h.call("_debug_die_on_next_apply")

    # ------------------------------------------------------------------ #
    # the fan-out data plane

    def apply_changes(self, per_doc_buffers, is_local: bool = False,
                      isolation: str = "doc"):
        """Routes one global delivery into per-shard sub-deliveries,
        dispatches each shard's farm, and merges the per-shard results
        into one global-index FarmApplyResult. Shards with no active docs
        are not dispatched; their docs report the same no-op patch an
        empty delivery produces."""
        if isolation != "doc":
            # amlint: disable=AM401 — API-usage validation: batch-wide
            # rollback cannot span shard-local fault domains
            raise ValueError(
                "MeshFarm supports isolation='doc' only (shards are "
                "independent fault domains)"
            )
        assert len(per_doc_buffers) == self.num_docs
        self._calls += 1
        _M_APPLY.inc()
        shard_of, local_of = self._shard_of, self._local_of
        active = [d for d, bufs in enumerate(per_doc_buffers) if bufs]
        np.add.at(self._doc_dispatches, active, 1)
        # plain ints: shard ids flow into flight-event fields and JSON
        # dumps, where a stray np.int64 would stringify
        touched = sorted({int(shard_of[d]) for d in active})
        counts = {
            s: sum(1 for d in active if shard_of[d] == s) for s in touched
        }
        if self.backend == "process":
            result = self._apply_process(
                per_doc_buffers, active, touched, counts, is_local
            )
        else:
            result = self._apply_inline(
                per_doc_buffers, active, touched, counts, is_local
            )
        if self.reconcile_interval and (
            self._calls % self.reconcile_interval == 0
        ):
            self.reconcile_actors()
        if self.rebalance_interval and (
            self._calls % self.rebalance_interval == 0
        ):
            if callable(self.rebalance_policy):
                self.rebalance_policy(self)
            elif self.rebalance_policy == "page_load":
                self.rebalance()
        return result

    def _apply_inline(self, per_doc_buffers, active, touched, counts,
                      is_local):
        shard_of, local_of = self._shard_of, self._local_of
        subs = [
            [[] for _ in range(f.num_docs)] for f in self.shards
        ]
        for d in active:
            subs[shard_of[d]][local_of[d]] = list(per_doc_buffers[d])

        def run_shard(s):
            t0 = time.perf_counter()
            with self._device_ctx(s):
                result = self.shards[s].apply_changes(
                    subs[s], is_local=is_local, isolation="doc"
                )
            if _METRICS.enabled:
                _shard_dispatch_ms(s).observe(
                    (time.perf_counter() - t0) * 1000.0,
                    exemplar=current_exemplar(),
                )
                _shard_docs(s).inc(counts[s])
            return result

        results = self._dispatch_shards(touched, run_shard)
        patches = [
            results[shard_of[g]][local_of[g]]
            if shard_of[g] in results
            else self.shards[shard_of[g]]._noop_patch(local_of[g])
            for g in range(self.num_docs)
        ]
        outcomes = [
            results[shard_of[g]].outcomes[local_of[g]]
            if shard_of[g] in results
            else _APPLIED
            for g in range(self.num_docs)
        ]
        return FarmApplyResult(patches, outcomes)

    def _apply_process(self, per_doc_buffers, active, touched, counts,
                       is_local):
        """Send-all-then-collect fan-out: every touched worker receives
        its pickled column batch before any result is awaited, so the
        per-shard host phases genuinely overlap across processes. The
        collect loop ALWAYS drains every touched shard — raising early
        would leave a queued response in a pipe and desynchronize the
        whole protocol — then crashes recover, then the first
        non-crash shard error (lowest shard id) re-raises with its shard
        attached, exactly like the inline dispatch path."""
        shard_of, local_of = self._shard_of, self._local_of
        want_phases = bool(get_profile().enabled)
        # the obs leg: the flight-enable bit mirrors this controller's
        # recorder into the worker, and the ambient DispatchSpan id rides
        # along so worker-side farm.dispatch/readback observations stamp
        # the controller's trace ids. None when observability is off — the
        # disabled path ships nothing extra.
        obs = None
        if _FLIGHT.enabled or _METRICS.enabled or _OBSERVATORY.enabled:
            obs = {"flight": _FLIGHT.enabled, "prof": _OBSERVATORY.enabled,
                   "exemplar": current_exemplar()}
        groups = {s: [] for s in touched}
        for d in active:
            groups[shard_of[d]].append(
                (int(local_of[d]), tuple(per_doc_buffers[d]))
            )
        sent = []
        crashed = {}
        for s in touched:
            batch = (
                self._tx_columns(s, groups[s]) if self._rings else groups[s]
            )
            try:
                self._handles[s].request(
                    "apply", (batch, is_local, want_phases, obs)
                )
                sent.append(s)
            except WorkerCrashError as exc:
                crashed[s] = exc
        responses = {}
        errors = {}
        for s in sent:
            try:
                responses[s] = self._handles[s].collect()
            except WorkerCrashError as exc:
                crashed[s] = exc
            except BaseException as exc:
                errors[s] = exc
        prof = get_profile()
        for s, resp in sorted(responses.items()):
            if _METRICS.enabled:
                _shard_dispatch_ms(s).observe(
                    resp["wall_s"] * 1000.0, exemplar=current_exemplar()
                )
                _shard_docs(s).inc(counts[s])
            if resp["phases"] and prof.enabled:
                prof.absorb_jsonl(resp["phases"])
            owners = self._owners[s]
            for loc, state in resp["noop"].items():
                self._noop_state[owners[loc]] = state
            for loc, blob in resp["q_entered"].items():
                self._qcache[owners[loc]] = exc_from_blob(blob)
        crash_outcomes = {}
        for s, cause in sorted(crashed.items()):
            in_flight = [d for d in active if shard_of[d] == s]
            crash_outcomes.update(
                self._recover_worker(s, in_flight, cause, phase="apply")
            )
        if errors:
            _raise_first_shard_error(errors)
        frames = {}
        outcome_cols = {}
        for s, resp in responses.items():
            frames[s], wires = self._rx_result(s, resp)
            outcome_cols[s] = [outcome_from_wire(w) for w in wires]
        outcomes = [
            outcome_cols[shard_of[g]][local_of[g]]
            if shard_of[g] in outcome_cols
            else crash_outcomes.get(g, _APPLIED)
            for g in range(self.num_docs)
        ]
        lazy = {
            g: (frames[s], loc)
            for s in frames
            for loc, g in enumerate(self._owners[s])
            if g is not None
        }
        patches = [
            _PENDING if g in lazy else self._noop_patch_mirror(g)
            for g in range(self.num_docs)
        ]
        committed = [
            d for d in active
            if outcomes[d].status == "applied"
        ]
        for d in committed:
            self._doc_log.setdefault(d, []).append(
                (tuple(per_doc_buffers[d]), is_local)
            )
        return _MeshApplyResult(patches, outcomes, lazy)

    # -- the shm transport's two legs ---------------------------------- #

    def _shm_stall(self, s: int, reason: str, nbytes: int) -> None:
        """One shm degradation tick: ring-full wait, oversize batch, or a
        worker response that fell back inline. Counted per shard and
        flight-recorded so a transport that quietly stopped being
        zero-copy shows up in the timeline."""
        if _METRICS.enabled:
            _shm_instruments(s)[3].inc()
        if _FLIGHT.enabled:
            # plain ints only: these fields land in flight JSONL dumps,
            # where a stray np.int64 would stringify (the PR 14 bug class)
            _FLIGHT.record(
                "mesh.shm.stall", shard=int(s), reason=reason,
                nbytes=int(nbytes),
            )

    def _tx_columns(self, s: int, batch: list):
        """Stages one shard's column batch in its send ring and returns
        the ``SlotRef`` control frame — or the batch itself when the
        ring cannot take it (oversize payload, or full past the acquire
        timeout), in which case this one delivery rides the pickle
        oracle path. Degrade, never deadlock."""
        send_ring, _ = self._rings[s]
        nbytes = _shm.measure_columns(batch)
        if nbytes > send_ring.slot_bytes:
            self._shm_stall(s, "oversize", nbytes)
            return batch
        waits = send_ring.stalls
        try:
            slot, gen = send_ring.acquire(timeout=1.0)
        except _shm.RingStall:
            self._shm_stall(s, "ring_full", nbytes)
            return batch
        if send_ring.stalls != waits:
            self._shm_stall(s, "waited", nbytes)
        view = send_ring.slot_view(slot)
        try:
            used = _shm.encode_columns_into(view, batch)
        finally:
            del view
        if _METRICS.enabled:
            _shm_instruments(s)[0].inc(used)
        return send_ring.publish(slot, gen, used)

    def _rx_result(self, s: int, resp: dict):
        """One apply response's bulk payload, as ``(patch frame,
        outcome wires)``: read out of the result ring when the worker
        shipped a ``SlotRef`` (the slot stays CONSUMER_HELD inside the
        returned ``_ShmPatches`` until someone materializes patches —
        that is the zero-copy hold), from the inline pickled fields
        otherwise. An inline response while the shm transport is on IS
        the worker's declared slot-exhaustion fallback — metered as a
        stall so the degradation stays visible."""
        ref = resp["patches"]
        if not isinstance(ref, _shm.SlotRef):
            if self._rings:
                self._shm_stall(s, "inline_response", len(ref))
            return _LazyPatches(ref), resp["outcomes"]
        _, result_ring = self._rings[s]
        view = result_ring.accept(ref)
        try:
            (p_off, p_len), wires = _shm.decode_result(view)
        finally:
            del view
        if _METRICS.enabled:
            m = _shm_instruments(s)
            m[1].inc(ref.nbytes)
            m[2].set(result_ring.slots_in_use())
        return _ShmPatches(result_ring, ref.slot, p_off, p_len), wires

    def _noop_patch_mirror(self, g: int) -> dict:
        """The patch of a delivery that changed nothing, built from the
        controller's no-op mirror — byte-identical to the owning farm's
        ``_noop_patch`` without a round trip."""
        max_op, clock, heads, pending = self._noop_state[g]
        return {
            "maxOp": max_op,
            "clock": dict(clock),
            "deps": list(heads),
            "pendingChanges": pending,
            "diffs": _empty_object_patch("_root", "map"),
        }

    def _recover_worker(self, s: int, in_flight, cause, phase: str):
        """Crash recovery: recover the dead worker's black box into the
        flight timeline and trigger the ``mesh.worker.crash`` dump, then
        respawn shard `s`'s worker, re-hydrate its committed state, and
        re-impose surviving quarantines; docs whose delivery was in
        flight when the worker died are quarantined (taxonomy:
        ``WorkerCrashError``, kind "worker_crash"). Returns {global doc:
        DocOutcome} for the in-flight docs.

        Re-hydration is two-source: with a mesh ``store_dir``, the
        respawned worker first recovers every fsynced commit from its
        shard store during spawn (``_worker_main``); the controller's
        per-doc delivery-log replay then lands on top — hash-graph dedup
        makes the overlap a no-op while repairing any group-commit
        durability window the crash cut off. Without a store, the replay
        is the only source, exactly as before."""
        h = self._handles[s]
        old_pid = h.pid
        heartbeat_age = h.heartbeat_age()
        _M_W_CRASHES.inc()
        if _FLIGHT.enabled:
            # black-box forensics BEFORE respawn (the fresh incarnation
            # will start rewriting the same path): absorb the dead
            # worker's final shard-tagged events, deduped against what it
            # already shipped live, then dump the merged timeline
            bb_path = h.spec.get("blackbox_path")
            blackbox = read_blackbox(bb_path) if bb_path else None
            recovered = 0
            if blackbox:
                recovered = _FLIGHT.absorb(
                    blackbox.get("events", ()), dedup=True
                )
                _M_TELEMETRY_RECOVERED.inc()
            _FLIGHT.record(
                "mesh.worker.crash", shard=s, pid=old_pid, phase=phase,
                cause=str(cause),
                heartbeat_age_s=(
                    None if heartbeat_age is None
                    else round(heartbeat_age, 3)
                ),
                blackbox=bb_path if blackbox else None,
                blackbox_events=recovered,
            )
            _FLIGHT.trigger("mesh.worker.crash", shard=s)
        freed_slots = 0
        if self._rings:
            # reclaim the ring slots the dead worker may have held: the
            # send ring entirely (this shard's delivery already failed —
            # nothing of ours is outstanding in it), the result ring's
            # PRODUCER_HELD slots only — CONSUMER_HELD ones back live
            # ``_ShmPatches`` from earlier responses and stay valid
            # across the respawn; the bumped generation counters keep
            # any stale pre-crash SlotRef from aliasing a reused slot
            send_ring, result_ring = self._rings[s]
            freed_slots = send_ring.reclaim() + result_ring.reclaim(
                held_by_producer_only=True
            )
        new_pid = h.respawn()
        _M_W_SPAWNS.inc()
        _M_W_RESPAWNS.inc()
        if self._rings:
            # the respawned worker re-attached the same segments by name
            _M_SHM_REMAPS.inc()
            if _FLIGHT.enabled:
                # plain ints only (JSONL dump fields — PR 14 bug class)
                _FLIGHT.record(
                    "mesh.shm.remap", shard=int(s),
                    epoch=int(h.spec.get("epoch", 0)),
                    freed_slots=int(freed_slots),
                )
        owned = [g for g in self._owners[s] if g is not None]
        in_flight = set(in_flight)
        replay_items = [
            (int(self._local_of[g]), self._doc_log.get(g, []))
            for g in owned
        ]
        rehydrated = h.replay(replay_items)
        _M_W_REHYDRATED.inc(rehydrated)
        survivors_quarantined = [
            g for g in owned if g in self._qcache and g not in in_flight
        ]
        for g in survivors_quarantined:
            h.force_quarantine(int(self._local_of[g]), self._qcache[g])
        outcomes = {}
        for g in sorted(in_flight):
            err = WorkerCrashError(
                f"worker for shard {s} (pid {old_pid}) died mid-delivery; "
                f"doc {g}'s delivery was in flight and is quarantined "
                f"pending release ({cause})"
            )
            self._qcache[g] = err
            h.force_quarantine(int(self._local_of[g]), err)
            _M_W_LOST.inc()
            outcomes[g] = DocOutcome("quarantined", err, error_kind(err))
        if _FLIGHT.enabled:
            _FLIGHT.record(
                "mesh.worker.respawn", shard=s, pid=new_pid,
                rehydrated=rehydrated, lost=len(in_flight),
            )
        return outcomes

    def _dispatch_shards(self, touched, fn):
        """Runs `fn(s)` for every touched shard; concurrently when the
        pool is enabled (context propagated so ambient profile/scope
        state follows each sub-dispatch), serially otherwise. Results
        come back keyed by shard id either way. Every future is drained
        before any failure surfaces — a mid-dispatch shard exception
        neither deadlocks the pool nor abandons other shards' completed
        results — and the FIRST failing shard's exception (lowest shard
        id) re-raises with the shard id attached (``exc.shard`` + a
        message prefix)."""
        results = {}
        errors = {}
        if self._executor is not None and len(touched) > 1:
            futures = {
                s: self._executor.submit(
                    contextvars.copy_context().run, fn, s
                )
                for s in touched
            }
            for s in touched:
                try:
                    results[s] = futures[s].result()
                except BaseException as exc:
                    errors[s] = exc
        else:
            for s in touched:
                try:
                    results[s] = fn(s)
                except BaseException as exc:
                    errors[s] = exc
        if errors:
            _raise_first_shard_error(errors)
        return results

    # ------------------------------------------------------------------ #
    # cross-shard actor reconcile

    def reconcile_actors(self) -> int:
        """Exchanges actor-table deltas between shards: the union of every
        shard's actor strings is interned into every shard (append-only,
        first-seen order, so the pass is deterministic). Returns the
        number of entries copied; a converged mesh returns 0."""
        union: list[str] = []
        seen: set[str] = set()
        for h in self._handles:
            for a in h.actor_table():
                if a not in seen:
                    seen.add(a)
                    union.append(a)
        synced = 0
        for h in self._handles:
            synced += h.intern_actors(union)
        _M_RECONCILE_RUNS.inc()
        _M_RECONCILE_SYNCED.inc(synced)
        if _FLIGHT.enabled:
            _FLIGHT.record(
                "mesh.reconcile", actors=len(union), synced=synced
            )
        return synced

    # ------------------------------------------------------------------ #
    # page-granular migration + the rebalancer

    def migrate_doc(self, d: int, dest_shard: int) -> None:
        """Moves global doc `d` onto `dest_shard` by whole pages: export
        (dense page readback + host state), id translation into the
        destination farm's interners, one adopt-scatter into freshly
        allocated pages, then the source slot is evicted and freed.
        Under the process backend the page snapshot travels over the
        pipe — export and adopt run in two different worker processes."""
        src_shard = int(self._shard_of[d])
        if src_shard == dest_shard:
            return
        if not self._free[dest_shard]:
            raise PackingLimitError(
                f"shard {dest_shard} has no free doc slots for migration"
            )
        src, dst = self._handles[src_shard], self._handles[dest_shard]
        l_src = int(self._local_of[d])
        l_dst = self._free[dest_shard].pop()
        export = src.export_doc(l_src)
        with self._device_ctx(dest_shard):
            dst.adopt_doc(l_dst, export)
        src.evict_doc(l_src)
        self._owners[src_shard][l_src] = None
        self._free[src_shard].append(l_src)
        self._owners[dest_shard][l_dst] = d
        self._shard_of[d] = dest_shard
        self._local_of[d] = l_dst
        _M_MIGRATED.inc()
        if _FLIGHT.enabled:
            _FLIGHT.record(
                "mesh.migrate", doc=d, src=src_shard, dest=dest_shard,
                rows=int(export["rows"]["key"].shape[0]),
            )

    def rebalance(self, max_moves: int = 1, min_gain_pages: int = 2):
        """Migrates the hottest doc off the most page-loaded shard onto
        the least-loaded one, up to `max_moves` times, while the page-load
        spread exceeds `min_gain_pages`. Heat = the controller's per-doc
        dispatch counts, tie-broken by row count. Returns the moves as
        (doc, src_shard, dest_shard) triples. Runs automatically every
        `rebalance_interval` applies when armed (the controller policy
        hook)."""
        moves = []
        for _ in range(max_moves):
            loads = np.fromiter(
                (h.pages_allocated() for h in self._handles),
                np.int64, count=self.num_shards,
            )
            src_shard = int(np.argmax(loads))
            dest_shard = int(np.argmin(loads))
            if (
                src_shard == dest_shard
                or loads[src_shard] - loads[dest_shard] < min_gain_pages
                or not self._free[dest_shard]
            ):
                break
            candidates = [
                g for g in self._owners[src_shard] if g is not None
            ]
            if not candidates:
                break
            lengths = self._handles[src_shard].doc_lengths()
            hot = max(
                candidates,
                key=lambda g: (
                    self._doc_dispatches[g],
                    lengths[self._local_of[g]],
                ),
            )
            self.migrate_doc(hot, dest_shard)
            moves.append((hot, src_shard, dest_shard))
            _M_REBALANCE.inc()
        if moves and _FLIGHT.enabled:
            _FLIGHT.record("mesh.rebalance", moves=len(moves))
        return moves

    def audit(self) -> None:
        """Cross-shard ownership invariants: every global doc is owned by
        exactly one shard slot, routing arrays agree with the owner
        tables, and free lists cover exactly the unowned slots. Raises
        AssertionError on any leak."""
        seen: dict[int, tuple[int, int]] = {}
        for s, owners in enumerate(self._owners):
            assert len(owners) == self._slots[s]
            frees = set(self._free[s])
            for loc, g in enumerate(owners):
                if g is None:
                    assert loc in frees, (s, loc)
                    continue
                assert loc not in frees, (s, loc)
                assert g not in seen, f"doc {g} owned twice: {seen[g]}, {(s, loc)}"
                seen[g] = (s, loc)
                assert int(self._shard_of[g]) == s
                assert int(self._local_of[g]) == loc
        assert len(seen) == self.num_docs, "docs lost across shards"

    # ------------------------------------------------------------------ #
    # TpuDocFarm facade (global doc indexes) — the surface SyncFarm and
    # the serve stack consume

    @property
    def quarantine(self):
        """{global doc: last failure} across every shard. Inline reads
        the live shard sets; the process backend serves the controller's
        quarantine mirror — the serve batcher hits this on EVERY submit,
        so it must not fan out round trips."""
        if self.backend == "process":
            return dict(self._qcache)
        out = {}
        for s, h in enumerate(self._handles):
            owners = self._owners[s]
            out.update({
                owners[loc]: exc
                for loc, exc in h.quarantine_map().items()
            })
        return out

    def release_quarantine(self, doc: int | None = None):
        if doc is not None:
            h, loc = self._local(doc)
            released = [doc] if h.release_quarantine(int(loc)) else []
        else:
            released = []
            for s, h in enumerate(self._handles):
                owners = self._owners[s]
                released.extend(owners[loc] for loc in h.release_quarantine())
        for g in released:
            self._qcache.pop(g, None)
        return released

    def get_patch(self, d: int):
        h, loc = self._local(d)
        return h.get_patch(loc)

    def get_heads(self, d: int):
        h, loc = self._local(d)
        return h.get_heads(loc)

    def get_all_changes(self, d: int):
        h, loc = self._local(d)
        return h.get_all_changes(loc)

    def get_changes(self, d: int, have_deps):
        h, loc = self._local(d)
        return h.get_changes(loc, have_deps)

    def get_change_by_hash(self, d: int, hash_):
        h, loc = self._local(d)
        return h.get_change_by_hash(loc, hash_)

    def get_missing_deps(self, d: int, heads=()):
        h, loc = self._local(d)
        return h.get_missing_deps(loc, heads)
