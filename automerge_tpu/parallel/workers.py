"""Mesh worker runtime: one shard's ``TpuDocFarm`` in its own process.

``MeshFarm(mesh_backend="process")`` pairs every shard with a worker
process (this module), mirroring how TPU inference stacks pair each
device with a host-side worker around a shared paged layout: the
controller keeps only the routing arrays, the actor reconcile and the
result fan-in, while ALL of a shard's host work — decode, column
transcode, device dispatch, patch materialization — runs under the
worker's own Python interpreter and its own JAX client. That is what
turns the mesh's device-dispatch scaling into wall-clock scaling: the
per-shard host phases that serialized under one GIL in the inline
backend now run in N processes.

Protocol (length-framed pickles over a ``multiprocessing`` pipe):

- parent -> child: ``(op, payload)`` — deliveries fan out as per-shard
  column batches (raw change bytes + local routing indices; shards
  share NO mutable state, so nothing else needs to travel). Under the
  pickle transport the batch itself rides in the frame; under the shm
  transport (``parallel/shm.py``) the batch is already sitting in the
  shard's send ring and ``payload[0]`` is a tiny ``SlotRef`` control
  handle instead — same tuple arity either way. Apply payloads carry
  an ``obs`` leg: the controller's flight-enable bit and the ambient
  ``DispatchSpan`` id, so worker-side latency observations stamp the
  controller's trace ids (restored via ``obs.scope.exemplar_context``);
- child -> parent: ``(status, payload, metrics_delta, flight_events)``
  — apply results return as compact frames (patch blob + flat outcome
  tuples, see ``tpu.farm.result_to_wire``) so the controller defers
  patch materialization until someone actually indexes the result.
  Under shm the worker struct-encodes the frame into its result ring
  and ``resp["patches"]``/``resp["outcomes"]`` become one shared
  ``SlotRef`` (falling back to the inline pickled form when the ring
  is briefly full — degrade, never deadlock); every response
  piggybacks the worker registry's metric delta (exemplars included),
  the worker flight recorder's unshipped tail (heartbeat pongs ship it
  too), and, on request, the worker's phase-profile dump for
  ``--watch`` attribution.

Crash forensics: when flight is enabled the worker maintains a bounded
**black-box file** (``obs.flight.write_blackbox``: shard-tagged flight
tail + the last delivery's phase profile), rewritten atomically after
every telemetry-bearing response, registered for an atexit flush, and
flushed again on the fault path — so a SIGKILL mid-delivery still
leaves the previous deliveries' events on disk for ``_recover_worker``
to absorb into the ``mesh.worker.crash`` dump.

Workers are spawned with the **spawn** (not fork) start method: a forked
JAX client shares page-table state with the parent and corrupts both;
spawn gives each worker a pristine interpreter. Consequently this module
must import cleanly WITHOUT pulling in jax or the farm — the heavy
imports happen inside ``_worker_main`` after the spawn env overrides are
applied (pinned by tests/test_mesh_workers_smoke.py).

Supervision lives in ``WorkerHandle``: readiness barrier at spawn,
heartbeat ping, crash detection on every receive (pipe EOF, dead
process, timeout), SIGKILL-hard ``close``. Respawn + doc re-hydration
policy is the controller's (meshfarm.py) — the handle only detects and
reports via ``WorkerCrashError``.
"""
# amlint: mesh-worker
# amlint: mesh-data-plane
from __future__ import annotations

import multiprocessing as mp
import os
import pickle
import signal
import time

from ..errors import WorkerCrashError
from . import shm as _shm

#: how long a worker waits for a free result slot before degrading the
#: one response to the inline pickle path (the controller meters it as a
#: ``mesh.shm.<s>.stalls`` tick)
_RESULT_SLOT_TIMEOUT_S = 0.25

_PING_TIMEOUT_S = 5.0


# ---------------------------------------------------------------------- #
# worker child


def _strip_forced_devices(env: dict) -> dict:
    """Drops ``--xla_force_host_platform_device_count`` from XLA_FLAGS:
    the controller may force N virtual host devices for the inline
    backend, but each worker owns exactly one real client."""
    flags = env.get("XLA_FLAGS")
    if flags and "--xla_force_host_platform_device_count" in flags:
        kept = [
            f for f in flags.split()
            if not f.startswith("--xla_force_host_platform_device_count")
        ]
        env = dict(env)
        env["XLA_FLAGS"] = " ".join(kept)
        if not env["XLA_FLAGS"]:
            del env["XLA_FLAGS"]
    return env


def _worker_main(conn, spec: dict) -> None:
    """Child entry point. Applies the spawn env overrides BEFORE the
    heavy imports (jax reads its env at client init), builds the shard
    farm, optionally pre-warms the jit caches against a throwaway farm,
    then serves the op loop until shutdown/EOF."""
    for k, v in spec["env"]:
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v
    stripped = _strip_forced_devices(dict(os.environ))
    if "XLA_FLAGS" in os.environ and "XLA_FLAGS" not in stripped:
        del os.environ["XLA_FLAGS"]
    os.environ.update(stripped)

    # shm transport: map the controller-owned rings by name BEFORE the
    # heavy imports (pure stdlib; a respawned worker re-attaches to the
    # same segments here — that is the "remap" the controller meters)
    send_ring = result_ring = None
    if spec.get("shm"):
        send_ring = _shm.attach_ring(spec["shm"]["send"])
        result_ring = _shm.attach_ring(spec["shm"]["result"])

    # each worker records into ITS OWN process-wide registry and flight
    # recorder and ships deltas/event tails back with every response; the
    # controller merges them.
    # amlint: disable=AM502 — this IS the worker's own registry: the
    # process-global singleton of the *worker* process, never the
    # controller's (deltas ship via diff_frames/merge_frame)
    from ..obs.metrics import diff_frames, get_metrics
    # amlint: disable=AM502,AM305 — the worker's own recorder IS the
    # shipping buffer: events ship over the pipe / the black-box file,
    # never through this process's exposition
    from ..obs.flight import get_flight, write_blackbox
    from ..obs.scope import exemplar_context
    from ..profiling import PhaseProfile, use_profile
    from ..tpu.farm import TpuDocFarm, exc_from_blob, exc_to_blob, result_to_wire

    metrics = get_metrics()  # amlint: disable=AM502 — same shipping buffer
    metrics.enable()
    flight = get_flight()  # amlint: disable=AM502,AM305 — shipping buffer
    # amlint: disable=AM502 — the worker's own observatory: per-program
    # compile/dispatch counters land in the worker registry and ship home
    # through the same metrics delta as everything else
    from ..obs.prof import get_observatory

    observatory = get_observatory()  # amlint: disable=AM502 — see above
    flight.shard = spec["shard"]
    flight.epoch = spec.get("epoch", 0)
    blackbox_path = spec.get("blackbox_path")
    m_blackbox = metrics.counter(
        "mesh.telemetry.blackbox.writes",
        "black-box files persisted by this worker",
    )
    last_phases = ""
    blackbox_mark = flight._seq  # no events yet -> no file

    def _flush_blackbox() -> None:
        # bounded + atomic; skipped while nothing new happened so the
        # obs-off path never touches the disk
        nonlocal blackbox_mark
        if blackbox_path is None or flight._seq == blackbox_mark:
            return
        blackbox_mark = flight._seq
        write_blackbox(blackbox_path, flight, last_phases)
        m_blackbox.inc()

    import atexit

    atexit.register(_flush_blackbox)
    farm_args = dict(
        capacity=spec["capacity"],
        quarantine_threshold=spec["quarantine_threshold"],
        page_size=spec["page_size"],
    )
    farm = TpuDocFarm(spec["num_docs"], **farm_args)
    store = None
    if spec.get("store_dir"):
        # per-shard crash-consistent store: opening IS recovery, so a
        # respawned worker re-hydrates every committed delivery from disk
        # before the controller's (idempotent) delivery-log replay lands.
        # The store layer records into this worker's own registry/recorder;
        # its counters ship home through the same metrics delta.
        from ..store import ShardStore, hydrate_farm

        store = ShardStore(spec["store_dir"])
        hydrate_farm(farm, store)
        farm.attach_store(store)
    if spec.get("warm_buffers"):
        # compile the all-docs-active dispatch shapes into THIS process's
        # jit cache before the readiness barrier lifts, so the measured
        # window never includes worker-side compilation
        warm = TpuDocFarm(spec["num_docs"], **farm_args)
        warm.apply_changes(
            [list(spec["warm_buffers"]) for _ in range(warm.num_docs)],
            isolation="doc",
        )
        del warm
    last_frame = metrics.frame()
    conn.send(("ready", os.getpid(), None, None))

    crash_armed = False
    while True:
        try:
            op, payload = conn.recv()
        except (EOFError, OSError):
            break
        if op == "shutdown":
            conn.send(("ok", None, None, None))
            break
        if op == "_debug_die_now":
            # fire-and-forget test hook: die as if kill -9'd externally
            os.kill(os.getpid(), signal.SIGKILL)
        if op == "_debug_die_on_next_apply":
            crash_armed = True
            conn.send(("ok", None, None, None))
            continue
        try:
            if op == "apply":
                if crash_armed:
                    os.kill(os.getpid(), signal.SIGKILL)
                # the obs leg toggles this worker's flight recorder to
                # mirror the controller's and restores the controller's
                # ambient dispatch-span id for exemplar stamping
                obs = payload[3] if len(payload) > 3 else None
                flight.enabled = bool(obs and obs.get("flight"))
                observatory.enabled = bool(obs and obs.get("prof"))
                if send_ring is not None and isinstance(payload[0],
                                                        _shm.SlotRef):
                    # the column batch is in the send ring, not the frame:
                    # validate the handle, copy the buffers out, free the
                    # slot so the controller's next delivery can reuse it
                    ref = payload[0]
                    view = send_ring.accept(ref)
                    try:
                        active = _shm.decode_columns(view)
                    finally:
                        del view
                        send_ring.release(ref.slot)
                    payload = (active,) + tuple(payload[1:])
                with exemplar_context(obs.get("exemplar") if obs else None):
                    resp = _do_apply(
                        farm, payload, PhaseProfile, use_profile,
                        result_to_wire, exc_to_blob,
                    )
                if result_ring is not None:
                    resp = _ship_result_shm(result_ring, resp)
                if isinstance(resp, dict) and resp.get("phases"):
                    last_phases = resp["phases"]
            else:
                resp = _dispatch(farm, op, payload, exc_to_blob, exc_from_blob)
            frame = metrics.frame()
            delta = diff_frames(frame, last_frame)
            last_frame = frame
            events = flight.ship()
            try:
                conn.send(("ok", resp, delta, events))
            except Exception as send_exc:  # unpicklable response payload
                conn.send(("err", exc_to_blob(send_exc), delta, events))
            _flush_blackbox()
        except BaseException as exc:  # ship the failure; keep serving
            _flush_blackbox()
            frame = metrics.frame()
            delta = diff_frames(frame, last_frame)
            last_frame = frame
            conn.send(("err", exc_to_blob(exc), delta, flight.ship()))
    if store is not None:
        store.close()  # final durability barrier on clean shutdown
    for ring in (send_ring, result_ring):
        if ring is not None:
            ring.close()  # attach side: drops the mapping, never unlinks


def _do_apply(farm, payload, PhaseProfile, use_profile, result_to_wire,
              exc_to_blob) -> dict:
    active, is_local, want_phases = payload[0], payload[1], payload[2]
    per_doc = [[] for _ in range(farm.num_docs)]
    for loc, bufs in active:
        per_doc[loc] = list(bufs)
    q_before = set(farm.quarantine)
    t0 = time.perf_counter()
    if want_phases:
        prof = PhaseProfile()
        with use_profile(prof):
            result = farm.apply_changes(per_doc, is_local=is_local,
                                        isolation="doc")
        phases = prof.to_jsonl()
    else:
        result = farm.apply_changes(per_doc, is_local=is_local,
                                    isolation="doc")
        phases = ""
    wall_s = time.perf_counter() - t0
    resp = result_to_wire(result)
    # the controller's quarantine mirror and no-op-patch mirror update
    # from these two deltas — untouched shards then serve facade reads
    # with ZERO round trips
    resp["q_entered"] = {
        loc: exc_to_blob(farm.quarantine[loc])
        for loc in set(farm.quarantine) - q_before
    }
    resp["noop"] = {
        loc: (farm.max_op[loc], dict(farm.clock[loc]),
              list(farm.heads[loc]), len(farm.queue[loc]))
        for loc, _ in active
    }
    resp["phases"] = phases
    resp["wall_s"] = wall_s
    return resp


def _ship_result_shm(result_ring, resp: dict) -> dict:
    """Moves the bulk of one apply response — the patch blob and the
    outcome tuples — into the result ring, leaving a ``SlotRef`` where
    the payload was. A full ring (controller holding every slot as lazy
    patches) or an oversize frame degrades THIS response to the inline
    pickled form instead of ever blocking the op loop; the controller
    notices the inline shape and meters the stall."""
    frame = _shm.encode_result(resp["patches"], resp["outcomes"])
    if len(frame) > result_ring.slot_bytes:
        return resp
    try:
        slot, gen = result_ring.acquire(timeout=_RESULT_SLOT_TIMEOUT_S)
    except _shm.RingStall:
        return resp
    view = result_ring.slot_view(slot)
    try:
        view[:len(frame)] = frame
    finally:
        del view
    ref = result_ring.publish(slot, gen, len(frame))
    resp["patches"] = ref
    resp["outcomes"] = ref
    return resp


def _dispatch(farm, op: str, payload, exc_to_blob, exc_from_blob):
    if op == "get_patch":
        return farm.get_patch(payload)
    if op == "get_heads":
        return farm.get_heads(payload)
    if op == "get_all_changes":
        return farm.get_all_changes(payload)
    if op == "get_changes":
        loc, have_deps = payload
        return farm.get_changes(loc, have_deps)
    if op == "get_change_by_hash":
        loc, hash_ = payload
        return farm.get_change_by_hash(loc, hash_)
    if op == "get_missing_deps":
        loc, heads = payload
        return farm.get_missing_deps(loc, heads)
    if op == "noop_state":
        loc = payload
        return (farm.max_op[loc], dict(farm.clock[loc]),
                list(farm.heads[loc]), len(farm.queue[loc]))
    if op == "release_quarantine":
        return farm.release_quarantine(payload)
    if op == "quarantine_map":
        return {loc: exc_to_blob(e) for loc, e in farm.quarantine.items()}
    if op == "force_quarantine":
        loc, blob = payload
        farm.quarantine[loc] = exc_from_blob(blob)
        return None
    if op == "actor_table":
        return list(farm.actors.table)
    if op == "intern_actors":
        missing = [a for a in payload if farm.actors.find(a) is None]
        for a in missing:
            farm.actors.intern(a)
        return len(missing)
    if op == "export_doc":
        return farm.export_doc(payload)
    if op == "adopt_doc":
        loc, export = payload
        farm.adopt_doc(loc, export)
        return None
    if op == "evict_doc":
        farm.evict_doc(payload)
        return None
    if op == "pages_allocated":
        return int(farm.engine.pages.allocated)
    if op == "doc_lengths":
        return farm.engine.lengths.tolist()
    if op == "replay":
        # crash re-hydration: the controller's committed delivery log,
        # replayed per doc in order. Doc-isolated applies commute across
        # docs, so per-doc replay reproduces the pre-crash patch state
        # byte for byte (pinned by tests/test_mesh_workers.py).
        rehydrated = 0
        for loc, deliveries in payload:
            for bufs, is_local in deliveries:
                per_doc = [[] for _ in range(farm.num_docs)]
                per_doc[loc] = list(bufs)
                farm.apply_changes(per_doc, is_local=is_local,
                                   isolation="doc")
            if deliveries:
                rehydrated += 1
        return rehydrated
    if op == "ping":
        return "pong"
    raise ValueError(f"unknown mesh worker op {op!r}")


# ---------------------------------------------------------------------- #
# controller-side handle


class WorkerHandle:
    """One shard worker's lifecycle + RPC surface, controller side.

    ``request``/``collect`` are split so the controller can fan a
    delivery out to every touched shard before collecting any result
    (the workers overlap); ``call`` is the sequential convenience. Every
    receive path detects death — pipe EOF, exited process, timeout — and
    raises ``WorkerCrashError``; recovery policy (respawn, re-hydrate,
    quarantine in-flight docs) belongs to the controller.

    ``on_delta`` receives each response's metric delta frame;
    ``on_flight`` receives each response's shipped flight-event tail;
    ``on_rpc`` fires once per request; ``on_pipe`` receives
    ``(direction, frame_bytes, pickle_seconds, kind)`` for every frame
    the handle moves — the mesh pickle tax, measured, with ``kind``
    splitting column-payload frames (``"payload"``: an apply request
    carrying the batch inline, a response carrying an inline patch
    blob) from control frames (``"control"``: everything else — ops,
    SlotRefs, acks) so the shm transport's win is attributable per
    frame class (all injected by meshfarm so this module never touches
    the controller's process-global registries). With ``on_pipe`` set
    the handle pickles frames explicitly (``Connection.send`` ==
    ``send_bytes(dumps(...))``, so the child's native protocol is
    unchanged).

    ``last_ok`` is the monotonic timestamp of the last successful
    response (readiness counts) — ``heartbeat_age()`` is what the crash
    event reports as "how long was this worker silent"."""

    def __init__(self, spec: dict, timeout: float | None = None,
                 on_delta=None, on_rpc=None, on_flight=None, on_pipe=None,
                 defer_ready: bool = False):
        self.spec = spec
        if timeout is None:
            timeout = float(os.environ.get("AM_MESH_WORKER_TIMEOUT_S", "600"))
        self.timeout = timeout
        self._on_delta = on_delta
        self._on_rpc = on_rpc
        self._on_flight = on_flight
        self._on_pipe = on_pipe
        self.conn = None
        self.proc = None
        self._ready = False
        self.last_ok: float | None = None
        self._start()
        if not defer_ready:
            self.ensure_ready()

    # -- lifecycle ----------------------------------------------------- #

    def _start(self) -> None:
        ctx = mp.get_context("spawn")
        parent_conn, child_conn = ctx.Pipe()
        proc = ctx.Process(
            target=_worker_main, args=(child_conn, self.spec),
            daemon=True, name=f"am-mesh-worker-{self.spec['shard']}",
        )
        proc.start()
        child_conn.close()
        self.conn, self.proc = parent_conn, proc
        self._ready = False

    def ensure_ready(self) -> int:
        """Blocks on the worker's readiness message (farm built, jit
        caches warmed). Deferring this lets a controller start every
        worker first so their initialization overlaps. Returns the
        worker pid."""
        if self._ready:
            return self.pid
        msg = self._recv(self.timeout)
        if msg[0] != "ready":
            self._kill()
            raise WorkerCrashError(
                f"shard {self.spec['shard']} worker sent {msg[0]!r} "
                "instead of readiness"
            )
        self._ready = True
        self.last_ok = time.monotonic()
        return msg[1]

    def spawn(self) -> int:
        """Starts the worker and waits for readiness. Returns the pid."""
        self._start()
        return self.ensure_ready()

    def respawn(self) -> int:
        self._kill()
        # a fresh epoch: the respawned worker's restarted flight seqs must
        # not collide with its previous life's in the merged timeline
        self.spec["epoch"] = self.spec.get("epoch", 0) + 1
        return self.spawn()

    def heartbeat_age(self, now: float | None = None) -> float | None:
        """Seconds since the last successful response, or None before
        readiness ever completed."""
        if self.last_ok is None:
            return None
        return (time.monotonic() if now is None else now) - self.last_ok

    def _kill(self) -> None:
        if self.proc is None:
            return
        if self.proc.is_alive():
            self.proc.terminate()
            self.proc.join(1.0)
        if self.proc.is_alive():
            self.proc.kill()
            self.proc.join(1.0)
        if self.conn is not None:
            self.conn.close()
        self.conn = self.proc = None

    def close(self, timeout: float = 5.0) -> None:
        """Clean shutdown: ack'd shutdown op, then join; stragglers are
        terminated. Leaves zero child processes behind (pinned by
        tests/test_mesh_workers_smoke.py)."""
        if self.proc is None:
            return
        try:
            self.conn.send(("shutdown", None))
            deadline = time.monotonic() + timeout
            while self.proc.is_alive() and time.monotonic() < deadline:
                if self.conn.poll(0.05):
                    self.conn.recv()  # the shutdown ack (or a straggler)
                else:
                    self.proc.join(0.05)
        except (OSError, EOFError, BrokenPipeError):
            pass
        self._kill()

    @property
    def alive(self) -> bool:
        return self.proc is not None and self.proc.is_alive()

    @property
    def pid(self) -> int | None:
        return None if self.proc is None else self.proc.pid

    # -- transport ----------------------------------------------------- #

    def _crash(self, why: str) -> WorkerCrashError:
        return WorkerCrashError(
            f"shard {self.spec['shard']} worker (pid {self.pid}): {why}"
        )

    def _recv(self, timeout: float):
        if self.conn is None:
            raise self._crash("not running")
        deadline = time.monotonic() + timeout
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                self._kill()
                raise self._crash(f"no response within {timeout:.0f}s")
            try:
                if self.conn.poll(min(0.2, remaining)):
                    return self._recv_frame()
            except (EOFError, OSError) as e:
                raise self._crash(f"pipe closed mid-receive ({e!r})") from e
            if not self.proc.is_alive():
                # drain a final message the worker flushed before dying
                try:
                    if self.conn.poll(0):
                        return self._recv_frame()
                except (EOFError, OSError):
                    pass
                raise self._crash(
                    f"process died (exitcode {self.proc.exitcode})"
                )

    def _recv_frame(self):
        """One frame off the pipe. ``Connection.recv`` IS
        ``loads(recv_bytes())``; splitting the two steps when ``on_pipe``
        is injected makes the frame size and deserialize time observable
        without changing the wire format."""
        if self._on_pipe is None:
            return self.conn.recv()
        buf = self.conn.recv_bytes()
        t0 = time.perf_counter()
        msg = pickle.loads(buf)
        dt = time.perf_counter() - t0
        # a response is a column payload iff the patch blob rides inline;
        # under shm it is a SlotRef and the frame is pure control
        payload_in = (
            isinstance(msg, tuple) and len(msg) == 4
            and isinstance(msg[1], dict)
            and isinstance(msg[1].get("patches"), (bytes, bytearray))
        )
        self._on_pipe("in", len(buf), dt,
                      "payload" if payload_in else "control")
        return msg

    def request(self, op: str, payload=None) -> None:
        if self._on_rpc is not None:
            self._on_rpc()
        if self.conn is None:
            raise self._crash("not running")
        try:
            if self._on_pipe is None:
                self.conn.send((op, payload))
            else:
                t0 = time.perf_counter()
                # amlint: disable=AM504 — the pickle-ORACLE transport: under
                # mesh_transport="pickle" the column batch legitimately rides
                # the frame (byte-for-byte parity baseline); under shm the
                # batch is a SlotRef by the time it reaches here
                buf = pickle.dumps((op, payload),
                                   protocol=pickle.HIGHEST_PROTOCOL)
                ser_s = time.perf_counter() - t0
                self.conn.send_bytes(buf)
                # an apply whose batch rides inline is the column payload
                # path; a SlotRef apply (shm) is a control frame
                payload_out = (
                    op == "apply" and isinstance(payload, tuple)
                    and bool(payload) and isinstance(payload[0], list)
                )
                self._on_pipe("out", len(buf), ser_s,
                              "payload" if payload_out else "control")
        except (OSError, BrokenPipeError, ValueError) as e:
            raise self._crash(f"pipe closed mid-send ({e!r})") from e

    def collect(self, timeout: float | None = None):
        status, payload, delta, events = self._recv(
            self.timeout if timeout is None else timeout
        )
        self.last_ok = time.monotonic()
        if delta and self._on_delta is not None:
            self._on_delta(delta)
        if events and self._on_flight is not None:
            self._on_flight(events)
        if status == "err":
            from ..tpu.farm import exc_from_blob

            raise exc_from_blob(payload)
        return payload

    def call(self, op: str, payload=None, timeout: float | None = None):
        self.request(op, payload)
        return self.collect(timeout)

    # -- the shard facade (local doc indexes) -------------------------- #

    def get_patch(self, loc):
        return self.call("get_patch", loc)

    def get_heads(self, loc):
        return self.call("get_heads", loc)

    def get_all_changes(self, loc):
        return self.call("get_all_changes", loc)

    def get_changes(self, loc, have_deps):
        return self.call("get_changes", (loc, have_deps))

    def get_change_by_hash(self, loc, hash_):
        return self.call("get_change_by_hash", (loc, hash_))

    def get_missing_deps(self, loc, heads=()):
        return self.call("get_missing_deps", (loc, heads))

    def release_quarantine(self, loc=None):
        return self.call("release_quarantine", loc)

    def quarantine_map(self) -> dict:
        from ..tpu.farm import exc_from_blob

        return {
            loc: exc_from_blob(blob)
            for loc, blob in self.call("quarantine_map").items()
        }

    def force_quarantine(self, loc, exc) -> None:
        from ..tpu.farm import exc_to_blob

        self.call("force_quarantine", (loc, exc_to_blob(exc)))

    def actor_table(self):
        return self.call("actor_table")

    def intern_actors(self, actors):
        return self.call("intern_actors", list(actors))

    def export_doc(self, loc):
        return self.call("export_doc", loc)

    def adopt_doc(self, loc, export) -> None:
        self.call("adopt_doc", (loc, export))

    def evict_doc(self, loc) -> None:
        self.call("evict_doc", loc)

    def pages_allocated(self):
        return self.call("pages_allocated")

    def doc_lengths(self):
        return self.call("doc_lengths")

    def noop_state(self, loc):
        return self.call("noop_state", loc)

    def replay(self, items):
        return self.call("replay", items)

    def ping(self, timeout: float = _PING_TIMEOUT_S) -> bool:
        self.request("ping")
        return self.collect(timeout) == "pong"
