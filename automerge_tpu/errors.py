"""Error taxonomy for the merge pipeline: classifiable faults, compatible bases.

The farm's north star is untrusted multi-user traffic at batch scale, where
"a ValueError happened" is useless: the fault-isolation layer (tpu/farm.py)
must decide per document whether a delivery was structurally corrupt
(re-request it), causally invalid (quarantine the peer), or over a packing
limit (shed/split), and the obs counters need an ``error_kind`` dimension.
This module is the single vocabulary for those decisions.

Every concrete class multiply inherits the exception type the pre-taxonomy
code raised (``ValueError``/``TypeError``), so existing callers and tests
that catch the stdlib types keep working; new code should catch
``AutomergeError`` or a specific subclass. amlint rule AM401 enforces that
the data-plane modules (codecs, columnar, opset, sync, farm, rga, ...)
raise taxonomy errors rather than bare stdlib ones.

Hierarchy::

    AutomergeError
    ├── DecodeError(ValueError)        structurally invalid bytes
    │   ├── ChecksumError              container checksum / hash mismatch
    │   ├── StoreCorruptError          persisted segment fails its checksum/hash graph
    │   └── StoreTornWriteError        torn/short frame at a WAL segment tail
    ├── EncodeError(ValueError)        unencodable value / malformed op dict
    ├── CausalityError(ValueError)     seq reuse/skip, unknown pred/dep/ref
    ├── PackingLimitError(ValueError)  merge-key / MAX_ELEMS / interner caps
    ├── SyncProtocolError(ValueError)  malformed or inapplicable peer message
    │   ├── SyncFrameError             malformed session envelope (outer framing)
    │   ├── RetryExhaustedError        retransmission budget spent; channel quarantined
    │   └── ChannelQuarantinedError    traffic shed: the sync channel is quarantined
    ├── QuarantinedError               delivery shed: the doc is quarantined
    ├── AdmissionRejectedError         serve front door refused the request at admission
    └── BackpressureError              serve front door: tenant queue full, retry later
"""
# amlint: host-only — pure-host layer: must not import tpu/ or jax
from __future__ import annotations


class AutomergeError(Exception):
    """Root of the taxonomy. ``kind`` is the obs/error-report dimension."""

    kind = "other"


class DecodeError(AutomergeError, ValueError):
    """Bytes that are not a structurally valid chunk/column/varint."""

    kind = "decode"


class ChecksumError(DecodeError):
    """Container checksum (or change-hash) does not match the data."""

    kind = "checksum"


class StoreCorruptError(DecodeError):
    """A persisted store segment is structurally complete but wrong: a
    frame checksum mismatch, a footer whose hash list disagrees with the
    rebuilt graph, or a compacted chunk that fails verification. Recovery
    quarantines the segment (and the documents it covers) rather than
    aborting the open; the docs are repairable via sync redelivery."""

    kind = "store_corrupt"


class StoreTornWriteError(DecodeError):
    """A short or torn frame at the tail of a write-ahead segment — the
    signature of a crash mid-append. Recovery truncates the segment at the
    last whole frame; everything before it is intact by construction."""

    kind = "store_torn"


class EncodeError(AutomergeError, ValueError):
    """A value or op dict that cannot be encoded into the wire format."""

    kind = "encode"


class CausalityError(AutomergeError, ValueError):
    """Causally invalid history: sequence number reuse or skip, duplicate
    opIds, predecessors/dependencies/list references that do not exist."""

    kind = "causality"


class PackingLimitError(AutomergeError, ValueError):
    """A device packing range would overflow: op counters beyond the
    merge-key range, list elements beyond the rank kernel's MAX_ELEMS, or
    an interner table past its bit-field cap."""

    kind = "packing"


class SyncProtocolError(AutomergeError, ValueError):
    """A peer sync message that is malformed or cannot be applied; local
    state is left untouched by the rejecting call."""

    kind = "sync"


class SyncFrameError(SyncProtocolError):
    """A session envelope (the outer seq/ack framing added by
    ``automerge_tpu.sync_session``) that is structurally invalid or fails
    its checksum; the inner reference wire format never saw the bytes and
    session state is untouched."""

    kind = "sync_frame"


class RetryExhaustedError(SyncProtocolError):
    """A supervised sync channel spent its full retransmission budget
    without an acknowledgement; the channel (not the document) is
    quarantined until ``SyncSession.release()``."""

    kind = "sync_retry"


class ChannelQuarantinedError(SyncProtocolError):
    """Traffic shed without processing: the sync channel is quarantined
    (see ``SyncSession.release``); the peer pair's documents stay live."""

    kind = "sync_quarantined"


class DeviceFaultError(AutomergeError):
    """The batched device program failed with this document's rows in the
    batch (isolated by the farm's dispatch bisection)."""

    kind = "device"


class WorkerCrashError(DeviceFaultError):
    """A mesh shard's worker process died (crash, kill, or unresponsive
    heartbeat). Documents whose delivery was in flight when the worker
    went down are quarantined with this error until released; the shard
    itself is respawned and re-hydrated from the controller's delivery
    log (see ``automerge_tpu.parallel.workers``)."""

    kind = "worker_crash"


class QuarantinedError(AutomergeError):
    """Delivery shed without processing: the target document is in the
    farm's quarantine set (see ``TpuDocFarm.release_quarantine``)."""

    kind = "quarantined"


class AdmissionRejectedError(AutomergeError):
    """The serving front door (automerge_tpu.serve) refused a request at
    admission — e.g. the target document is in the farm's quarantine set,
    so queueing its traffic would only grow a batch the farm will shed.
    The client's retransmission path is the retry loop: once the cause
    clears (``release_quarantine``), the same frame is admitted."""

    kind = "admission"


class BackpressureError(AutomergeError):
    """The serving front door's bounded per-tenant queue is full: the
    tenant is submitting faster than the batcher drains. The request was
    not enqueued; the client should back off and retransmit (the session
    layer's timeout/backoff machinery does exactly that)."""

    kind = "backpressure"


def error_kind(exc: BaseException) -> str:
    """The ``error_kind`` dimension for one exception: the taxonomy class's
    ``kind``, or ``"other"`` for exceptions outside the taxonomy."""
    return getattr(exc, "kind", "other") if isinstance(exc, AutomergeError) else "other"


_KIND_INDEX: dict[str, type] = {}


def error_from_kind(kind: str, message: str) -> AutomergeError:
    """Rebuilds a taxonomy exception from its persisted ``kind`` dimension.

    The store's quarantine sidecar records causes as ``(kind, message)``
    pairs; hydration turns them back into catchable exceptions of the
    original class. Unknown kinds rebuild as the ``AutomergeError`` root
    so a newer sidecar never crashes an older reader."""
    if not _KIND_INDEX:
        stack: list[type] = [AutomergeError]
        while stack:
            cls = stack.pop()
            _KIND_INDEX.setdefault(cls.kind, cls)
            stack.extend(cls.__subclasses__())
    return _KIND_INDEX.get(kind, AutomergeError)(message)
