"""amlint — repo-native static analysis for automerge_tpu.

The TPU backend's correctness hangs on invariants the type system cannot
see: the merge-key bit layout (``slot << 44 | ctr << 20 | actor``), the
interner packing caps, the purity rules jax imposes on traced code, and the
host/device module split. This package enforces them over the AST on every
commit (tests/test_static_analysis.py is the tier-1 gate).

Library API::

    from automerge_tpu.analysis import run_analysis
    findings = run_analysis(["automerge_tpu"])       # unsuppressed only
    everything = run_analysis(paths, include_suppressed=True)

CLI::

    python -m automerge_tpu.analysis [paths...]      # exit 1 on findings
    python -m automerge_tpu.analysis --list-rules
    python -m automerge_tpu.analysis --select AM403,AM701
    python -m automerge_tpu.analysis --changed HEAD~1   # incremental
    python -m automerge_tpu.analysis --json

Exit codes are pinned: 0 = clean, 1 = unsuppressed findings, 2 = usage
error (unknown rule id in ``--select`` or an ``# amlint: disable=``
directive, unreadable path, bad ``--changed`` ref) — usage errors print
one line to stderr, never a traceback.

Every scan builds a whole-program :class:`graph.CallGraph` over the file
set and hands it to every rule family, so the reachability rules (AM2xx
tracer taint, AM303 recording-in-traced-code, AM403 blocking-in-serve,
AM502/AM305 worker import hygiene) are *transitive*: they follow calls
and imports across files — from-imports, module aliases, inferable
method receivers — with bounded depth, and print the discovery chain
(``[reachable via a -> b -> c]``) in every diagnostic.

Rule families (see core.RULES for the catalog):

- **AM1xx packing/hotpath**: bit-layout constant consistency (AM101),
  magic shift/mask literals (AM102), interner caps (AM103), packing-limit
  diagnostic wording (AM104), per-row Python (``sort(key=lambda)``,
  range-loop ``int()``/``bool()`` coercion) in profiled hot-phase modules
  (AM105).
- **AM2xx tracer safety**: Python control flow on traced values (AM201),
  host calls on traced values (AM202), dtype-less array construction
  (AM203), captured-state mutation in traced code (AM204).
- **AM3xx boundary**: host-only modules importing the device layer
  (AM301), hidden host syncs inside device profiling phases (AM302),
  metric/span recording inside jit/vmap/Pallas-reachable code (AM303),
  metric/event names out of sync with the README observability catalog
  in either direction (AM304); worker-executed modules reaching the
  telemetry exposition/fan-in layer (``get_flight``, ``obs.export``) —
  worker telemetry leaves the process only through the shipping buffer:
  pipe deltas, shipped flight tails and the black-box file (AM305);
  bare ``jax.jit`` references bypassing the amprof observatory —
  compiled programs register through ``tpu/jitprof.profiled_jit`` so
  recompiles carry program identity, with justified
  ``# amlint: unprofiled-jit`` escapes (AM306).
- **AM4xx taxonomy/serve**: data-plane modules raising bare ValueError/
  TypeError instead of classifiable taxonomy errors (AM401); sync
  data-plane modules calling wall clocks or the global RNG directly
  instead of the injectable clock/RNG the chaos suite replays (AM402);
  blocking calls (time.sleep, bare socket, synchronous device readbacks)
  inside serve/ event-loop code (AM403); sync v2 wire-codec modules
  (``sync_v2``, ``tpu/fingerprint``, the ``v2-wire-codec`` marker)
  raising any exception class outside ``automerge_tpu.errors`` — the
  negotiated fallback catches exactly the taxonomy, so anything else
  kills the channel instead of downgrading it to v1 (AM404).
- **AM5xx mesh**: dense per-doc ``range()`` statement loops in the mesh
  controller's routing/merge-result paths — sparse active lists and
  comprehensions keep per-delivery Python O(active), not O(farm)
  (AM501); worker-executed modules importing the controller layer or
  touching process-global registry accessors — workers speak the pipe
  protocol and ship metric deltas explicitly (AM502); controller/worker
  pipe-frame drift — ops sent with no handler, dead handlers, wrong
  request/response tuple arity, response fields read that nothing
  writes (AM503, modules ``workers``/``meshfarm`` plus files marked
  ``# amlint: pipe-protocol``); ``pickle.dumps``/``pickle.dump`` on the
  shm transport's data plane (``parallel/shm.py`` plus files marked
  ``# amlint: mesh-data-plane``) — bulk column payloads ride the
  shared-memory rings struct-framed, so a pickled send path silently
  refunds the zero-copy win; the pickle parity-oracle transport carries
  the one justified suppression (AM504).
- **AM6xx durability**: bare write-mode ``open()``/``os.write`` in
  durability-plane modules (``store/`` stems or files marked
  ``# amlint: durability-plane``) — durable bytes flow only through
  ``store.atomic.atomic_write`` (tmp + fsync + rename) or the WAL's
  checksummed appender, so crash recovery can prove exactly what
  committed; the two primitives themselves carry justified suppressions
  (AM601).
- **AM7xx shape stability**: ``profiled_jit``/``jax.jit`` dispatch sites
  fed an array whose shape derives from an unbucketed dynamic length —
  no pow2/bucket helper on the dataflow path from ``len()``/``.shape``/
  a dynamic slice to the dispatch. The static twin of amprof's runtime
  ``prof.recompile.storm`` detector: it reports the storm before the
  compile time is burned, with the dataflow chain in the diagnostic
  (AM701).

Suppression: ``# amlint: disable=AM102`` trailing a line or standing alone
on the line above; ``# amlint: disable-file=AM203`` for a whole file.

This package is stdlib-only by design: importing it (and running the CLI)
must never initialise jax, so the gate runs on any host.
"""
from __future__ import annotations

import tokenize
from pathlib import Path

from . import (boundary, catalog, datarules, durability, hotpath, meshrules,
               obsrules, packing, profrules, protorules, shaperules, taxonomy,
               tracer, workerrules)
from .core import RULES, FileContext, Finding, UsageError, collect_files
from .graph import CallGraph

__all__ = [
    "RULES",
    "Finding",
    "UsageError",
    "CallGraph",
    "run_analysis",
    "format_report",
    "default_target",
]

#: every rule family, in report order — each exposes check(ctxs, graph)
FAMILIES = (packing, tracer, boundary, obsrules, catalog, taxonomy,
            hotpath, meshrules, workerrules, profrules, durability,
            shaperules, protorules, datarules)


def default_target() -> Path:
    """The automerge_tpu package directory (the CLI's default scan root)."""
    return Path(__file__).resolve().parent.parent


def run_analysis(paths, include_suppressed: bool = False) -> list[Finding]:
    """Runs every rule family over the given files/directories.

    Returns findings sorted by (path, line, rule). Suppressed findings are
    dropped unless ``include_suppressed`` is set (they then carry
    ``suppressed=True``). Unparseable files yield an AM000 finding instead
    of raising. A suppression directive naming an unknown rule id raises
    :class:`UsageError` — a typo'd ``disable=`` silently un-suppresses,
    which is worse than failing loudly."""
    ctxs: list[FileContext] = []
    findings: list[Finding] = []
    for p in paths:
        if not Path(p).exists():
            raise UsageError(f"no such file or directory: {p}")
    for path, display in collect_files([Path(p) for p in paths]):
        try:
            ctxs.append(FileContext(path, display))
        except (SyntaxError, UnicodeDecodeError, tokenize.TokenError) as exc:
            findings.append(Finding("AM000", display, getattr(exc, "lineno", 1) or 1,
                                    0, f"could not parse: {exc}"))
        except OSError as exc:
            raise UsageError(f"cannot read {display}: {exc}") from exc
    for ctx in ctxs:
        for line, rid in ctx.unknown_suppressions:
            raise UsageError(
                f"{ctx.display}:{line}: unknown rule id {rid!r} in "
                f"suppression directive (see --list-rules)"
            )
    graph = CallGraph(ctxs)
    for family in FAMILIES:
        findings.extend(family.check(ctxs, graph))
    findings.sort(key=lambda f: (f.path, f.line, f.rule_id, f.col))
    if not include_suppressed:
        findings = [f for f in findings if not f.suppressed]
    return findings


def format_report(findings: list[Finding]) -> str:
    lines = [f.format() for f in findings]
    active = sum(1 for f in findings if not f.suppressed)
    suppressed = len(findings) - active
    tail = f"{active} finding(s)"
    if suppressed:
        tail += f", {suppressed} suppressed"
    lines.append(tail)
    return "\n".join(lines)
