"""AM503 — pipe-protocol conformance between controller and workers.

The mesh pipe protocol (parallel/workers.py) is stringly typed by
construction: the controller sends ``(op, payload)`` frames and the
worker answers ``(status, payload, metrics_delta, flight_events)``
4-tuples. Nothing at runtime checks that both ends agree — a renamed op
surfaces as a worker ``ValueError`` mid-delivery, a dropped tuple element
as an unpack crash on the controller, and a misspelled response field as
a ``KeyError`` deep in the fan-in loop. With the shared-memory data plane
coming (ROADMAP item 2), protocol drift gets strictly more expensive to
catch at runtime, so this rule checks the contract at lint time:

1. **op coverage, both directions** — every op literal the controller
   sends (``handle.request("op", ...)``, ``handle.call("op", ...)``, or a
   raw ``self.conn.send(("op", payload))`` frame) has a matching worker
   handler (an ``op == "..."`` comparison in the dispatch ladder), and
   every handled op is sent by somebody (dead handlers are drift too);
2. **frame arity at every construction site** — worker responses
   (``conn.send((...))`` on the child's bare ``conn``) must be 4-tuples,
   controller requests (``self.conn.send((...))``) must be 2-tuples, and
   tuple-unpacks of ``_recv()``/``recv()`` results must bind exactly 4
   (respectively 2) names;
3. **field conformance** — every literal key the controller reads off a
   response dict (``resp["wall_s"]``, ``resp.get("phases")``) is a key
   some worker-side producer writes (subscript stores on ``resp`` plus
   the dict literals of wire builders like ``tpu.farm.result_to_wire``,
   resolved through the call graph).

Scope: modules whose stem is in ``PROTOCOL_STEMS`` (``workers``,
``meshfarm``) plus files marked ``# amlint: pipe-protocol`` (the fixture
hook). The dispatch-ladder convention is a variable literally named
``op`` compared against string constants, and response dicts are
variables named ``resp`` — the in-tree protocol spelling. The field
check only runs when every ``resp = <call>()`` producer resolved through
the graph (a partial scan that cannot see the wire builder stays silent
rather than guessing).
"""
from __future__ import annotations

import ast
import re
from pathlib import Path

from .core import FileContext, Finding, dotted_name

#: modules that speak the controller/worker pipe protocol
PROTOCOL_STEMS = frozenset({"workers", "meshfarm"})

_MARKER_RE = re.compile(r"#\s*amlint:\s*pipe-protocol\b")

#: request/response frame arities — the (op, payload) and
#: (status, payload, metrics_delta, flight_events) contracts
REQUEST_ARITY = 2
RESPONSE_ARITY = 4

#: call leaves that bind a response on the controller side (reads, not
#: writes — they never mark the producer set incomplete)
_READ_SIDE_LEAVES = frozenset({"call", "collect", "recv"})

#: max producer-call recursion when collecting write keys (resp =
#: _do_apply(...) -> resp = result_to_wire(...) -> dict literal)
_PRODUCER_DEPTH = 3


def _in_scope(ctx: FileContext) -> bool:
    return (
        Path(ctx.path).stem in PROTOCOL_STEMS
        or _MARKER_RE.search(ctx.source) is not None
    )


def _str_const(node: ast.AST | None) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


class _Protocol:
    """Everything collected across the in-scope files of one scan."""

    def __init__(self):
        #: op -> [(ctx, node)] send sites / handler compare sites
        self.sent: dict[str, list] = {}
        self.handled: dict[str, list] = {}
        self.reads: list[tuple[FileContext, ast.AST, str]] = []
        self.writes: set[str] = set()
        self.write_sources = 0
        self.unresolved_producer = False
        self.findings: list[Finding] = []


def _function_write_keys(fn: ast.AST, graph, ctx: FileContext,
                         depth: int, proto: _Protocol) -> set[str]:
    """Literal dict keys a producer function contributes to a response:
    dict-literal keys plus string subscript-store keys, following
    ``resp = other_builder(...)`` producer calls through the graph."""
    out: set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Dict):
            for key in node.keys:
                k = _str_const(key)
                if k is not None:
                    out.add(k)
        elif isinstance(node, ast.Subscript) and isinstance(
            node.ctx, ast.Store
        ):
            k = _str_const(node.slice)
            if k is not None:
                out.add(k)
        elif depth > 0 and isinstance(node, ast.Assign) and len(
            node.targets
        ) == 1 and isinstance(node.targets[0], ast.Name) and \
                node.targets[0].id == "resp" and isinstance(
                    node.value, ast.Call):
            out |= _producer_keys(node.value, graph, ctx, depth - 1, proto)
    return out


def _producer_keys(call: ast.Call, graph, ctx: FileContext, depth: int,
                   proto: _Protocol) -> set[str]:
    """Write keys contributed by one ``resp = f(...)`` producer call."""
    leaf = (dotted_name(call.func) or "").rsplit(".", 1)[-1]
    if leaf in _READ_SIDE_LEAVES:
        return set()
    target = None
    if graph is not None:
        mod = graph.module_for(ctx)
        if mod is not None:
            enclosing = None
            parent = getattr(call, "_amlint_parent", None)
            while parent is not None:
                if isinstance(parent, ast.ClassDef):
                    enclosing = parent.name
                    break
                parent = getattr(parent, "_amlint_parent", None)
            target = graph.resolve_call(mod, call.func, enclosing)
    if target is None:
        proto.unresolved_producer = True
        return set()
    proto.write_sources += 1
    return _function_write_keys(target.node, graph, target.ctx, depth, proto)


def _collect(ctx: FileContext, graph, proto: _Protocol) -> None:
    for node in ast.walk(ctx.tree):
        # --- sent ops + frame arity ----------------------------------- #
        if isinstance(node, ast.Call) and isinstance(
            node.func, ast.Attribute
        ):
            leaf = node.func.attr
            if leaf in ("request", "call") and node.args:
                op = _str_const(node.args[0])
                if op is not None:
                    proto.sent.setdefault(op, []).append((ctx, node))
            elif leaf == "send" and node.args and isinstance(
                node.args[0], ast.Tuple
            ):
                frame = node.args[0]
                op = _str_const(frame.elts[0]) if frame.elts else None
                receiver = dotted_name(node.func.value) or ""
                if receiver == "conn":
                    # child side: response frames off the bare pipe end
                    if len(frame.elts) != RESPONSE_ARITY:
                        proto.findings.append(ctx.finding(
                            "AM503", node,
                            f"worker response frame built with "
                            f"{len(frame.elts)} element(s): the pipe "
                            f"contract is the {RESPONSE_ARITY}-tuple "
                            "(status, payload, metrics_delta, "
                            "flight_events) at every construction site "
                            "— the controller's collect() unpack crashes "
                            "on anything else",
                        ))
                elif receiver.endswith(".conn"):
                    # controller side: request frames
                    if len(frame.elts) != REQUEST_ARITY:
                        proto.findings.append(ctx.finding(
                            "AM503", node,
                            f"controller request frame built with "
                            f"{len(frame.elts)} element(s): the pipe "
                            f"contract is the {REQUEST_ARITY}-tuple "
                            "(op, payload) — the worker loop's unpack "
                            "crashes on anything else",
                        ))
                    if op is not None:
                        proto.sent.setdefault(op, []).append((ctx, node))
        # --- handled ops ---------------------------------------------- #
        if isinstance(node, ast.Compare) and isinstance(
            node.left, ast.Name
        ) and node.left.id == "op" and len(node.ops) == 1 and isinstance(
            node.ops[0], (ast.Eq, ast.NotEq)
        ):
            op = _str_const(node.comparators[0])
            if op is not None and isinstance(node.ops[0], ast.Eq):
                proto.handled.setdefault(op, []).append((ctx, node))
        # --- unpack arities ------------------------------------------- #
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Tuple) and isinstance(
                    node.value, ast.Call):
            leaf = (dotted_name(node.value.func) or "").rsplit(".", 1)[-1]
            width = len(node.targets[0].elts)
            if leaf == "_recv" and width != RESPONSE_ARITY:
                proto.findings.append(ctx.finding(
                    "AM503", node,
                    f"response unpack binds {width} name(s): worker "
                    f"frames are {RESPONSE_ARITY}-tuples (status, "
                    "payload, metrics_delta, flight_events)",
                ))
            elif leaf == "recv" and width != REQUEST_ARITY:
                proto.findings.append(ctx.finding(
                    "AM503", node,
                    f"request unpack binds {width} name(s): controller "
                    f"frames are {REQUEST_ARITY}-tuples (op, payload)",
                ))
        # --- response-field reads and writes -------------------------- #
        if isinstance(node, ast.Subscript) and isinstance(
            node.value, ast.Name
        ) and node.value.id == "resp":
            key = _str_const(node.slice)
            if key is not None:
                if isinstance(node.ctx, ast.Store):
                    proto.writes.add(key)
                    proto.write_sources += 1
                else:
                    proto.reads.append((ctx, node, key))
        if isinstance(node, ast.Call) and isinstance(
            node.func, ast.Attribute
        ) and node.func.attr == "get" and isinstance(
            node.func.value, ast.Name
        ) and node.func.value.id == "resp" and node.args:
            key = _str_const(node.args[0])
            if key is not None:
                proto.reads.append((ctx, node, key))
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                node.targets[0].id == "resp":
            if isinstance(node.value, ast.Dict):
                for key in node.value.keys:
                    k = _str_const(key)
                    if k is not None:
                        proto.writes.add(k)
                proto.write_sources += 1
            elif isinstance(node.value, ast.Call):
                proto.writes |= _producer_keys(
                    node.value, graph, ctx, _PRODUCER_DEPTH, proto
                )


def check(ctxs: list[FileContext], graph=None) -> list[Finding]:
    proto = _Protocol()
    scoped = [ctx for ctx in ctxs if _in_scope(ctx)]
    for ctx in scoped:
        _collect(ctx, graph, proto)

    # direction 1: every sent op has a handler (only checkable when the
    # handler side is in the scan)
    if proto.handled:
        for op, sites in sorted(proto.sent.items()):
            if op in proto.handled:
                continue
            for ctx, node in sites:
                proto.findings.append(ctx.finding(
                    "AM503", node,
                    f"controller sends frame type {op!r} but no worker "
                    "handler matches it (no `op == ...` arm in the "
                    "dispatch ladder): the worker will raise mid-delivery",
                ))
    # direction 2: every handler is reachable from a send site
    if proto.sent:
        for op, sites in sorted(proto.handled.items()):
            if op in proto.sent:
                continue
            for ctx, node in sites:
                proto.findings.append(ctx.finding(
                    "AM503", node,
                    f"worker handles frame type {op!r} but nothing sends "
                    "it: a dead handler is protocol drift — delete it or "
                    "wire up the sender",
                ))
    # direction 3: fields read by the receiver are fields written by the
    # sender — skipped when a producer call could not be resolved (a
    # partial scan must not guess at the write set)
    if proto.write_sources and not proto.unresolved_producer:
        for ctx, node, key in proto.reads:
            if key not in proto.writes:
                proto.findings.append(ctx.finding(
                    "AM503", node,
                    f"response field {key!r} is read but no worker-side "
                    "producer writes it (known fields: "
                    f"{sorted(proto.writes)}): this is a KeyError waiting "
                    "in the fan-in loop",
                ))
    return proto.findings
