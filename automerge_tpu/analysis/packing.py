"""AM1xx — packing-invariant rules.

The engine packs three fields into one int64 merge key::

    slot << _OP_BITS | counter << ACTOR_BITS | actor_intern_index

Every limit in the tpu layer derives from that layout: actor tables cap at
2^ACTOR_BITS, op counters at 2^(_OP_BITS - ACTOR_BITS), slot/element tables
at 2^(63 - _OP_BITS) (the sign bit must never flip under the sorted-table
invariant). These rules extract the constants from the analyzed files and
verify every definition, literal shift/mask, interner cap and diagnostic
message agrees with one canonical layout.
"""
from __future__ import annotations

import ast

from .core import (
    FileContext,
    Finding,
    dotted_name,
    module_constants,
    static_str_parts,
)

# Canonical constant-name groups. Different modules name the same logical
# quantity differently (engine._MKEY_OP_BITS vs rga._OP_BITS); AM101 treats
# each group as one constant and flags cross-file disagreement.
_GROUPS = {
    "ACTOR_BITS": {"ACTOR_BITS"},
    "ACTOR_MASK": {"ACTOR_MASK"},
    "OP_BITS": {"_MKEY_OP_BITS", "_OP_BITS", "OP_BITS"},
    "OP_MASK": {"_OP_MASK", "OP_MASK"},
    "MAX_COUNTER": {"MAX_COUNTER", "_MAX_COUNTER"},
    "MAX_SLOTS": {"_MAX_SLOTS", "MAX_SLOTS"},
    "MAX_ELEMS": {"MAX_ELEMS", "_MAX_ELEMS"},
}
_NAME_TO_GROUP = {n: g for g, names in _GROUPS.items() for n in names}

# The repo's canonical layout, used as the fallback when the analyzed file
# set does not itself define the widths (e.g. a lone file that imports
# ACTOR_BITS). AM101 verifies the real definitions against relations, not
# against these numbers, so the fallback cannot mask a layout change.
_DEFAULT_LAYOUT = {"ACTOR_BITS": 20, "OP_BITS": 44}

_MERGE_KEY_PHRASE = "merge-key packing range"
_RANK_KERNEL_PHRASE = "rank kernel"


def _file_groups(ctx: FileContext) -> dict[str, tuple[int, int]]:
    """{group: (value, lineno)} for the canonical constants this file
    defines at module level."""
    out: dict[str, tuple[int, int]] = {}
    for name, (value, line) in module_constants(ctx.tree).items():
        group = _NAME_TO_GROUP.get(name)
        if group is not None:
            out[group] = (value, line)
    return out


def _canonical_layout(per_file: dict[FileContext, dict]) -> dict[str, int]:
    layout = dict(_DEFAULT_LAYOUT)
    for groups in per_file.values():
        for group, (value, _line) in groups.items():
            layout.setdefault(group, value)
    return layout


def _imports_canonical_name(ctx: FileContext) -> bool:
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.ImportFrom):
            for alias in node.names:
                if alias.name in _NAME_TO_GROUP:
                    return True
    return False


def check(ctxs: list[FileContext], graph=None) -> list[Finding]:
    per_file = {ctx: _file_groups(ctx) for ctx in ctxs}
    layout = _canonical_layout(per_file)
    findings: list[Finding] = []
    findings += _check_layout_consistency(per_file, layout)
    for ctx in ctxs:
        in_scope = (
            "tpu" in ctx.path.parts
            or ctx.path.name == "columnar.py"
            or per_file[ctx]
            or _imports_canonical_name(ctx)
        )
        if in_scope:
            findings += _check_magic_literals(ctx, layout)
        findings += _check_interner_caps(ctx)
        findings += _check_diagnostics(ctx)
    return findings


# ---------------------------------------------------------------------- #
# AM101 — layout relations

def _check_layout_consistency(per_file, layout) -> list[Finding]:
    findings: list[Finding] = []

    # cross-file agreement within each group
    by_group: dict[str, dict[int, list[tuple[FileContext, int]]]] = {}
    for ctx, groups in per_file.items():
        for group, (value, line) in groups.items():
            by_group.setdefault(group, {}).setdefault(value, []).append((ctx, line))
    for group, values in by_group.items():
        if len(values) > 1:
            rendering = ", ".join(str(v) for v in sorted(values))
            for sites in values.values():
                for ctx, line in sites:
                    findings.append(ctx.finding(
                        "AM101",
                        _at(line),
                        f"canonical constant {group} disagrees across files "
                        f"(values: {rendering}); one layout must govern every "
                        "packing site",
                    ))

    actor_bits = layout.get("ACTOR_BITS")
    op_bits = layout.get("OP_BITS")

    def relation(ctx, line, msg):
        findings.append(ctx.finding("AM101", _at(line), msg))

    for ctx, groups in per_file.items():
        if "ACTOR_MASK" in groups and actor_bits is not None:
            value, line = groups["ACTOR_MASK"]
            if value != (1 << actor_bits) - 1:
                relation(ctx, line,
                         f"ACTOR_MASK = {value:#x} does not match "
                         f"(1 << ACTOR_BITS) - 1 for ACTOR_BITS={actor_bits}")
        if "OP_MASK" in groups and op_bits is not None:
            value, line = groups["OP_MASK"]
            if value != (1 << op_bits) - 1:
                relation(ctx, line,
                         f"op-id mask = {value:#x} does not match "
                         f"(1 << OP_BITS) - 1 for OP_BITS={op_bits}")
        if "MAX_COUNTER" in groups and actor_bits is not None and op_bits is not None:
            value, line = groups["MAX_COUNTER"]
            if value != 1 << (op_bits - actor_bits):
                relation(ctx, line,
                         f"MAX_COUNTER = {value} does not equal "
                         f"1 << (OP_BITS - ACTOR_BITS) = "
                         f"{1 << (op_bits - actor_bits)}: counters would "
                         "overflow into the slot field of the merge key")
        for cap_group in ("MAX_SLOTS", "MAX_ELEMS"):
            if cap_group in groups and op_bits is not None:
                value, line = groups[cap_group]
                if value > 1 << (63 - op_bits):
                    relation(ctx, line,
                             f"{cap_group} = {value} exceeds 1 << (63 - "
                             f"OP_BITS) = {1 << (63 - op_bits)}: the packed "
                             "int64 sort key would overflow the sign bit")
        if op_bits is not None and op_bits > 63 and "OP_BITS" in groups:
            value, line = groups["OP_BITS"]
            relation(ctx, line, f"OP_BITS = {value} exceeds the 63 value bits "
                                "of an int64 sort key")
        if (
            actor_bits is not None and op_bits is not None
            and actor_bits >= op_bits and ("ACTOR_BITS" in groups or "OP_BITS" in groups)
        ):
            _, line = groups.get("ACTOR_BITS", groups.get("OP_BITS"))
            relation(ctx, line,
                     f"ACTOR_BITS={actor_bits} leaves no counter bits below "
                     f"OP_BITS={op_bits}")
    return findings


class _at:
    """Minimal location shim so FileContext.finding works from a lineno."""

    def __init__(self, lineno: int, col_offset: int = 0):
        self.lineno = lineno
        self.col_offset = col_offset


# ---------------------------------------------------------------------- #
# AM102 — magic shift/mask literals

def _check_magic_literals(ctx: FileContext, layout) -> list[Finding]:
    widths = {}
    for group in ("ACTOR_BITS", "OP_BITS"):
        if group in layout:
            widths[layout[group]] = group
    masks = {(1 << w) - 1: g for w, g in widths.items()}
    findings: list[Finding] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.BinOp):
            continue
        if isinstance(node.op, (ast.LShift, ast.RShift)):
            rhs = node.right
            # `1 << 20`-style cap definitions are constants, not packing
            # operations on a value; only flag shifts of a computed operand
            if (
                isinstance(rhs, ast.Constant)
                and isinstance(rhs.value, int)
                and rhs.value in widths
                and not isinstance(node.left, ast.Constant)
            ):
                findings.append(ctx.finding(
                    "AM102", rhs,
                    f"literal shift by {rhs.value} duplicates the canonical "
                    f"{widths[rhs.value]} constant; use the named constant so "
                    "the layout has a single source of truth",
                ))
        elif isinstance(node.op, ast.BitAnd):
            for side in (node.left, node.right):
                if (
                    isinstance(side, ast.Constant)
                    and isinstance(side.value, int)
                    and side.value in masks
                ):
                    group = masks[side.value]
                    findings.append(ctx.finding(
                        "AM102", side,
                        f"literal mask {side.value:#x} duplicates "
                        f"(1 << {group}) - 1; use the named mask constant",
                    ))
    return findings


# ---------------------------------------------------------------------- #
# AM103 — interner caps

def _check_interner_caps(ctx: FileContext) -> list[Finding]:
    findings: list[Finding] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        name = dotted_name(node.func)
        if name is None or not name.split(".")[-1].endswith("Interner"):
            continue
        has_cap = any(
            kw.arg == "max_size" and not (
                isinstance(kw.value, ast.Constant) and kw.value.value is None
            )
            for kw in node.keywords
        ) or len(node.args) >= 1  # first positional arg is max_size
        if not has_cap:
            findings.append(ctx.finding(
                "AM103", node,
                "interner constructed without max_size: an overflowing table "
                "silently corrupts the merge-key packing (slot/actor indexes "
                "ride fixed-width bit fields); pass max_size= or suppress "
                "with a justification if the table is never packed",
            ))
    return findings


# ---------------------------------------------------------------------- #
# AM104 — diagnostic/range message consistency

def _enclosing_test(node: ast.AST):
    """The test expression of the nearest enclosing if/while, stopping at a
    function boundary."""
    cur = getattr(node, "_amlint_parent", None)
    while cur is not None:
        if isinstance(cur, (ast.If, ast.While)):
            return cur.test
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Module)):
            return None
        cur = getattr(cur, "_amlint_parent", None)
    return None


def _names_in(expr: ast.AST) -> set[str]:
    out = set()
    for sub in ast.walk(expr):
        if isinstance(sub, ast.Name):
            out.add(sub.id)
        elif isinstance(sub, ast.Attribute):
            out.add(sub.attr)
    return out


def _check_diagnostics(ctx: FileContext) -> list[Finding]:
    findings: list[Finding] = []
    for node in ast.walk(ctx.tree):
        if not (isinstance(node, ast.Raise) and isinstance(node.exc, ast.Call)):
            continue
        test = _enclosing_test(node)
        if test is None:
            continue
        guard_names = _names_in(test)
        message = static_str_parts(node.exc)
        if guard_names & _GROUPS["MAX_COUNTER"]:
            if _MERGE_KEY_PHRASE not in message:
                findings.append(ctx.finding(
                    "AM104", node,
                    "diagnostic for a MAX_COUNTER guard must say "
                    f"'{_MERGE_KEY_PHRASE}': the counter cap protects the "
                    "merge-key packing for ALL ops, not a specific kernel",
                ))
        elif guard_names & _GROUPS["MAX_ELEMS"]:
            if _RANK_KERNEL_PHRASE not in message:
                findings.append(ctx.finding(
                    "AM104", node,
                    "diagnostic for a MAX_ELEMS guard must name the "
                    f"'{_RANK_KERNEL_PHRASE}': the element cap protects the "
                    "RGA sibling-sort key packing",
                ))
        elif guard_names & _GROUPS["MAX_SLOTS"]:
            if "slot" not in message.lower():
                findings.append(ctx.finding(
                    "AM104", node,
                    "diagnostic for a MAX_SLOTS guard must mention the slot "
                    "table so debuggers land on the interner, not a kernel",
                ))
    return findings
