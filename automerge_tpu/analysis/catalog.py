"""AM304 — observability catalog consistency: code and README agree.

The README's "Metric catalog" / "Flight-recorder event catalog" tables are
the operator contract: dashboards, alerts and the `--watch` CLI are built
against those names. The contract rots in both directions — a new
instrument lands in code without a catalog row (invisible to operators),
or a catalog row survives the removal of its instrument (alerting on a
metric that can never move). AM304 closes the loop:

- **forward**: every metric registered with a literal dotted name
  (``.counter("x.y")`` / ``.gauge`` / ``.histogram``) and every flight
  event recorded with a literal kind (``.record("x.y", ...)``) must
  appear in the README catalog. Dynamic names (f-strings like
  ``f"farm.quarantine.causes.{kind}"``) are exempt from the forward
  check; their static fragments participate in the reverse match.
- **reverse**: when the scan covers the whole package (detected by
  ``obs/metrics.py`` being among the scanned files), every catalog row
  must name something the code records — exactly (literal names) or by
  fragment (a ``<placeholder>`` row matches an f-string prefix, a
  ``{name}.hits``-style dynamic registration matches by suffix). Reverse
  findings anchor on the README row's line.

Scope: files under the ``automerge_tpu`` package, plus any file carrying
the ``# amlint: metric-catalog`` marker (how the fixture triple opts in
— fixtures for other rules register toy metric names that must not
fire). The README is found by walking up from the scanned file; no
README within the tree means no findings (the rule degrades to a no-op
on extracted single files).
"""
from __future__ import annotations

import ast
import re
from pathlib import Path

from .core import FileContext, Finding, static_str_parts

_REGISTER_ATTRS = {"counter", "gauge", "histogram"}
#: a catalog-relevant name: lowercase dotted, optional <placeholder> parts
_NAME_RE = re.compile(r"^[a-z][a-z0-9_]*(\.[a-z0-9_<>]+)+$")
_TOKEN_RE = re.compile(r"`([^`]+)`")
_MARKER_RE = re.compile(r"#\s*amlint:\s*metric-catalog")
#: README section headings whose tables form the catalog
_CATALOG_HEADINGS = ("metric catalog", "event catalog")


# ---------------------------------------------------------------------- #
# README side

def find_readme(path: Path) -> Path | None:
    """Nearest README.md walking up from `path` (the repo-root README for
    package files and for the fixture tree)."""
    for parent in path.resolve().parents:
        candidate = parent / "README.md"
        if candidate.is_file():
            return candidate
    return None


def catalog_names(text: str) -> dict[str, int]:
    """{name: line} for every backticked metric/event name in the README's
    catalog tables. Rows use the ``\\`full.name\\` / \\`.suffix\\```
    shorthand — a leading-dot token replaces the previous full name's last
    component."""
    out: dict[str, int] = {}
    in_catalog = False
    last_full: str | None = None
    for lineno, line in enumerate(text.splitlines(), 1):
        stripped = line.strip()
        if stripped.startswith("#"):
            heading = stripped.lstrip("#").strip().lower()
            in_catalog = any(h in heading for h in _CATALOG_HEADINGS)
            last_full = None
            continue
        if not in_catalog or not stripped.startswith("|"):
            continue
        for token in _TOKEN_RE.findall(stripped):
            if "/" in token or " " in token or token.endswith(".py"):
                continue
            if token.startswith(".") and last_full is not None:
                name = last_full.rsplit(".", 1)[0] + token
            else:
                name = token
            if _NAME_RE.match(name):
                out.setdefault(name, lineno)
                last_full = name
    return out


def _matches(readme_name: str, literals: set[str],
             fragments: set[str]) -> bool:
    if readme_name in literals:
        return True
    # `<placeholder>` rows match up to the placeholder
    prefix = readme_name.split("<", 1)[0]
    if prefix != readme_name:
        return any(
            lit.startswith(prefix) for lit in literals
        ) or any(
            frag.startswith(prefix) or prefix.startswith(frag)
            for frag in fragments
        )
    # dynamic registrations (f-strings) match by their static fragments:
    # a prefix fragment ("farm.quarantine.causes.") or a suffix fragment
    # (".hits" from f"{name}.hits")
    return any(
        (readme_name.startswith(frag) or readme_name.endswith(frag))
        for frag in fragments
    )


# ---------------------------------------------------------------------- #
# code side

def _in_scope(ctx: FileContext) -> bool:
    if _MARKER_RE.search(ctx.source):
        return True
    return "automerge_tpu" in ctx.path.parts


def _collect(ctx: FileContext) -> tuple[list[tuple[str, ast.AST]], set[str]]:
    """(literal (name, node) registrations, dynamic-name static fragments)
    for one file: ``.counter/.gauge/.histogram("a.b", ...)`` metric
    registrations and ``.record("a.b", ...)`` flight events."""
    literals: list[tuple[str, ast.AST]] = []
    fragments: set[str] = set()
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call) or not node.args:
            continue
        if not isinstance(node.func, ast.Attribute):
            continue
        if node.func.attr not in _REGISTER_ATTRS and node.func.attr != "record":
            continue
        first = node.args[0]
        if isinstance(first, ast.Constant) and isinstance(first.value, str):
            if _NAME_RE.match(first.value):
                literals.append((first.value, node))
        elif isinstance(first, ast.JoinedStr):
            frag = static_str_parts(first)
            if len(frag) >= 3:
                fragments.add(frag)
    return literals, fragments


# ---------------------------------------------------------------------- #

def check(ctxs: list[FileContext], graph=None) -> list[Finding]:
    findings: list[Finding] = []
    all_literals: set[str] = set()
    all_fragments: set[str] = set()
    readme: Path | None = None
    full_package_scan = False

    for ctx in ctxs:
        if ctx.path.name == "metrics.py" and ctx.path.parent.name == "obs":
            full_package_scan = True
        if not _in_scope(ctx):
            continue
        literals, fragments = _collect(ctx)
        all_fragments |= fragments
        if not literals:
            continue
        ctx_readme = find_readme(ctx.path)
        if ctx_readme is None:
            continue
        readme = readme or ctx_readme
        catalog = catalog_names(ctx_readme.read_text(encoding="utf-8"))
        for name, node in literals:
            all_literals.add(name)
            if name not in catalog:
                findings.append(ctx.finding(
                    "AM304", node,
                    f"metric/event name `{name}` is recorded here but "
                    "missing from the README catalog — add a catalog row "
                    "(or rename to a cataloged name)",
                ))

    if full_package_scan and readme is not None:
        text = readme.read_text(encoding="utf-8")
        for name, lineno in sorted(catalog_names(text).items()):
            if not _matches(name, all_literals, all_fragments):
                findings.append(Finding(
                    "AM304", str(readme), lineno, 0,
                    f"catalog row `{name}` names no metric/event recorded "
                    "anywhere in the package — remove the stale row (or "
                    "restore the instrument)",
                ))
    return findings
