"""AM306 — compiled programs register through the amprof observatory.

The observatory (obs/prof.py) can only attribute recompiles, dispatch
latencies and shape buckets to a program if the program was jitted
through ``tpu/jitprof.profiled_jit``. A bare ``jax.jit`` reference —
``@jax.jit``, ``@partial(jax.jit, ...)`` or a direct ``jax.jit(fn)``
call — creates an anonymous compiled program the profiling plane cannot
see, and its recompiles surface as unattributed ``engine.jit.recompiles``
with no flight identity.

Exempt references:

- a ``jax.jit`` call fed directly to an ``Observatory.register(...)``
  call, or any reference inside a function named ``profiled_jit`` — that
  IS the blessed registration site (tpu/jitprof.py);
- lines carrying a justified ``# amlint: unprofiled-jit`` marker (core.py
  treats the marker as a line suppression for this rule, same
  trailing/standalone placement as ``disable=``).
"""
from __future__ import annotations

import ast

from .core import FileContext, Finding, dotted_name

#: leaf names of calls whose arguments are registration-bound jits
_REGISTER_LEAVES = frozenset({"register"})

#: enclosing function names that ARE the blessed jit wrapper
_WRAPPER_FUNCS = frozenset({"profiled_jit"})


def _exempt(node: ast.AST) -> bool:
    cur = getattr(node, "_amlint_parent", None)
    while cur is not None:
        if isinstance(cur, ast.Call):
            name = dotted_name(cur.func)
            if name is not None and name.split(".")[-1] in _REGISTER_LEAVES:
                return True
        if (isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef))
                and cur.name in _WRAPPER_FUNCS):
            return True
        cur = getattr(cur, "_amlint_parent", None)
    return False


def check(ctxs: list[FileContext], graph=None) -> list[Finding]:
    findings: list[Finding] = []
    for ctx in ctxs:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Attribute):
                continue
            if dotted_name(node) != "jax.jit":
                continue
            if _exempt(node):
                continue
            findings.append(ctx.finding(
                "AM306", node,
                "bare jax.jit reference bypasses the amprof observatory — "
                "register the program with tpu/jitprof.profiled_jit "
                "(or justify with `# amlint: unprofiled-jit`)",
            ))
    return findings
