"""Dynamic-length dataflow for the AM70x shape-stability family.

The runtime observatory (obs/prof.py) can only report a recompile storm
*after* the compiler has already burned the time: ``prof.recompile.storm``
fires when one program sees 4 compiles inside 10 seconds. The static twin
asks the question before any dispatch happens: does an array argument's
shape derive from an **unbucketed dynamic length**?

The engine below runs per function and tracks, statement-ordered with a
two-pass fixpoint (so loop-carried flows converge), which local names are
*length-tainted*:

- **sources**: ``len(x)``, ``.shape`` / ``.shape[i]`` reads — the host
  integers that vary call-to-call;
- **propagation**: arithmetic, ``max``/``min``, tuple/list packing,
  subscripts of tainted containers; slicing with a tainted bound produces
  a tainted *array* (its leading dimension now varies), and array
  constructors (``zeros``/``ones``/``empty``/``full``/``arange``/
  ``concatenate``/``pad``...) called with a tainted shape argument produce
  tainted arrays;
- **sanitizers**: any call whose leaf name mentions ``pow2`` or ``bucket``
  (the in-tree helpers are ``_pow2``/``_next_pow2``/``bucket_index``)
  returns a *clean* value whatever its arguments — rounding a length to a
  power-of-two bucket is exactly the discipline that caps the compile
  count at log2(maxlen) per program;
- **sinks**: calls to known jit dispatch callables (discovered by
  shaperules.py: ``@profiled_jit``/``@jax.jit``-decorated defs, ``x =
  jax.jit(f)`` bindings, and from-imports the call graph resolves to
  either). A tainted argument at a sink is the finding.

Taint values carry a provenance chain (``len(rows) @ line 12 -> cols @
line 14``) so the diagnostic shows the actual dataflow path, mirroring the
``[reachable via ...]`` chains the call-graph rules print.
"""
from __future__ import annotations

import ast

from .core import dotted_name

#: array constructors whose result's shape is its (possibly tainted)
#: arguments — the hop from a dynamic *integer* to a dynamic *array shape*
_ARRAY_CTORS = frozenset({
    "zeros", "ones", "empty", "full", "array", "arange", "linspace",
    "concatenate", "stack", "pad", "tile", "repeat", "broadcast_to",
    "reshape", "resize",
})

#: provenance chains are capped: past this depth the path is noise
_MAX_CHAIN = 6


def is_sanitizer(name: str | None) -> bool:
    """A call that rounds a dynamic length onto a static bucket grid."""
    if not name:
        return False
    leaf = name.rsplit(".", 1)[-1].lower()
    return "pow2" in leaf or "bucket" in leaf


class ShapeFlow:
    """Length-taint walk over one function body.

    ``dispatch`` maps a *call* AST node predicate onto a program label:
    ``dispatch(call_node) -> str | None`` (None = not a jit dispatch).
    ``report(call_node, program, chain)`` receives each sink hit; it is
    only invoked on the second (reporting) pass.
    """

    def __init__(self, fn: ast.AST, dispatch, report):
        self.fn = fn
        self.dispatch = dispatch
        self.report = report
        self.env: dict[str, tuple[str, ...]] = {}
        self.reporting = False

    def run(self) -> None:
        body = getattr(self.fn, "body", None) or []
        self.reporting = False
        for stmt in body:
            self._stmt(stmt)
        self.reporting = True
        for stmt in body:
            self._stmt(stmt)

    # ------------------------------ statements ------------------------ #

    def _stmt(self, stmt: ast.AST) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return  # nested defs get their own ShapeFlow
        if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            value = getattr(stmt, "value", None)
            chain = self._expr(value) if value is not None else None
            targets = (stmt.targets if isinstance(stmt, ast.Assign)
                       else [stmt.target])
            for target in targets:
                if isinstance(stmt, ast.AugAssign) and chain is None:
                    chain = self._expr(target)
                self._bind(target, chain, stmt.lineno)
            return
        if isinstance(stmt, (ast.If, ast.While)):
            self._expr(stmt.test)
            for s in stmt.body + stmt.orelse:
                self._stmt(s)
            return
        if isinstance(stmt, ast.For):
            # iterating a container does not make the element a length
            self._expr(stmt.iter)
            self._bind(stmt.target, None, stmt.lineno)
            for s in stmt.body + stmt.orelse:
                self._stmt(s)
            return
        if isinstance(stmt, ast.With):
            for item in stmt.items:
                self._expr(item.context_expr)
                if item.optional_vars is not None:
                    self._bind(item.optional_vars, None, stmt.lineno)
            for s in stmt.body:
                self._stmt(s)
            return
        if isinstance(stmt, ast.Try):
            for s in stmt.body + stmt.orelse + stmt.finalbody:
                self._stmt(s)
            for handler in stmt.handlers:
                for s in handler.body:
                    self._stmt(s)
            return
        if isinstance(stmt, (ast.Return, ast.Expr)):
            if getattr(stmt, "value", None) is not None:
                self._expr(stmt.value)
            return
        if isinstance(stmt, ast.Raise):
            if stmt.exc is not None:
                self._expr(stmt.exc)
            return
        # Import/Pass/Break/Continue/Delete/Global: nothing flows

    def _bind(self, target: ast.AST, chain, lineno: int) -> None:
        if isinstance(target, ast.Name):
            if chain is not None:
                step = f"{target.id} @ line {lineno}"
                self.env[target.id] = self._extend(chain, step)
            else:
                self.env.pop(target.id, None)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._bind(elt, chain, lineno)
        elif isinstance(target, ast.Starred):
            self._bind(target.value, chain, lineno)
        # attribute/subscript stores: out of scope for a per-function walk

    @staticmethod
    def _extend(chain: tuple[str, ...], step: str) -> tuple[str, ...]:
        if chain and chain[-1] == step:
            return chain
        if len(chain) >= _MAX_CHAIN:
            return chain
        return chain + (step,)

    # ------------------------------ expressions ------------------------ #

    def _expr(self, node: ast.AST | None) -> tuple[str, ...] | None:
        """Returns the provenance chain if this expression is
        length-tainted, else None. Walks every subexpression so sinks
        nested anywhere (``outs.append(prog(x))``) are still seen."""
        if node is None or isinstance(node, ast.Constant):
            return None
        if isinstance(node, ast.Name):
            return self.env.get(node.id)
        if isinstance(node, ast.Attribute):
            base = self._expr(node.value)
            if node.attr == "shape":
                src = ast.unparse(node) if hasattr(ast, "unparse") else ".shape"
                return (f"{src} @ line {node.lineno}",)
            return base
        if isinstance(node, ast.Subscript):
            base = self._expr(node.value)
            idx = self._expr(node.slice)
            if isinstance(node.slice, ast.Slice):
                bounds = [b for b in (node.slice.lower, node.slice.upper,
                                      node.slice.step) if b is not None]
                for b in bounds:
                    t = self._expr(b)
                    if t is not None:
                        # a slice bounded by a dynamic length yields an
                        # array whose leading dim varies per call
                        return self._extend(
                            t, f"slice @ line {node.lineno}"
                        )
                return base
            return base or idx
        if isinstance(node, ast.Call):
            return self._call(node)
        if isinstance(node, ast.BinOp):
            return self._expr(node.left) or self._expr(node.right)
        if isinstance(node, ast.UnaryOp):
            return self._expr(node.operand)
        if isinstance(node, ast.BoolOp):
            out = None
            for v in node.values:
                out = out or self._expr(v)
            return out
        if isinstance(node, ast.Compare):
            self._expr(node.left)
            for comp in node.comparators:
                self._expr(comp)
            return None  # a comparison result is a bool, not a length
        if isinstance(node, ast.IfExp):
            self._expr(node.test)
            return self._expr(node.body) or self._expr(node.orelse)
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            out = None
            for elt in node.elts:
                out = out or self._expr(elt)
            return out
        if isinstance(node, ast.Dict):
            out = None
            for x in node.keys + node.values:
                if x is not None:
                    out = out or self._expr(x)
            return out
        if isinstance(node, ast.Starred):
            return self._expr(node.value)
        if isinstance(node, (ast.JoinedStr, ast.FormattedValue)):
            for sub in ast.iter_child_nodes(node):
                self._expr(sub)
            return None
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp,
                             ast.DictComp)):
            for gen in node.generators:
                self._expr(gen.iter)
                for cond in gen.ifs:
                    self._expr(cond)
            if isinstance(node, ast.DictComp):
                self._expr(node.key)
                self._expr(node.value)
            else:
                self._expr(node.elt)
            return None
        if isinstance(node, ast.Slice):
            for x in (node.lower, node.upper, node.step):
                if x is not None:
                    self._expr(x)
            return None
        if isinstance(node, (ast.Await, ast.Yield, ast.YieldFrom)):
            value = getattr(node, "value", None)
            return self._expr(value) if value is not None else None
        return None

    def _call(self, node: ast.Call) -> tuple[str, ...] | None:
        name = dotted_name(node.func)
        leaf = name.rsplit(".", 1)[-1] if name else None

        arg_chains = [self._expr(a) for a in node.args]
        kw_chains = [self._expr(kw.value) for kw in node.keywords]
        tainted = next(
            (c for c in arg_chains + kw_chains if c is not None), None
        )

        # sink: a jit dispatch fed a length-tainted argument
        program = self.dispatch(node)
        if program is not None:
            if tainted is not None and self.reporting:
                self.report(node, program, tainted)
            return None

        # sanitizer: bucketing helpers return statically stable lengths
        if is_sanitizer(name):
            return None

        # source: len() of anything is a per-call dynamic length
        if leaf == "len" and name == "len":
            src = ast.unparse(node) if hasattr(ast, "unparse") else "len(...)"
            return (f"{src} @ line {node.lineno}",)

        # array constructors: dynamic length becomes dynamic shape
        if leaf in _ARRAY_CTORS and tainted is not None:
            return self._extend(
                tainted, f"{name}(...) @ line {node.lineno}"
            )

        # max/min/abs/sum and plain arithmetic helpers propagate
        if leaf in ("max", "min", "abs", "sum", "int") and tainted is not None:
            return tainted

        # method on a tainted receiver stays tainted (n.bit_length(), ...)
        if isinstance(node.func, ast.Attribute):
            recv = self._expr(node.func.value)
            if recv is not None:
                return recv
        return None
