"""AM504 — shm data-plane modules keep bulk payloads out of pickle.

The zero-copy mesh transport (parallel/shm.py) exists because the
per-delivery column batches are flat bytes on both ends: the send ring
carries them as ``struct``-framed counts + lengths + raw concatenation,
the result ring carries struct-framed outcome tuples next to the patch
blob, and the pipe is left with control frames only. That win is easy to
quietly lose: one convenient ``pickle.dumps(batch)`` on a send path and
the transport is back to paying the serialization tax it was built to
remove — while every dashboard still says "shm".

So in shm-transport scope a ``pickle.dumps``/``pickle.dump`` call is a
finding: bulk column payloads (numpy arrays, column-batch dicts, patch
columns) go through the shm codecs (``encode_columns``/``encode_result``)
or stay out of the data plane entirely. The ONE blessed exception is the
pickle-ORACLE path — ``mesh_transport="pickle"`` keeps the whole batch
in the pipe frame as the byte-for-byte parity baseline and the fallback
for hosts without POSIX shared memory — and that site carries a
justified ``# amlint: disable=AM504`` suppression, exactly like the
durability plane's blessed raw handle (AM601).

``pickle.loads`` is deliberately NOT flagged: the patch blob inside a
result frame is opaque pickled bytes by design (produced by
``tpu.farm.result_to_wire`` outside this scope, materialized lazily by
the controller straight from the mapped segment), so receive-side
unpickling is the contract, not a leak. The rule guards the SEND paths,
where a pickle call means payload bytes are being re-serialized.

Scope: modules whose filename stem is in ``SHM_DATA_PLANE_STEMS``, plus
any file carrying an ``# amlint: mesh-data-plane`` marker (how
workers.py/meshfarm.py opt in, and the fixture hook).
"""
from __future__ import annotations

import ast
import re
from pathlib import Path

from .core import FileContext, Finding, dotted_name

_MARKER_RE = re.compile(r"#\s*amlint:\s*mesh-data-plane\b")

#: module stems always in scope (the shm transport itself)
SHM_DATA_PLANE_STEMS = frozenset({"shm"})

#: the serializers that re-grow the pickle tax on a send path
_PICKLE_SENDERS = frozenset({"pickle.dumps", "pickle.dump"})


def _in_scope(ctx: FileContext) -> bool:
    return (
        Path(ctx.path).stem in SHM_DATA_PLANE_STEMS
        or _MARKER_RE.search(ctx.source) is not None
    )


def _pickle_aliases(tree: ast.AST) -> frozenset:
    """Names that resolve to pickle's send-side serializers in this file:
    the dotted forms plus anything bound by ``from pickle import dumps``
    (aliased or not)."""
    names = set(_PICKLE_SENDERS)
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module == "pickle":
            for alias in node.names:
                if alias.name in ("dumps", "dump"):
                    names.add(alias.asname or alias.name)
        elif isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name == "pickle" and alias.asname:
                    names.add(f"{alias.asname}.dumps")
                    names.add(f"{alias.asname}.dump")
    return frozenset(names)


def check(ctxs: list[FileContext], graph=None) -> list[Finding]:
    findings: list[Finding] = []
    for ctx in ctxs:
        if not _in_scope(ctx):
            continue
        senders = _pickle_aliases(ctx.tree)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func)
            if name in senders:
                findings.append(ctx.finding(
                    "AM504", node,
                    f"{name}() in an shm data-plane module: bulk column "
                    f"payloads ride the shared-memory rings struct-framed "
                    f"(shm.encode_columns/encode_result), never pickle — "
                    f"one re-serialized send path silently refunds the "
                    f"zero-copy win; if this IS the pickle parity-oracle "
                    f"transport, justify it with a suppression",
                ))
    return findings
