"""amlint core: file model, suppression parsing, findings, const evaluation.

The analyzer is repo-native: it knows this codebase's invariants (the
merge-key bit layout, the jit purity rules, the host/device module split)
and enforces them over the AST. Everything here is stdlib-only — importing
the analysis package must never pull in jax, so the lint gate runs in any
environment (CI, pre-commit, a bare host) without device initialisation.

Suppression syntax (checked by tests/test_static_analysis.py):

    x = (ctr << 20) | actor  # amlint: disable=AM102
    # amlint: disable=AM103 — value payloads are never packed into keys
    self.values = _Interner()
    # amlint: disable-file=AM203

A trailing comment suppresses its own line; a standalone comment suppresses
the next code line; ``disable-file`` suppresses a rule for the whole file.
``# amlint: host-only`` marks a module as host-only for AM301.
"""
from __future__ import annotations

import ast
import dataclasses
import io
import re
import tokenize
from pathlib import Path

#: rule id -> (family, one-line summary). The single catalog the CLI,
#: README and tests key off.
RULES: dict[str, tuple[str, str]] = {
    "AM000": ("core", "file could not be parsed (syntax/tokenize error)"),
    "AM101": ("packing", "bit-layout constants are inconsistent with the "
                         "canonical merge-key layout (slot<<44 | ctr<<20 | actor)"),
    "AM102": ("packing", "magic shift/mask literal duplicates a canonical "
                         "bit-layout constant (use ACTOR_BITS/_OP_BITS/...)"),
    "AM103": ("packing", "_Interner constructed without a max_size packing cap"),
    "AM104": ("packing", "packing-limit diagnostic names the wrong range "
                         "(merge-key vs rank-kernel)"),
    "AM105": ("hotpath", "per-row Python in a profiled hot phase: "
                         "sort(key=lambda ...) or int()/bool() coercion "
                         "over range-indexed rows (use column ops and a "
                         "precomputed sort-key column)"),
    "AM106": ("hotpath", "per-byte Python decode loop in a decode hot-path "
                         "module (vectorize: continuation-bit mask + "
                         "prefix scan, record-level run expansion)"),
    "AM107": ("hotpath", "per-change/per-op Python loop in a gate/transcode "
                         "hot path (compute gate verdicts and op columns "
                         "with batched column programs; scalar-oracle "
                         "loops carry justified suppressions)"),
    "AM201": ("tracer", "Python-level control flow on a traced value inside "
                        "jit/pallas-traced code"),
    "AM202": ("tracer", "host-side call (np.*, int()/float(), .item()) on a "
                        "traced value inside jit/pallas-traced code"),
    "AM203": ("tracer", "dtype-less np/jnp array construction in a "
                        "device-adjacent module"),
    "AM204": ("tracer", "mutation of captured host state inside jit/pallas-"
                        "traced code"),
    "AM301": ("boundary", "host-only module imports the device layer "
                          "(automerge_tpu.tpu or jax)"),
    "AM302": ("boundary", "hidden host synchronisation inside a device "
                          "PhaseProfile phase"),
    "AM303": ("boundary", "metric/span recording call inside jit/vmap/"
                          "Pallas-reachable code (record on the host "
                          "around the dispatch)"),
    "AM304": ("boundary", "metric/event name recorded in code is missing "
                          "from the README catalog, or a catalog row names "
                          "nothing the code records (the observability "
                          "contract must stay exact in both directions)"),
    "AM305": ("boundary", "worker-executed module reaches the telemetry "
                          "exposition/fan-in layer (get_flight, obs.export: "
                          "render_exposition/serve_exposition/"
                          "snapshot_record/SnapshotWriter) — worker "
                          "telemetry leaves the process only through the "
                          "shipping buffer: pipe deltas, shipped flight "
                          "tails and the black-box file"),
    "AM306": ("boundary", "bare jax.jit call site (compiled programs must "
                          "register through the amprof observatory via "
                          "tpu/jitprof.profiled_jit so recompiles carry "
                          "program identity; justify exceptions with "
                          "`# amlint: unprofiled-jit`)"),
    "AM401": ("taxonomy","bare ValueError/TypeError raised in a data-plane "
                          "module (raise a classifiable taxonomy error from "
                          "automerge_tpu.errors)"),
    "AM402": ("taxonomy", "direct wall-clock/sleep/global-RNG call "
                          "(time.time/time.sleep/random.*) in a sync "
                          "data-plane module (inject a clock/RNG instead)"),
    "AM403": ("serve", "blocking call (time.sleep, bare socket, synchronous "
                       "jax.device_get/block_until_ready) in serve/ "
                       "event-loop code (the loop must stay non-blocking; "
                       "justify dispatch-point suppressions)"),
    "AM404": ("taxonomy", "non-taxonomy exception class raised in a sync v2 "
                          "wire-codec module (sync_v2/tpu.fingerprint or "
                          "`# amlint: v2-wire-codec`) — the session layer's "
                          "negotiated fallback catches exactly the "
                          "automerge_tpu.errors taxonomy, so any other class "
                          "kills the channel instead of downgrading it to v1"),
    "AM501": ("mesh", "dense per-doc `for ... in range(...)` statement loop "
                      "in a mesh routing/merge-result path (build sparse "
                      "active lists with comprehensions or vectorize with "
                      "numpy)"),
    "AM502": ("mesh", "worker-executed module imports the mesh controller "
                      "layer (meshfarm/serve) or touches a process-global "
                      "registry accessor (get_metrics/get_flight/...) — "
                      "workers speak the pipe protocol and record into "
                      "explicitly shipped sinks"),
    "AM503": ("protocol", "controller/worker pipe frames drift: an op is "
                          "sent without a worker handler (or handled but "
                          "never sent), a response/request tuple is built "
                          "or unpacked at the wrong arity (responses are "
                          "(status, payload, metrics_delta, flight_events) "
                          "4-tuples, requests (op, payload) 2-tuples), or "
                          "a response field is read that no worker-side "
                          "producer writes"),
    "AM504": ("protocol", "pickle.dumps/pickle.dump in an shm data-plane "
                          "module (parallel/shm.py or `# amlint: "
                          "mesh-data-plane`) — bulk column payloads ride "
                          "the shared-memory rings struct-framed, never "
                          "pickle; the pickle parity-oracle transport is "
                          "the one justified suppression"),
    "AM601": ("store", "bare write-mode open()/os.write in a durability-"
                       "plane module (store/ or `# amlint: durability-"
                       "plane`) — durable bytes go through "
                       "store.atomic.atomic_write or the WAL's checksummed "
                       "appender so recovery can prove the commit point; "
                       "justify raw handles with a suppression"),
    "AM701": ("shape", "jit dispatch whose array-shape argument derives "
                       "from an unbucketed dynamic length (len()/.shape/"
                       "dynamic slice with no pow2/bucket helper on the "
                       "dataflow path) — the static twin of amprof's "
                       "prof.recompile.storm: every new length costs a "
                       "fresh XLA compile"),
}

_SUPPRESS_RE = re.compile(
    r"#\s*amlint:\s*(disable|disable-file)\s*=\s*([A-Z0-9,\s]+)"
)


class UsageError(Exception):
    """Operator error (unknown rule id, unreadable path): the CLI prints
    one line and exits 2 — never a traceback, never conflated with the
    exit-1 'findings exist' outcome."""
_HOST_ONLY_RE = re.compile(r"#\s*amlint:\s*host-only")
_HOT_PATH_RE = re.compile(r"#\s*amlint:\s*hot-path")
#: justified observatory bypass: suppresses AM306 on its line (trailing)
#: or the next code line (standalone), like a disable=AM306
_UNPROFILED_JIT_RE = re.compile(r"#\s*amlint:\s*unprofiled-jit\b")


@dataclasses.dataclass
class Finding:
    """One rule violation at a source location."""

    rule_id: str
    path: str
    line: int
    col: int
    message: str
    suppressed: bool = False

    def format(self) -> str:
        tag = " (suppressed)" if self.suppressed else ""
        return f"{self.path}:{self.line}:{self.col}: {self.rule_id} {self.message}{tag}"


class FileContext:
    """One parsed source file plus its amlint comment directives."""

    def __init__(self, path: Path, display: str):
        self.path = path
        self.display = display
        self.source = path.read_text(encoding="utf-8")
        self.tree = ast.parse(self.source, filename=str(path))
        # parent links for rules that need enclosing-statement context
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                child._amlint_parent = node  # type: ignore[attr-defined]
        self.line_suppress: dict[int, set[str]] = {}
        self.file_suppress: set[str] = set()
        self.host_only_marker = False
        self.hot_path_marker = False
        #: (line, id) pairs for disable directives naming ids not in RULES
        #: — a typo'd suppression silently un-suppresses, so the CLI treats
        #: these as usage errors (exit 2)
        self.unknown_suppressions: list[tuple[int, str]] = []
        self._parse_comments()

    # ------------------------------------------------------------------ #

    def _parse_comments(self) -> None:
        code_lines: set[int] = set()
        comments: list[tuple[int, bool, str]] = []  # (line, standalone, text)
        line_has_code: dict[int, bool] = {}
        reader = io.StringIO(self.source).readline
        for tok in tokenize.generate_tokens(reader):
            if tok.type == tokenize.COMMENT:
                standalone = not line_has_code.get(tok.start[0], False)
                comments.append((tok.start[0], standalone, tok.string))
            elif tok.type not in (
                tokenize.NL,
                tokenize.NEWLINE,
                tokenize.INDENT,
                tokenize.DEDENT,
                tokenize.ENDMARKER,
                tokenize.ENCODING,
            ):
                line_has_code[tok.start[0]] = True
                code_lines.add(tok.start[0])

        sorted_code = sorted(code_lines)
        for line, standalone, text in comments:
            if _HOST_ONLY_RE.search(text):
                self.host_only_marker = True
            if _HOT_PATH_RE.search(text):
                self.hot_path_marker = True
            m = _SUPPRESS_RE.search(text)
            ids: set[str] = set()
            kind = None
            if m:
                ids = {p.strip() for p in m.group(2).split(",") if p.strip()}
                kind = m.group(1)
                for rid in sorted(ids):
                    if rid not in RULES:
                        self.unknown_suppressions.append((line, rid))
            if _UNPROFILED_JIT_RE.search(text):
                ids.add("AM306")
                kind = kind or "disable"
            if not ids:
                continue
            if kind == "disable-file":
                self.file_suppress |= ids
            elif standalone:
                target = next((c for c in sorted_code if c > line), None)
                if target is not None:
                    self.line_suppress.setdefault(target, set()).update(ids)
            else:
                self.line_suppress.setdefault(line, set()).update(ids)

    def is_suppressed(self, rule_id: str, line: int) -> bool:
        if rule_id in self.file_suppress:
            return True
        return rule_id in self.line_suppress.get(line, set())

    def finding(self, rule_id: str, node: ast.AST, message: str) -> Finding:
        line = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0)
        return Finding(
            rule_id,
            self.display,
            line,
            col,
            message,
            suppressed=self.is_suppressed(rule_id, line),
        )


# ---------------------------------------------------------------------- #
# constant evaluation (packing-layout extraction)

class NotConst(Exception):
    """Expression is not statically evaluable to an int."""


_BIN_OPS = {
    ast.LShift: lambda a, b: a << b,
    ast.RShift: lambda a, b: a >> b,
    ast.BitOr: lambda a, b: a | b,
    ast.BitAnd: lambda a, b: a & b,
    ast.BitXor: lambda a, b: a ^ b,
    ast.Add: lambda a, b: a + b,
    ast.Sub: lambda a, b: a - b,
    ast.Mult: lambda a, b: a * b,
    ast.FloorDiv: lambda a, b: a // b,
    ast.Pow: lambda a, b: a ** b,
}

_IINFO = {
    "int8": (-(1 << 7), (1 << 7) - 1),
    "int16": (-(1 << 15), (1 << 15) - 1),
    "int32": (-(1 << 31), (1 << 31) - 1),
    "int64": (-(1 << 63), (1 << 63) - 1),
    "uint8": (0, (1 << 8) - 1),
    "uint16": (0, (1 << 16) - 1),
    "uint32": (0, (1 << 32) - 1),
    "uint64": (0, (1 << 64) - 1),
}


def eval_const(node: ast.AST, env: dict[str, int]) -> int:
    """Evaluates a module-level constant expression: int literals, names of
    previously evaluated constants, bitwise/arithmetic operators, and the
    ``jnp.iinfo(jnp.int32).max`` idiom."""
    if isinstance(node, ast.Constant):
        if isinstance(node.value, bool) or not isinstance(node.value, int):
            raise NotConst(node)
        return node.value
    if isinstance(node, ast.Name):
        if node.id in env:
            return env[node.id]
        raise NotConst(node)
    if isinstance(node, ast.BinOp):
        fn = _BIN_OPS.get(type(node.op))
        if fn is None:
            raise NotConst(node)
        return fn(eval_const(node.left, env), eval_const(node.right, env))
    if isinstance(node, ast.UnaryOp):
        v = eval_const(node.operand, env)
        if isinstance(node.op, ast.USub):
            return -v
        if isinstance(node.op, ast.Invert):
            return ~v
        if isinstance(node.op, ast.UAdd):
            return v
        raise NotConst(node)
    if isinstance(node, ast.Attribute) and node.attr in ("max", "min"):
        # jnp.iinfo(jnp.int32).max / np.iinfo(np.int64).min
        call = node.value
        if (
            isinstance(call, ast.Call)
            and isinstance(call.func, ast.Attribute)
            and call.func.attr == "iinfo"
            and len(call.args) == 1
            and isinstance(call.args[0], ast.Attribute)
            and call.args[0].attr in _IINFO
        ):
            lo, hi = _IINFO[call.args[0].attr]
            return hi if node.attr == "max" else lo
    raise NotConst(node)


def module_constants(tree: ast.Module) -> dict[str, tuple[int, int]]:
    """Extracts statically evaluable module-level int constants.

    Returns {name: (value, lineno)}; assignments that cannot be evaluated
    are skipped (the env still accumulates, so later constants may refer to
    earlier ones)."""
    env: dict[str, int] = {}
    out: dict[str, tuple[int, int]] = {}
    for stmt in tree.body:
        targets: list[ast.expr] = []
        value = None
        if isinstance(stmt, ast.Assign):
            targets, value = stmt.targets, stmt.value
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            targets, value = [stmt.target], stmt.value
        if value is None:
            continue
        for target in targets:
            if not isinstance(target, ast.Name):
                continue
            try:
                v = eval_const(value, env)
            except NotConst:
                continue
            env[target.id] = v
            out[target.id] = (v, stmt.lineno)
    return out


# ---------------------------------------------------------------------- #
# helpers shared by the rule modules

def dotted_name(node: ast.AST) -> str | None:
    """'jax.lax.fori_loop' for a Name/Attribute chain, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def static_str_parts(node: ast.AST) -> str:
    """Concatenation of every statically known string fragment in an
    expression (Constant strings and the literal parts of f-strings)."""
    parts: list[str] = []
    for sub in ast.walk(node):
        if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
            parts.append(sub.value)
    return "".join(parts)


def collect_files(paths: list[Path]) -> list[tuple[Path, str]]:
    """Expands files/directories into (path, display) pairs, sorted for
    deterministic reports. Hidden dirs and __pycache__ are skipped."""
    seen: dict[Path, str] = {}
    for p in paths:
        p = Path(p)
        if p.is_file() and p.suffix == ".py":
            seen[p.resolve()] = str(p)
        elif p.is_dir():
            for f in sorted(p.rglob("*.py")):
                if any(
                    part.startswith(".") or part == "__pycache__"
                    for part in f.parts
                ):
                    continue
                seen[f.resolve()] = str(f)
    return sorted(seen.items(), key=lambda kv: kv[1])
