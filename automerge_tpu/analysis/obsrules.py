"""AM303 — observability hygiene: no metric/span recording in traced code.

The amtrace instruments (automerge_tpu/obs) are host-side Python: a
counter ``inc()`` or a ``with trace.span(...)`` inside code that jax
traces would execute ONCE at trace time and then be baked out of the
compiled program — the metric silently stops counting (or worse, counts
compile events as steady-state traffic). All recording must happen in the
host wrappers around a dispatch, never inside it.

The rule reuses the AM20x taint walker's trace-root discovery
(tracer._ModuleChecker: jit-like decorators with static_argnums honoured,
functions referenced as combinator arguments, nested defs handed to
``jax.vmap``/``pl.pallas_call``/...) and extends it with a plain
reachability pass: from every traced root, direct calls into module-level
and nested functions are followed, so a helper called from a jitted entry
point is checked too.

Flagged inside jit/vmap/Pallas-reachable code:

- any call whose root name was imported from ``automerge_tpu.obs`` (or the
  ``profiling`` shim) — ``get_metrics()``, ``get_trace()``,
  ``use_profile(...)``, ...;
- any attribute call spelling a recording verb: ``.inc()``, ``.observe()``,
  ``.span()``, ``.phase()``, ``.record()`` (the flight recorder's verb).
  (``Gauge.set`` is deliberately NOT matched — ``.set(...)`` is too common
  a spelling on host containers; gauges must therefore be set in host code
  by convention.)
"""
from __future__ import annotations

import ast

from .core import FileContext, Finding, dotted_name
from .tracer import _ModuleChecker

_RECORD_ATTRS = {"inc", "observe", "span", "phase", "record"}
_OBS_MODULE_HINTS = {"obs", "metrics", "spans", "profiling"}


def _obs_aliases(tree: ast.Module) -> set[str]:
    """Top-level names bound from the obs package (or the profiling shim):
    ``from automerge_tpu.obs.metrics import get_metrics`` binds
    ``get_metrics``; ``import automerge_tpu.obs as obs`` binds ``obs``."""
    out: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            parts = (node.module or "").split(".")
            if any(p in _OBS_MODULE_HINTS for p in parts):
                for alias in node.names:
                    out.add(alias.asname or alias.name)
        elif isinstance(node, ast.Import):
            for alias in node.names:
                parts = alias.name.split(".")
                if any(p in _OBS_MODULE_HINTS for p in parts):
                    out.add((alias.asname or alias.name).split(".")[0])
    return out


class _ObsChecker(_ModuleChecker):
    """Reuses the AM20x walker's traced-root discovery; overrides the
    per-function analysis with a recording-call scan plus direct-call
    reachability (taint is irrelevant here — a recording call is wrong in
    traced code whatever its arguments)."""

    def __init__(self, ctx: FileContext):
        super().__init__(ctx)
        self.obs_aliases = _obs_aliases(ctx.tree)

    def _analyze_function(self, fn, tainted, worklist) -> None:
        nested = {
            n.name: n
            for n in ast.walk(fn)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
            and n is not fn
        }
        for node in ast.walk(fn):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if not isinstance(node, ast.Call):
                continue
            fname = dotted_name(node.func)
            root = fname.split(".")[0] if fname else None
            if root in self.obs_aliases:
                self._emit(
                    "AM303", node,
                    f"`{fname}` (an obs/profiling binding) called inside "
                    f"jit/vmap/Pallas-reachable code ({fn.name}): traced "
                    "code runs once at trace time — record on the host "
                    "around the dispatch",
                )
            elif (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in _RECORD_ATTRS
            ):
                self._emit(
                    "AM303", node,
                    f"`.{node.func.attr}()` metric/span recording inside "
                    f"jit/vmap/Pallas-reachable code ({fn.name}): traced "
                    "code runs once at trace time — record on the host "
                    "around the dispatch",
                )
            # reachability: follow direct calls into sibling functions
            callee = None
            if isinstance(node.func, ast.Name):
                callee = nested.get(node.func.id) or self.module_funcs.get(
                    node.func.id
                )
            if callee is not None and callee is not fn:
                worklist.append((callee, frozenset()))


def check(ctxs: list[FileContext]) -> list[Finding]:
    findings: list[Finding] = []
    for ctx in ctxs:
        findings += _ObsChecker(ctx).run()
    return findings
