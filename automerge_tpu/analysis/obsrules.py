"""AM303 — observability hygiene: no metric/span recording in traced code.

The amtrace instruments (automerge_tpu/obs) are host-side Python: a
counter ``inc()`` or a ``with trace.span(...)`` inside code that jax
traces would execute ONCE at trace time and then be baked out of the
compiled program — the metric silently stops counting (or worse, counts
compile events as steady-state traffic). All recording must happen in the
host wrappers around a dispatch, never inside it.

The rule reuses the AM20x taint walker's trace-root discovery
(tracer._ModuleChecker: jit-like decorators with static_argnums honoured,
functions referenced as combinator arguments, nested defs handed to
``jax.vmap``/``pl.pallas_call``/...) and extends it with a *transitive*
reachability pass over the whole scan: from every traced root, calls into
module-level and nested functions are followed, and calls the call graph
(graph.py) resolves across files — from-imported helpers, module-alias
attributes, same-scan class methods — are followed into their home
modules too, with a bounded depth. Every diagnostic prints the discovery
chain (``[reachable via root -> helper -> ...]``) so a finding three
frames below the jit entry point is still actionable.

Flagged inside jit/vmap/Pallas-reachable code:

- any call whose root name was imported from ``automerge_tpu.obs`` (or the
  ``profiling`` shim) — ``get_metrics()``, ``get_trace()``,
  ``use_profile(...)``, ...;
- any attribute call spelling a recording verb: ``.inc()``, ``.observe()``,
  ``.span()``, ``.phase()``, ``.record()`` (the flight recorder's verb).
  (``Gauge.set`` is deliberately NOT matched — ``.set(...)`` is too common
  a spelling on host containers; gauges must therefore be set in host code
  by convention.)
"""
from __future__ import annotations

import ast

from .core import FileContext, Finding, dotted_name
from .tracer import _Coordinator, _ModuleChecker

_RECORD_ATTRS = {"inc", "observe", "span", "phase", "record"}
_OBS_MODULE_HINTS = {"obs", "metrics", "spans", "profiling"}


def _obs_aliases(tree: ast.Module) -> set[str]:
    """Top-level names bound from the obs package (or the profiling shim):
    ``from automerge_tpu.obs.metrics import get_metrics`` binds
    ``get_metrics``; ``import automerge_tpu.obs as obs`` binds ``obs``."""
    out: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            parts = (node.module or "").split(".")
            if any(p in _OBS_MODULE_HINTS for p in parts):
                for alias in node.names:
                    out.add(alias.asname or alias.name)
        elif isinstance(node, ast.Import):
            for alias in node.names:
                parts = alias.name.split(".")
                if any(p in _OBS_MODULE_HINTS for p in parts):
                    out.add((alias.asname or alias.name).split(".")[0])
    return out


class _ObsChecker(_ModuleChecker):
    """Reuses the AM20x walker's traced-root discovery; overrides the
    per-function analysis with a recording-call scan plus direct-call
    reachability (taint is irrelevant here — a recording call is wrong in
    traced code whatever its arguments)."""

    def __init__(self, ctx: FileContext, coordinator=None):
        super().__init__(ctx, coordinator)
        self.obs_aliases = _obs_aliases(ctx.tree)

    def _analyze_function(self, fn, tainted, chain) -> None:
        self._current_chain = chain
        nested = {
            n.name: n
            for n in ast.walk(fn)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
            and n is not fn
        }
        # walk the BODY only: decorator expressions run at def time on the
        # host, so `@profiled_jit(...)` must not drag the registration
        # helper into "traced code"
        for node in (n for stmt in fn.body for n in ast.walk(stmt)):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if not isinstance(node, ast.Call):
                continue
            fname = dotted_name(node.func)
            root = fname.split(".")[0] if fname else None
            if root in self.obs_aliases:
                self._emit(
                    "AM303", node,
                    f"`{fname}` (an obs/profiling binding) called inside "
                    f"jit/vmap/Pallas-reachable code ({fn.name}): traced "
                    "code runs once at trace time — record on the host "
                    "around the dispatch",
                )
            elif (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in _RECORD_ATTRS
            ):
                self._emit(
                    "AM303", node,
                    f"`.{node.func.attr}()` metric/span recording inside "
                    f"jit/vmap/Pallas-reachable code ({fn.name}): traced "
                    "code runs once at trace time — record on the host "
                    "around the dispatch",
                )
            # transitive reachability: same-module calls directly, anything
            # else (from-imports, aliases, methods) through the call graph
            callee = None
            if isinstance(node.func, ast.Name):
                callee = nested.get(node.func.id) or self.module_funcs.get(
                    node.func.id
                )
            if callee is not None and callee is not fn:
                self.coordinator.enqueue(
                    self, callee, frozenset(), chain + (callee.name,)
                )
            elif callee is None:
                cross = self.resolve_cross(node)
                if cross is not None and cross.node is not fn:
                    self.coordinator.enqueue_info(cross, frozenset(), chain)
        self._current_chain = ()


def check(ctxs: list[FileContext], graph=None) -> list[Finding]:
    return _Coordinator(ctxs, graph, checker_cls=_ObsChecker).run()
