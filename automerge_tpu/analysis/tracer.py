"""AM2xx — tracer-safety rules.

JAX tracing imposes purity rules the Python type system cannot see: code
reachable from a ``jax.jit`` / ``jax.vmap`` / Pallas entry point receives
tracers, and Python-level branching on a tracer, host-library calls on a
tracer, or mutation of captured host state all either raise at trace time
or (worse) silently bake one traced execution into the compiled program.

The checker builds a per-module view of traced code:

- **roots**: functions decorated with jit-like decorators (``@jax.jit``,
  ``@partial(jax.jit, ...)``, ``@jax.vmap``), with ``static_argnums`` /
  ``static_argnames`` honoured, plus functions *referenced* as arguments of
  tracing combinators (``jax.vmap(f)``, ``jax.lax.fori_loop(_, _, f, _)``,
  ``pl.pallas_call(f)``, ``jax.lax.scan(f, ...)``) whose parameters are all
  traced (``partial``-bound arguments are host constants and stay static);
- **taint**: inside a traced function, parameters are traced; taint
  propagates through expressions and assignments, and is *blocked* by the
  static accessors (``.shape``, ``.dtype``, ``.ndim``, ``len()``) — shape
  math is host-side and branching on it is legal;
- **interprocedural**: a call from traced code taints the callee's
  parameters positionally, so shared helpers are checked under the taint
  they actually receive. Resolution is whole-scan (graph.py): direct
  same-module calls, from-imported helpers in other scanned modules, and
  ``Class.meth``/module-alias attribute targets all propagate taint, with
  the discovery chain carried along so every diagnostic prints the actual
  ``[reachable via root -> helper -> ...]`` path from its trace root.

Rules:
- AM201: ``if``/``while``/``assert``/``and``/``or``/ternary/``for`` over a
  traced value (TracerBoolConversionError at runtime, or a silently
  specialised branch).
- AM202: host escapes — ``np.*`` calls, ``int()``/``float()``/``bool()``,
  ``.item()``/``.tolist()`` — applied to a traced value.
- AM203: dtype-less ``np.zeros/ones/empty/full/array`` (and jnp
  equivalents) in modules that import jax: default dtypes differ between
  hosts and backends (int32 vs int64, x64 flag), which corrupts packed
  int64 opids — transcode hot paths must pin every dtype.
- AM204: mutation of captured host state (``global``/``nonlocal``,
  ``obj.attr = ...`` or ``.append()``-style calls on closure/module names)
  inside traced code — traced mutations run once at trace time, not per
  call.
"""
from __future__ import annotations

import ast

from .core import FileContext, Finding, dotted_name
from .graph import format_chain

_JIT_DECORATORS = {"jit", "vmap", "pmap", "profiled_jit"}
_COMBINATORS = {
    "jit", "vmap", "pmap", "scan", "fori_loop", "while_loop", "cond",
    "switch", "pallas_call", "reduce", "associative_scan", "remat",
    "checkpoint", "grad", "value_and_grad", "custom_vjp", "custom_jvp",
}
_JAX_ROOTS = {"jax", "jnp", "lax", "pl", "pltpu", "pallas"}
_SHAPE_ATTRS = {"shape", "dtype", "ndim", "size", "weak_type", "sharding", "aval"}
_STATIC_CALLS = {"len", "range", "isinstance", "type", "enumerate", "zip"}
_COERCIONS = {"int", "float", "bool", "complex"}
_HOST_METHODS = {"item", "tolist"}
_MUTATORS = {"append", "extend", "insert", "add", "update", "pop", "clear",
             "remove", "setdefault", "discard", "popitem"}
_DTYPE_CTORS = {"zeros": 1, "ones": 1, "empty": 1, "full": 2, "array": 1}


def _np_aliases(tree: ast.Module) -> set[str]:
    out = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name == "numpy":
                    out.add(alias.asname or "numpy")
    return out


def _jnp_aliases(tree: ast.Module) -> set[str]:
    out = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name == "jax.numpy":
                    out.add(alias.asname or "jax.numpy")
    return out


def _import_aliases(tree: ast.Module) -> set[str]:
    """Every top-level name bound by an import (module aliases and
    from-imported names): functional APIs like jnp.append are not captured
    host state."""
    out: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                out.add((alias.asname or alias.name).split(".")[0])
        elif isinstance(node, ast.ImportFrom):
            for alias in node.names:
                out.add(alias.asname or alias.name)
    return out


def _imports_jax(tree: ast.Module) -> bool:
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            if any(a.name == "jax" or a.name.startswith("jax.") for a in node.names):
                return True
        elif isinstance(node, ast.ImportFrom):
            if node.module and (node.module == "jax" or node.module.startswith("jax.")):
                return True
    return False


def _is_combinator_call(func: ast.expr) -> bool:
    name = dotted_name(func)
    if name is None:
        return False
    parts = name.split(".")
    if parts[-1] not in _COMBINATORS:
        return False
    return len(parts) == 1 or any(p in _JAX_ROOTS for p in parts[:-1])


def _is_jit_like(node: ast.expr) -> bool:
    name = dotted_name(node)
    if name is None:
        return False
    parts = name.split(".")
    return parts[-1] in _JIT_DECORATORS and (
        len(parts) == 1 or any(p in _JAX_ROOTS for p in parts[:-1])
    )


def _const_strings(node: ast.expr) -> set[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return {node.value}
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        out = set()
        for elt in node.elts:
            out |= _const_strings(elt)
        return out
    return set()


def _const_ints(node: ast.expr) -> set[int]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return {node.value}
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        out = set()
        for elt in node.elts:
            out |= _const_ints(elt)
        return out
    return set()


def _decorator_statics(dec: ast.expr):
    """(is_traced, static_argnums, static_argnames) for a decorator node."""
    if _is_jit_like(dec):
        return True, set(), set()
    if isinstance(dec, ast.Call):
        func_name = dotted_name(dec.func)
        target_is_jit = False
        if func_name and func_name.split(".")[-1] == "partial" and dec.args:
            target_is_jit = _is_jit_like(dec.args[0])
        elif _is_jit_like(dec.func):
            target_is_jit = True
        if target_is_jit:
            nums: set[int] = set()
            names: set[str] = set()
            for kw in dec.keywords:
                if kw.arg == "static_argnums":
                    nums |= _const_ints(kw.value)
                elif kw.arg == "static_argnames":
                    names |= _const_strings(kw.value)
            return True, nums, names
    return False, set(), set()


def _param_names(fn) -> list[str]:
    args = fn.args
    names = [a.arg for a in args.posonlyargs + args.args]
    names += [a.arg for a in args.kwonlyargs]
    if args.vararg:
        names.append(args.vararg.arg)
    if args.kwarg:
        names.append(args.kwarg.arg)
    return names


def _assigned_names(fn) -> set[str]:
    """Every name bound anywhere inside the function body (its locals)."""
    out: set[str] = set(_param_names(fn))
    for node in ast.walk(fn):
        if isinstance(node, (ast.Name,)) and isinstance(node.ctx, ast.Store):
            out.add(node.id)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            if node is not fn:
                out.add(node.name)
        elif isinstance(node, ast.comprehension):
            for sub in ast.walk(node.target):
                if isinstance(sub, ast.Name):
                    out.add(sub.id)
    return out


class _Coordinator:
    """Whole-scan driver: one checker per file, one shared worklist of
    ``(checker, fn, tainted params, discovery chain)`` items, so taint
    crossing a module boundary lands in the right file's checker with the
    chain that got it there."""

    def __init__(self, ctxs: list[FileContext], graph=None,
                 checker_cls=None):
        self.graph = graph
        cls = checker_cls or _ModuleChecker
        self.checkers: dict[int, _ModuleChecker] = {
            id(ctx): cls(ctx, self) for ctx in ctxs
        }
        self.worklist: list[tuple] = []

    def enqueue(self, checker, fn, tainted: frozenset,
                chain: tuple[str, ...]) -> None:
        self.worklist.append((checker, fn, tainted, chain))

    def enqueue_info(self, fi, tainted: frozenset,
                     chain: tuple[str, ...]) -> None:
        """Cross-module hop: route a graph-resolved FuncInfo to the
        checker that owns its file, extending the chain."""
        checker = self.checkers.get(id(fi.ctx))
        if checker is not None:
            self.worklist.append(
                (checker, fi.node, tainted, chain + (fi.label,))
            )

    def run(self) -> list[Finding]:
        for checker in self.checkers.values():
            checker.seed()
        while self.worklist:
            checker, fn, tainted, chain = self.worklist.pop()
            key = (id(fn), tainted)
            if key in checker._done:
                continue
            checker._done.add(key)
            checker._analyze_function(fn, tainted, chain)
        findings: list[Finding] = []
        for checker in self.checkers.values():
            findings.extend(checker.findings)
        return findings


class _ModuleChecker:
    def __init__(self, ctx: FileContext, coordinator: _Coordinator = None):
        self.ctx = ctx
        self.coordinator = coordinator
        self.tree = ctx.tree
        self.np_aliases = _np_aliases(ctx.tree)
        self.jnp_aliases = _jnp_aliases(ctx.tree)
        self.import_aliases = _import_aliases(ctx.tree)
        self.findings: list[Finding] = []
        self._emitted: set[tuple[str, int, int]] = set()
        self.module_funcs = {
            n.name: n
            for n in self.tree.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        # (func name, frozenset of tainted params) already analyzed
        self._done: set[tuple[int, frozenset]] = set()
        self.traced_names: set[str] = set()
        #: chain of the function currently under analysis — every finding
        #: it emits prints the path from its trace root
        self._current_chain: tuple[str, ...] = ()

    # ------------------------------------------------------------------ #

    def seed(self) -> None:
        """Discovers this module's trace roots and enqueues them on the
        coordinator with single-element chains."""
        co = self.coordinator

        for fn in self.module_funcs.values():
            for dec in fn.decorator_list:
                traced, nums, names = _decorator_statics(dec)
                if traced:
                    params = _param_names(fn)
                    tainted = frozenset(
                        p for i, p in enumerate(params)
                        if i not in nums and p not in names
                    )
                    co.enqueue(self, fn, tainted, (fn.name,))
                    self.traced_names.add(fn.name)
                    break

        # module functions referenced as combinator arguments anywhere
        for fn, exempt_names, exempt_count in self._combinator_refs(self.tree):
            params = _param_names(fn)
            tainted = frozenset(
                p for i, p in enumerate(params)
                if i >= exempt_count and p not in exempt_names
            )
            co.enqueue(self, fn, tainted, (fn.name,))
            self.traced_names.add(fn.name)

        # nested defs passed to combinators inside otherwise-host functions
        # (e.g. `return jax.jit(impl, ...)` in a factory) are trace roots too
        module_fn_nodes = set(map(id, self.module_funcs.values()))
        for fn in self.module_funcs.values():
            nested = {
                n.name: n for n in ast.walk(fn)
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
                and n is not fn
            }
            if not nested:
                continue
            for sub, exempt_names, exempt_count in self._combinator_refs(fn, nested):
                if id(sub) in module_fn_nodes:
                    continue  # already handled by the module-wide scan
                params = _param_names(sub)
                tainted = frozenset(
                    p for i, p in enumerate(params)
                    if i >= exempt_count and p not in exempt_names
                )
                co.enqueue(self, sub, tainted, (sub.name,))

    def resolve_cross(self, call: ast.Call):
        """Graph resolution for calls the per-module lookup missed:
        from-imported helpers, module-alias attributes, same-scan class
        methods. Returns a FuncInfo or None."""
        co = self.coordinator
        if co is None or co.graph is None:
            return None
        mod = co.graph.module_for(self.ctx)
        if mod is None:
            return None
        return co.graph.resolve_call(mod, call.func)

    def _combinator_refs(self, scope: ast.AST, local_funcs=None):
        """(function node, partial-bound kwnames, partial-bound positional
        count) for every module/nested function referenced as an argument
        of a tracing combinator within `scope`."""
        funcs = dict(self.module_funcs)
        if local_funcs:
            funcs.update(local_funcs)
        refs = []
        for node in ast.walk(scope):
            if not (isinstance(node, ast.Call) and _is_combinator_call(node.func)):
                continue
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                if isinstance(arg, ast.Name) and arg.id in funcs:
                    refs.append((funcs[arg.id], set(), 0))
                elif isinstance(arg, ast.Call):
                    fname = dotted_name(arg.func)
                    if (
                        fname
                        and fname.split(".")[-1] == "partial"
                        and arg.args
                        and isinstance(arg.args[0], ast.Name)
                        and arg.args[0].id in funcs
                    ):
                        bound = {kw.arg for kw in arg.keywords if kw.arg}
                        refs.append(
                            (funcs[arg.args[0].id], bound, len(arg.args) - 1)
                        )
        return refs

    # ------------------------------------------------------------------ #

    def _emit(self, rule_id: str, node: ast.AST, message: str) -> None:
        key = (rule_id, getattr(node, "lineno", 1), getattr(node, "col_offset", 0))
        if key not in self._emitted:
            self._emitted.add(key)
            if self._current_chain:
                message += format_chain(self._current_chain)
            self.findings.append(self.ctx.finding(rule_id, node, message))

    def _analyze_function(self, fn, tainted: frozenset,
                          chain: tuple[str, ...]) -> None:
        locals_ = _assigned_names(fn)
        nested = {
            n.name: n for n in ast.walk(fn)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)) and n is not fn
        }
        env = set(tainted)
        self._current_chain = chain
        state = _FnState(self, fn, locals_, nested, chain)
        # pass 1: propagate taint (loops make later lines feed earlier ones);
        # pass 2: report with the stable env
        state.walk_block(fn.body, env, report=False)
        self._current_chain = chain  # a recursed nested def may have moved it
        state.walk_block(fn.body, env, report=True)

        # nested functions referenced in combinators run traced with the
        # enclosing env visible as closure state
        for sub, exempt_names, exempt_count in self._combinator_refs(fn, nested):
            if sub is fn:
                continue
            params = _param_names(sub)
            sub_tainted = frozenset(
                p for i, p in enumerate(params)
                if i >= exempt_count and p not in exempt_names
            ) | frozenset(n for n in env if n not in _assigned_names(sub))
            key = (id(sub), sub_tainted)
            if key not in self._done:
                self._done.add(key)
                self._analyze_function(sub, sub_tainted, chain + (sub.name,))
        # pl.when-decorated nested defs execute inside the trace
        for sub in nested.values():
            for dec in sub.decorator_list:
                if isinstance(dec, ast.Call) and (
                    (dotted_name(dec.func) or "").split(".")[-1] == "when"
                ):
                    sub_tainted = frozenset(
                        n for n in env if n not in _assigned_names(sub)
                    )
                    key = (id(sub), sub_tainted)
                    if key not in self._done:
                        self._done.add(key)
                        self._analyze_function(
                            sub, sub_tainted, chain + (sub.name,)
                        )
        self._current_chain = ()


class _FnState:
    """Per-function walk: statement-ordered taint propagation + findings."""

    def __init__(self, mod: _ModuleChecker, fn, locals_, nested, chain):
        self.mod = mod
        self.fn = fn
        self.locals = locals_
        self.nested = nested
        self.chain = chain
        self.report = False

    # ------------------------------ statements ------------------------ #

    def walk_block(self, stmts, env: set, report: bool) -> None:
        self.report = report
        for stmt in stmts:
            self.walk_stmt(stmt, env)

    def walk_stmt(self, stmt, env: set) -> None:
        mod = self.mod
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            return  # nested defs handled by the module checker
        if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            value = stmt.value
            t = self.taint(value, env) if value is not None else False
            targets = (
                stmt.targets if isinstance(stmt, ast.Assign) else [stmt.target]
            )
            for target in targets:
                if isinstance(stmt, ast.AugAssign):
                    t = t or self.taint(target, env)
                self._bind(target, t, env)
        elif isinstance(stmt, ast.If):
            if self.taint(stmt.test, env) and self.report:
                mod._emit("AM201", stmt,
                          "Python-level `if` on a traced value inside traced "
                          f"code ({self.fn.name}): use jnp.where/lax.cond")
            for s in stmt.body + stmt.orelse:
                self.walk_stmt(s, env)
        elif isinstance(stmt, ast.While):
            if self.taint(stmt.test, env) and self.report:
                mod._emit("AM201", stmt,
                          "Python-level `while` on a traced value inside "
                          f"traced code ({self.fn.name}): use lax.while_loop")
            for s in stmt.body + stmt.orelse:
                self.walk_stmt(s, env)
        elif isinstance(stmt, ast.Assert):
            if self.taint(stmt.test, env) and self.report:
                mod._emit("AM201", stmt,
                          "assert on a traced value inside traced code "
                          f"({self.fn.name}): use checkify or a host-side "
                          "prevalidation pass")
        elif isinstance(stmt, ast.For):
            if self.taint(stmt.iter, env) and self.report:
                mod._emit("AM201", stmt,
                          "Python `for` over a traced value inside traced "
                          f"code ({self.fn.name}): use lax.fori_loop/scan")
            self._bind(stmt.target, self.taint(stmt.iter, env), env)
            for s in stmt.body + stmt.orelse:
                self.walk_stmt(s, env)
        elif isinstance(stmt, ast.With):
            for item in stmt.items:
                self.taint(item.context_expr, env)
                if item.optional_vars is not None:
                    self._bind(item.optional_vars, False, env)
            for s in stmt.body:
                self.walk_stmt(s, env)
        elif isinstance(stmt, ast.Try):
            for s in stmt.body + stmt.orelse + stmt.finalbody:
                self.walk_stmt(s, env)
            for handler in stmt.handlers:
                for s in handler.body:
                    self.walk_stmt(s, env)
        elif isinstance(stmt, (ast.Global, ast.Nonlocal)):
            if self.report:
                mod._emit("AM204", stmt,
                          f"`{'global' if isinstance(stmt, ast.Global) else 'nonlocal'}`"
                          " inside traced code mutates host state at trace "
                          "time, not per execution")
        elif isinstance(stmt, ast.Return):
            if stmt.value is not None:
                self.taint(stmt.value, env)
        elif isinstance(stmt, ast.Expr):
            self.taint(stmt.value, env)
        elif isinstance(stmt, ast.Raise):
            if stmt.exc is not None:
                self.taint(stmt.exc, env)
        # Import/Pass/Break/Continue/Delete: nothing to do

    def _bind(self, target, tainted: bool, env: set) -> None:
        if isinstance(target, ast.Name):
            if tainted:
                env.add(target.id)
            else:
                env.discard(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._bind(elt, tainted, env)
        elif isinstance(target, ast.Starred):
            self._bind(target.value, tainted, env)
        elif isinstance(target, ast.Attribute):
            base = target.value
            if (
                isinstance(base, ast.Name)
                and base.id not in self.locals
                and self.report
            ):
                self.mod._emit(
                    "AM204", target,
                    f"assignment to `{base.id}.{target.attr}` mutates "
                    "captured host state inside traced code",
                )
        # Subscript stores are allowed: pallas Ref writes (out_ref[...] = x)
        # are the output idiom

    # ------------------------------ expressions ------------------------ #

    def taint(self, node, env: set) -> bool:
        if node is None or isinstance(node, ast.Constant):
            return False
        if isinstance(node, ast.Name):
            return node.id in env
        if isinstance(node, ast.Attribute):
            if node.attr in _SHAPE_ATTRS:
                self.taint(node.value, env)
                return False
            return self.taint(node.value, env)
        if isinstance(node, ast.Subscript):
            base = node.value
            base_t = self.taint(base, env)
            idx_t = self.taint(node.slice, env)
            return base_t or idx_t
        if isinstance(node, ast.Call):
            return self._call_taint(node, env)
        if isinstance(node, ast.BoolOp):
            parts = [self.taint(v, env) for v in node.values]
            if any(parts) and self.report:
                self.mod._emit(
                    "AM201", node,
                    "`and`/`or` coerces a traced value to bool inside traced "
                    f"code ({self.fn.name}): use jnp.logical_and/or or &,|",
                )
            return any(parts)
        if isinstance(node, ast.IfExp):
            t = self.taint(node.test, env)
            if t and self.report:
                self.mod._emit(
                    "AM201", node,
                    "conditional expression on a traced value inside traced "
                    f"code ({self.fn.name}): use jnp.where",
                )
            return t or self.taint(node.body, env) or self.taint(node.orelse, env)
        if isinstance(node, (ast.BinOp,)):
            return self.taint(node.left, env) | self.taint(node.right, env)
        if isinstance(node, ast.UnaryOp):
            return self.taint(node.operand, env)
        if isinstance(node, ast.Compare):
            t = self.taint(node.left, env)
            for comp in node.comparators:
                t |= self.taint(comp, env)
            return t
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            return any(self.taint(e, env) for e in node.elts)
        if isinstance(node, ast.Dict):
            return any(
                self.taint(x, env) for x in (node.keys + node.values) if x
            )
        if isinstance(node, ast.Starred):
            return self.taint(node.value, env)
        if isinstance(node, (ast.JoinedStr, ast.FormattedValue)):
            for sub in ast.iter_child_nodes(node):
                self.taint(sub, env)
            return False
        if isinstance(node, ast.Slice):
            return any(
                self.taint(x, env)
                for x in (node.lower, node.upper, node.step) if x
            )
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp, ast.DictComp)):
            t = False
            inner = set(env)
            for gen in node.generators:
                it = self.taint(gen.iter, inner)
                t |= it
                self._bind(gen.target, it, inner)
                for cond in gen.ifs:
                    if self.taint(cond, inner) and self.report:
                        self.mod._emit(
                            "AM201", cond,
                            "comprehension filter on a traced value inside "
                            f"traced code ({self.fn.name})",
                        )
            if isinstance(node, ast.DictComp):
                t |= self.taint(node.key, inner) | self.taint(node.value, inner)
            else:
                t |= self.taint(node.elt, inner)
            return t
        if isinstance(node, ast.Lambda):
            return False
        if isinstance(node, (ast.Await, ast.YieldFrom)):
            return self.taint(node.value, env)
        if isinstance(node, ast.Yield):
            return self.taint(node.value, env) if node.value else False
        return False

    def _call_taint(self, node: ast.Call, env: set) -> bool:
        mod = self.mod
        fname = dotted_name(node.func)
        arg_taints = [self.taint(a, env) for a in node.args]
        kw_taints = [self.taint(kw.value, env) for kw in node.keywords]
        args_tainted = any(arg_taints) or any(kw_taints)

        if fname in _STATIC_CALLS:
            return False
        last = fname.split(".")[-1] if fname else None

        # host coercions on tracers
        if fname in _COERCIONS:
            if args_tainted and self.report:
                mod._emit("AM202", node,
                          f"`{fname}()` forces a traced value to a host "
                          f"scalar inside traced code ({self.fn.name})")
            return False
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr in _HOST_METHODS
            and self.taint(node.func.value, env)
        ):
            if self.report:
                mod._emit("AM202", node,
                          f"`.{node.func.attr}()` transfers a traced value "
                          f"to the host inside traced code ({self.fn.name})")
            return False
        # numpy on tracers
        if fname:
            root = fname.split(".")[0]
            if root in mod.np_aliases and args_tainted:
                if self.report:
                    mod._emit("AM202", node,
                              f"`{fname}` applies host numpy to a traced "
                              f"value inside traced code ({self.fn.name}): "
                              "use jax.numpy")
                return True
        # mutating method on a captured (non-local) name
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr in _MUTATORS
            and isinstance(node.func.value, ast.Name)
            and node.func.value.id not in self.locals
            and node.func.value.id not in mod.import_aliases
            and self.report
        ):
            mod._emit("AM204", node,
                      f"`{node.func.value.id}.{node.func.attr}()` mutates "
                      "captured host state inside traced code "
                      f"({self.fn.name})")

        # call into another function: propagate taint positionally.
        # Same-module defs resolve directly; everything else (from-imports,
        # module aliases, same-scan class methods) goes through the graph.
        callee = None
        if isinstance(node.func, ast.Name):
            callee = self.nested.get(node.func.id) or mod.module_funcs.get(
                node.func.id
            )
        cross = None
        if callee is None and args_tainted:
            cross = mod.resolve_cross(node)
        target = callee if callee is not None else (
            cross.node if cross is not None else None
        )
        if target is not None and target is not self.fn:
            params = _param_names(target)
            tainted_params = frozenset(
                params[i] for i, t in enumerate(arg_taints)
                if t and i < len(params)
            ) | frozenset(
                kw.arg for kw, t in zip(node.keywords, kw_taints)
                if t and kw.arg
            )
            if tainted_params:
                if callee is not None:
                    mod.coordinator.enqueue(
                        mod, callee, tainted_params,
                        self.chain + (callee.name,)
                    )
                else:
                    mod.coordinator.enqueue_info(
                        cross, tainted_params, self.chain
                    )

        func_taint = False
        if isinstance(node.func, ast.Attribute):
            func_taint = self.taint(node.func.value, env)
        return args_tainted or func_taint


# ---------------------------------------------------------------------- #
# AM203 — dtype-less array construction (module-wide scan)

def _check_dtypes(ctx: FileContext) -> list[Finding]:
    if not _imports_jax(ctx.tree):
        return []
    np_like = _np_aliases(ctx.tree) | _jnp_aliases(ctx.tree) | {"jnp"}
    findings: list[Finding] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        fname = dotted_name(node.func)
        if fname is None or "." not in fname:
            continue
        root, last = fname.split(".")[0], fname.split(".")[-1]
        if root not in np_like or last not in _DTYPE_CTORS:
            continue
        dtype_pos = _DTYPE_CTORS[last]
        has_dtype = len(node.args) > dtype_pos or any(
            kw.arg == "dtype" for kw in node.keywords
        )
        if not has_dtype:
            findings.append(ctx.finding(
                "AM203", node,
                f"`{fname}` without an explicit dtype: default dtypes vary "
                "with platform and the x64 flag, which corrupts packed int64 "
                "opids in transcode hot paths — pin the dtype",
            ))
    return findings


def check(ctxs: list[FileContext], graph=None) -> list[Finding]:
    findings = _Coordinator(ctxs, graph).run()
    for ctx in ctxs:
        findings += _check_dtypes(ctx)
    return findings
