"""AM3xx — host/device boundary rules.

The package keeps a strict layering: the columnar codecs, the sequential
OpSet engine, the frontend and the sync wire protocol are pure host Python
(they must import cleanly without jax and never pull device kernels), while
everything under ``tpu/`` is the device layer. The farm's profiling phases
likewise encode the boundary: a phase named for device work must not hide a
host synchronisation inside it, or the phase table lies about where time
goes and the device pipeline silently serialises.

- AM301: a host-only module (marked ``# amlint: host-only`` or on the
  built-in list) imports ``automerge_tpu.tpu`` / ``.tpu`` / ``jax``.
- AM302: inside ``with prof.phase("device...")`` blocks, lexical calls that
  force a device->host transfer (``np.*``, ``int()``/``float()``/
  ``bool()``, ``.item()``, ``.tolist()``, ``print``) are flagged.
"""
from __future__ import annotations

import ast

from .core import FileContext, Finding, dotted_name
from .tracer import _np_aliases

# Modules at the automerge_tpu package root that form the host-only layer.
# ``# amlint: host-only`` in a module marks it explicitly (and is how the
# fixture tests exercise the rule); the list keeps the rule self-contained
# for the repo even if a marker goes missing.
_HOST_ONLY_BASENAMES = {
    "columnar.py", "opset.py", "codecs.py", "common.py", "sync.py",
    "uuid.py", "backend.py", "native.py", "profiling.py",
}
_HOST_ONLY_DIRS = {"frontend"}


def _is_host_only(ctx: FileContext) -> bool:
    if ctx.host_only_marker:
        return True
    parts = ctx.path.parts
    if "automerge_tpu" not in parts:
        return False
    if any(d in parts for d in _HOST_ONLY_DIRS):
        return True
    idx = len(parts) - 1 - parts[::-1].index("automerge_tpu")
    at_package_root = idx == len(parts) - 2
    return at_package_root and ctx.path.name in _HOST_ONLY_BASENAMES


def _forbidden_import(module: str | None, level: int) -> str | None:
    """Why an import target crosses the boundary, or None if it is fine."""
    if module is None:
        return None  # `from . import sibling` — checked per alias below
    head = module.split(".")[0]
    if head == "jax":
        return "imports jax (device runtime) into the host-only layer"
    if head == "tpu" or module.startswith("automerge_tpu.tpu") or (
        level > 0 and head == "tpu"
    ):
        return "imports the device kernel layer (tpu/)"
    return None


def _check_imports(ctx: FileContext) -> list[Finding]:
    if not _is_host_only(ctx):
        return []
    findings: list[Finding] = []
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                reason = _forbidden_import(alias.name, 0)
                if reason:
                    findings.append(ctx.finding(
                        "AM301", node,
                        f"host-only module {reason}: the host layer must "
                        "import cleanly without device dependencies",
                    ))
        elif isinstance(node, ast.ImportFrom):
            reason = _forbidden_import(node.module, node.level)
            if reason is None and node.module is None and node.level > 0:
                # `from . import tpu` pulls the device package by name
                if any(alias.name == "tpu" for alias in node.names):
                    reason = "imports the device kernel layer (tpu/)"
            if reason:
                findings.append(ctx.finding(
                    "AM301", node,
                    f"host-only module {reason}: the host layer must "
                    "import cleanly without device dependencies",
                ))
    return findings


# ---------------------------------------------------------------------- #
# AM302 — device-phase hygiene

_SYNC_METHODS = {"item", "tolist"}
_SYNC_BUILTINS = {"int", "float", "bool", "print"}


def _device_phase_name(stmt: ast.With) -> str | None:
    for item in stmt.items:
        call = item.context_expr
        if (
            isinstance(call, ast.Call)
            and isinstance(call.func, ast.Attribute)
            and call.func.attr == "phase"
            and call.args
            and isinstance(call.args[0], ast.Constant)
            and isinstance(call.args[0].value, str)
            and "device" in call.args[0].value
        ):
            return call.args[0].value
    return None


def _check_device_phases(ctx: FileContext) -> list[Finding]:
    np_aliases = _np_aliases(ctx.tree) | {"np"}
    findings: list[Finding] = []
    for stmt in ast.walk(ctx.tree):
        if not isinstance(stmt, ast.With):
            continue
        phase = _device_phase_name(stmt)
        if phase is None:
            continue
        for node in ast.walk(stmt):
            if not isinstance(node, ast.Call):
                continue
            fname = dotted_name(node.func)
            hidden = None
            if fname and fname.split(".")[0] in np_aliases:
                hidden = f"`{fname}` copies device results to the host"
            elif fname in _SYNC_BUILTINS and node.args and not all(
                isinstance(a, ast.Constant) for a in node.args
            ):
                hidden = f"`{fname}()` blocks on a device value"
            elif (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in _SYNC_METHODS
            ):
                hidden = f"`.{node.func.attr}()` blocks on a device value"
            if hidden:
                findings.append(ctx.finding(
                    "AM302", node,
                    f"hidden host sync in device phase '{phase}': {hidden}; "
                    "move it to a host phase so the profile stays honest",
                ))
    return findings


def check(ctxs: list[FileContext], graph=None) -> list[Finding]:
    findings: list[Finding] = []
    for ctx in ctxs:
        findings += _check_imports(ctx)
        findings += _check_device_phases(ctx)
    return findings
