"""AM701 — shape-stability: no unbucketed dynamic lengths at jit dispatch.

XLA compiles one program per distinct argument-shape signature. An array
whose leading dimension is a raw ``len(batch)`` therefore costs a fresh
compile for every new batch size — the recompile storm amprof's runtime
detector (``prof.recompile.storm``, obs/prof.py) can only report after
the compile time is already burned. Every in-tree dispatch path rounds
lengths onto a power-of-two grid first (``_pow2`` in tpu/engine.py and
tpu/sync_farm.py, ``_next_pow2`` in tpu/text_engine.py), capping the
compile count at log2(maxlen) per program.

This rule is the static twin of the storm detector: it flags a
``profiled_jit``/``jax.jit`` dispatch site when an argument's dataflow
path from a dynamic length (``len()``, ``.shape``, a dynamically bounded
slice) reaches the dispatch with **no pow2/bucket helper on the path**
(dataflow.py holds the taint engine). The diagnostic prints the dataflow
chain, mirroring the ``[reachable via ...]`` chains of the call-graph
rules.

Dispatch callables are discovered structurally, package-wide:

- top-level defs decorated ``@profiled_jit("name", ...)`` (the label is
  the registered program name) or with any jit-like decorator;
- module/function-level bindings ``x = jax.jit(f)`` and
  ``x = profiled_jit("name", ...)(f)``;
- from-imports and module-alias attribute calls the call graph resolves
  to either of the above — the dispatch site and the program definition
  are usually in different modules.

Suppress a deliberately shape-dynamic dispatch with
``# amlint: disable=AM701`` and a justification.
"""
from __future__ import annotations

import ast

from .core import FileContext, Finding, dotted_name
from .dataflow import ShapeFlow
from .tracer import _decorator_statics, _is_jit_like

__all__ = ["check"]


def _program_label(fn: ast.AST) -> str | None:
    """The registered program name if ``fn`` is jit-dispatch-decorated."""
    for dec in fn.decorator_list:
        traced, _nums, _names = _decorator_statics(dec)
        if not traced:
            continue
        if isinstance(dec, ast.Call):
            leaf = (dotted_name(dec.func) or "").rsplit(".", 1)[-1]
            if leaf == "profiled_jit" and dec.args and isinstance(
                dec.args[0], ast.Constant
            ) and isinstance(dec.args[0].value, str):
                return dec.args[0].value
        return fn.name
    return None


def _binding_label(value: ast.expr) -> str | None:
    """Program label when ``value`` is a jit-dispatch factory expression:
    ``jax.jit(f)`` or ``profiled_jit("name", ...)(f)``."""
    if not isinstance(value, ast.Call):
        return None
    if _is_jit_like(value.func):
        name = dotted_name(value.func) or "jax.jit"
        if value.args and isinstance(value.args[0], ast.Name):
            return value.args[0].id
        return name
    if isinstance(value.func, ast.Call):
        leaf = (dotted_name(value.func.func) or "").rsplit(".", 1)[-1]
        if leaf == "profiled_jit":
            inner = value.func
            if inner.args and isinstance(inner.args[0], ast.Constant) and \
                    isinstance(inner.args[0].value, str):
                return inner.args[0].value
            return "profiled_jit"
    return None


def _module_dispatch(tree: ast.Module) -> dict[str, str]:
    """{local name: program label} for one module's dispatch callables."""
    out: dict[str, str] = {}
    for stmt in tree.body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            label = _program_label(stmt)
            if label is not None:
                out[stmt.name] = label
        elif isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 and \
                isinstance(stmt.targets[0], ast.Name):
            label = _binding_label(stmt.value)
            if label is not None:
                out[stmt.targets[0].id] = label
    return out


def _local_dispatch(fn: ast.AST) -> dict[str, str]:
    """Function-local ``prog = jax.jit(f)``-style bindings."""
    out: dict[str, str] = {}
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name):
            label = _binding_label(node.value)
            if label is not None:
                out[node.targets[0].id] = label
    return out


def check(ctxs: list[FileContext], graph=None) -> list[Finding]:
    findings: list[Finding] = []
    # pass 1: every module's dispatch names, keyed by module name so
    # from-imports and module aliases resolve cross-file
    dispatch_by_module: dict[str, dict[str, str]] = {}
    infos = []
    for ctx in ctxs:
        info = graph.module_for(ctx) if graph is not None else None
        infos.append((ctx, info))
        table = _module_dispatch(ctx.tree)
        if info is not None:
            dispatch_by_module[info.name] = table
        elif table:
            dispatch_by_module[ctx.path.stem] = table

    # pass 2: length-taint every function against the resolved sinks
    for ctx, info in infos:
        module_table = dispatch_by_module.get(
            info.name if info is not None else ctx.path.stem, {}
        )

        def resolver(call: ast.Call, *, _info=info, _table=module_table,
                     _local=None):
            func = call.func
            if isinstance(func, ast.Name):
                if _local and func.id in _local:
                    return _local[func.id]
                if func.id in _table:
                    return _table[func.id]
                if _info is not None:
                    imported = _info.from_imports.get(func.id)
                    if imported is not None:
                        target = dispatch_by_module.get(imported[0], {})
                        if imported[1] in target:
                            return target[imported[1]]
                return None
            name = dotted_name(func)
            if name and _info is not None and "." in name:
                root, leaf = name.split(".")[0], name.split(".")[-1]
                target_mod = _info.import_aliases.get(root)
                if target_mod is not None:
                    target = dispatch_by_module.get(target_mod, {})
                    if leaf in target:
                        return target[leaf]
            return None

        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            local = _local_dispatch(node)
            # the dispatch defs themselves are sinks, not sites: skip the
            # decorated body (its params are tracer-checked by AM2xx)
            if _program_label(node) is not None:
                continue

            def dispatch(call, _local=local, _resolver=resolver):
                return _resolver(call, _local=_local)

            def report(call, program, chain, _ctx=ctx):
                findings.append(_ctx.finding(
                    "AM701", call,
                    f"jit dispatch `{program}` fed an array-shape argument "
                    "derived from an unbucketed dynamic length — every new "
                    "length costs a fresh XLA compile (the runtime twin is "
                    "prof.recompile.storm); route the length through a "
                    "pow2/bucket helper before building the array "
                    f"[dataflow: {' -> '.join(chain)}]",
                ))

            ShapeFlow(node, dispatch, report).run()
    return findings
