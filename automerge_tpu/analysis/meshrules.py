"""AM501 — mesh data-plane hygiene: no dense per-doc Python statement
loops in mesh routing / merge-result paths.

The mesh controller sits on EVERY delivery's path: it routes a global
per-doc buffer list into per-shard sub-deliveries and merges per-shard
results back into one global result. A farm is thousands of documents of
which a delivery touches a handful, so a ``for d in range(num_docs)``
statement loop that subscripts per-doc state row by row turns an O(active)
fan-out into an O(farm) Python scan per call — the controller-side twin of
the per-row walks AM105 banned from the farm's hot phases.

The blessed shapes (what meshfarm.py itself uses):

- build a sparse active list with a comprehension
  (``active = [d for d, bufs in enumerate(per_doc) if bufs]``) and run
  statement loops over THAT;
- express whole-batch transforms as comprehensions (a comprehension
  builds its output in one pass with no per-iteration statement
  overhead, and is the documented idiom for the merge step);
- vectorize routing math with numpy (``np.add.at``, boolean masks).

Flagged: a ``for`` STATEMENT over ``range(...)`` whose body subscripts by
the loop variable — the dense per-doc scan shape. Comprehensions and
loops over sparse lists are exempt by construction.

Scope: modules whose filename stem is in ``MESH_STEMS`` (the parallel/
controller layer), plus any file carrying a ``# amlint: mesh-routing``
marker (the fixture hook, and the opt-in for future controller modules
living elsewhere).
"""
from __future__ import annotations

import ast
import re
from pathlib import Path

from .core import FileContext, Finding

#: the mesh controller modules (parallel/): routing + result-merge paths
MESH_STEMS = frozenset({"mesh", "meshfarm"})

_MARKER_RE = re.compile(r"#\s*amlint:\s*mesh-routing")


def _in_scope(ctx: FileContext) -> bool:
    return (
        Path(ctx.path).stem in MESH_STEMS
        or _MARKER_RE.search(ctx.source) is not None
    )


def _is_range_loop(node: ast.For) -> bool:
    return (
        isinstance(node.iter, ast.Call)
        and isinstance(node.iter.func, ast.Name)
        and node.iter.func.id == "range"
    )


def _subscripts_by(body, var: str) -> bool:
    for stmt in body:
        for sub in ast.walk(stmt):
            if (
                isinstance(sub, ast.Subscript)
                and isinstance(sub.slice, ast.Name)
                and sub.slice.id == var
            ):
                return True
    return False


def check(ctxs: list[FileContext], graph=None) -> list[Finding]:
    findings: list[Finding] = []
    for ctx in ctxs:
        if not _in_scope(ctx):
            continue
        for node in ast.walk(ctx.tree):
            if (
                isinstance(node, ast.For)
                and _is_range_loop(node)
                and isinstance(node.target, ast.Name)
                and _subscripts_by(node.body, node.target.id)
            ):
                findings.append(ctx.finding(
                    "AM501", node,
                    "dense per-doc `for ... in range(...)` statement loop "
                    "subscripting by the loop index in a mesh routing/"
                    "merge-result path: build a sparse active list with a "
                    "comprehension (`[d for d, bufs in enumerate(...) if "
                    "bufs]`) or vectorize with numpy so per-doc Python "
                    "touches only active docs",
                ))
    return findings
