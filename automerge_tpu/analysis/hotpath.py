"""AM105/AM106/AM107 — hot-phase hygiene: no per-row Python in the farm's
profiled hot phases, no per-byte Python in the decode hot path, no
per-change/per-op Python in the gate/transcode hot paths.

BENCH_r05 showed the merge farm spending >85% of wall time in host-side
Python that re-walks state row by row (``visibility`` + ``patch_assembly``
+ ``decode``). The fix was structural — column masks, batched
searchsorted, precomputed sort-key columns — and this rule keeps the
anti-patterns from creeping back into the modules that implement the
profiled phases:

- ``xs.sort(key=lambda ...)`` / ``sorted(xs, key=lambda ...)``: a Python
  callback per element where a precomputed, vectorisable sort-key column
  (e.g. transcode.lamport_keys) does the same work in one argsort;
- ``int(...)`` / ``bool(...)`` coercion of subscripted values inside a
  ``for``/comprehension over ``range(...)``: the classic row-at-a-time
  scan over a dense array, where a boolean mask or column gather should
  run first so per-row Python only touches rows that survive the filter.

Scope: modules whose filename stem is in ``HOT_PHASE_STEMS`` (the farm's
assembly layers), plus any file carrying a ``# amlint: hot-path`` marker.
Deliberately-cold call sites inside a hot module (per-call table builds,
debug paths) carry justified ``# amlint: disable=AM105`` suppressions.

AM106 bans the shape the vectorized decode (tpu/decode.py) replaced: a
``while``/``for`` loop that steps one byte at a time through a buffer —
a subscript of a buffer-named value (``buf``/``buffer``/``data``/...)
together with a ``+= 1`` cursor increment in the same loop body. LEB128
boundary detection is one continuation-bit mask + prefix scan; run
expansion is a record-level walk plus ``np.repeat`` — per-BYTE Python
must not creep back into decode modules. Scope: filename stems in
``DECODE_STEMS`` plus hot-path-marked files; the scalar parity oracle
(codecs.py) keeps its byte loops under justified suppressions — it IS
the reference the vector passes are tested against.

AM107 bans the shape the columnar causal gate replaced (BENCH_r07): a
``for`` STATEMENT in a hot-phase module that walks deliveries
change-by-change or ops op-by-op — a loop target named ``change``/``op``,
or iteration over a pending/applied/decoded collection, or over a
change's ``["ops"]`` list. Gate verdicts come from dep-index columns
(transcode.gate_verdicts) and op rows from cached column blocks; per-
change Python belongs only on the scalar oracle chain, whose loops carry
justified suppressions (it owns the canonical result/error for re-routed
anomalies). Comprehensions are deliberately exempt: sparse bookkeeping
builds (plan lists, per-doc dict updates) are not the quadratic shape
this rule hunts.
"""
from __future__ import annotations

import ast
from pathlib import Path

from .core import FileContext, Finding, dotted_name

#: modules implementing the profiled hot phases (gate+transcode, pack,
#: visibility, patch_assembly) plus the mesh controller layer that fans
#: deliveries across shard farms (parallel/)
HOT_PHASE_STEMS = frozenset({"farm", "transcode", "mesh", "meshfarm"})

#: modules implementing the decode hot path (AM106): the scalar codec
#: layer and the vectorized column decode
DECODE_STEMS = frozenset({"codecs", "decode"})

#: names a per-byte decode loop subscripts (the cursor walks one of these)
_BUF_NAMES = frozenset({"buf", "buffer", "data", "raw", "chunk", "payload",
                        "stream"})

_COERCIONS = {"int", "bool"}


def _in_scope(ctx: FileContext) -> bool:
    return Path(ctx.path).stem in HOT_PHASE_STEMS or ctx.hot_path_marker


def _in_decode_scope(ctx: FileContext) -> bool:
    return Path(ctx.path).stem in DECODE_STEMS or ctx.hot_path_marker


def _is_key_lambda_sort(node: ast.Call) -> str | None:
    """'sort'/'sorted' when the call passes key=lambda, else None."""
    name = None
    if isinstance(node.func, ast.Attribute) and node.func.attr == "sort":
        name = ".sort"
    else:
        fname = dotted_name(node.func)
        if fname == "sorted":
            name = "sorted"
    if name is None:
        return None
    for kw in node.keywords:
        if kw.arg == "key" and isinstance(kw.value, ast.Lambda):
            return name
    return None


def _is_range_loop(iter_node: ast.expr) -> bool:
    return (
        isinstance(iter_node, ast.Call)
        and isinstance(iter_node.func, ast.Name)
        and iter_node.func.id == "range"
    )


def _coercion_of_subscript(node: ast.Call) -> bool:
    if not (
        isinstance(node.func, ast.Name)
        and node.func.id in _COERCIONS
        and len(node.args) == 1
    ):
        return False
    return any(isinstance(sub, ast.Subscript) for sub in ast.walk(node.args[0]))


def _range_loop_bodies(tree: ast.Module):
    """Yields (report_node, body_nodes) for every range()-driven loop:
    ``for i in range(...)`` statements and range()-driven comprehensions."""
    for node in ast.walk(tree):
        if isinstance(node, ast.For) and _is_range_loop(node.iter):
            yield node, node.body
        elif isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp,
                               ast.GeneratorExp)):
            if any(_is_range_loop(gen.iter) for gen in node.generators):
                if isinstance(node, ast.DictComp):
                    yield node, [node.key, node.value]
                else:
                    yield node, [node.elt]


def _is_buffer_subscript(node: ast.Subscript) -> bool:
    base = node.value
    if isinstance(base, ast.Name):
        return base.id in _BUF_NAMES
    if isinstance(base, ast.Attribute):
        return base.attr in _BUF_NAMES
    return False


def _is_cursor_step(node: ast.AugAssign) -> bool:
    return (
        isinstance(node.op, ast.Add)
        and isinstance(node.value, ast.Constant)
        and node.value.value == 1
    )


#: loop targets that name a per-change / per-op walk
_CHANGE_TARGETS = frozenset({"change", "op"})

#: iterables holding the delivery's change stream
_CHANGE_ITERS = frozenset({"pending", "applied", "decoded", "applied_ops"})


def _is_change_loop(node: ast.For) -> bool:
    """``for`` statements that walk changes or ops one at a time: the
    target is named ``change``/``op`` (possibly inside a tuple unpack),
    the iterable is a pending/applied/decoded collection, or the
    iterable is someone's ``["ops"]`` list."""
    target = node.target
    names = []
    if isinstance(target, ast.Name):
        names = [target.id]
    elif isinstance(target, ast.Tuple):
        names = [e.id for e in target.elts if isinstance(e, ast.Name)]
    if any(n in _CHANGE_TARGETS for n in names):
        return True
    it = node.iter
    if isinstance(it, ast.Name) and it.id in _CHANGE_ITERS:
        return True
    if isinstance(it, ast.Subscript):
        sl = it.slice
        if isinstance(sl, ast.Constant) and sl.value == "ops":
            return True
    return False


def _check_change_loops(ctx: FileContext, findings: list) -> None:
    """AM107: per-change/per-op ``for`` statements in gate/transcode hot
    paths — the work belongs in batched column programs."""
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.For) and _is_change_loop(node):
            findings.append(ctx.finding(
                "AM107", node,
                "per-change/per-op Python loop in a gate/transcode hot "
                "path: compute gate verdicts from dep-index columns "
                "(transcode.gate_verdicts) and take op rows from cached "
                "column blocks — scalar-oracle loops carry justified "
                "suppressions",
            ))


def _check_byte_loops(ctx: FileContext, findings: list) -> None:
    """AM106: a while/for loop whose body both subscripts a buffer-named
    value and advances a cursor by one — the per-byte scalar decode shape
    the vectorized column passes replaced."""
    for node in ast.walk(ctx.tree):
        if not isinstance(node, (ast.While, ast.For)):
            continue
        has_subscript = False
        has_step = False
        for stmt in node.body:
            for sub in ast.walk(stmt):
                if isinstance(sub, ast.Subscript) and _is_buffer_subscript(sub):
                    has_subscript = True
                elif isinstance(sub, ast.AugAssign) and _is_cursor_step(sub):
                    has_step = True
        if has_subscript and has_step:
            findings.append(ctx.finding(
                "AM106", node,
                "per-byte decode loop in a decode hot-path module: the "
                "loop walks a buffer one byte at a time — decode the "
                "column with a masked vector pass (continuation-bit mask "
                "+ prefix scan, record-level run expansion; see "
                "tpu/decode.py)",
            ))


def check(ctxs: list[FileContext], graph=None) -> list[Finding]:
    findings: list[Finding] = []
    for ctx in ctxs:
        if _in_decode_scope(ctx):
            _check_byte_loops(ctx, findings)
        if not _in_scope(ctx):
            continue
        _check_change_loops(ctx, findings)
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call):
                spelling = _is_key_lambda_sort(node)
                if spelling is not None:
                    findings.append(ctx.finding(
                        "AM105", node,
                        f"`{spelling}(key=lambda ...)` in a hot-phase "
                        "module: a Python callback runs per element — "
                        "precompute a vectorisable sort-key column (e.g. "
                        "transcode.lamport_keys) and argsort it",
                    ))
        for loop, body in _range_loop_bodies(ctx.tree):
            for stmt in body:
                for sub in ast.walk(stmt):
                    if isinstance(sub, ast.Call) and _coercion_of_subscript(sub):
                        findings.append(ctx.finding(
                            "AM105", sub,
                            "per-row `int()`/`bool()` coercion inside a "
                            "range()-indexed loop in a hot-phase module: "
                            "filter with boolean column masks first so "
                            "per-row Python only touches surviving rows",
                        ))
    return findings
