"""AM401/AM402/AM403/AM404 — data-plane hygiene: classifiable errors,
injectable time, non-blocking serve loops, taxonomy-only wire codecs.

The fault-isolation layer (tpu/farm.py) routes per-document failures by
taxonomy class (automerge_tpu/errors.py): ``DecodeError`` means re-request
the bytes, ``CausalityError`` means distrust the peer, ``PackingLimitError``
means shed/split — and the obs quarantine counters are dimensioned by
``error_kind``. A bare ``ValueError``/``TypeError`` raised anywhere on the
data plane collapses into the ``other`` bucket and strips the isolation
layer of that signal, so the data-plane modules (codecs, columnar, opset,
sync, farm, rga, transcode, engines, sync drivers) must raise taxonomy
errors.

Scope: modules whose filename stem is in ``DATA_PLANE_STEMS``, plus any
file carrying an ``# amlint: error-taxonomy`` marker (how the test fixtures
opt in). The frontend and other API-surface modules are deliberately out of
scope — their errors face the local programmer, not untrusted traffic.

Deliberate bare raises (argument-type validation, API-usage errors,
internal invariants that indicate a bug rather than bad input) stay bare
with a justified ``# amlint: disable=AM401`` suppression.

AM402 guards the *time* axis of the same determinism story: the sync
supervision layer (sync_session.py) has retransmission timeouts, backoff
jitter and a watchdog — the first time-based control flow in the stack.
A direct ``time.time()``/``time.sleep()``/``random.random()`` call in a
sync data-plane module makes that control flow unreplayable (the chaos
soak suite cannot reproduce a failure schedule) and couples tests to wall
clocks. Those modules (``SYNC_DATA_PLANE_STEMS``, plus files marked
``# amlint: sync-data-plane``) must take an injected clock callable and a
``random.Random`` instance; constructing an RNG (``random.Random(seed)``,
``random.SystemRandom()``) is allowed — that *is* the injection point —
and the one real-time default carries a justified suppression.

AM403 guards the serving front door (automerge_tpu/serve): its core runs
inside an event loop (asyncio or a simulated-time harness), where ONE
blocking call stalls every client channel at once. ``time.sleep`` (yield
with ``await asyncio.sleep`` or let the harness advance the clock), bare
``socket`` construction (asyncio owns the transports), and synchronous
device readbacks (``jax.device_get``/``block_until_ready`` — the batcher's
single flush dispatch is the only place device latency may be paid, with a
justified suppression) are all banned in serve modules (any file under a
``serve/`` directory, plus files marked ``# amlint: serve-event-loop``).

AM404 tightens AM401 for the sync v2 wire codec (``sync_v2.py``,
``tpu/fingerprint.py``, plus files carrying the ``v2-wire-codec`` marker):
the session layer's negotiated-fallback dispatch catches exactly
``SyncProtocolError`` — a v2 codec path that raises ANY class outside
``automerge_tpu.errors`` (``RuntimeError``, ``KeyError``, a homegrown
exception) would sail past the fallback handler and kill the channel
instead of downgrading it to v1. So in v2 wire-codec scope every ``raise``
of an exception *class* must name something imported from
``automerge_tpu.errors`` — not just "no bare ValueError" (AM401) but
"nothing outside the taxonomy at all". Re-raising a caught variable is
fine; deliberate internal-invariant raises carry a justified
``# amlint: disable=AM404`` suppression.

AM403 is *transitively* enforced: beyond the direct per-file walk, the
call graph (graph.py) BFS-reaches every function a serve-scope function
can call — across files, through from-imports and inferable method
receivers, with bounded depth — and flags blocking calls found in those
helpers too, printing the discovery chain (``[reachable via
batcher.flush -> engine.drain -> ...]``). A helper that blocks is exactly
as fatal to the event loop as blocking inline; the suppression (or the
fix) belongs at the blocking call site, which is where the finding lands.
"""
from __future__ import annotations

import ast
import re
from pathlib import Path

from .core import FileContext, Finding, dotted_name
from .graph import format_chain

#: data-plane module stems the rule applies to (serve/ modules face the
#: same untrusted traffic the farm does: admission decisions and shed
#: accounting key off error_kind too)
DATA_PLANE_STEMS = frozenset({
    "codecs", "columnar", "opset", "sync", "sync_v2", "farm", "rga",
    "sync_farm", "sync_batch", "sync_session", "fingerprint", "transcode",
    "engine", "text_engine", "server", "batcher", "loadgen", "meshfarm",
})

_MARKER_RE = re.compile(r"#\s*amlint:\s*error-taxonomy")

#: the stdlib classes whose bare raise loses the error_kind dimension
_BARE = {"ValueError", "TypeError"}

#: sync data-plane module stems AM402 applies to (the modules whose
#: control flow the chaos suite must be able to replay deterministically;
#: the serve layer runs whole fleets in simulated time, so it is held to
#: the same injectable-clock discipline)
SYNC_DATA_PLANE_STEMS = frozenset({
    "sync", "sync_v2", "sync_session", "sync_farm", "sync_batch",
    "fingerprint", "server", "batcher", "loadgen",
})

#: v2 wire-codec module stems AM404 applies to (the modules whose raises
#: the session fallback dispatch must be able to classify)
V2_WIRE_CODEC_STEMS = frozenset({"sync_v2", "fingerprint"})

_V2_MARKER_RE = re.compile(r"#\s*amlint:\s*v2-wire-codec")

_SYNC_MARKER_RE = re.compile(r"#\s*amlint:\s*sync-data-plane")

_SERVE_MARKER_RE = re.compile(r"#\s*amlint:\s*serve-event-loop")

#: calls that block the serving event loop (AM403): sleeps, bare socket
#: construction/dialing, and synchronous device readbacks. Matched on the
#: dotted prefix (``socket.``) or the exact name; ``block_until_ready`` /
#: ``device_get`` are also caught as method/attr tails because the array
#: handle they block on can be any local name.
_BLOCKING_CALLS = frozenset({"time.sleep", "jax.device_get"})
_BLOCKING_PREFIXES = ("socket.",)
_BLOCKING_ATTRS = frozenset({"block_until_ready", "device_get"})

#: wall-clock reads and sleeps that make supervised control flow
#: unreplayable (call sites must take an injected clock instead)
_CLOCK_CALLS = frozenset({
    "time.time", "time.time_ns", "time.sleep", "time.monotonic",
    "time.monotonic_ns", "time.perf_counter", "time.perf_counter_ns",
})

#: random.* attributes that are NOT the module-global RNG: constructing an
#: instance is the injection pattern the rule demands
_RNG_CONSTRUCTORS = frozenset({"Random", "SystemRandom"})


def _in_scope(ctx: FileContext) -> bool:
    return (
        Path(ctx.path).stem in DATA_PLANE_STEMS
        or _MARKER_RE.search(ctx.source) is not None
    )


def _in_sync_scope(ctx: FileContext) -> bool:
    return (
        Path(ctx.path).stem in SYNC_DATA_PLANE_STEMS
        or _SYNC_MARKER_RE.search(ctx.source) is not None
    )


def _in_serve_scope(ctx: FileContext) -> bool:
    return (
        "serve" in Path(ctx.path).parts
        or _SERVE_MARKER_RE.search(ctx.source) is not None
    )


def _in_v2_codec_scope(ctx: FileContext) -> bool:
    return (
        Path(ctx.path).stem in V2_WIRE_CODEC_STEMS
        or _V2_MARKER_RE.search(ctx.source) is not None
    )


def _taxonomy_imports(tree: ast.Module) -> set[str]:
    """Local names bound by ``from automerge_tpu.errors import ...`` (or the
    relative ``from .errors import ...`` / ``from ..errors import ...``
    spellings) — the only exception classes AM404 permits a v2 wire-codec
    module to raise."""
    names: set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.ImportFrom) or node.module is None:
            continue
        if node.module != "errors" and not node.module.endswith(".errors"):
            continue
        if node.module == "errors" and node.level == 0:
            continue  # an unrelated top-level `errors` package
        for alias in node.names:
            names.add(alias.asname or alias.name)
    return names


def _check_am404(ctx: FileContext, findings: list[Finding]) -> None:
    taxonomy = _taxonomy_imports(ctx.tree)
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Raise) or node.exc is None:
            continue
        exc = node.exc
        if isinstance(exc, ast.Call):
            exc = exc.func
        if not isinstance(exc, ast.Name):
            continue
        # Only exception *classes* are policed; re-raising a caught
        # lowercase variable (`raise exc`) is the wrap-and-rethrow idiom
        # the taxonomy itself uses.
        if not exc.id.endswith(("Error", "Exception")):
            continue
        if exc.id in taxonomy:
            continue
        findings.append(ctx.finding(
            "AM404", node,
            f"{exc.id} raised in a v2 wire-codec module: the session "
            "layer's negotiated fallback catches exactly the taxonomy "
            "(SyncProtocolError and friends from automerge_tpu.errors) — "
            "any other class sails past the fallback dispatch and kills "
            "the channel instead of downgrading it to v1; raise a "
            "taxonomy error, or justify-suppress a deliberate "
            "internal-invariant raise",
        ))


def _time_imports(tree: ast.Module) -> set[str]:
    """Local names bound by ``from time import ...``/``from random import
    ...`` to the banned callables (so aliased direct calls are caught)."""
    names: set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.ImportFrom) or node.module not in (
            "time", "random"
        ):
            continue
        for alias in node.names:
            if node.module == "time":
                if f"time.{alias.name}" in _CLOCK_CALLS:
                    names.add(alias.asname or alias.name)
            elif alias.name not in _RNG_CONSTRUCTORS:
                names.add(alias.asname or alias.name)
    return names


def _check_am402(ctx: FileContext, findings: list[Finding]) -> None:
    aliased = _time_imports(ctx.tree)
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        name = dotted_name(node.func)
        if name is None:
            continue
        banned = (
            name in _CLOCK_CALLS
            or (
                name.startswith("random.")
                and name.split(".", 1)[1] not in _RNG_CONSTRUCTORS
            )
            or name in aliased
        )
        if banned:
            findings.append(ctx.finding(
                "AM402", node,
                f"direct {name}() call in a sync data-plane module: "
                "retransmission timeouts, backoff jitter and watchdog "
                "decisions must be driven by an injected clock callable "
                "and random.Random instance so the chaos suite can replay "
                "them deterministically; suppress with a justification at "
                "the single real-time default",
            ))


def _sleep_aliases(tree: ast.Module) -> set[str]:
    """Local names bound to ``time.sleep`` via ``from time import ...``."""
    names: set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.ImportFrom) or node.module != "time":
            continue
        for alias in node.names:
            if alias.name == "sleep":
                names.add(alias.asname or alias.name)
    return names


def _blocking_name(name: str, sleep_names: set[str]) -> bool:
    tail = name.rsplit(".", 1)[-1]
    return (
        name in _BLOCKING_CALLS
        or name.startswith(_BLOCKING_PREFIXES)
        or tail in _BLOCKING_ATTRS
        or name in sleep_names
    )


def _check_am403(ctx: FileContext, findings: list[Finding]) -> None:
    sleep_names = _sleep_aliases(ctx.tree)
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        name = dotted_name(node.func)
        if name is None:
            continue
        if _blocking_name(name, sleep_names):
            findings.append(ctx.finding(
                "AM403", node,
                f"blocking {name}() call in serve event-loop code: one "
                "blocked call stalls every client channel at once — yield "
                "with `await asyncio.sleep`, let the injected clock/harness "
                "advance time, hand transports to asyncio, and pay device "
                "readback latency only at the batcher's flush dispatch "
                "(suppress there with a justification)",
            ))


def _check_am403_transitive(ctxs: list[FileContext], graph,
                            findings: list[Finding]) -> None:
    """Blocking calls in helpers the serve layer reaches through the call
    graph. Serve-scope files themselves are owned by the direct walk — the
    transitive pass only reports in files *outside* serve scope, so no call
    site is ever double-flagged."""
    if graph is None:
        return
    roots = []
    serve_ctx_ids: set[int] = set()
    for ctx in ctxs:
        if not _in_serve_scope(ctx):
            continue
        serve_ctx_ids.add(id(ctx))
        mod = graph.module_for(ctx)
        if mod is not None:
            roots.extend(mod.functions.values())
    if not roots:
        return
    sleep_cache: dict[int, set[str]] = {}
    emitted: set[tuple[str, int, int]] = set()
    for fi, chain in graph.reachable(roots).values():
        if id(fi.ctx) in serve_ctx_ids:
            continue
        if id(fi.ctx) not in sleep_cache:
            sleep_cache[id(fi.ctx)] = _sleep_aliases(fi.ctx.tree)
        sleep_names = sleep_cache[id(fi.ctx)]
        for node in ast.walk(fi.node):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func)
            if name is None or not _blocking_name(name, sleep_names):
                continue
            key = (str(fi.ctx.path), node.lineno, node.col_offset)
            if key in emitted:
                continue
            emitted.add(key)
            findings.append(fi.ctx.finding(
                "AM403", node,
                f"blocking {name}() call reachable from serve event-loop "
                "code: a helper that blocks stalls every client channel "
                "exactly like blocking inline — yield, take an injected "
                "clock, or justify-suppress at this call site"
                + format_chain(chain),
            ))


def check(ctxs: list[FileContext], graph=None) -> list[Finding]:
    findings: list[Finding] = []
    _check_am403_transitive(ctxs, graph, findings)
    for ctx in ctxs:
        if _in_sync_scope(ctx):
            _check_am402(ctx, findings)
        if _in_serve_scope(ctx):
            _check_am403(ctx, findings)
        if _in_v2_codec_scope(ctx):
            _check_am404(ctx, findings)
        if not _in_scope(ctx):
            continue
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Raise) or node.exc is None:
                continue
            exc = node.exc
            if isinstance(exc, ast.Call):
                exc = exc.func
            if isinstance(exc, ast.Name) and exc.id in _BARE:
                findings.append(ctx.finding(
                    "AM401", node,
                    f"bare {exc.id} raised in a data-plane module: raise a "
                    "taxonomy error from automerge_tpu.errors (DecodeError/"
                    "ChecksumError/CausalityError/PackingLimitError/"
                    "SyncProtocolError/...) so the fault-isolation layer "
                    "and the error_kind obs dimension can classify it; "
                    "suppress with a justification where a bare raise is "
                    "deliberate",
                ))
    return findings
