"""AM401 — error-taxonomy hygiene: data-plane modules raise classifiable errors.

The fault-isolation layer (tpu/farm.py) routes per-document failures by
taxonomy class (automerge_tpu/errors.py): ``DecodeError`` means re-request
the bytes, ``CausalityError`` means distrust the peer, ``PackingLimitError``
means shed/split — and the obs quarantine counters are dimensioned by
``error_kind``. A bare ``ValueError``/``TypeError`` raised anywhere on the
data plane collapses into the ``other`` bucket and strips the isolation
layer of that signal, so the data-plane modules (codecs, columnar, opset,
sync, farm, rga, transcode, engines, sync drivers) must raise taxonomy
errors.

Scope: modules whose filename stem is in ``DATA_PLANE_STEMS``, plus any
file carrying an ``# amlint: error-taxonomy`` marker (how the test fixtures
opt in). The frontend and other API-surface modules are deliberately out of
scope — their errors face the local programmer, not untrusted traffic.

Deliberate bare raises (argument-type validation, API-usage errors,
internal invariants that indicate a bug rather than bad input) stay bare
with a justified ``# amlint: disable=AM401`` suppression.
"""
from __future__ import annotations

import ast
import re
from pathlib import Path

from .core import FileContext, Finding

#: data-plane module stems the rule applies to
DATA_PLANE_STEMS = frozenset({
    "codecs", "columnar", "opset", "sync", "farm", "rga",
    "sync_farm", "sync_batch", "transcode", "engine", "text_engine",
})

_MARKER_RE = re.compile(r"#\s*amlint:\s*error-taxonomy")

#: the stdlib classes whose bare raise loses the error_kind dimension
_BARE = {"ValueError", "TypeError"}


def _in_scope(ctx: FileContext) -> bool:
    return (
        Path(ctx.path).stem in DATA_PLANE_STEMS
        or _MARKER_RE.search(ctx.source) is not None
    )


def check(ctxs: list[FileContext]) -> list[Finding]:
    findings: list[Finding] = []
    for ctx in ctxs:
        if not _in_scope(ctx):
            continue
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Raise) or node.exc is None:
                continue
            exc = node.exc
            if isinstance(exc, ast.Call):
                exc = exc.func
            if isinstance(exc, ast.Name) and exc.id in _BARE:
                findings.append(ctx.finding(
                    "AM401", node,
                    f"bare {exc.id} raised in a data-plane module: raise a "
                    "taxonomy error from automerge_tpu.errors (DecodeError/"
                    "ChecksumError/CausalityError/PackingLimitError/"
                    "SyncProtocolError/...) so the fault-isolation layer "
                    "and the error_kind obs dimension can classify it; "
                    "suppress with a justification where a bare raise is "
                    "deliberate",
                ))
    return findings
