"""AM601 — durability-plane write discipline: all durable bytes go
through the atomic/checksummed writer.

The store tier's whole crash-consistency argument rests on two write
primitives and nothing else:

1. ``store.atomic.atomic_write`` — tmp + fsync + ``os.replace`` for
   files replaced as a unit (manifests, cold chunks, sidecars, black
   boxes). The rename is the commit point; a crash leaves old or new,
   never a torn mix.
2. the WAL's checksummed append handle — every appended frame carries
   ``length + sha256``, so recovery can prove exactly where a torn write
   starts and truncate there.

A bare ``open(path, "w"/"wb"/"a"/...)`` or ``os.write`` anywhere else on
the durability plane is a write the recovery scan cannot reason about: no
checksum to verify, no rename to anchor the commit point, and a crash
mid-write silently persists a half-state the next open will trust. That
is precisely the corruption class the crash-point sweep
(tests/test_store.py) exists to rule out, so the rule closes the hole
statically.

Flagged in scope: ``open()`` calls whose mode is write-capable (contains
``w``, ``a``, ``x`` or ``+``) or not statically known, and raw descriptor
writes (``os.write``/``os.pwrite``/``os.writev``). Reads are free.

Scope: modules under a ``store`` package directory, plus any file
carrying an ``# amlint: durability-plane`` marker (the fixture hook, and
the opt-in for durable artifacts written outside the store tree). The
two blessed primitives above are themselves in scope and carry justified
``# amlint: disable=AM601`` suppressions — the escape hatch is the
documented pattern for "this raw handle IS the checksummed writer".
"""
from __future__ import annotations

import ast
import re
from pathlib import Path

from .core import FileContext, Finding, dotted_name

_MARKER_RE = re.compile(r"#\s*amlint:\s*durability-plane\b")

#: raw descriptor writes that bypass both blessed primitives
RAW_WRITERS = frozenset({"os.write", "os.pwrite", "os.writev"})

_WRITE_MODE = re.compile(r"[wax+]")


def _in_scope(ctx: FileContext) -> bool:
    return (
        "store" in Path(ctx.path).parts
        or _MARKER_RE.search(ctx.source) is not None
    )


def _open_mode(node: ast.Call):
    """The mode argument of an ``open()`` call: its literal value, None
    when omitted (read mode), or Ellipsis when not statically known."""
    mode = node.args[1] if len(node.args) > 1 else None
    if mode is None:
        for kw in node.keywords:
            if kw.arg == "mode":
                mode = kw.value
                break
    if mode is None:
        return None
    if isinstance(mode, ast.Constant) and isinstance(mode.value, str):
        return mode.value
    return ...


def check(ctxs: list[FileContext], graph=None) -> list[Finding]:
    findings: list[Finding] = []
    for ctx in ctxs:
        if not _in_scope(ctx):
            continue
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func)
            if name == "open":
                mode = _open_mode(node)
                if mode is None:
                    continue
                if mode is ... or _WRITE_MODE.search(mode):
                    shown = "<dynamic>" if mode is ... else repr(mode)
                    findings.append(ctx.finding(
                        "AM601", node,
                        f"bare open(..., {shown}) in a durability-plane "
                        f"module: recovery cannot reason about this write "
                        f"(no checksum, no rename commit point) — go "
                        f"through store.atomic.atomic_write or the WAL's "
                        f"checksummed appender, or justify the raw handle "
                        f"with a suppression",
                    ))
            elif name in RAW_WRITERS:
                findings.append(ctx.finding(
                    "AM601", node,
                    f"raw descriptor write {name}() in a durability-plane "
                    f"module bypasses the atomic/checksummed writer — a "
                    f"crash mid-write persists an unverifiable half-state",
                ))
    return findings
