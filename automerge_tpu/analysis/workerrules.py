"""AM502 + AM305 — mesh worker hygiene: no controller imports, no
process-global registry access, no exposition-layer telemetry in
worker-executed modules.

A mesh worker (parallel/workers.py) is spawned — not forked — so the
child re-imports its module tree under a pristine interpreter. Two bug
classes break that isolation and both have bitten multi-process serving
stacks:

1. **Controller imports.** A worker module that imports the controller
   layer (``parallel/meshfarm.py`` or anything under ``serve/``) drags
   the whole fan-in/routing machinery — and, transitively, its inline
   thread pool and env mutation — into every spawned child. Beyond the
   startup cost, it invites the worker to call controller entry points
   that assume they own the routing arrays, turning a one-directional
   pipe protocol into shared-state spaghetti.
2. **Process-global registry access.** ``get_metrics()``/``get_flight()``
   and friends hand back *per-process* singletons. Code written for the
   controller that reaches for them from a worker silently records into
   the child's registry and the numbers never surface — the classic
   "metrics vanish under the process backend" failure. Worker code must
   either receive its sinks explicitly or, where it deliberately uses
   the worker-process singleton as the shipping buffer (the one blessed
   pattern: record locally, ship ``diff_frames`` deltas over the pipe),
   carry a justified suppression saying so.

Flagged in scope:

- AM502: ``import``/``from ... import`` whose module path contains a
  controller-only segment (``meshfarm`` or ``serve``), or that imports
  such a module by name from a package;
- AM502: importing or calling a process-global registry accessor
  (``get_metrics``, ``get_flight``, ``get_amscope``, ``get_trace``,
  ``get_profile``).
- AM305: reaching the telemetry exposition/fan-in layer — importing
  ``obs.export`` (or any of ``render_exposition`` /
  ``serve_exposition`` / ``snapshot_record`` / ``SnapshotWriter`` by
  name), calling one of those, or importing/calling ``get_flight``.
  A worker's telemetry leaves its process exactly three ways, all
  shipping-buffer shaped: metric ``diff_frames`` deltas on the pipe,
  ``FlightRecorder.ship()`` event tails on the pipe, and the bounded
  black-box file for crash forensics. Exposing a worker's own registry
  on an exposition page (or snapshotting it to JSONL) publishes numbers
  the controller never sees — the split-brain telemetry bug. The one
  blessed pattern (the worker's own singleton AS the shipping buffer)
  carries a justified ``# amlint: disable=AM502,AM305`` suppression.

Scope (both rules): modules whose filename stem is in ``WORKER_STEMS``,
plus any file carrying a ``# amlint: mesh-worker`` marker (the fixture
hook, and the opt-in for future worker-executed modules living
elsewhere).

Both rules are *transitively* enforced: beyond the direct per-statement
walk, the module-import closure (graph.import_closure, bounded depth)
is checked — a worker module that imports an innocent helper which in
turn imports ``meshfarm``/``serve`` (AM502) or the ``obs.export``
exposition layer (AM305) drags the same machinery into every spawned
child, two hops removed. The finding anchors on the *first-hop* import
statement in the worker module (that line owns the fix) and prints the
module chain (``[reachable via workers -> helper -> meshfarm]``).
Direct edges (chain length 2) are owned by the direct walk and never
double-flagged.
"""
from __future__ import annotations

import ast
import re
from pathlib import Path

from .core import FileContext, Finding, dotted_name
from .graph import format_chain

#: modules whose code executes inside spawned mesh worker processes
WORKER_STEMS = frozenset({"workers"})

_MARKER_RE = re.compile(r"#\s*amlint:\s*mesh-worker\b")

#: module-path segments that mark a controller-only import
CONTROLLER_SEGMENTS = frozenset({"meshfarm", "serve"})

#: process-global registry accessors (obs + profiling singletons)
GLOBAL_ACCESSORS = frozenset({
    "get_metrics", "get_flight", "get_amscope", "get_trace", "get_profile",
    "get_observatory",
})

#: exposition/fan-in layer names a worker must never touch (AM305):
#: publishing a worker's own registry bypasses the shipping buffer
EXPOSITION_NAMES = frozenset({
    "render_exposition", "serve_exposition", "snapshot_record",
    "SnapshotWriter",
})


def _in_scope(ctx: FileContext) -> bool:
    return (
        Path(ctx.path).stem in WORKER_STEMS
        or _MARKER_RE.search(ctx.source) is not None
    )


def _controller_import(node: ast.AST) -> bool:
    if isinstance(node, ast.Import):
        return any(
            CONTROLLER_SEGMENTS & set(alias.name.split("."))
            for alias in node.names
        )
    if isinstance(node, ast.ImportFrom):
        if CONTROLLER_SEGMENTS & set((node.module or "").split(".")):
            return True
        # `from . import meshfarm` / `from ..serve import batcher` style
        return any(alias.name in CONTROLLER_SEGMENTS for alias in node.names)
    return False


def _imported_accessors(node: ast.AST) -> set[str]:
    if isinstance(node, ast.ImportFrom):
        return GLOBAL_ACCESSORS & {alias.name for alias in node.names}
    return set()


def _exposition_import(node: ast.AST) -> set[str]:
    """Exposition-layer names this import drags into a worker module:
    the ``obs.export`` module itself, or any ``EXPOSITION_NAMES`` member
    imported by name."""
    if isinstance(node, ast.Import):
        return {
            alias.name for alias in node.names
            if "export" in alias.name.split(".")
        }
    if isinstance(node, ast.ImportFrom):
        if "export" in (node.module or "").split("."):
            return {node.module or "export"}
        return EXPOSITION_NAMES & {alias.name for alias in node.names} | {
            alias.name for alias in node.names if alias.name == "export"
        }
    return set()


def _check_transitive(ctx: FileContext, graph,
                      findings: list[Finding]) -> None:
    """Controller/exposition modules reached through the import closure.
    Chain length 2 is a direct import — the per-statement walk owns it."""
    if graph is None:
        return
    mod = graph.module_for(ctx)
    if mod is None:
        return
    for target, (chain, anchor) in sorted(graph.import_closure(mod.name).items()):
        if len(chain) <= 2:
            continue
        short = tuple(name.rsplit(".", 1)[-1] for name in chain)
        parts = set(target.split("."))
        if CONTROLLER_SEGMENTS & parts:
            findings.append(ctx.finding(
                "AM502", anchor,
                f"worker-executed module transitively imports the mesh "
                f"controller layer ({target}): this import drags the "
                "routing/fan-in machinery into every spawned child — break "
                "the chain at this line or move the helper out of the "
                "controller's import graph" + format_chain(short),
            ))
        elif "export" in parts:
            findings.append(ctx.finding(
                "AM305", anchor,
                f"worker-executed module transitively imports the telemetry "
                f"exposition layer ({target}): a worker must not publish "
                "its own registry — telemetry ships over the pipe or the "
                "black-box file only; break the chain at this line"
                + format_chain(short),
            ))


def check(ctxs: list[FileContext], graph=None) -> list[Finding]:
    findings: list[Finding] = []
    for ctx in ctxs:
        if not _in_scope(ctx):
            continue
        _check_transitive(ctx, graph, findings)
        for node in ast.walk(ctx.tree):
            if _controller_import(node):
                findings.append(ctx.finding(
                    "AM502", node,
                    "worker-executed module imports the mesh controller "
                    "layer (meshfarm/serve): workers speak the pipe "
                    "protocol only — the controller owns routing, fan-in "
                    "and respawn policy",
                ))
                continue
            imported = _imported_accessors(node)
            if imported:
                findings.append(ctx.finding(
                    "AM502", node,
                    f"worker-executed module imports process-global "
                    f"registry accessor(s) {sorted(imported)}: a worker's "
                    f"singletons are invisible to the controller — inject "
                    f"sinks explicitly, or justify the record-locally/"
                    f"ship-deltas pattern with a suppression",
                ))
                if "get_flight" in imported:
                    findings.append(ctx.finding(
                        "AM305", node,
                        "worker-executed module imports get_flight: worker "
                        "flight events leave the process only as shipped "
                        "tails (FlightRecorder.ship() over the pipe) or "
                        "the black-box file — justify the shipping-buffer "
                        "pattern with a suppression",
                    ))
                continue
            exposition = _exposition_import(node)
            if exposition:
                findings.append(ctx.finding(
                    "AM305", node,
                    f"worker-executed module imports the telemetry "
                    f"exposition layer ({sorted(exposition)}): exposing a "
                    f"worker's own registry publishes numbers the "
                    f"controller never sees — telemetry ships over the "
                    f"pipe (metric deltas + flight tails) or the "
                    f"black-box file only",
                ))
                continue
            if isinstance(node, ast.Call):
                name = dotted_name(node.func)
                leaf = name.rsplit(".", 1)[-1] if name else None
                if leaf in GLOBAL_ACCESSORS:
                    findings.append(ctx.finding(
                        "AM502", node,
                        f"worker-executed module calls process-global "
                        f"registry accessor {leaf}(): records land in the "
                        f"worker's own singleton and never surface — "
                        f"inject sinks explicitly, or justify the "
                        f"record-locally/ship-deltas pattern with a "
                        f"suppression",
                    ))
                if leaf == "get_flight":
                    findings.append(ctx.finding(
                        "AM305", node,
                        "worker-executed module calls get_flight(): worker "
                        "flight events leave the process only as shipped "
                        "tails or the black-box file — justify the "
                        "shipping-buffer pattern with a suppression",
                    ))
                elif leaf in EXPOSITION_NAMES:
                    findings.append(ctx.finding(
                        "AM305", node,
                        f"worker-executed module calls exposition-layer "
                        f"{leaf}(): a worker must not publish its own "
                        f"registry — telemetry ships over the pipe or the "
                        f"black-box file only",
                    ))
    return findings
