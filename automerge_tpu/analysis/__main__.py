"""CLI entry point: ``python -m automerge_tpu.analysis [paths...]``.

Exit codes: 0 = no unsuppressed findings, 1 = findings, 2 = bad usage.
"""
from __future__ import annotations

import argparse
import sys

from . import RULES, default_target, format_report, run_analysis


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m automerge_tpu.analysis",
        description="amlint: packing-invariant, tracer-safety and "
                    "host/device boundary checks for automerge_tpu",
    )
    parser.add_argument(
        "paths", nargs="*",
        help="files or directories to analyze (default: the installed "
             "automerge_tpu package)",
    )
    parser.add_argument(
        "--list-rules", action="store_true",
        help="print the rule catalog and exit",
    )
    parser.add_argument(
        "--show-suppressed", action="store_true",
        help="include suppressed findings in the report (they do not "
             "affect the exit code)",
    )
    parser.add_argument(
        "-q", "--quiet", action="store_true",
        help="suppress the report; exit code only",
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule_id, (family, summary) in sorted(RULES.items()):
            print(f"{rule_id}  [{family:8s}] {summary}")
        return 0

    paths = args.paths or [str(default_target())]
    findings = run_analysis(paths, include_suppressed=args.show_suppressed)
    active = [f for f in findings if not f.suppressed]
    if not args.quiet:
        print(format_report(findings))
    return 1 if active else 0


if __name__ == "__main__":
    sys.exit(main())
