"""CLI entry point: ``python -m automerge_tpu.analysis [paths...]``.

Exit codes (pinned, tested): 0 = no unsuppressed findings, 1 = findings,
2 = bad usage (unknown rule id in ``--select`` or a suppression
directive, unreadable path, bad ``--changed`` ref). Usage errors print
one line to stderr — never a traceback.

``--changed <git-ref>`` is the incremental mode: only files changed
since ``ref`` (plus untracked files), *widened* to every scanned module
that transitively imports a changed one — reachability rules anchored in
an importer can produce findings in the changed file. When the import
graph says a changed module is reachable from a rule-scoped module (the
pipe-protocol endpoints ``workers``/``meshfarm``, or anything under
``serve/``), the whole-program contracts may shift and the scan falls
back to the full file set; the chosen mode is announced on stderr.
"""
from __future__ import annotations

import argparse
import json
import subprocess
import sys
from pathlib import Path

from . import (RULES, CallGraph, UsageError, default_target, format_report,
               run_analysis)
from .core import FileContext, collect_files
from .graph import module_name
from .protorules import PROTOCOL_STEMS
from .workerrules import WORKER_STEMS


def _parse_select(spec: str) -> set[str]:
    ids = {part.strip() for part in spec.split(",") if part.strip()}
    if not ids:
        raise UsageError("--select: no rule ids given")
    unknown = sorted(ids - set(RULES))
    if unknown:
        raise UsageError(
            f"--select: unknown rule id(s) {', '.join(unknown)} "
            f"(see --list-rules)"
        )
    return ids


def _changed_files(ref: str) -> list[Path]:
    """Files changed since ``ref`` plus untracked files, as absolute
    paths. Any git failure is a usage error (bad ref, not a repo)."""
    def run(*argv: str) -> list[str]:
        proc = subprocess.run(
            ["git", *argv], capture_output=True, text=True
        )
        if proc.returncode != 0:
            detail = (proc.stderr or proc.stdout).strip().splitlines()
            raise UsageError(
                f"--changed {ref}: git {argv[0]} failed: "
                f"{detail[0] if detail else 'unknown error'}"
            )
        return [line for line in proc.stdout.splitlines() if line.strip()]

    top = Path(run("rev-parse", "--show-toplevel")[0])
    names = run("diff", "--name-only", ref, "--")
    names += run("ls-files", "--others", "--exclude-standard")
    out = []
    for name in names:
        p = top / name
        if p.suffix == ".py" and p.exists():
            out.append(p.resolve())
    return sorted(set(out))


def _rule_scoped(modname: str) -> bool:
    """Modules that anchor whole-program contracts: the pipe-protocol
    endpoints and the serve event-loop roots."""
    parts = set(modname.split("."))
    return bool(parts & (PROTOCOL_STEMS | WORKER_STEMS)) or "serve" in parts


def _resolve_changed(ref: str, paths: list[str]) -> tuple[list[str], str]:
    """The file list ``--changed ref`` should lint, plus a one-line mode
    note for stderr. Empty list = nothing to lint."""
    changed = set(_changed_files(ref))
    pairs = collect_files([Path(p) for p in paths])
    in_scan = {path: display for path, display in pairs}
    changed_in_scan = sorted(p for p in changed if p in in_scan)
    if not changed_in_scan:
        return [], "no changed python files in the scan set"

    ctxs = []
    for path, display in pairs:
        try:
            ctxs.append(FileContext(path, display))
        except Exception:
            # unparseable files still get their AM000 from run_analysis
            # if they end up in the scan list
            continue
    graph = CallGraph(ctxs)
    changed_mods = {module_name(p) for p in changed_in_scan}
    importers = graph.importers_closure(changed_mods)
    scoped = sorted(m for m in (importers | changed_mods) if _rule_scoped(m))
    if scoped:
        return [display for _path, display in pairs], (
            f"full scan: changed module(s) sit in the import graph of "
            f"rule-scoped module(s) ({', '.join(scoped[:3])})"
        )
    keep = [
        display for path, display in pairs
        if path in changed or module_name(path) in importers
    ]
    return keep, (
        f"incremental: {len(keep)} of {len(pairs)} file(s) "
        f"(changed + transitive importers)"
    )


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m automerge_tpu.analysis",
        description="amlint: packing-invariant, tracer-safety and "
                    "host/device boundary checks for automerge_tpu",
    )
    parser.add_argument(
        "paths", nargs="*",
        help="files or directories to analyze (default: the installed "
             "automerge_tpu package)",
    )
    parser.add_argument(
        "--list-rules", action="store_true",
        help="print the rule catalog and exit",
    )
    parser.add_argument(
        "--select", metavar="IDS",
        help="comma-separated rule ids to report (others run but are "
             "filtered); unknown ids exit 2",
    )
    parser.add_argument(
        "--changed", metavar="REF",
        help="incremental mode: lint files changed since REF (plus "
             "untracked files and their transitive importers); falls "
             "back to a full scan when a rule-scoped module imports a "
             "changed one",
    )
    parser.add_argument(
        "--json", action="store_true", dest="as_json",
        help="emit findings as a JSON object on stdout",
    )
    parser.add_argument(
        "--show-suppressed", action="store_true",
        help="include suppressed findings in the report (they do not "
             "affect the exit code)",
    )
    parser.add_argument(
        "-q", "--quiet", action="store_true",
        help="suppress the report; exit code only",
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule_id, (family, summary) in sorted(RULES.items()):
            print(f"{rule_id}  [{family:8s}] {summary}")
        return 0

    try:
        selected = _parse_select(args.select) if args.select else None
        paths = args.paths or [str(default_target())]
        if args.changed is not None:
            paths, note = _resolve_changed(args.changed, paths)
            print(f"amlint: --changed {args.changed}: {note}",
                  file=sys.stderr)
            if not paths:
                if args.as_json:
                    print(json.dumps(
                        {"findings": [], "active": 0, "suppressed": 0}
                    ))
                elif not args.quiet:
                    print("0 finding(s)")
                return 0
        findings = run_analysis(
            paths, include_suppressed=args.show_suppressed
        )
    except UsageError as exc:
        print(f"amlint: error: {exc}", file=sys.stderr)
        return 2

    if selected is not None:
        findings = [f for f in findings if f.rule_id in selected]
    active = [f for f in findings if not f.suppressed]
    if args.as_json:
        print(json.dumps({
            "findings": [
                {
                    "rule": f.rule_id,
                    "path": f.path,
                    "line": f.line,
                    "col": f.col,
                    "message": f.message,
                    "suppressed": f.suppressed,
                }
                for f in findings
            ],
            "active": len(active),
            "suppressed": len(findings) - len(active),
        }, indent=2))
    elif not args.quiet:
        print(format_report(findings))
    return 1 if active else 0


if __name__ == "__main__":
    sys.exit(main())
