"""Whole-program import/call-graph engine for amlint.

Before this module every reachability-flavoured rule (AM303 "no recording
in traced code", AM403 "no blocking calls in serve event-loop code",
AM502 "workers never import the controller") worked off *direct* calls
and *direct* imports inside one file. That misses exactly the bugs the
rules exist for: a blocking ``jax.device_get`` two frames below a serve
entry point, a worker module that reaches the controller through an
innocent-looking helper import. This module gives every rule the same
three whole-scan facts:

- **module summaries** (:class:`ModuleInfo`): per scanned file, the
  dotted module name, its top-level functions and class methods, its
  import aliases (``import x.y as z``) and from-imports (``from .a
  import b`` — including function-level imports, which the worker spawn
  path uses deliberately), with relative imports resolved against the
  module's package;
- **call resolution** (:meth:`CallGraph.resolve_call`): a call
  expression resolved to the function definition it statically targets —
  plain names through module functions and from-imports, dotted names
  through module aliases, ``self.meth()`` through the enclosing class,
  ``ClassName.meth``/``ClassName()`` through same-scan classes, and
  local variables whose class is inferable from a one-function
  ``x = ClassName(...)`` assignment. Anything the resolver cannot prove
  (attributes of parameters, ``self.farm.apply_changes``) stays
  unresolved — reachability stops at the honest static boundary instead
  of guessing;
- **transitive reachability** (:meth:`CallGraph.reachable`): BFS from a
  root set with a bounded call depth (``MAX_CALL_DEPTH``), returning the
  shortest discovery chain per reached function so rule diagnostics can
  print the actual ``[reachable via a -> b -> c]`` path;
- **module-import closure** (:meth:`CallGraph.import_closure`): the
  same idea one level up — which modules a module drags in transitively,
  with the chain of module names and the anchoring first-hop import
  statement (what AM502/AM305 flag).

The graph is built only from the files handed to ``run_analysis`` — a
single-fixture scan degrades gracefully to per-module behaviour (no
cross-file edges exist), which keeps the fixture triples hermetic.
Stdlib-only, like everything else in the analysis package.
"""
from __future__ import annotations

import ast
import dataclasses
from pathlib import Path

from .core import FileContext, dotted_name

#: bound on transitive call-chain depth: deep enough to cross a few
#: helper layers, shallow enough that one unresolved facade does not
#: drag half the package into every rule's scope
MAX_CALL_DEPTH = 6

#: bound on transitive module-import chains (AM502/AM305)
MAX_IMPORT_DEPTH = 8


def module_name(path: Path) -> str:
    """Dotted module name for a scanned file: package files become
    ``automerge_tpu.x.y``; anything outside the package (fixtures,
    scratch files) is just its stem, so cross-file resolution only ever
    links files that genuinely share the package namespace."""
    parts = list(path.parts)
    if "automerge_tpu" not in parts:
        return path.stem
    idx = len(parts) - 1 - parts[::-1].index("automerge_tpu")
    rel = parts[idx:-1] + [path.stem]
    if path.stem == "__init__":
        rel = parts[idx:-1]
    return ".".join(rel)


@dataclasses.dataclass
class FuncInfo:
    """One statically known function: a top-level def or a class method."""

    module: str
    qualname: str  # "f" or "Class.f"
    node: ast.AST  # FunctionDef | AsyncFunctionDef
    ctx: FileContext

    @property
    def key(self) -> tuple[str, str]:
        return (self.module, self.qualname)

    @property
    def label(self) -> str:
        """Human chain label: module-qualified outside the defining file."""
        tail = self.module.rsplit(".", 1)[-1]
        return f"{tail}.{self.qualname}"


class ModuleInfo:
    """Per-module summary the resolver queries."""

    def __init__(self, ctx: FileContext):
        self.ctx = ctx
        self.name = module_name(ctx.path)
        self.functions: dict[str, FuncInfo] = {}
        self.classes: dict[str, ast.ClassDef] = {}
        #: local alias -> dotted module path (``import x.y as z``)
        self.import_aliases: dict[str, str] = {}
        #: local name -> (dotted module path, attr) for from-imports;
        #: attr may itself be a submodule — decided at resolve time
        self.from_imports: dict[str, tuple[str, str]] = {}
        #: every dotted module path this module imports, mapped to the
        #: first import statement that pulls it in (the finding anchor)
        self.imported_modules: dict[str, ast.AST] = {}
        self._summarize()

    # ------------------------------------------------------------------ #

    def _resolve_relative(self, module: str | None, level: int) -> str:
        if level == 0:
            return module or ""
        base = self.name.split(".")
        # a module's package is its dotted name minus the last component;
        # each additional level strips one more
        base = base[: max(len(base) - level, 0)]
        if module:
            base = base + module.split(".")
        return ".".join(base)

    def _summarize(self) -> None:
        tree = self.ctx.tree
        for stmt in tree.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions[stmt.name] = FuncInfo(
                    self.name, stmt.name, stmt, self.ctx
                )
            elif isinstance(stmt, ast.ClassDef):
                self.classes[stmt.name] = stmt
                for sub in stmt.body:
                    if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        qual = f"{stmt.name}.{sub.name}"
                        self.functions[qual] = FuncInfo(
                            self.name, qual, sub, self.ctx
                        )
        # imports anywhere in the file: the worker spawn path imports
        # inside functions on purpose, and those edges are the ones
        # AM502's transitive check exists for
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    self.import_aliases[alias.asname or
                                        alias.name.split(".")[0]] = (
                        alias.name if alias.asname else alias.name.split(".")[0]
                    )
                    if alias.asname:
                        self.import_aliases[alias.asname] = alias.name
                    self.imported_modules.setdefault(alias.name, node)
            elif isinstance(node, ast.ImportFrom):
                target = self._resolve_relative(node.module, node.level)
                if target:
                    self.imported_modules.setdefault(target, node)
                for alias in node.names:
                    self.from_imports[alias.asname or alias.name] = (
                        target, alias.name
                    )
                    # `from pkg import submodule` also imports the module
                    sub = f"{target}.{alias.name}" if target else alias.name
                    self.imported_modules.setdefault(sub, node)


class CallGraph:
    """The whole-scan graph every reachability rule queries."""

    def __init__(self, ctxs: list[FileContext]):
        self.modules: dict[str, ModuleInfo] = {}
        self.by_ctx: dict[int, ModuleInfo] = {}
        for ctx in ctxs:
            try:
                mod = ModuleInfo(ctx)
            except RecursionError:  # pragma: no cover - absurd nesting
                continue
            # first file wins on a name collision (standalone fixtures
            # sharing a stem): deterministic because ctxs arrive sorted
            self.modules.setdefault(mod.name, mod)
            self.by_ctx[id(ctx)] = mod
        self._callee_cache: dict[tuple[str, str], list] = {}

    # ------------------------------------------------------------------ #
    # resolution

    def module_for(self, ctx: FileContext) -> ModuleInfo | None:
        return self.by_ctx.get(id(ctx))

    def function(self, module: str, qualname: str) -> FuncInfo | None:
        mod = self.modules.get(module)
        return mod.functions.get(qualname) if mod else None

    def _module_target(self, mod: ModuleInfo, root: str) -> str | None:
        """The dotted module path a local name refers to, if it names a
        module in this scan (``import x.y as z`` or ``from pkg import
        sub`` where ``pkg.sub`` is a scanned module)."""
        target = mod.import_aliases.get(root)
        if target and target in self.modules:
            return target
        fi = mod.from_imports.get(root)
        if fi:
            candidate = f"{fi[0]}.{fi[1]}" if fi[0] else fi[1]
            if candidate in self.modules:
                return candidate
        return None

    def resolve_call(
        self,
        mod: ModuleInfo,
        func: ast.expr,
        enclosing_class: str | None = None,
        local_types: dict[str, str] | None = None,
    ) -> FuncInfo | None:
        """The function definition a call expression statically targets,
        or None when the receiver is not provable from this scan."""
        if isinstance(func, ast.Name):
            fi = mod.functions.get(func.id)
            if fi is not None:
                return fi
            # constructing a same-scan class reaches its __init__
            if func.id in mod.classes:
                return mod.functions.get(f"{func.id}.__init__")
            imported = mod.from_imports.get(func.id)
            if imported is not None:
                target_mod, attr = imported
                target = self.modules.get(target_mod)
                if target is not None:
                    hit = target.functions.get(attr)
                    if hit is not None:
                        return hit
                    if attr in target.classes:
                        return target.functions.get(f"{attr}.__init__")
            return None
        name = dotted_name(func)
        if name is None or "." not in name:
            return None
        parts = name.split(".")
        root, leaf = parts[0], parts[-1]
        if root == "self" and enclosing_class is not None and len(parts) == 2:
            return mod.functions.get(f"{enclosing_class}.{leaf}")
        if len(parts) == 2:
            if root in mod.classes:
                return mod.functions.get(f"{root}.{leaf}")
            if local_types and root in local_types:
                cls = local_types[root]
                hit = self.function_in_any(cls, leaf, mod)
                if hit is not None:
                    return hit
        # module-alias attribute: `transcode.gate_verdicts(...)`
        target_mod = self._module_target(mod, root)
        if target_mod is not None:
            # honour one submodule hop: `pkg.mod.fn`
            for depth in range(len(parts) - 1, 0, -1):
                candidate = ".".join(
                    [target_mod] + parts[1:depth]
                ) if depth > 1 else target_mod
                target = self.modules.get(candidate)
                if target is not None:
                    hit = target.functions.get(parts[depth])
                    if hit is not None and depth == len(parts) - 1:
                        return hit
        return None

    def function_in_any(self, cls: str, meth: str,
                        prefer: ModuleInfo) -> FuncInfo | None:
        """``Class.meth`` looked up in ``prefer`` first, then in the
        module the class was from-imported from."""
        hit = prefer.functions.get(f"{cls}.{meth}")
        if hit is not None:
            return hit
        imported = prefer.from_imports.get(cls)
        if imported is not None:
            target = self.modules.get(imported[0])
            if target is not None:
                return target.functions.get(f"{imported[1]}.{meth}")
        return None

    @staticmethod
    def local_class_types(mod: ModuleInfo, fn: ast.AST) -> dict[str, str]:
        """{local var: class name} for one-function ``x = ClassName(...)``
        assignments — the 'method receivers where inferable' contract."""
        out: dict[str, str] = {}
        for node in ast.walk(fn):
            if not (isinstance(node, ast.Assign) and len(node.targets) == 1):
                continue
            target = node.targets[0]
            if not isinstance(target, ast.Name):
                continue
            value = node.value
            if not isinstance(value, ast.Call):
                continue
            cname = dotted_name(value.func)
            if cname is None:
                continue
            leaf = cname.split(".")[-1]
            if leaf in mod.classes or (
                leaf in mod.from_imports and leaf[:1].isupper()
            ):
                out[target.id] = leaf
        return out

    # ------------------------------------------------------------------ #
    # call reachability

    def callees(self, fi: FuncInfo) -> list[tuple[FuncInfo, ast.AST]]:
        """Resolved (callee, call node) pairs inside one function."""
        cached = self._callee_cache.get(fi.key)
        if cached is not None:
            return cached
        mod = self.by_ctx.get(id(fi.ctx))
        out: list[tuple[FuncInfo, ast.AST]] = []
        if mod is not None:
            enclosing = fi.qualname.split(".")[0] if "." in fi.qualname else None
            local_types = self.local_class_types(mod, fi.node)
            for node in ast.walk(fi.node):
                if not isinstance(node, ast.Call):
                    continue
                hit = self.resolve_call(mod, node.func, enclosing, local_types)
                if hit is not None and hit.key != fi.key:
                    out.append((hit, node))
        self._callee_cache[fi.key] = out
        return out

    def reachable(
        self, roots: list[FuncInfo], max_depth: int = MAX_CALL_DEPTH
    ) -> dict[tuple[str, str], tuple[FuncInfo, tuple[str, ...]]]:
        """Every function reachable from ``roots`` within ``max_depth``
        calls: ``{key: (FuncInfo, chain)}`` where ``chain`` is the
        shortest discovery path of human labels, root first. Roots are
        included with a single-element chain."""
        out: dict[tuple[str, str], tuple[FuncInfo, tuple[str, ...]]] = {}
        frontier: list[tuple[FuncInfo, tuple[str, ...]]] = []
        for root in roots:
            if root.key not in out:
                chain = (root.label,)
                out[root.key] = (root, chain)
                frontier.append((root, chain))
        depth = 0
        while frontier and depth < max_depth:
            depth += 1
            next_frontier: list[tuple[FuncInfo, tuple[str, ...]]] = []
            for fi, chain in frontier:
                for callee, _node in self.callees(fi):
                    if callee.key in out:
                        continue
                    sub = chain + (callee.label,)
                    out[callee.key] = (callee, sub)
                    next_frontier.append((callee, sub))
            frontier = next_frontier
        return out

    # ------------------------------------------------------------------ #
    # module-import reachability

    def import_closure(
        self, start: str, max_depth: int = MAX_IMPORT_DEPTH
    ) -> dict[str, tuple[tuple[str, ...], ast.AST]]:
        """Modules transitively imported by ``start`` (scanned modules
        only): ``{module: (chain of module names from start, first-hop
        import node in start)}``. The anchor node is where the offending
        edge enters the flagged module — that line owns the fix (or the
        justified suppression)."""
        start_mod = self.modules.get(start)
        if start_mod is None:
            return {}
        out: dict[str, tuple[tuple[str, ...], ast.AST]] = {}
        frontier: list[tuple[str, tuple[str, ...], ast.AST]] = []
        for target, node in start_mod.imported_modules.items():
            if target in self.modules and target != start:
                if target not in out:
                    out[target] = ((start, target), node)
                    frontier.append((target, (start, target), node))
        depth = 1
        while frontier and depth < max_depth:
            depth += 1
            next_frontier = []
            for modname, chain, anchor in frontier:
                mod = self.modules[modname]
                for target in mod.imported_modules:
                    if target in self.modules and target not in out \
                            and target != start:
                        sub = chain + (target,)
                        out[target] = (sub, anchor)
                        next_frontier.append((target, sub, anchor))
            frontier = next_frontier
        return out

    def importers_closure(self, targets: set[str]) -> set[str]:
        """Every scanned module that transitively imports one of
        ``targets`` (used by the CLI's ``--changed`` fallback logic)."""
        importers: dict[str, set[str]] = {name: set() for name in self.modules}
        for name, mod in self.modules.items():
            for target in mod.imported_modules:
                if target in importers:
                    importers[target].add(name)
        out: set[str] = set()
        frontier = [t for t in targets if t in importers]
        while frontier:
            cur = frontier.pop()
            for importer in importers.get(cur, ()):
                if importer not in out and importer not in targets:
                    out.add(importer)
                    frontier.append(importer)
        return out


def format_chain(chain: tuple[str, ...]) -> str:
    """The diagnostic suffix every reachability rule appends: the actual
    call path from the rule's root to the finding site."""
    return " [reachable via " + " -> ".join(chain) + "]"
