"""Test-support utilities that ship with the package.

``automerge_tpu.testing.faults`` is the fault-injection harness: deterministic
binary-change corrupters plus the failure-point registry that the farm,
engine and sync layers consult (`fire`). Production modules import only the
near-zero-cost ``fire`` hook; everything else is test-side.

``automerge_tpu.testing.chaos`` is the chaos transport: a seeded simulated
network (drop/duplicate/reorder/corrupt/truncate/delay, partitions, peer
restarts) plus the ManualClock and harness that drive supervised sync
sessions through it in simulated time. It consults the same failure-point
registry (``chaos.send``/``chaos.deliver``), so network chaos and merge
faults compose.
"""
