"""Test-support utilities that ship with the package.

``automerge_tpu.testing.faults`` is the fault-injection harness: deterministic
binary-change corrupters plus the failure-point registry that the farm,
engine and sync layers consult (`fire`). Production modules import only the
near-zero-cost ``fire`` hook; everything else is test-side.
"""
