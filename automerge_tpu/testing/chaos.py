"""Deterministic chaos transport: a seeded simulated network for sync tests
and bench.

The sync supervision layer (automerge_tpu/sync_session.py) promises
convergence over lossy, restart-prone transports; this module is the
adversary that promise is tested against. A ``ChaosLink`` is one directed
byte pipe with seeded per-frame drop/duplicate/reorder/delay/corrupt/
truncate probabilities and byte accounting; a ``ChaosNetwork`` wires links
between named peers and adds partition/heal and in-flight-loss events (the
transport half of a peer restart). ``ChaosHarness`` drives a set of
supervised sessions over a network against a ``ManualClock`` until a
predicate holds, advancing simulated time only when the network goes quiet
— so retransmission timeouts and backoff fire without real sleeping.

Everything is driven by one injected ``random.Random`` and one injected
clock: the same seed replays the same failure schedule byte for byte.

The harness composes with the fault-injection registry
(automerge_tpu/testing/faults.py): every send and delivery consults the
``chaos.send``/``chaos.deliver`` failure points, so tests can combine
network chaos with merge-path faults (e.g. a poisoned document quarantined
by the farm while its sync channel is also dropping frames).

This module must stay importable on any host: no jax, no tpu imports.
"""
from __future__ import annotations

from dataclasses import dataclass

from ..errors import SyncProtocolError
from .faults import fire as _fault_point


class ManualClock:
    """An injectable clock tests advance by hand. Instances are callable
    (``clock()``), matching the ``SyncSession`` clock contract."""

    def __init__(self, start: float = 0.0):
        self.t = float(start)

    def __call__(self) -> float:
        return self.t

    def now(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


@dataclass
class ChaosConfig:
    """Per-link failure probabilities (all independent per frame) and the
    extra latency range applied when a frame is delayed."""

    drop: float = 0.0        # frame vanishes
    duplicate: float = 0.0   # frame delivered twice
    reorder: float = 0.0     # frame may overtake earlier in-flight frames
    corrupt: float = 0.0     # one random bit flipped
    truncate: float = 0.0    # random tail cut off
    delay: float = 0.0       # frame held for extra latency
    min_delay: float = 0.05  # extra latency range when delayed
    max_delay: float = 1.5
    base_delay: float = 0.0  # fixed latency every frame pays (per-link skew)

    @classmethod
    def lossy(cls, p: float) -> "ChaosConfig":
        """The soak-suite shape: loss, duplication and reordering all at
        probability ``p``, plus occasional latency spikes."""
        return cls(drop=p, duplicate=p, reorder=p, delay=p / 2)


@dataclass
class LinkStats:
    frames_sent: int = 0
    frames_delivered: int = 0
    frames_dropped: int = 0
    frames_duplicated: int = 0
    frames_corrupted: int = 0
    frames_truncated: int = 0
    frames_delayed: int = 0
    frames_reordered: int = 0
    bytes_sent: int = 0
    bytes_delivered: int = 0

    def as_dict(self) -> dict:
        return dict(self.__dict__)


class ChaosLink:
    """One directed lossy pipe. ``send`` applies the failure schedule and
    queues surviving copies; ``deliver`` returns every frame whose
    simulated arrival time has passed, in (possibly reordered) order."""

    def __init__(self, rng, clock, config: ChaosConfig | None = None,
                 name: str = ""):
        self.rng = rng
        self.clock = clock
        self.config = config or ChaosConfig()
        self.name = name
        self.partitioned = False
        self.stats = LinkStats()
        self._queue: list[tuple[float, float, bytes]] = []  # (at, order, frame)
        self._order = 0.0

    def send(self, frame: bytes) -> None:
        _fault_point("chaos.send", link=self.name, frame=frame)
        cfg, rng = self.config, self.rng
        self.stats.frames_sent += 1
        self.stats.bytes_sent += len(frame)
        if self.partitioned or rng.random() < cfg.drop:
            self.stats.frames_dropped += 1
            return
        copies = 1
        if rng.random() < cfg.duplicate:
            copies = 2
            self.stats.frames_duplicated += 1
        for _ in range(copies):
            damaged = frame
            roll = rng.random()
            if roll < cfg.corrupt and len(frame) > 0:
                buf = bytearray(frame)
                bit = rng.randrange(len(buf) * 8)
                buf[bit >> 3] ^= 1 << (bit & 7)
                damaged = bytes(buf)
                self.stats.frames_corrupted += 1
            elif roll < cfg.corrupt + cfg.truncate and len(frame) > 1:
                damaged = frame[: rng.randrange(1, len(frame))]
                self.stats.frames_truncated += 1
            at = self.clock() + cfg.base_delay
            if rng.random() < cfg.delay:
                at += rng.uniform(cfg.min_delay, cfg.max_delay)
                self.stats.frames_delayed += 1
            self._order += 1.0
            order = self._order
            if rng.random() < cfg.reorder:
                order -= rng.uniform(0.0, 3.0)  # may overtake in-flight frames
                self.stats.frames_reordered += 1
            self._queue.append((at, order, damaged))

    def deliver(self) -> list[bytes]:
        """Frames whose arrival time has passed, earliest order first."""
        now = self.clock()
        ready = sorted(
            (m for m in self._queue if m[0] <= now), key=lambda m: (m[1],)
        )
        self._queue = [m for m in self._queue if m[0] > now]
        out = []
        for _, _, frame in ready:
            _fault_point("chaos.deliver", link=self.name, frame=frame)
            self.stats.frames_delivered += 1
            self.stats.bytes_delivered += len(frame)
            out.append(frame)
        return out

    @property
    def in_flight(self) -> int:
        return len(self._queue)

    def next_arrival(self) -> float | None:
        return min((m[0] for m in self._queue), default=None)

    def clear(self) -> int:
        """Drops everything in flight (a peer restart loses its inbox)."""
        n = len(self._queue)
        self._queue = []
        self.stats.frames_dropped += n
        return n


class ChaosNetwork:
    """Directed links between named peers, created lazily with a shared
    default config (override per link via ``link(a, b).config``)."""

    def __init__(self, rng, clock, config: ChaosConfig | None = None):
        self.rng = rng
        self.clock = clock
        self.config = config or ChaosConfig()
        self._links: dict[tuple, ChaosLink] = {}

    def link(self, src, dst) -> ChaosLink:
        key = (src, dst)
        if key not in self._links:
            self._links[key] = ChaosLink(
                self.rng, self.clock, self.config, name=f"{src}->{dst}"
            )
        return self._links[key]

    def send(self, src, dst, frame: bytes) -> None:
        self.link(src, dst).send(frame)

    def deliver(self, dst) -> list[tuple[object, bytes]]:
        """Every ready (src, frame) addressed to ``dst``."""
        out = []
        for (src, d), link in self._links.items():
            if d != dst:
                continue
            for frame in link.deliver():
                out.append((src, frame))
        return out

    def partition(self, a, b) -> None:
        """Severs both directions between two peers (in-flight frames
        still arrive; new sends are dropped)."""
        self.link(a, b).partitioned = True
        self.link(b, a).partitioned = True

    def heal(self, a, b) -> None:
        self.link(a, b).partitioned = False
        self.link(b, a).partitioned = False

    def partition_one_way(self, src, dst) -> None:
        """Asymmetric partition: ``src -> dst`` drops while ``dst -> src``
        keeps flowing — the half-open failure real networks produce (dead
        uplink, live downlink) that a symmetric partition can't model:
        one side keeps receiving and acking while its own frames vanish."""
        self.link(src, dst).partitioned = True

    def heal_one_way(self, src, dst) -> None:
        self.link(src, dst).partitioned = False

    def set_latency(self, src, dst, base: float) -> None:
        """Per-link latency skew: every ``src -> dst`` frame arrives at
        least ``base`` simulated seconds late, on top of the probabilistic
        delay. Skewing the two directions differently exercises the
        stop-and-wait timers against asymmetric RTT halves."""
        from dataclasses import replace

        link = self.link(src, dst)
        link.config = replace(link.config, base_delay=base)

    def drop_in_flight(self, peer) -> int:
        """Clears every queue to or from ``peer`` (the transport half of a
        peer restart)."""
        dropped = 0
        for (src, dst), link in self._links.items():
            if src == peer or dst == peer:
                dropped += link.clear()
        return dropped

    @property
    def in_flight(self) -> int:
        """Frames queued across every link (0 = the network is quiet)."""
        return sum(link.in_flight for link in self._links.values())

    def next_arrival(self) -> float | None:
        """Earliest simulated arrival time across every link, or None when
        nothing is in flight — event-driven harnesses (serve/loadgen.py)
        jump the clock here instead of ticking through quiet gaps."""
        times = [
            t
            for link in self._links.values()
            if (t := link.next_arrival()) is not None
        ]
        return min(times, default=None)

    def stats(self) -> dict:
        return {link.name: link.stats.as_dict() for link in self._links.values()}


class ChaosHarness:
    """Drives supervised sessions over a chaos network in simulated time.

    Sessions register per directed edge (``add_session(src, dst, s)`` —
    ``s`` speaks for ``src`` on the ``src -> dst`` channel). Each ``step()``
    polls every session, routes the produced frames, and hands deliveries
    to the addressed session; ``run_until`` repeats steps, jumping the
    clock forward over quiet gaps so timeouts and backoff fire without
    real sleeping. Frames the supervision layer rejects
    (``SyncProtocolError``: corruption, truncation) are counted and
    dropped — that is the transport noise the retransmission path exists
    to absorb."""

    def __init__(self, network: ChaosNetwork, clock: ManualClock):
        self.network = network
        self.clock = clock
        self.sessions: dict[tuple, object] = {}
        self.rejected = 0
        self.patches = 0

    def add_session(self, src, dst, session) -> None:
        self.sessions[(src, dst)] = session

    def step(self) -> bool:
        """One poll/route/deliver sweep; True if any frame moved."""
        activity = False
        for (src, dst), session in self.sessions.items():
            frame = session.poll()
            if frame is not None:
                self.network.send(src, dst, frame)
                activity = True
        for receiver in {src for src, _dst in self.sessions}:
            for sender, frame in self.network.deliver(receiver):
                # the frame on link sender->receiver lands at the session
                # speaking for receiver on the (receiver, sender) edge
                session = self.sessions.get((receiver, sender))
                if session is None:
                    continue
                activity = True
                try:
                    if session.handle(frame) is not None:
                        self.patches += 1
                except SyncProtocolError:
                    self.rejected += 1
        return activity

    def run_until(self, predicate, max_time: float = 300.0,
                  idle_step: float = 0.26, tick: float = 0.02) -> bool:
        """Steps until ``predicate()`` holds or ``max_time`` simulated
        seconds elapse. Returns whether the predicate was met. Every step
        advances the clock by ``tick`` (so retransmission deadlines always
        approach, even while chatter keeps the network busy) and quiet
        steps jump ``idle_step`` further."""
        deadline = self.clock() + max_time
        while self.clock() < deadline:
            if predicate():
                return True
            busy = self.step()
            self.clock.advance(tick if busy else idle_step)
        return predicate()
