"""Deterministic fault injection for the merge pipeline.

Two halves:

1. **Corrupters** — pure functions that damage a binary change (or sync
   message) in a *specific, reproducible* way, so tests can assert the exact
   taxonomy error each damage class produces (automerge_tpu/errors.py):
   truncation and garbage → ``DecodeError``, bit flips and checksum damage →
   ``ChecksumError``, chunk-type rewrites (checksum kept valid) →
   ``DecodeError``, seq reuse/gaps and fabricated deps → ``CausalityError``
   (or a permanent queue), counter/element floods → ``PackingLimitError``.

2. **Failure points** — a registry of named hooks the farm, engine and sync
   layers consult at their phase boundaries (``fire``). Tests register a
   hook with ``inject`` to make a specific phase raise — e.g. "the device
   dispatch fails whenever doc 3's rows are in the batch" — which is how the
   farm's bisect/quarantine/fallback paths are exercised without a real
   wedged accelerator. With nothing registered, ``fire`` is a dict lookup.

This module must stay importable on any host: no jax, no tpu imports (the
sync layer, a host-only module, imports ``fire``).
"""
from __future__ import annotations

import contextlib
from hashlib import sha256

from ..columnar import MAGIC_BYTES, encode_change

# ---------------------------------------------------------------------- #
# failure points

_HOOKS: dict[str, list] = {}

#: points consulted by production code, for discoverability in tests
POINTS = (
    "farm.decode",           # per doc, before buffers are decoded
    "farm.device_dispatch",  # before the batched device merge (docs=tuple)
    "engine.apply_batch",    # host driver, before the merge program
    "engine.visible_state",  # host driver, before the visibility program
    "sync.receive_message",  # before a peer message is decoded
    "session.receive",       # before a session frame is decoded (frame=bytes)
    "chaos.send",            # chaos transport, before a frame enters a link
    "chaos.deliver",         # chaos transport, before a frame leaves a link
    "store.append",          # before a commit frame hits the WAL (doc=int)
    "store.fsync",           # inside the fsync seam, before fdatasync (path=str)
    "store.rotate",          # at each rotation stage (stage="footer"|"rename")
    "store.compact",         # at each compaction stage (stage="write"|"verify"|
                             #   "swap"|"cleanup")
)


def fire(point: str, **context) -> None:
    """Consults every hook registered for `point`. Hooks simulate failures
    by raising; the exception propagates into the caller's fault-handling
    path exactly like an organic one. Near-zero cost when nothing is
    registered (one dict lookup)."""
    hooks = _HOOKS.get(point)
    if hooks:
        for hook in list(hooks):
            hook(**context)


@contextlib.contextmanager
def inject(point: str, hook):
    """Registers `hook` at a failure point for the dynamic extent.

    The hook is called as ``hook(**context)`` with the point's keyword
    context (e.g. ``docs=(...)`` at ``farm.device_dispatch``) and should
    raise to simulate a failure at that point."""
    _HOOKS.setdefault(point, []).append(hook)
    try:
        yield hook
    finally:
        _HOOKS[point].remove(hook)
        if not _HOOKS[point]:
            del _HOOKS[point]


def fail_docs(poisoned, exc_factory=None):
    """Hook for ``farm.device_dispatch``/``engine.apply_batch``: raises
    whenever any of `poisoned` docs is in the dispatched group, simulating
    a device program that a specific document's rows crash."""
    poisoned = set(poisoned)
    make = exc_factory or (lambda hit: RuntimeError(
        f"injected device fault: poisoned docs {sorted(hit)} in batch"
    ))

    def hook(**context):
        docs = context.get("docs")
        hit = poisoned if docs is None else poisoned & set(docs)
        if hit:
            raise make(hit)

    return hook


def fail_always(exc_factory=None):
    """Hook that fails unconditionally (a wedged device / dead peer)."""
    make = exc_factory or (lambda: RuntimeError("injected unconditional fault"))

    def hook(**_context):
        raise make()

    return hook


def fail_at(n: int, exc_factory=None, stage: str | None = None):
    """Hook that fails on its `n`-th firing (1-based), counting only
    firings whose ``stage`` context matches when one is given. The store
    crash-point sweep walks `n` across every durability boundary of a
    workload; the hook's ``fired`` attribute reports how many matching
    firings happened, so the sweep knows when it has walked off the end."""
    make = exc_factory or (lambda: RuntimeError(f"injected fault at firing {n}"))

    def hook(**context):
        if stage is not None and context.get("stage") != stage:
            return
        hook.fired += 1
        if hook.fired == n:
            raise make()

    hook.fired = 0
    return hook


# ---------------------------------------------------------------------- #
# binary corrupters
#
# Container layout (columnar.encode_container): MAGIC(4) | checksum(4) |
# chunk_type(1) | LEB-length | body. The checksum covers everything from
# the chunk-type byte onward.

_HEADER_END = 8  # MAGIC + checksum; the hashed region starts here


def truncated(buffer: bytes, keep: int | None = None) -> bytes:
    """Drops the tail of the buffer (default: keep the first half, but
    always at least the magic bytes so the failure is a short read, not a
    magic-byte mismatch). Decode raises ``DecodeError``."""
    buffer = bytes(buffer)
    if keep is None:
        keep = max(len(buffer) // 2, len(MAGIC_BYTES) + 1)
    return buffer[:keep]


def bit_flipped(buffer: bytes, bit: int = 0) -> bytes:
    """Flips one bit of the chunk body, leaving the stored checksum stale.
    Decode raises ``ChecksumError`` (the checksum covers the body)."""
    buffer = bytearray(buffer)
    index = _HEADER_END + (bit // 8) % max(len(buffer) - _HEADER_END, 1)
    buffer[index] ^= 1 << (bit % 8)
    return bytes(buffer)


def corrupt_checksum(buffer: bytes) -> bytes:
    """Flips one bit of the stored checksum itself. Decode raises
    ``ChecksumError``."""
    buffer = bytearray(buffer)
    buffer[len(MAGIC_BYTES)] ^= 0x01
    return bytes(buffer)


def _rechecksummed(buffer: bytearray) -> bytes:
    """Recomputes and stores the container checksum over the (possibly
    mutated) hashed region, producing a structurally 'valid' container."""
    digest = sha256(bytes(buffer[_HEADER_END:])).digest()
    buffer[len(MAGIC_BYTES):_HEADER_END] = digest[:4]
    return bytes(buffer)


def bad_chunk_type(buffer: bytes, chunk_type: int = 0x7E) -> bytes:
    """Rewrites the chunk-type byte and *recomputes the checksum*, so the
    container verifies but carries an unknown chunk type — the
    checksum-preserving field mutation of the container header. Decode
    raises ``DecodeError`` ('Unexpected chunk type')."""
    buffer = bytearray(buffer)
    buffer[_HEADER_END] = chunk_type
    return _rechecksummed(buffer)


def garbage(length: int = 64, seed: int = 0) -> bytes:
    """Deterministic bytes that are not an Automerge container at all.
    Decode raises ``DecodeError`` (magic-byte mismatch)."""
    out = bytearray()
    state = seed & 0xFFFFFFFF
    while len(out) < length:
        state = (1103515245 * state + 12345) & 0xFFFFFFFF
        out.append((state >> 16) & 0xFF)
    # make sure we never accidentally start with the magic bytes
    if bytes(out[:4]) == MAGIC_BYTES:
        out[0] ^= 0xFF
    return bytes(out[:length])


# ---------------------------------------------------------------------- #
# semantically poisoned (but structurally valid) change factories

def make_change(actor: str, seq: int, start_op: int, deps, ops) -> bytes:
    """A structurally valid change; the building block the poisoned
    factories mutate. deps are sorted for the caller."""
    return encode_change({
        "actor": actor, "seq": seq, "startOp": start_op, "time": 0,
        "deps": sorted(deps), "ops": list(ops),
    })


def set_op(key: str, value, obj: str = "_root", pred=()) -> dict:
    return {"action": "set", "obj": obj, "key": key, "datatype": "uint",
            "value": value, "pred": list(pred)}


def seq_reused(actor: str, seq: int, start_op: int, deps=()) -> bytes:
    """A change re-using an already-committed seq for `actor` (deliver after
    that seq has applied). The gate raises ``CausalityError``
    ('Reuse of sequence number')."""
    return make_change(actor, seq, start_op, deps,
                       [set_op("poison-reuse", seq)])


def seq_skipped(actor: str, seq: int, start_op: int, deps=()) -> bytes:
    """A change whose seq skips ahead of the committed clock (deliver with
    satisfied deps). The gate raises ``CausalityError``
    ('Skipped sequence number')."""
    return make_change(actor, seq, start_op, deps,
                       [set_op("poison-skip", seq)])


def counter_overflow(actor: str, seq: int, max_counter: int, deps=()) -> bytes:
    """A change whose op counter sits at `max_counter` (pass the engine's
    MAX_COUNTER, e.g. ``automerge_tpu.tpu.rga.MAX_COUNTER``): prevalidation
    raises ``PackingLimitError`` ('merge-key packing range')."""
    return make_change(actor, seq, max_counter, deps,
                       [set_op("poison-overflow", 1)])


def insert_flood(actor: str, seq: int, start_op: int, obj: str, n: int,
                 deps=()) -> bytes:
    """`n` consecutive list inserts into `obj`; with ``n`` past the doc's
    remaining MAX_ELEMS budget, prevalidation raises ``PackingLimitError``
    (rank-kernel range)."""
    ops = []
    for _ in range(n):
        ops.append({"action": "set", "obj": obj, "elemId": "_head",
                    "insert": True, "value": "x", "pred": []})
    return make_change(actor, seq, start_op, deps, ops)


#: a dependency hash that can never be satisfied (no change hashes to it)
MISSING_DEP = "00" * 32


def missing_dep(actor: str, seq: int, start_op: int) -> bytes:
    """A change depending on a hash no peer will ever produce — the
    dep-graph analogue of a cycle (neither this change nor anything after
    it for the actor can ever become ready). Deliveries queue forever
    rather than erroring; tests assert the queue stays bounded and healthy
    docs are unaffected."""
    return make_change(actor, seq, start_op, [MISSING_DEP],
                       [set_op("poison-dep", seq)])


#: (name, corrupter(valid_buffer) -> poisoned_buffer, expected error kind)
#: — the byte-level corpus over any structurally valid change
BYTE_CORPUS = (
    ("truncated", truncated, "decode"),
    ("bit_flipped", bit_flipped, "checksum"),
    ("corrupt_checksum", corrupt_checksum, "checksum"),
    ("bad_chunk_type", bad_chunk_type, "decode"),
    ("garbage", lambda _buf: garbage(48), "decode"),
)
