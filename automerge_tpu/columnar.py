"""L1 binary format: column schemas, containers, change/document transcoding.

Byte-compatible with the reference implementation's columnar layer
(/root/reference/backend/columnar.js): same column IDs, value-type tags,
container framing (magic bytes + SHA-256 checksum + chunk type), change
chunk layout and document chunk layout. SHA-256 via hashlib, DEFLATE via
zlib (raw streams).
"""
# amlint: host-only — pure-host layer: must not import tpu/ or jax
from __future__ import annotations

import os
import struct
import zlib
from hashlib import sha256

import numpy as np

from .codecs import (
    MAX_SAFE_INTEGER,
    MIN_SAFE_INTEGER,
    BooleanDecoder,
    BooleanEncoder,
    Decoder,
    DecodeCache,
    DeltaDecoder,
    DeltaEncoder,
    Encoder,
    RLEDecoder,
    RLEEncoder,
    bytes_to_hex,
    hex_to_bytes,
)
from .common import parse_op_id
from .errors import ChecksumError, DecodeError, EncodeError

# These bytes don't mean anything, they were generated randomly
# (columnar.js:24); they identify an Automerge binary container.
MAGIC_BYTES = bytes([0x85, 0x6F, 0x4A, 0x83])

CHUNK_TYPE_DOCUMENT = 0
CHUNK_TYPE_CHANGE = 1
CHUNK_TYPE_DEFLATE = 2  # like CHUNK_TYPE_CHANGE but with DEFLATE compression

DEFLATE_MIN_SIZE = 256


class ColumnType:
    GROUP_CARD = 0
    ACTOR_ID = 1
    INT_RLE = 2
    INT_DELTA = 3
    BOOLEAN = 4
    STRING_RLE = 5
    VALUE_LEN = 6
    VALUE_RAW = 7


COLUMN_TYPE_DEFLATE = 8


class ValueType:
    NULL = 0
    FALSE = 1
    TRUE = 2
    LEB128_UINT = 3
    LEB128_INT = 4
    IEEE754 = 5
    UTF8 = 6
    BYTES = 7
    COUNTER = 8
    TIMESTAMP = 9
    MIN_UNKNOWN = 10
    MAX_UNKNOWN = 15


# make* actions must be at even-numbered indexes in this list (columnar.js:51)
ACTIONS = ["makeMap", "set", "makeList", "del", "makeText", "inc", "makeTable", "link"]

OBJECT_TYPE = {"makeMap": "map", "makeList": "list", "makeText": "text", "makeTable": "table"}

COMMON_COLUMNS = [
    ("objActor", 0 << 4 | ColumnType.ACTOR_ID),
    ("objCtr", 0 << 4 | ColumnType.INT_RLE),
    ("keyActor", 1 << 4 | ColumnType.ACTOR_ID),
    ("keyCtr", 1 << 4 | ColumnType.INT_DELTA),
    ("keyStr", 1 << 4 | ColumnType.STRING_RLE),
    ("idActor", 2 << 4 | ColumnType.ACTOR_ID),
    ("idCtr", 2 << 4 | ColumnType.INT_DELTA),
    ("insert", 3 << 4 | ColumnType.BOOLEAN),
    ("action", 4 << 4 | ColumnType.INT_RLE),
    ("valLen", 5 << 4 | ColumnType.VALUE_LEN),
    ("valRaw", 5 << 4 | ColumnType.VALUE_RAW),
    ("chldActor", 6 << 4 | ColumnType.ACTOR_ID),
    ("chldCtr", 6 << 4 | ColumnType.INT_DELTA),
]

CHANGE_COLUMNS = COMMON_COLUMNS + [
    ("predNum", 7 << 4 | ColumnType.GROUP_CARD),
    ("predActor", 7 << 4 | ColumnType.ACTOR_ID),
    ("predCtr", 7 << 4 | ColumnType.INT_DELTA),
]

DOC_OPS_COLUMNS = COMMON_COLUMNS + [
    ("succNum", 8 << 4 | ColumnType.GROUP_CARD),
    ("succActor", 8 << 4 | ColumnType.ACTOR_ID),
    ("succCtr", 8 << 4 | ColumnType.INT_DELTA),
]

DOCUMENT_COLUMNS = [
    ("actor", 0 << 4 | ColumnType.ACTOR_ID),
    ("seq", 0 << 4 | ColumnType.INT_DELTA),
    ("maxOp", 1 << 4 | ColumnType.INT_DELTA),
    ("time", 2 << 4 | ColumnType.INT_DELTA),
    ("message", 3 << 4 | ColumnType.STRING_RLE),
    ("depsNum", 4 << 4 | ColumnType.GROUP_CARD),
    ("depsIndex", 4 << 4 | ColumnType.INT_DELTA),
    ("extraLen", 5 << 4 | ColumnType.VALUE_LEN),
    ("extraRaw", 5 << 4 | ColumnType.VALUE_RAW),
]


def deflate_raw(data: bytes) -> bytes:
    comp = zlib.compressobj(6, zlib.DEFLATED, -15)
    return comp.compress(bytes(data)) + comp.flush()


def inflate_raw(data: bytes) -> bytes:
    return zlib.decompress(bytes(data), -15)


class ParsedOpId:
    """OpId mapped to an actor-table index (columnar.js:101 actorIdToActorNum)."""

    __slots__ = ("counter", "actor_num", "actor_id")

    def __init__(self, counter, actor_num, actor_id):
        self.counter = counter
        self.actor_num = actor_num
        self.actor_id = actor_id

    def sort_key(self):
        return (self.counter, self.actor_id)


def _parse(op_id: str) -> ParsedOpId:
    p = parse_op_id(op_id)
    return ParsedOpId(p.counter, None, p.actor_id)


def expand_multi_ops(ops, start_op, actor):
    """Expands multi-insert set ops and multiOp deletions into individual ops
    (columnar.js:446)."""
    op_num = start_op
    expanded = []
    for op in ops:
        if op.get("action") == "set" and op.get("values") is not None and op.get("insert"):
            if op.get("pred"):
                raise EncodeError("multi-insert pred must be empty")
            last_elem_id = op.get("elemId")
            datatype = op.get("datatype")
            for value in op["values"]:
                if not _valid_datatype(value, datatype):
                    raise EncodeError(
                        f"Decode failed: bad value/datatype association ({value},{datatype})"
                    )
                new_op = {
                    "action": "set",
                    "obj": op["obj"],
                    "elemId": last_elem_id,
                    "value": value,
                    "pred": [],
                    "insert": True,
                }
                if datatype is not None:
                    new_op["datatype"] = datatype
                expanded.append(new_op)
                last_elem_id = f"{op_num}@{actor}"
                op_num += 1
        elif op.get("action") == "del" and op.get("multiOp", 0) > 1:
            if len(op.get("pred", [])) != 1:
                raise EncodeError("multiOp deletion must have exactly one pred")
            start_elem = parse_op_id(op["elemId"])
            start_pred = parse_op_id(op["pred"][0])
            for i in range(op["multiOp"]):
                expanded.append(
                    {
                        "action": "del",
                        "obj": op["obj"],
                        "elemId": f"{start_elem.counter + i}@{start_elem.actor_id}",
                        "pred": [f"{start_pred.counter + i}@{start_pred.actor_id}"],
                    }
                )
                op_num += 1
        else:
            expanded.append(op)
            op_num += 1
    return expanded


def _valid_datatype(value, datatype):
    if datatype is None:
        return isinstance(value, (str, bool)) or value is None
    return isinstance(value, (int, float)) and not isinstance(value, bool)


def parse_all_op_ids(changes, single):
    """Parses string opIds in changes into ParsedOpId form and builds the
    actor-ID table (columnar.js:133)."""
    actors = {}
    new_changes = []
    for change in changes:
        change = dict(change)
        actors[change["actor"]] = True
        change["ops"] = expand_multi_ops(change["ops"], change["startOp"], change["actor"])
        parsed_ops = []
        for op in change["ops"]:
            op = dict(op)
            if op["obj"] != "_root":
                op["obj"] = _parse(op["obj"])
                actors[op["obj"].actor_id] = True
            if op.get("elemId") and op["elemId"] != "_head":
                op["elemId"] = _parse(op["elemId"])
                actors[op["elemId"].actor_id] = True
            if op.get("child"):
                op["child"] = _parse(op["child"])
                actors[op["child"].actor_id] = True
            op["pred"] = [_parse(p) for p in op.get("pred", [])]
            for pred in op["pred"]:
                actors[pred.actor_id] = True
            parsed_ops.append(op)
        change["ops"] = parsed_ops
        new_changes.append(change)

    actor_ids = sorted(actors.keys())
    if single:
        author = changes[0]["actor"]
        actor_ids = [author] + [a for a in actor_ids if a != author]

    index_of = {a: i for i, a in enumerate(actor_ids)}
    for change in new_changes:
        change["actorNum"] = index_of[change["actor"]]
        for i, op in enumerate(change["ops"]):
            op["id"] = ParsedOpId(change["startOp"] + i, change["actorNum"], change["actor"])
            for field in ("obj", "elemId", "child"):
                v = op.get(field)
                if isinstance(v, ParsedOpId):
                    v.actor_num = index_of[v.actor_id]
            for pred in op["pred"]:
                pred.actor_num = index_of[pred.actor_id]
    return new_changes, actor_ids


def _get_number_type_and_value(op):
    """Determines the value-type tag for a numeric value (columnar.js:228)."""
    datatype = op.get("datatype")
    value = op["value"]
    if datatype == "counter":
        return ValueType.COUNTER, value
    if datatype == "timestamp":
        return ValueType.TIMESTAMP, value
    if datatype == "uint":
        return ValueType.LEB128_UINT, value
    if datatype == "int":
        return ValueType.LEB128_INT, value
    if datatype == "float64":
        return ValueType.IEEE754, struct.pack("<d", value)
    if (
        isinstance(value, int)
        and not isinstance(value, bool)
        and MIN_SAFE_INTEGER <= value <= MAX_SAFE_INTEGER
    ):
        return ValueType.LEB128_INT, value
    return ValueType.IEEE754, struct.pack("<d", value)


def encode_value(op, columns):
    """Encodes op['value'] into the valLen/valRaw columns (columnar.js:259)."""
    value = op.get("value")
    datatype = op.get("datatype")
    if (op["action"] not in ("set", "inc")) or value is None:
        columns["valLen"].append_value(ValueType.NULL)
    elif value is False:
        columns["valLen"].append_value(ValueType.FALSE)
    elif value is True:
        columns["valLen"].append_value(ValueType.TRUE)
    elif isinstance(value, str):
        num_bytes = columns["valRaw"].append_raw_string(value)
        columns["valLen"].append_value(num_bytes << 4 | ValueType.UTF8)
    elif isinstance(value, (bytes, bytearray)) and not (
        isinstance(datatype, int) and ValueType.MIN_UNKNOWN <= datatype <= ValueType.MAX_UNKNOWN
    ):
        num_bytes = columns["valRaw"].append_raw_bytes(value)
        columns["valLen"].append_value(num_bytes << 4 | ValueType.BYTES)
    elif isinstance(value, (int, float)) and not isinstance(value, bool):
        type_tag, enc = _get_number_type_and_value(op)
        if type_tag == ValueType.LEB128_UINT:
            num_bytes = columns["valRaw"].append_uint53(enc)
        elif type_tag == ValueType.IEEE754:
            num_bytes = columns["valRaw"].append_raw_bytes(enc)
        else:
            num_bytes = columns["valRaw"].append_int53(enc)
        columns["valLen"].append_value(num_bytes << 4 | type_tag)
    elif (
        isinstance(datatype, int)
        and ValueType.MIN_UNKNOWN <= datatype <= ValueType.MAX_UNKNOWN
        and isinstance(value, (bytes, bytearray))
    ):
        num_bytes = columns["valRaw"].append_raw_bytes(value)
        columns["valLen"].append_value(num_bytes << 4 | datatype)
    elif datatype:
        raise EncodeError(f"Unknown datatype {datatype} for value {value}")
    else:
        raise EncodeError(f"Unsupported value in operation: {value}")


def decode_value(size_tag, data):
    """Decodes a (valLen tag, valRaw bytes) pair into {'value': v, 'datatype': d}
    (columnar.js:300)."""
    if size_tag == ValueType.NULL:
        return {"value": None}
    if size_tag == ValueType.FALSE:
        return {"value": False}
    if size_tag == ValueType.TRUE:
        return {"value": True}
    tag = size_tag % 16
    if tag == ValueType.UTF8:
        return {"value": bytes(data).decode("utf-8", "surrogatepass")}
    if tag == ValueType.LEB128_UINT:
        return {"value": Decoder(data).read_uint53(), "datatype": "uint"}
    if tag == ValueType.LEB128_INT:
        return {"value": Decoder(data).read_int53(), "datatype": "int"}
    if tag == ValueType.IEEE754:
        if len(data) == 8:
            return {"value": struct.unpack("<d", bytes(data))[0], "datatype": "float64"}
        raise DecodeError(f"Invalid length for floating point number: {len(data)}")
    if tag == ValueType.COUNTER:
        return {"value": Decoder(data).read_int53(), "datatype": "counter"}
    if tag == ValueType.TIMESTAMP:
        return {"value": Decoder(data).read_int53(), "datatype": "timestamp"}
    return {"value": bytes(data), "datatype": tag}


def encode_ops(ops, for_document):
    """Encodes parsed ops into columns; returns a list of
    (column_id, column_name, encoder) sorted by column id (columnar.js:370)."""
    columns = {
        "objActor": RLEEncoder("uint"),
        "objCtr": RLEEncoder("uint"),
        "keyActor": RLEEncoder("uint"),
        "keyCtr": DeltaEncoder(),
        "keyStr": RLEEncoder("utf8"),
        "insert": BooleanEncoder(),
        "action": RLEEncoder("uint"),
        "valLen": RLEEncoder("uint"),
        "valRaw": Encoder(),
        "chldActor": RLEEncoder("uint"),
        "chldCtr": DeltaEncoder(),
    }
    if for_document:
        columns["idActor"] = RLEEncoder("uint")
        columns["idCtr"] = DeltaEncoder()
        columns["succNum"] = RLEEncoder("uint")
        columns["succActor"] = RLEEncoder("uint")
        columns["succCtr"] = DeltaEncoder()
    else:
        columns["predNum"] = RLEEncoder("uint")
        columns["predCtr"] = DeltaEncoder()
        columns["predActor"] = RLEEncoder("uint")

    for op in ops:
        # objActor/objCtr
        if op["obj"] == "_root":
            columns["objActor"].append_value(None)
            columns["objCtr"].append_value(None)
        elif op["obj"].actor_num >= 0 and op["obj"].counter > 0:
            columns["objActor"].append_value(op["obj"].actor_num)
            columns["objCtr"].append_value(op["obj"].counter)
        else:
            raise EncodeError(f"Unexpected objectId reference: {op['obj']}")

        # keyActor/keyCtr/keyStr
        if op.get("key") is not None:
            columns["keyActor"].append_value(None)
            columns["keyCtr"].append_value(None)
            columns["keyStr"].append_value(op["key"])
        elif op.get("elemId") == "_head" and op.get("insert"):
            columns["keyActor"].append_value(None)
            columns["keyCtr"].append_value(0)
            columns["keyStr"].append_value(None)
        elif op.get("elemId") is not None and op["elemId"].actor_num >= 0 and op["elemId"].counter > 0:
            columns["keyActor"].append_value(op["elemId"].actor_num)
            columns["keyCtr"].append_value(op["elemId"].counter)
            columns["keyStr"].append_value(None)
        else:
            raise EncodeError(f"Unexpected operation key: {op}")

        columns["insert"].append_value(bool(op.get("insert")))

        # action
        action = op["action"]
        if action in ACTIONS:
            columns["action"].append_value(ACTIONS.index(action))
        elif isinstance(action, int):
            columns["action"].append_value(action)
        else:
            raise EncodeError(f"Unexpected operation action: {action}")

        encode_value(op, columns)

        child = op.get("child")
        if child is not None and child.counter:
            columns["chldActor"].append_value(child.actor_num)
            columns["chldCtr"].append_value(child.counter)
        else:
            columns["chldActor"].append_value(None)
            columns["chldCtr"].append_value(None)

        if for_document:
            columns["idActor"].append_value(op["id"].actor_num)
            columns["idCtr"].append_value(op["id"].counter)
            succ = sorted(op["succ"], key=ParsedOpId.sort_key)
            columns["succNum"].append_value(len(succ))
            for s in succ:
                columns["succActor"].append_value(s.actor_num)
                columns["succCtr"].append_value(s.counter)
        else:
            pred = sorted(op["pred"], key=ParsedOpId.sort_key)
            columns["predNum"].append_value(len(pred))
            for p in pred:
                columns["predActor"].append_value(p.actor_num)
                columns["predCtr"].append_value(p.counter)

    spec = DOC_OPS_COLUMNS if for_document else CHANGE_COLUMNS
    column_list = [
        (column_id, name, columns[name]) for name, column_id in spec if name in columns
    ]
    column_list.sort(key=lambda c: c[0])
    return column_list


def decode_ops(rows, for_document):
    """Turns decoded column rows into op dicts in backend form (columnar.js:483)."""
    new_ops = []
    for row in rows:
        obj = "_root" if row["objCtr"] is None else f"{row['objCtr']}@{row['objActor']}"
        if row["keyStr"] is not None:
            elem_id = None
        elif row["keyCtr"] == 0:
            elem_id = "_head"
        else:
            elem_id = f"{row['keyCtr']}@{row['keyActor']}"
        action = ACTIONS[row["action"]] if row["action"] < len(ACTIONS) else row["action"]
        if elem_id is not None:
            new_op = {"obj": obj, "elemId": elem_id, "action": action}
        else:
            new_op = {"obj": obj, "key": row["keyStr"], "action": action}
        new_op["insert"] = bool(row["insert"])
        if action in ("set", "inc"):
            new_op["value"] = row["valLen"]
            if row.get("valLen_datatype") is not None:
                new_op["datatype"] = row["valLen_datatype"]
        if bool(row["chldCtr"] is None) != bool(row["chldActor"] is None):
            raise DecodeError(f"Mismatched child columns: {row['chldCtr']} and {row['chldActor']}")
        if row["chldCtr"] is not None:
            new_op["child"] = f"{row['chldCtr']}@{row['chldActor']}"
        if for_document:
            new_op["id"] = f"{row['idCtr']}@{row['idActor']}"
            new_op["succ"] = [f"{s['succCtr']}@{s['succActor']}" for s in row["succNum"]]
            _check_sorted_op_ids([(s["succCtr"], s["succActor"]) for s in row["succNum"]])
        else:
            new_op["pred"] = [f"{p['predCtr']}@{p['predActor']}" for p in row["predNum"]]
            _check_sorted_op_ids([(p["predCtr"], p["predActor"]) for p in row["predNum"]])
        new_ops.append(new_op)
    return new_ops


def _check_sorted_op_ids(op_ids):
    last = None
    for op_id in op_ids:
        if last is not None and last >= op_id:
            raise DecodeError("operation IDs are not in ascending order")
        last = op_id


def encoder_by_column_id(column_id):
    t = column_id & 7
    if t == ColumnType.INT_DELTA:
        return DeltaEncoder()
    if t == ColumnType.BOOLEAN:
        return BooleanEncoder()
    if t == ColumnType.STRING_RLE:
        return RLEEncoder("utf8")
    if t == ColumnType.VALUE_RAW:
        return Encoder()
    return RLEEncoder("uint")


def decoder_by_column_id(column_id, buffer):
    t = column_id & 7
    if t == ColumnType.INT_DELTA:
        return DeltaDecoder(buffer)
    if t == ColumnType.BOOLEAN:
        return BooleanDecoder(buffer)
    if t == ColumnType.STRING_RLE:
        return RLEDecoder("utf8", buffer)
    if t == ColumnType.VALUE_RAW:
        return Decoder(buffer)
    return RLEDecoder("uint", buffer)


def make_decoders(columns, column_spec):
    """Merges the columns present in the data with the expected column spec,
    instantiating empty decoders for missing columns (columnar.js:553).

    `columns` is a list of (column_id, buffer); `column_spec` is a list of
    (name, column_id). Returns a list of dicts {columnId, columnName?, decoder}.
    """
    empty = b""
    decoders = []
    ci = 0
    si = 0
    while ci < len(columns) or si < len(column_spec):
        if ci == len(columns) or (si < len(column_spec) and column_spec[si][1] < columns[ci][0]):
            name, column_id = column_spec[si]
            decoders.append(
                {"columnId": column_id, "columnName": name, "decoder": decoder_by_column_id(column_id, empty)}
            )
            si += 1
        elif si == len(column_spec) or columns[ci][0] < column_spec[si][1]:
            column_id, buffer = columns[ci]
            decoders.append({"columnId": column_id, "decoder": decoder_by_column_id(column_id, buffer)})
            ci += 1
        else:
            column_id, buffer = columns[ci]
            name = column_spec[si][0]
            decoders.append(
                {"columnId": column_id, "columnName": name, "decoder": decoder_by_column_id(column_id, buffer)}
            )
            ci += 1
            si += 1
    return decoders


def _decode_value_columns(columns, col_index, actor_ids, result):
    """Reads one value from columns[col_index]; returns number of columns
    consumed (columnar.js:339)."""
    col = columns[col_index]
    column_id = col["columnId"]
    name = col.get("columnName")
    if (
        column_id % 8 == ColumnType.VALUE_LEN
        and col_index + 1 < len(columns)
        and columns[col_index + 1]["columnId"] == column_id + 1
    ):
        size_tag = col["decoder"].read_value()
        raw = columns[col_index + 1]["decoder"].read_raw_bytes(size_tag >> 4)
        decoded = decode_value(size_tag, raw)
        result[name] = decoded["value"]
        if decoded.get("datatype") is not None:
            result[name + "_datatype"] = decoded["datatype"]
        return 2
    if column_id % 8 == ColumnType.ACTOR_ID:
        actor_num = col["decoder"].read_value()
        if actor_num is None:
            result[name] = None
        else:
            if actor_num >= len(actor_ids):
                raise DecodeError(f"No actor index {actor_num}")
            result[name] = actor_ids[actor_num]
    else:
        result[name] = col["decoder"].read_value()
    return 1


def decode_columns(columns, actor_ids, column_spec):
    """Decodes a full set of columns into a list of row dicts (columnar.js:577)."""
    columns = make_decoders(columns, column_spec)
    rows = []
    while any(not col["decoder"].done for col in columns):
        row = {}
        col = 0
        while col < len(columns):
            column_id = columns[col]["columnId"]
            group_id = column_id >> 4
            group_cols = 1
            while col + group_cols < len(columns) and columns[col + group_cols]["columnId"] >> 4 == group_id:
                group_cols += 1
            if column_id % 8 == ColumnType.GROUP_CARD:
                values = []
                count = columns[col]["decoder"].read_value()
                for _ in range(count or 0):
                    value = {}
                    offset = 1
                    while offset < group_cols:
                        offset += _decode_value_columns(columns, col + offset, actor_ids, value)
                    values.append(value)
                row[columns[col].get("columnName")] = values
                col += group_cols
            else:
                col += _decode_value_columns(columns, col, actor_ids, row)
        rows.append(row)
    return rows


def decode_column_info(decoder):
    """Reads the (columnId, bufferLen) table from a chunk (columnar.js:609)."""
    column_id_mask = ~COLUMN_TYPE_DEFLATE
    last = -1
    columns = []
    num_columns = decoder.read_uint53()
    for _ in range(num_columns):
        column_id = decoder.read_uint53()
        buffer_len = decoder.read_uint53()
        if (column_id & column_id_mask) <= (last & column_id_mask if last >= 0 else -1):
            raise DecodeError("Columns must be in ascending order")
        last = column_id
        columns.append({"columnId": column_id, "bufferLen": buffer_len})
    return columns


def encode_column_info(encoder, columns):
    """`columns` is a list of (column_id, buffer_bytes)."""
    non_empty = [(cid, buf) for cid, buf in columns if len(buf) > 0]
    encoder.append_uint53(len(non_empty))
    for cid, buf in non_empty:
        encoder.append_uint53(cid)
        encoder.append_uint53(len(buf))


def encode_container(chunk_type, body: bytes):
    """Wraps a chunk body with magic bytes, checksum, type and length
    (columnar.js:659). Returns (hash_hex, bytes)."""
    header = Encoder()
    header.append_byte(chunk_type)
    header.append_uint53(len(body))
    header_buf = header.buffer
    digest = sha256(header_buf + body).digest()
    out = MAGIC_BYTES + digest[:4] + header_buf + body
    return bytes_to_hex(digest), out


def decode_container_header(decoder, compute_hash):
    if decoder.read_raw_bytes(len(MAGIC_BYTES)) != MAGIC_BYTES:
        raise DecodeError("Data does not begin with magic bytes 85 6f 4a 83")
    expected_hash = decoder.read_raw_bytes(4)
    hash_start = decoder.offset
    chunk_type = decoder.read_byte()
    chunk_length = decoder.read_uint53()
    chunk_data = decoder.read_raw_bytes(chunk_length)
    header = {"chunkType": chunk_type, "chunkLength": chunk_length, "chunkData": chunk_data}
    if compute_hash:
        digest = sha256(decoder.buf[hash_start : decoder.offset]).digest()
        if digest[:4] != expected_hash:
            raise ChecksumError("checksum does not match data")
        header["hash"] = bytes_to_hex(digest)
    return header


def decode_change_header(decoder):
    num_deps = decoder.read_uint53()
    deps = [bytes_to_hex(decoder.read_raw_bytes(32)) for _ in range(num_deps)]
    change = {
        "actor": decoder.read_hex_string(),
        "seq": decoder.read_uint53(),
        "startOp": decoder.read_uint53(),
        "time": decoder.read_int53(),
        "message": decoder.read_prefixed_string(),
        "deps": deps,
    }
    actor_ids = [change["actor"]]
    num_actor_ids = decoder.read_uint53()
    for _ in range(num_actor_ids):
        actor_ids.append(decoder.read_hex_string())
    change["actorIds"] = actor_ids
    return change


def encode_change(change_obj) -> bytes:
    """Encodes a change (JS-object form) into the binary change format
    (columnar.js:710). Deflates if large."""
    changes, actor_ids = parse_all_op_ids([change_obj], True)
    change = changes[0]

    body = Encoder()
    deps = change.get("deps")
    if not isinstance(deps, list):
        raise TypeError("deps is not an array")  # amlint: disable=AM401 — argument-type validation
    body.append_uint53(len(deps))
    for h in sorted(deps):
        body.append_raw_bytes(hex_to_bytes(h))
    body.append_hex_string(change["actor"])
    body.append_uint53(change["seq"])
    body.append_uint53(change["startOp"])
    body.append_int53(change["time"])
    body.append_prefixed_string(change.get("message") or "")
    body.append_uint53(len(actor_ids) - 1)
    for actor in actor_ids[1:]:
        body.append_hex_string(actor)

    columns = encode_ops(change["ops"], False)
    column_buffers = [(cid, enc.buffer) for cid, _name, enc in columns]
    encode_column_info(body, column_buffers)
    for _cid, buf in column_buffers:
        body.append_raw_bytes(buf)
    if change.get("extraBytes"):
        body.append_raw_bytes(change["extraBytes"])

    hex_hash, data = encode_container(CHUNK_TYPE_CHANGE, body.buffer)
    if change_obj.get("hash") and change_obj["hash"] != hex_hash:
        raise ChecksumError(f"Change hash does not match encoding: {change_obj['hash']} != {hex_hash}")
    return deflate_change(data) if len(data) >= DEFLATE_MIN_SIZE else data


def decode_change_columns(buffer):
    """Decodes a binary change into header metadata plus raw column buffers
    (columnar.js:741)."""
    buffer = bytes(buffer)
    if buffer[8] == CHUNK_TYPE_DEFLATE:
        buffer = inflate_change(buffer)
    decoder = Decoder(buffer)
    header = decode_container_header(decoder, True)
    chunk = Decoder(header["chunkData"])
    if not decoder.done:
        raise DecodeError("Encoded change has trailing data")
    if header["chunkType"] != CHUNK_TYPE_CHANGE:
        raise DecodeError(f"Unexpected chunk type: {header['chunkType']}")

    change = decode_change_header(chunk)
    columns = decode_column_info(chunk)
    for col in columns:
        if col["columnId"] & COLUMN_TYPE_DEFLATE:
            raise DecodeError("change must not contain deflated columns")
        col["buffer"] = chunk.read_raw_bytes(col["bufferLen"])
    if not chunk.done:
        change["extraBytes"] = chunk.read_raw_bytes(len(chunk.buf) - chunk.offset)

    change["columns"] = columns
    change["hash"] = header["hash"]
    return change


_CHANGE_COLUMN_IDS = {cid: name for name, cid in CHANGE_COLUMNS}


def ops_from_column_arrays(arrs, actor_ids):
    """Assembles backend-form change ops from dense column arrays
    (struct-of-arrays) — the shared back half of the array-at-a-time decode
    paths (native/codecs.cpp and the vectorized passes in tpu/decode.py).

    `arrs` maps column names (objActor, objCtr, keyActor, keyCtr, idActor,
    idCtr, action, valLen, chldActor, chldCtr, predNum, predActor, predCtr)
    to int64 arrays with nulls as ``native.NULL_SENTINEL``, plus "insert"
    (bool array), "keyStr" as a ``(blob bytes, offsets int64[n, 2])`` pair
    (``(-1, -1)`` rows are null) and "valRaw" raw bytes. Missing/short
    columns are padded with nulls exactly like the generic decoder chain
    reading exhausted columns. Returns the op list, or None when the arrays
    are degenerate for the fast path (the caller falls back to the per-op
    decoder chain, which raises the canonical error). Output is identical
    to decode_ops(decode_columns(...)) — differentially tested."""
    from .native import NULL_SENTINEL

    empty_i = np.empty(0, np.int64)
    obj_actor = arrs.get("objActor", empty_i)
    obj_ctr = arrs.get("objCtr", empty_i)
    key_actor = arrs.get("keyActor", empty_i)
    key_ctr = arrs.get("keyCtr", empty_i)
    id_actor = arrs.get("idActor", empty_i)
    id_ctr = arrs.get("idCtr", empty_i)
    action = arrs.get("action", empty_i)
    val_len = arrs.get("valLen", empty_i)
    chld_actor = arrs.get("chldActor", empty_i)
    chld_ctr = arrs.get("chldCtr", empty_i)
    pred_num = arrs.get("predNum", empty_i)
    pred_actor = arrs.get("predActor", empty_i)
    pred_ctr = arrs.get("predCtr", empty_i)
    insert = arrs.get("insert", np.empty(0, bool))
    key_blob, key_offs = arrs.get("keyStr", (b"", np.empty((0, 2), np.int64)))
    val_raw = arrs.get("valRaw", b"")

    n_rows = max(
        obj_actor.size, obj_ctr.size, key_actor.size, key_ctr.size,
        id_actor.size, id_ctr.size, action.size, val_len.size,
        chld_actor.size, chld_ctr.size, pred_num.size, insert.size,
        key_offs.shape[0],
    )
    NULLS = NULL_SENTINEL

    def pad(arr, fill=NULLS):
        if arr.size >= n_rows:
            return arr
        out = np.full(n_rows, fill, arr.dtype)
        out[: arr.size] = arr
        return out

    obj_actor, obj_ctr = pad(obj_actor), pad(obj_ctr)
    key_actor, key_ctr = pad(key_actor), pad(key_ctr)
    action, val_len = pad(action), pad(val_len)
    chld_actor, chld_ctr = pad(chld_actor), pad(chld_ctr)
    pred_num = pad(pred_num)
    insert = (
        np.concatenate([insert, np.zeros(n_rows - insert.size, bool)])
        if insert.size < n_rows
        else insert
    )

    # valRaw slices: cumulative (valLen >> 4) with nulls contributing 0
    sizes = np.where(val_len == NULLS, 0, val_len >> 4)
    val_ends = np.cumsum(sizes)
    val_starts = val_ends - sizes
    if val_ends.size and val_ends[-1] > len(val_raw):
        return None

    num_actors = len(actor_ids)
    total_preds = int(np.sum(np.where(pred_num == NULLS, 0, pred_num)))
    if pred_actor.size < total_preds or pred_ctr.size < total_preds:
        return None

    # per-column masked value pass: every set/inc row's (valLen tag, valRaw
    # slice) pair decodes in bulk — varint payloads through one [rows, 8]
    # byte-matrix scan, doubles through one view cast — instead of a
    # Decoder object per row (decode_value). Rows the pass cannot prove
    # well-formed decode through decode_value itself, which raises the
    # canonical error.
    set_inc = (action == _ACTION_SET_IDX) | (action == _ACTION_INC_IDX)
    values = _decode_values_bulk(
        val_len, sizes, val_starts, val_raw, set_inc, NULLS
    )

    # pred column: the strings, actor-range check and ascending check all
    # run as one pass over the flat pred rows before any op materialises
    used_preds = pred_actor[:total_preds]
    used_pred_ctr = pred_ctr[:total_preds]
    if used_preds.size and int(used_preds.max()) >= num_actors:
        bad = int(used_preds[used_preds >= num_actors][0])
        raise DecodeError(f"No actor index {bad}")
    pred_strs = [
        f"{c}@{actor_ids[a]}"
        for c, a in zip(used_pred_ctr.tolist(), used_preds.tolist())
    ]
    pred_counts = np.where(pred_num == NULLS, 0, pred_num)
    pred_bounds = np.zeros(n_rows + 1, np.int64)
    np.cumsum(pred_counts, out=pred_bounds[1:])
    if total_preds:
        # ascending within each op's pred group, on (ctr, actorId string)
        row_of = np.repeat(np.arange(n_rows), pred_counts)
        same = row_of[1:] == row_of[:-1]
        for j in np.nonzero(same)[0]:
            a = (int(used_pred_ctr[j]), actor_ids[int(used_preds[j])])
            b = (int(used_pred_ctr[j + 1]), actor_ids[int(used_preds[j + 1])])
            if a >= b:
                raise DecodeError("operation IDs are not in ascending order")
    pred_bounds_l = pred_bounds.tolist()

    # plain-Python row materialisation: numpy scalar indexing costs more
    # than the dict build itself at this row count, so columns convert to
    # lists once and the loop runs on ints
    obj_actor_l = obj_actor.tolist()
    obj_ctr_l = obj_ctr.tolist()
    key_actor_l = key_actor.tolist()
    key_ctr_l = key_ctr.tolist()
    action_l = action.tolist()
    chld_actor_l = chld_actor.tolist()
    chld_ctr_l = chld_ctr.tolist()
    insert_l = insert.tolist()
    key_offs_l = key_offs.tolist()
    num_actions = len(ACTIONS)

    ops = []
    key_n = len(key_offs_l)
    key_memo: dict = {}  # (start, end) -> decoded str: RLE keys repeat
    obj_memo: dict = {}
    for i in range(n_rows):
        oa, oc = obj_actor_l[i], obj_ctr_l[i]
        if oc == NULLS:
            obj = "_root"
        else:
            obj = obj_memo.get(oc * num_actors + oa if oa != NULLS else None)
            if obj is None:
                if oa == NULLS or oa >= num_actors:
                    raise DecodeError(f"No actor index {oa}")
                obj = f"{oc}@{actor_ids[oa]}"
                obj_memo[oc * num_actors + oa] = obj
        ks = None
        if i < key_n and key_offs_l[i][0] >= 0:
            span = (key_offs_l[i][0], key_offs_l[i][1])
            ks = key_memo.get(span)
            if ks is None:
                ks = key_blob[span[0]:span[1]].decode("utf-8", "surrogatepass")
                key_memo[span] = ks
        if ks is not None:
            elem_id = None
        elif key_ctr_l[i] != NULLS and key_ctr_l[i] == 0:
            elem_id = "_head"
        else:
            kc, ka = key_ctr_l[i], key_actor_l[i]
            if kc == NULLS or ka == NULLS:
                return None  # degenerate key row: defer to the generic path
            if ka >= num_actors:
                raise DecodeError(f"No actor index {ka}")
            elem_id = f"{kc}@{actor_ids[ka]}"
        act = action_l[i] if action_l[i] != NULLS else None
        act_name = ACTIONS[act] if act is not None and act < num_actions else act
        if elem_id is not None:
            op = {"obj": obj, "elemId": elem_id, "action": act_name}
        else:
            op = {"obj": obj, "key": ks, "action": act_name}
        op["insert"] = insert_l[i]
        if act_name in ("set", "inc"):
            value, datatype = values[i]
            op["value"] = value
            if datatype is not None:
                op["datatype"] = datatype
        cc, ca = chld_ctr_l[i], chld_actor_l[i]
        if (cc == NULLS) != (ca == NULLS):
            raise DecodeError(
                "Mismatched child columns: "
                f"{None if cc == NULLS else cc} and "
                f"{None if ca == NULLS else ca}"
            )
        if cc != NULLS:
            if ca >= num_actors:
                raise DecodeError(f"No actor index {ca}")
            op["child"] = f"{cc}@{actor_ids[ca]}"
        op["pred"] = pred_strs[pred_bounds_l[i]:pred_bounds_l[i + 1]]
        ops.append(op)
    return ops


_ACTION_SET_IDX = ACTIONS.index("set")
_ACTION_INC_IDX = ACTIONS.index("inc")

#: valLen type tags whose payload is a single LEB128 varint
_VARINT_TAG_DATATYPE = {
    ValueType.LEB128_UINT: "uint",
    ValueType.LEB128_INT: "int",
    ValueType.COUNTER: "counter",
    ValueType.TIMESTAMP: "timestamp",
}


def _decode_values_bulk(val_len, sizes, val_starts, val_raw, mask, NULLS):
    """Bulk decode_value over the (valLen, valRaw) columns: returns a list
    with ``(value, datatype)`` at every row where `mask` is set (None
    elsewhere). The varint-tagged rows decode through one masked byte-
    matrix pass; IEEE754 rows through one view cast; rows the vector pass
    cannot prove well-formed fall through to decode_value per row, which
    produces the canonical value or error."""
    n = val_len.shape[0]
    out = [None] * n
    idx = np.nonzero(mask)[0]
    if idx.size == 0:
        return out
    tags = np.where(val_len == NULLS, 0, val_len)[idx]
    t = tags % 16
    starts = val_starts[idx]
    szs = sizes[idx]

    special = tags <= ValueType.TRUE  # NULL / FALSE / TRUE full tags
    for j in np.nonzero(special)[0]:
        out[idx[j]] = ((None, False, True)[tags[j]], None)

    is_varint = ~special & (
        (t == ValueType.LEB128_UINT) | (t == ValueType.LEB128_INT)
        | (t == ValueType.COUNTER) | (t == ValueType.TIMESTAMP)
    )
    hard = np.zeros(idx.shape[0], bool)
    raw_arr = np.frombuffer(val_raw, np.uint8)
    if is_varint.any() and raw_arr.size == 0:
        hard[is_varint] = True  # zero-size varint slices: canonical error
        is_varint[:] = False
    if is_varint.any():
        v = np.nonzero(is_varint)[0]
        cols = np.arange(8)
        pos = starts[v, None] + cols[None, :]
        in_slice = cols[None, :] < np.minimum(szs[v], 8)[:, None]
        b = np.where(
            in_slice, raw_arr[np.minimum(pos, raw_arr.size - 1)], 0
        ).astype(np.int64)
        is_end = ((b & 0x80) == 0) & in_slice
        has_end = is_end.any(axis=1)
        first_end = is_end.argmax(axis=1)
        keep = cols[None, :] <= first_end[:, None]
        payload = (b & 0x7F) * keep
        u = (payload << (7 * cols)[None, :]).sum(axis=1)
        lengths = first_end + 1
        last = b[np.arange(v.shape[0]), first_end]
        sgn = ((last & 0x40) != 0).astype(np.int64)
        s = u - (sgn << (7 * lengths))
        signed_tag = t[v] != ValueType.LEB128_UINT
        vals = np.where(signed_tag, s, u)
        in_range = np.where(
            signed_tag,
            (vals >= MIN_SAFE_INTEGER) & (vals <= MAX_SAFE_INTEGER),
            u <= MAX_SAFE_INTEGER,
        )
        ok = has_end & in_range
        hard[v[~ok]] = True
        vals_l = vals.tolist()
        for k, j in enumerate(v):
            if ok[k]:
                out[idx[j]] = (vals_l[k], _VARINT_TAG_DATATYPE[int(t[j])])

    is_f64 = ~special & (t == ValueType.IEEE754)
    if is_f64.any():
        v = np.nonzero(is_f64)[0]
        exact = szs[v] == 8
        hard[v[~exact]] = True  # canonical "Invalid length" via decode_value
        v = v[exact]
        if v.size:
            mat = raw_arr[starts[v, None] + np.arange(8)[None, :]]
            floats = mat.copy().view("<f8").ravel().tolist()
            for k, j in enumerate(v):
                out[idx[j]] = (floats[k], "float64")

    rest = ~special & ~is_varint & ~is_f64
    for j in np.nonzero(rest | hard)[0]:
        if out[idx[j]] is None or hard[j]:
            decoded = decode_value(
                int(tags[j]), val_raw[starts[j]:starts[j] + szs[j]]
            )
            out[idx[j]] = (decoded["value"], decoded.get("datatype"))
    return out


def _native_change_ops(cols, actor_ids):
    """Array-at-a-time change-op decoding through the native column codecs
    (native/codecs.cpp); returns None when the fast path does not apply
    (library missing, unknown columns present). ~20x faster than the
    per-op decoder chain for bulk applyChanges ingest: each column is
    decoded to a dense array in one native call and the op dicts are
    assembled by ops_from_column_arrays."""
    from . import native

    if not native.available():
        return None
    by_name = {}
    for cid, buf in cols:
        name = _CHANGE_COLUMN_IDS.get(cid)
        if name is None:
            return None  # unknown column: preserve via the generic path
        by_name[name] = bytes(buf)

    empty = b""

    def ints(name, kind, max_count=None):
        """Decodes an int column fully; returns int64 array (nulls =
        native.NULL_SENTINEL)."""
        buf = by_name.get(name, empty)
        if not buf:
            return np.empty(0, np.int64)
        cap = max_count
        for attempt in range(3):
            try:
                if kind == "delta":
                    return native.delta_decode(buf, max_count=cap)
                return native.rle_decode(buf, max_count=cap)
            except ValueError:
                if cap is None:
                    cap = max(1024, len(buf) * 64)
                cap *= 16
                if attempt == 2:
                    raise
        raise AssertionError

    try:
        arrs = {
            "objActor": ints("objActor", "rle"),
            "objCtr": ints("objCtr", "rle"),
            "keyActor": ints("keyActor", "rle"),
            "keyCtr": ints("keyCtr", "delta"),
            "idActor": ints("idActor", "rle"),
            "idCtr": ints("idCtr", "delta"),
            "action": ints("action", "rle"),
            "valLen": ints("valLen", "rle"),
            "chldActor": ints("chldActor", "rle"),
            "chldCtr": ints("chldCtr", "delta"),
            "predNum": ints("predNum", "rle"),
            "predActor": ints("predActor", "rle"),
            "predCtr": ints("predCtr", "delta"),
            "insert": (
                native.bool_decode(by_name["insert"])
                if by_name.get("insert")
                else np.empty(0, bool)
            ),
            "keyStr": (
                native.strrle_decode(by_name["keyStr"])
                if by_name.get("keyStr")
                else (b"", np.empty((0, 2), np.int64))
            ),
            "valRaw": by_name.get("valRaw", empty),
        }
    except ValueError:
        return None  # malformed for the fast path: let the generic path raise
    return ops_from_column_arrays(arrs, actor_ids)


# Vectorized decode backend (tpu/decode.py): registered by the device layer
# when it loads, so decode_change gains the masked-vector-pass fast path on
# hosts without the native library WITHOUT this host-only module importing
# tpu/ (amlint AM301). Signature matches _native_change_ops.
_VECTOR_DECODER = None


def set_vector_decoder(fn) -> None:
    """Registers `fn(cols, actor_ids) -> ops | None` as the vectorized
    change-op decode backend (see tpu/decode.py)."""
    global _VECTOR_DECODER
    _VECTOR_DECODER = fn


def decode_change(buffer):
    """Decodes one binary change into its object representation."""
    change = decode_change_columns(buffer)
    cols = [(c["columnId"], c["buffer"]) for c in change["columns"]]
    ops = _native_change_ops(cols, change["actorIds"])
    if ops is None and _VECTOR_DECODER is not None:
        ops = _VECTOR_DECODER(cols, change["actorIds"])
    if ops is None:
        ops = decode_ops(decode_columns(cols, change["actorIds"], CHANGE_COLUMNS), False)
    change["ops"] = ops
    del change["actorIds"]
    del change["columns"]
    return change


# ---------------------------------------------------------------------- #
# decode memoization: a change gossiped to N documents (the farm fans one
# delivery across a batch) or replayed across sync rounds (sync peers re-
# derive metadata for every candidate every round) is parsed ONCE. Keyed by
# the raw chunk bytes — the change hash is sha256 over those bytes, so the
# key identifies the change exactly. Both caches share one metric family:
# codecs.decode_cache.{hits,misses,evictions,bytes}. Entry counts bound the
# working set; AM_DECODE_CACHE_BYTES (default 64 MiB, split across both)
# bounds pinned host memory so a few huge chunks cannot exhaust it.

_DECODE_CACHE_BYTES = int(
    os.environ.get("AM_DECODE_CACHE_BYTES", str(64 << 20))
)
_DECODED_CHANGE_CACHE = DecodeCache(
    int(os.environ.get("AM_DECODE_CACHE_CHANGES", "8192")),
    max_bytes=_DECODE_CACHE_BYTES // 2,
)
_DECODED_META_CACHE = DecodeCache(
    int(os.environ.get("AM_DECODE_CACHE_METAS", "16384")),
    max_bytes=_DECODE_CACHE_BYTES // 2,
)


def decode_change_cached(buffer):
    """`decode_change` through the bounded decode LRU.

    Returns a SHALLOW COPY of the cached change dict: callers may attach
    top-level keys (the farm adds ``change["buffer"]``) but must treat the
    shared ``ops``/``deps`` values as immutable."""
    key = bytes(buffer)
    change = _DECODED_CHANGE_CACHE.get(key)
    if change is None:
        change = decode_change(key)
        _DECODED_CHANGE_CACHE.put(key, change)
    return dict(change)


def decode_change_meta_cached(buffer):
    """`decode_change_meta(buffer, compute_hash=True)` through the decode
    LRU. Returns a shallow copy; the shared ``deps``/``change`` values must
    be treated as immutable."""
    key = bytes(buffer)
    meta = _DECODED_META_CACHE.get(key)
    if meta is None:
        meta = decode_change_meta(key, True)
        _DECODED_META_CACHE.put(key, meta)
    return dict(meta)


def clear_decode_caches():
    """Empties both decode LRUs (testing hook; never required for
    correctness — entries are keyed by immutable bytes)."""
    _DECODED_CHANGE_CACHE.clear()
    _DECODED_META_CACHE.clear()


def decode_change_meta(buffer, compute_hash):
    """Decodes only the header fields of a binary change (columnar.js:783)."""
    buffer = bytes(buffer)
    if buffer[8] == CHUNK_TYPE_DEFLATE:
        buffer = inflate_change(buffer)
    header = decode_container_header(Decoder(buffer), compute_hash)
    if header["chunkType"] != CHUNK_TYPE_CHANGE:
        raise DecodeError("Buffer chunk type is not a change")
    meta = decode_change_header(Decoder(header["chunkData"]))
    meta["change"] = buffer
    if compute_hash:
        meta["hash"] = header["hash"]
    return meta


def deflate_change(buffer: bytes) -> bytes:
    header = decode_container_header(Decoder(buffer), False)
    if header["chunkType"] != CHUNK_TYPE_CHANGE:
        raise DecodeError(f"Unexpected chunk type: {header['chunkType']}")
    compressed = deflate_raw(header["chunkData"])
    out = Encoder()
    out.append_raw_bytes(buffer[:8])  # copy MAGIC_BYTES and checksum
    out.append_byte(CHUNK_TYPE_DEFLATE)
    out.append_uint53(len(compressed))
    out.append_raw_bytes(compressed)
    return out.buffer


def inflate_change(buffer: bytes) -> bytes:
    header = decode_container_header(Decoder(buffer), False)
    if header["chunkType"] != CHUNK_TYPE_DEFLATE:
        raise DecodeError(f"Unexpected chunk type: {header['chunkType']}")
    decompressed = inflate_raw(header["chunkData"])
    out = Encoder()
    out.append_raw_bytes(buffer[:8])
    out.append_byte(CHUNK_TYPE_CHANGE)
    out.append_uint53(len(decompressed))
    out.append_raw_bytes(decompressed)
    return out.buffer


def split_containers(buffer):
    """Splits concatenated binary chunks into a list of single-chunk buffers."""
    buffer = bytes(buffer)
    decoder = Decoder(buffer)
    chunks = []
    start = 0
    while not decoder.done:
        decode_container_header(decoder, False)
        chunks.append(buffer[start : decoder.offset])
        start = decoder.offset
    return chunks


def decode_changes(binary_changes):
    """Decodes a list of binary changes and/or documents into change objects."""
    decoded = []
    for binary_change in binary_changes:
        for chunk in split_containers(binary_change):
            if chunk[8] == CHUNK_TYPE_DOCUMENT:
                decoded.extend(decode_document(chunk))
            elif chunk[8] in (CHUNK_TYPE_CHANGE, CHUNK_TYPE_DEFLATE):
                decoded.append(decode_change(chunk))
            # ignore chunks of unknown type
    return decoded


def _sort_op_ids_key(op_id):
    if op_id == "_root":
        return (-1, "")
    p = parse_op_id(op_id)
    return (p.counter, p.actor_id)


def group_change_ops(changes, ops):
    """Reconstructs per-change op lists from a document's flat op set
    (columnar.js:876). Mutates `changes`."""
    changes_by_actor = {}
    for change in changes:
        change["ops"] = []
        changes_by_actor.setdefault(change["actor"], [])
        if change["seq"] != len(changes_by_actor[change["actor"]]) + 1:
            raise DecodeError(
                f"Expected seq = {len(changes_by_actor[change['actor']]) + 1}, got {change['seq']}"
            )
        if change["seq"] > 1 and changes_by_actor[change["actor"]][change["seq"] - 2]["maxOp"] > change["maxOp"]:
            raise DecodeError("maxOp must increase monotonically per actor")
        changes_by_actor[change["actor"]].append(change)

    ops_by_id = {}
    for op in ops:
        if op["action"] == "del":
            raise DecodeError("document should not contain del operations")
        op["pred"] = ops_by_id[op["id"]]["pred"] if op["id"] in ops_by_id else []
        ops_by_id[op["id"]] = op
        for succ in op["succ"]:
            if succ not in ops_by_id:
                if op.get("elemId") is not None:
                    elem_id = op["id"] if op["insert"] else op["elemId"]
                    ops_by_id[succ] = {
                        "id": succ, "action": "del", "obj": op["obj"], "elemId": elem_id, "pred": []
                    }
                else:
                    ops_by_id[succ] = {
                        "id": succ, "action": "del", "obj": op["obj"], "key": op["key"], "pred": []
                    }
            ops_by_id[succ]["pred"].append(op["id"])
        del op["succ"]
    for op in ops_by_id.values():
        if op["action"] == "del":
            ops.append(op)

    for op in ops:
        p = parse_op_id(op["id"])
        actor_changes = changes_by_actor[p.actor_id]
        left, right = 0, len(actor_changes)
        while left < right:
            index = (left + right) // 2
            if actor_changes[index]["maxOp"] < p.counter:
                left = index + 1
            else:
                right = index
        if left >= len(actor_changes):
            raise DecodeError(f"Operation ID {op['id']} outside of allowed range")
        actor_changes[left]["ops"].append(op)

    for change in changes:
        change["ops"].sort(key=lambda op: _sort_op_ids_key(op["id"]))
        change["startOp"] = change["maxOp"] - len(change["ops"]) + 1
        del change["maxOp"]
        for i, op in enumerate(change["ops"]):
            expected_id = f"{change['startOp'] + i}@{change['actor']}"
            if op["id"] != expected_id:
                raise DecodeError(f"Expected opId {expected_id}, got {op['id']}")
            del op["id"]


def decode_document_changes(changes, expected_heads):
    """Finalises changes decoded from a document: resolves dep indexes into
    hashes, re-encodes each change to compute its hash (columnar.js:945)."""
    heads = {}
    for i, change in enumerate(changes):
        change["deps"] = []
        for dep in change["depsNum"]:
            index = dep["depsIndex"]
            if index >= len(changes) or "hash" not in changes[index]:
                raise DecodeError(f"No hash for index {index} while processing index {i}")
            h = changes[index]["hash"]
            change["deps"].append(h)
            heads.pop(h, None)
        change["deps"].sort()
        del change["depsNum"]

        if change.get("extraLen_datatype") != ValueType.BYTES:
            raise DecodeError(f"Bad datatype for extra bytes: {ValueType.BYTES}")
        change["extraBytes"] = change["extraLen"]
        change.pop("extraLen_datatype", None)
        change.pop("extraLen", None)
        change.pop("extraRaw", None)

        changes[i] = decode_change(encode_change(change))
        heads[changes[i]["hash"]] = True

    actual_heads = sorted(heads.keys())
    if actual_heads != sorted(expected_heads):
        raise ChecksumError(
            f"Mismatched heads hashes: expected {', '.join(expected_heads)}, "
            f"got {', '.join(actual_heads)}"
        )


def encode_document_header(doc) -> bytes:
    """Encodes a document chunk. `doc` is a dict with keys changesColumns,
    opsColumns (lists of (column_id, buffer)), actorIds, heads, headsIndexes,
    extraBytes (columnar.js:983)."""
    changes_columns = [list(c) for c in doc["changesColumns"]]
    ops_columns = [list(c) for c in doc["opsColumns"]]
    for col in changes_columns:
        _deflate_column(col)
    for col in ops_columns:
        _deflate_column(col)

    body = Encoder()
    body.append_uint53(len(doc["actorIds"]))
    for actor in doc["actorIds"]:
        body.append_hex_string(actor)
    heads = sorted(doc["heads"])
    body.append_uint53(len(heads))
    for head in heads:
        body.append_raw_bytes(hex_to_bytes(head))
    encode_column_info(body, [(c[0], c[1]) for c in changes_columns])
    encode_column_info(body, [(c[0], c[1]) for c in ops_columns])
    for _cid, buf in changes_columns:
        body.append_raw_bytes(buf)
    for _cid, buf in ops_columns:
        body.append_raw_bytes(buf)
    for index in doc.get("headsIndexes", []):
        body.append_uint53(index)
    if doc.get("extraBytes"):
        body.append_raw_bytes(doc["extraBytes"])
    _hash, data = encode_container(CHUNK_TYPE_DOCUMENT, body.buffer)
    return data


def decode_document_header(buffer):
    doc_decoder = Decoder(bytes(buffer))
    header = decode_container_header(doc_decoder, True)
    decoder = Decoder(header["chunkData"])
    if not doc_decoder.done:
        raise DecodeError("Encoded document has trailing data")
    if header["chunkType"] != CHUNK_TYPE_DOCUMENT:
        raise DecodeError(f"Unexpected chunk type: {header['chunkType']}")

    actor_ids = [decoder.read_hex_string() for _ in range(decoder.read_uint53())]
    num_heads = decoder.read_uint53()
    heads = [bytes_to_hex(decoder.read_raw_bytes(32)) for _ in range(num_heads)]
    heads_indexes = []

    changes_columns = decode_column_info(decoder)
    ops_columns = decode_column_info(decoder)
    for col in changes_columns:
        col["buffer"] = decoder.read_raw_bytes(col["bufferLen"])
        _inflate_column(col)
    for col in ops_columns:
        col["buffer"] = decoder.read_raw_bytes(col["bufferLen"])
        _inflate_column(col)
    if not decoder.done:
        for _ in range(num_heads):
            heads_indexes.append(decoder.read_uint53())

    extra_bytes = decoder.read_raw_bytes(len(decoder.buf) - decoder.offset)
    return {
        "changesColumns": [(c["columnId"], c["buffer"]) for c in changes_columns],
        "opsColumns": [(c["columnId"], c["buffer"]) for c in ops_columns],
        "actorIds": actor_ids,
        "heads": heads,
        "headsIndexes": heads_indexes,
        "extraBytes": extra_bytes,
    }


def decode_document(buffer):
    """Decodes a document chunk into the list of changes it contains."""
    doc = decode_document_header(buffer)
    changes = decode_columns(doc["changesColumns"], doc["actorIds"], DOCUMENT_COLUMNS)
    ops = decode_ops(decode_columns(doc["opsColumns"], doc["actorIds"], DOC_OPS_COLUMNS), True)
    group_change_ops(changes, ops)
    decode_document_changes(changes, doc["heads"])
    return changes


def _deflate_column(column):
    if len(column[1]) >= DEFLATE_MIN_SIZE:
        column[1] = deflate_raw(column[1])
        column[0] |= COLUMN_TYPE_DEFLATE


def _inflate_column(column):
    if column["columnId"] & COLUMN_TYPE_DEFLATE:
        column["buffer"] = inflate_raw(column["buffer"])
        column["columnId"] ^= COLUMN_TYPE_DEFLATE
