"""Data synchronisation protocol: Bloom-filter have/need change exchange.

Port of /root/reference/backend/sync.js (wire-format compatible). Based on:
Martin Kleppmann and Heidi Howard, "Byzantine Eventual Consistency and the
Fundamental Limits of Peer-to-Peer Databases" (https://arxiv.org/abs/2012.00472).

The batched multi-document variant (thousands of (doc, peer) pairs with
device-side Bloom construction/query) lives in automerge_tpu.tpu.sync_batch;
this module is the single-document protocol implementation.
"""
# amlint: host-only — pure-host layer: must not import tpu/ or jax
from __future__ import annotations

from math import ceil

from . import backend as Backend
from .codecs import Decoder, Encoder, bytes_to_hex, hex_to_bytes
from .columnar import decode_change_meta_cached
from .errors import AutomergeError, EncodeError, SyncProtocolError
from .obs.metrics import get_metrics
from .testing.faults import fire as _fault_point

HASH_SIZE = 32
MESSAGE_TYPE_SYNC = 0x42
PEER_STATE_TYPE = 0x43

# 1% false positive rate; the parameters are encoded in the wire format so
# they can be changed without breaking protocol compatibility (sync.js:29-31)
BITS_PER_ENTRY = 10
NUM_PROBES = 7

# sync-protocol metrics (obs/metrics.py; disabled unless a workload opts
# in). The batched farm driver (tpu/sync_farm.py) records into the SAME
# instruments — fetched by name from the process-wide registry — so
# sequential and batched sync accumulate one set of totals.
_METRICS = get_metrics()
_M_MSGS_GEN = _METRICS.counter(
    "sync.messages.generated", "sync messages encoded for peers"
)
_M_MSGS_RECV = _METRICS.counter(
    "sync.messages.received", "sync messages decoded from peers"
)
_M_BYTES_SENT = _METRICS.counter(
    "sync.bytes.sent", "wire bytes of generated sync messages"
)
_M_BYTES_RECV = _METRICS.counter(
    "sync.bytes.received", "wire bytes of received sync messages"
)
_M_CHANGES_SENT = _METRICS.counter(
    "sync.changes.sent", "changes attached to generated sync messages"
)
_M_CHANGES_RECV = _METRICS.counter(
    "sync.changes.received", "changes carried by received sync messages"
)
_M_NEED_REQUESTED = _METRICS.counter(
    "sync.changes.need_requested", "hashes peers explicitly requested via need"
)
_M_BLOOM_PROBES = _METRICS.counter(
    "sync.bloom.probes", "Bloom filter bit probes evaluated (host + device)"
)
_M_BLOOM_HITS = _METRICS.counter(
    "sync.bloom.hits", "Bloom membership tests that returned positive"
)
_M_BLOOM_FP = _METRICS.counter(
    "sync.bloom.false_positives",
    "Bloom positives contradicted by an explicit peer need (changes the "
    "filter wrongly claimed the peer already had)",
)
_M_REJECTED = _METRICS.counter(
    "sync.messages.rejected",
    "received sync messages rejected as malformed or inapplicable "
    "(SyncProtocolError; local state untouched)",
)


class BloomFilter:
    """Bloom filter over SHA-256 change hashes, serialisable for network
    transmission (sync.js:38)."""

    def __init__(self, arg):
        if isinstance(arg, list):
            self.num_entries = len(arg)
            self.num_bits_per_entry = BITS_PER_ENTRY
            self.num_probes = NUM_PROBES
            self.bits = bytearray(ceil(self.num_entries * self.num_bits_per_entry / 8))
            for h in arg:
                self.add_hash(h)
        elif isinstance(arg, (bytes, bytearray, memoryview)):
            arg = bytes(arg)
            if len(arg) == 0:
                self.num_entries = 0
                self.num_bits_per_entry = 0
                self.num_probes = 0
                self.bits = bytearray(0)
            else:
                decoder = Decoder(arg)
                self.num_entries = decoder.read_uint32()
                self.num_bits_per_entry = decoder.read_uint32()
                self.num_probes = decoder.read_uint32()
                self.bits = bytearray(
                    decoder.read_raw_bytes(ceil(self.num_entries * self.num_bits_per_entry / 8))
                )
        else:
            raise TypeError("invalid argument")  # amlint: disable=AM401 — argument-type validation

    @property
    def bytes(self) -> bytes:
        if self.num_entries == 0:
            return b""
        encoder = Encoder()
        encoder.append_uint32(self.num_entries)
        encoder.append_uint32(self.num_bits_per_entry)
        encoder.append_uint32(self.num_probes)
        encoder.append_raw_bytes(self.bits)
        return encoder.buffer

    def get_probes(self, hash_):
        """Triple-hashing probe sequence from the first 12 bytes of the hash
        (sync.js:88; Dillinger & Manolios, FMCAD 2004)."""
        hash_bytes = hex_to_bytes(hash_)
        modulo = 8 * len(self.bits)
        if len(hash_bytes) != 32:
            raise SyncProtocolError(f"Not a 256-bit hash: {hash_}")
        x = int.from_bytes(hash_bytes[0:4], "little") % modulo
        y = int.from_bytes(hash_bytes[4:8], "little") % modulo
        z = int.from_bytes(hash_bytes[8:12], "little") % modulo
        probes = [x]
        for _ in range(1, self.num_probes):
            x = (x + y) % modulo
            y = (y + z) % modulo
            probes.append(x)
        return probes

    def add_hash(self, hash_):
        for probe in self.get_probes(hash_):
            self.bits[probe >> 3] |= 1 << (probe & 7)

    def contains_hash(self, hash_):
        if self.num_entries == 0:
            return False
        probes = self.get_probes(hash_)
        for i, probe in enumerate(probes):
            if not (self.bits[probe >> 3] & (1 << (probe & 7))):
                _M_BLOOM_PROBES.inc(i + 1)
                return False
        _M_BLOOM_PROBES.inc(len(probes))
        _M_BLOOM_HITS.inc()
        return True


def _encode_hashes(encoder, hashes):
    if not isinstance(hashes, list):
        raise TypeError("hashes must be a list")  # amlint: disable=AM401 — argument-type validation
    encoder.append_uint32(len(hashes))
    for i, h in enumerate(hashes):
        if i > 0 and hashes[i - 1] >= h:
            raise EncodeError("hashes must be sorted")
        data = hex_to_bytes(h)
        if len(data) != HASH_SIZE:
            raise TypeError("heads hashes must be 256 bits")  # amlint: disable=AM401 — argument-type validation
        encoder.append_raw_bytes(data)


def _decode_hashes(decoder):
    return [bytes_to_hex(decoder.read_raw_bytes(HASH_SIZE)) for _ in range(decoder.read_uint32())]


def encode_sync_message(message) -> bytes:
    encoder = Encoder()
    encoder.append_byte(MESSAGE_TYPE_SYNC)
    _encode_hashes(encoder, message["heads"])
    _encode_hashes(encoder, message["need"])
    encoder.append_uint32(len(message["have"]))
    for have in message["have"]:
        _encode_hashes(encoder, have["lastSync"])
        encoder.append_prefixed_bytes(have["bloom"])
    encoder.append_uint32(len(message["changes"]))
    for change in message["changes"]:
        encoder.append_prefixed_bytes(change)
    return encoder.buffer


def decode_sync_message(data):
    decoder = Decoder(data)
    message_type = decoder.read_byte()
    if message_type != MESSAGE_TYPE_SYNC:
        raise SyncProtocolError(f"Unexpected message type: {message_type}")
    heads = _decode_hashes(decoder)
    need = _decode_hashes(decoder)
    have_count = decoder.read_uint32()
    message = {"heads": heads, "need": need, "have": [], "changes": []}
    for _ in range(have_count):
        last_sync = _decode_hashes(decoder)
        bloom = decoder.read_prefixed_bytes()
        message["have"].append({"lastSync": last_sync, "bloom": bloom})
    change_count = decoder.read_uint32()
    for _ in range(change_count):
        message["changes"].append(decoder.read_prefixed_bytes())
    # Trailing bytes are ignored for forward compatibility
    return message


#: version tag of the optional session-supervision extension appended after
#: sharedHeads by encode_sync_state(..., session=...). Pre-extension blobs
#: simply end after the hashes; pre-extension decoders ignore trailing
#: bytes, so the formats are compatible in both directions.
SESSION_EXT_VERSION = 1


def encode_sync_state(sync_state, session=None) -> bytes:
    """Persists the durable part of a peer state (sharedHeads only; the
    ephemeral fields are deliberately dropped, sync.js:206).

    `session`, when given, is the supervision envelope persisted by
    ``SyncSession.save()`` — ``{"epoch", "seqOut", "lastSeen",
    "peerEpoch"}`` plus the watchdog counters (``wdRounds``, ``wdStage``,
    ``wdStalls``, ``wdEscalations``, ``wdResets``) — appended as a
    versioned extension block that old decoders skip as trailing bytes.
    The watchdog fields sit AFTER the original extension fields so the
    encoding is prefix-identical to pre-watchdog blobs: old decoders stop
    after ``peerEpoch`` and ignore the tail, and blobs written before the
    watchdog fields existed decode with the counters at zero (without the
    tail a restart silently re-armed a stalled channel's escalation
    ladder from scratch)."""
    encoder = Encoder()
    encoder.append_byte(PEER_STATE_TYPE)
    _encode_hashes(encoder, sync_state["sharedHeads"])
    if session is not None:
        encoder.append_byte(SESSION_EXT_VERSION)
        encoder.append_uint32(session["epoch"])
        encoder.append_uint53(session["seqOut"])
        encoder.append_uint53(session["lastSeen"])
        peer_epoch = session.get("peerEpoch")
        encoder.append_byte(0 if peer_epoch is None else 1)
        encoder.append_uint32(peer_epoch or 0)
        encoder.append_uint32(session.get("wdRounds", 0))
        encoder.append_uint32(session.get("wdStage", 0))
        encoder.append_uint32(session.get("wdStalls", 0))
        encoder.append_uint32(session.get("wdEscalations", 0))
        encoder.append_uint32(session.get("wdResets", 0))
    return encoder.buffer


def decode_sync_state(data):
    """Restores a persisted peer state. Truncated or garbage bytes raise
    ``SyncProtocolError`` (never a raw ``IndexError``/``DecodeError``) and
    construct no partial state. A blob carrying the session extension
    yields a ``"session"`` key (consumed by ``SyncSession.restore``);
    pre-extension blobs decode exactly as before."""
    try:
        decoder = Decoder(data)
        record_type = decoder.read_byte()
        if record_type != PEER_STATE_TYPE:
            raise SyncProtocolError(f"Unexpected record type: {record_type}")
        shared_heads = _decode_hashes(decoder)
        session = None
        if not decoder.done:
            version = decoder.read_byte()
            if version != SESSION_EXT_VERSION:
                raise SyncProtocolError(
                    f"Unknown sync-state session extension version: {version}"
                )
            epoch = decoder.read_uint32()
            seq_out = decoder.read_uint53()
            last_seen = decoder.read_uint53()
            peer_known = decoder.read_byte()
            peer_epoch = decoder.read_uint32()
            session = {
                "epoch": epoch,
                "seqOut": seq_out,
                "lastSeen": last_seen,
                "peerEpoch": peer_epoch if peer_known else None,
                "wdRounds": 0,
                "wdStage": 0,
                "wdStalls": 0,
                "wdEscalations": 0,
                "wdResets": 0,
            }
            if not decoder.done:
                # watchdog/backoff tail (absent in blobs written before
                # the counters were persisted; prefix-identical)
                session["wdRounds"] = decoder.read_uint32()
                session["wdStage"] = decoder.read_uint32()
                session["wdStalls"] = decoder.read_uint32()
                session["wdEscalations"] = decoder.read_uint32()
                session["wdResets"] = decoder.read_uint32()
    except SyncProtocolError:
        raise
    except (ValueError, TypeError, IndexError) as exc:
        raise SyncProtocolError(f"malformed sync state: {exc}") from exc
    state = init_sync_state()
    state["sharedHeads"] = shared_heads
    if session is not None:
        state["session"] = session
    return state


def make_bloom_filter(backend, last_sync):
    new_changes = Backend.get_changes(backend, last_sync)
    hashes = [decode_change_meta_cached(change)["hash"] for change in new_changes]
    return {"lastSync": last_sync, "bloom": BloomFilter(hashes).bytes}


def get_changes_to_send(backend, have, need):
    """Changes to send given the peer's have/need (sync.js:246): Bloom-negative
    changes, their dependents closure, plus explicitly needed hashes."""
    _M_NEED_REQUESTED.inc(len(need))
    if not have:
        changes = [Backend.get_change_by_hash(backend, h) for h in need]
        return [c for c in changes if c is not None]

    last_sync_hashes = {}
    bloom_filters = []
    for h in have:
        for hash_ in h["lastSync"]:
            last_sync_hashes[hash_] = True
        bloom_filters.append(BloomFilter(h["bloom"]))

    changes = [
        decode_change_meta_cached(change)
        for change in Backend.get_changes(backend, list(last_sync_hashes.keys()))
    ]

    change_hashes = {}
    dependents = {}
    hashes_to_send = {}
    for change in changes:
        change_hashes[change["hash"]] = True
        for dep in change["deps"]:
            dependents.setdefault(dep, []).append(change["hash"])
        if all(not bloom.contains_hash(change["hash"]) for bloom in bloom_filters):
            hashes_to_send[change["hash"]] = True

    # Include any changes that depend on a Bloom-negative change
    stack = list(hashes_to_send.keys())
    while stack:
        hash_ = stack.pop()
        for dep in dependents.get(hash_, []):
            if dep not in hashes_to_send:
                hashes_to_send[dep] = True
                stack.append(dep)

    changes_to_send = []
    for hash_ in need:
        # a needed hash we hold but withheld as Bloom-positive is a
        # *detected* false positive: the filter claimed the peer had it
        if hash_ in change_hashes and hash_ not in hashes_to_send:
            _M_BLOOM_FP.inc()
        hashes_to_send[hash_] = True
        if hash_ not in change_hashes:
            change = Backend.get_change_by_hash(backend, hash_)
            if change is not None:
                changes_to_send.append(change)

    for change in changes:
        if change["hash"] in hashes_to_send:
            changes_to_send.append(change["change"])
    return changes_to_send


def init_sync_state():
    return {
        "sharedHeads": [],
        "lastSentHeads": [],
        "theirHeads": None,
        "theirNeed": None,
        "theirHave": None,
        "sentHashes": {},
    }


def generate_sync_message(backend, sync_state):
    """Generates the next message to send to a peer, or None if in sync
    (sync.js:327). Returns (sync_state, message_bytes_or_None)."""
    if backend is None:
        raise ValueError("generate_sync_message called with no Automerge document")  # amlint: disable=AM401 — API-usage validation
    if sync_state is None:
        raise ValueError("generate_sync_message requires a sync_state, created by init_sync_state()")  # amlint: disable=AM401 — API-usage validation

    shared_heads = sync_state["sharedHeads"]
    last_sent_heads = sync_state["lastSentHeads"]
    their_heads = sync_state["theirHeads"]
    their_need = sync_state["theirNeed"]
    their_have = sync_state["theirHave"]
    sent_hashes = sync_state["sentHashes"]
    our_heads = Backend.get_heads(backend)

    our_need = Backend.get_missing_deps(backend, their_heads or [])

    our_have = []
    if their_heads is None or all(h in their_heads for h in our_need):
        our_have = [make_bloom_filter(backend, shared_heads)]

    if their_have and len(their_have) > 0:
        last_sync = their_have[0]["lastSync"]
        if not all(Backend.get_change_by_hash(backend, h) for h in last_sync):
            reset_msg = {
                "heads": our_heads, "need": [],
                "have": [{"lastSync": [], "bloom": b""}], "changes": [],
            }
            encoded = encode_sync_message(reset_msg)
            _M_MSGS_GEN.inc()
            _M_BYTES_SENT.inc(len(encoded))
            return sync_state, encoded

    changes_to_send = (
        get_changes_to_send(backend, their_have, their_need)
        if isinstance(their_have, list) and isinstance(their_need, list)
        else []
    )

    heads_unchanged = isinstance(last_sent_heads, list) and our_heads == last_sent_heads
    heads_equal = isinstance(their_heads, list) and our_heads == their_heads
    if heads_unchanged and heads_equal and not changes_to_send:
        return sync_state, None

    changes_to_send = [
        c for c in changes_to_send if not sent_hashes.get(decode_change_meta_cached(c)["hash"])
    ]

    sync_message = {"heads": our_heads, "have": our_have, "need": our_need, "changes": changes_to_send}
    if changes_to_send:
        sent_hashes = dict(sent_hashes)
        for change in changes_to_send:
            sent_hashes[decode_change_meta_cached(change)["hash"]] = True

    sync_state = dict(sync_state, lastSentHeads=our_heads, sentHashes=sent_hashes)
    encoded = encode_sync_message(sync_message)
    _M_MSGS_GEN.inc()
    _M_BYTES_SENT.inc(len(encoded))
    _M_CHANGES_SENT.inc(len(changes_to_send))
    return sync_state, encoded


def _advance_heads(my_old_heads, my_new_heads, our_old_shared_heads):
    new_heads = [head for head in my_new_heads if head not in my_old_heads]
    common_heads = [head for head in our_old_shared_heads if head in my_new_heads]
    return sorted(set(new_heads + common_heads))


def receive_sync_message(backend, old_sync_state, binary_message):
    """Processes a received sync message; returns (backend, sync_state, patch)
    (sync.js:420)."""
    if backend is None:
        raise ValueError("receive_sync_message called with no Automerge document")  # amlint: disable=AM401 — API-usage validation
    if old_sync_state is None:
        raise ValueError("receive_sync_message requires a sync_state, created by init_sync_state()")  # amlint: disable=AM401 — API-usage validation

    shared_heads = old_sync_state["sharedHeads"]
    last_sent_heads = old_sync_state["lastSentHeads"]
    sent_hashes = old_sync_state["sentHashes"]
    patch = None
    # A malformed peer message must not poison local state: reject with
    # SyncProtocolError, leaving the backend handle usable (not frozen) and
    # the caller's sync_state object untouched. Raw decode exceptions from
    # corrupt bytes (DecodeError/ChecksumError, or an IndexError from a
    # short buffer) never propagate out of this function.
    try:
        _fault_point("sync.receive_message", message=binary_message)
        message = decode_sync_message(binary_message)
    except SyncProtocolError:
        _M_REJECTED.inc()
        raise
    except (ValueError, TypeError, IndexError) as exc:
        _M_REJECTED.inc()
        raise SyncProtocolError(f"malformed sync message: {exc}") from exc
    _M_MSGS_RECV.inc()
    _M_BYTES_RECV.inc(len(binary_message))
    _M_CHANGES_RECV.inc(len(message["changes"]))
    before_heads = Backend.get_heads(backend)

    if message["changes"]:
        try:
            backend, patch = Backend.apply_changes(backend, message["changes"])
        except (AutomergeError, ValueError, KeyError, IndexError) as exc:
            # OpSet.apply_changes commits only after a clean run, so the
            # backend state is untouched here
            _M_REJECTED.inc()
            raise SyncProtocolError(
                f"sync message carried inapplicable changes: {exc}"
            ) from exc
        shared_heads = _advance_heads(before_heads, Backend.get_heads(backend), shared_heads)

    if not message["changes"] and message["heads"] == before_heads:
        last_sent_heads = message["heads"]

    known_heads = [h for h in message["heads"] if Backend.get_change_by_hash(backend, h)]
    if len(known_heads) == len(message["heads"]):
        shared_heads = message["heads"]
        if len(message["heads"]) == 0:
            last_sent_heads = []
            sent_hashes = {}
    else:
        shared_heads = sorted(set(known_heads + shared_heads))

    sync_state = {
        "sharedHeads": shared_heads,
        "lastSentHeads": last_sent_heads,
        "theirHave": message["have"],
        "theirHeads": message["heads"],
        "theirNeed": message["need"],
        "sentHashes": sent_hashes,
    }
    return backend, sync_state, patch
