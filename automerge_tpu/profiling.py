"""Per-phase timing for the batched backend (SURVEY.md §5.1).

The reference has no profiling layer (only nyc coverage); for the TPU
build a phase breakdown is a first-class requirement: the applyChanges
pipeline spans host decode, the causal gate, dense-row transcoding, the
device merge program, and host patch assembly, and optimisation work needs
to know where the time goes (the bench's phase table is built on this).

Usage:
    prof = PhaseProfile()
    with prof.phase("decode"):
        ...
    prof.as_dict()   # {"decode": {"total_s": ..., "calls": ...}, ...}

Timers nest (a phase started inside another phase simply accumulates to
its own bucket); `enabled=False` turns every context into a no-op with a
single attribute test of overhead. A module-level `get_profile()` hands
out the ambient profile installed by `use_profile()` so deep call sites
(the farm, the engine) need no plumbing.
"""
# amlint: host-only — pure-host layer: must not import tpu/ or jax
from __future__ import annotations

import contextlib
import time
from typing import Iterator


class PhaseProfile:
    """Accumulates wall-clock totals and call counts per named phase."""

    __slots__ = ("totals", "counts", "enabled")

    def __init__(self, enabled: bool = True):
        self.totals: dict[str, float] = {}
        self.counts: dict[str, int] = {}
        self.enabled = enabled

    @contextlib.contextmanager
    def phase(self, name: str) -> Iterator[None]:
        if not self.enabled:
            yield
            return
        start = time.perf_counter()
        try:
            yield
        finally:
            elapsed = time.perf_counter() - start
            self.totals[name] = self.totals.get(name, 0.0) + elapsed
            self.counts[name] = self.counts.get(name, 0) + 1

    def reset(self) -> None:
        self.totals.clear()
        self.counts.clear()

    def as_dict(self) -> dict:
        return {
            name: {"total_s": self.totals[name], "calls": self.counts[name]}
            for name in sorted(self.totals)
        }

    def table(self) -> str:
        """Human-readable breakdown, largest phase first."""
        if not self.totals:
            return "(no phases recorded)"
        width = max(len(n) for n in self.totals)
        total = sum(self.totals.values())
        lines = []
        for name in sorted(self.totals, key=self.totals.get, reverse=True):
            t = self.totals[name]
            lines.append(
                f"{name.ljust(width)}  {t * 1e3:10.2f} ms  "
                f"{100 * t / total:5.1f}%  x{self.counts[name]}"
            )
        return "\n".join(lines)


_NULL = PhaseProfile(enabled=False)
_current = _NULL


def get_profile() -> PhaseProfile:
    """The ambient profile (a disabled no-op unless one is installed)."""
    return _current


@contextlib.contextmanager
def use_profile(profile: PhaseProfile) -> Iterator[PhaseProfile]:
    """Installs `profile` as the ambient profile for the dynamic extent."""
    global _current
    prev = _current
    _current = profile
    try:
        yield profile
    finally:
        _current = prev
