"""Per-phase timing shim over the amtrace span layer (SURVEY.md §5.1).

Historically this module held the whole profiling layer: a flat per-phase
wall-clock accumulator behind a *module-global* ambient slot. The real
implementation now lives in ``automerge_tpu/obs/spans.py`` — nested span
trees, latency histograms, and ambient propagation via ``contextvars`` (so
concurrent farms in different threads/tasks no longer cross-pollute each
other's profiles). This module keeps the original surface working:

    prof = PhaseProfile()
    with prof.phase("decode"):
        ...
    prof.as_dict()   # {"decode": {"total_s": ..., "calls": ...}, ...}
    prof.table()     # flat breakdown, largest phase first

``PhaseProfile`` IS a ``Trace`` — phases recorded through it are spans
(nesting under the ambient span), and the flat ``totals``/``counts``/
``as_dict``/``table`` views aggregate the tree by name exactly like the
old accumulator. ``get_profile()``/``use_profile()`` are the span layer's
ambient accessors, so a profile installed here is the same object the
farm's ``obs`` spans record into; `enabled=False` keeps the historical
one-attribute-test disabled cost.
"""
# amlint: host-only — pure-host layer: must not import tpu/ or jax
from __future__ import annotations

from .obs.spans import Trace, get_trace, use_trace


class PhaseProfile(Trace):
    """Flat-view compatibility wrapper over a span tree."""

    __slots__ = ()

    @property
    def totals(self) -> dict[str, float]:
        return {name: t for name, (t, _) in self.totals_by_name().items()}

    @property
    def counts(self) -> dict[str, int]:
        return {name: c for name, (_, c) in self.totals_by_name().items()}

    def as_dict(self) -> dict:
        return {
            name: {"total_s": t, "calls": c}
            for name, (t, c) in sorted(self.totals_by_name().items())
        }

    def table(self) -> str:
        """Human-readable breakdown, largest phase first."""
        flat = self.totals_by_name()
        if not flat:
            return "(no phases recorded)"
        width = max(len(n) for n in flat)
        total = sum(t for t, _ in flat.values())
        lines = []
        for name in sorted(flat, key=lambda n: flat[n][0], reverse=True):
            t, calls = flat[name]
            pct = 100 * t / total if total else 0.0
            lines.append(
                f"{name.ljust(width)}  {t * 1e3:10.2f} ms  "
                f"{pct:5.1f}%  x{calls}"
            )
        return "\n".join(lines)


# the ambient accessors ARE the span layer's: one mechanism, two spellings
get_profile = get_trace
use_profile = use_trace
