"""Per-phase timing shim over the amtrace span layer (SURVEY.md §5.1).

Historically this module held the whole profiling layer: a flat per-phase
wall-clock accumulator behind a *module-global* ambient slot. The real
implementation now lives in ``automerge_tpu/obs/spans.py`` — nested span
trees, latency histograms, and ambient propagation via ``contextvars`` (so
concurrent farms in different threads/tasks no longer cross-pollute each
other's profiles). This module keeps the original surface working:

    prof = PhaseProfile()
    with prof.phase("decode"):
        ...
    prof.as_dict()   # {"decode": {"total_s": ..., "calls": ...}, ...}
    prof.table()     # flat breakdown, largest phase first

``PhaseProfile`` IS a ``Trace`` — phases recorded through it are spans
(nesting under the ambient span), and the flat ``totals``/``counts``/
``as_dict``/``table`` views aggregate the tree **by path** ("outer/inner"
keys; top-level phases keep their bare names, so the bench's phase table
is unchanged). Aggregating by *name* — the original shim behaviour —
silently merged same-named spans that lived under different parents,
losing their individual call counts in the table renderer; the path keys
keep every distinct span visible. ``get_profile()``/``use_profile()`` are
the span layer's ambient accessors, so a profile installed here is the
same object the farm's ``obs`` spans record into; `enabled=False` keeps
the historical one-attribute-test disabled cost.
"""
# amlint: host-only — pure-host layer: must not import tpu/ or jax
from __future__ import annotations

from .obs.spans import Trace, get_trace, use_trace


class PhaseProfile(Trace):
    """Flat-view compatibility wrapper over a span tree."""

    __slots__ = ()

    @property
    def totals(self) -> dict[str, float]:
        return {path: t for path, (t, _) in self.totals_by_path().items()}

    @property
    def counts(self) -> dict[str, int]:
        return {path: c for path, (_, c) in self.totals_by_path().items()}

    def as_dict(self) -> dict:
        return {
            path: {"total_s": t, "calls": c}
            for path, (t, c) in sorted(self.totals_by_path().items())
        }

    def table(self) -> str:
        """Human-readable breakdown, largest phase first. Rows are keyed
        by span PATH, so two same-named phases under different parents
        render as two rows with their own times and call counts instead of
        one silently merged row."""
        flat = self.totals_by_path()
        if not flat:
            return "(no phases recorded)"
        width = max(len(n) for n in flat)
        # total time = top-level spans only (nested spans are already
        # inside their parents' wall time; summing every path would
        # double-count and deflate every percentage)
        total = sum(t for path, (t, _) in flat.items() if "/" not in path)
        lines = []
        for name in sorted(flat, key=lambda n: flat[n][0], reverse=True):
            t, calls = flat[name]
            pct = 100 * t / total if total else 0.0
            lines.append(
                f"{name.ljust(width)}  {t * 1e3:10.2f} ms  "
                f"{pct:5.1f}%  x{calls}"
            )
        return "\n".join(lines)


# the ambient accessors ARE the span layer's: one mechanism, two spellings
get_profile = get_trace
use_profile = use_trace
