"""profiled_jit — the one blessed ``jax.jit`` call site in the tpu layer.

Every compiled program registers through the amprof observatory so
recompiles, dispatch latencies and shape buckets carry program identity
(obs/prof.py). amlint AM306 flags any other ``jax.jit`` call in the
package; the call below is exempt because it feeds
``Observatory.register`` directly.

Usage (decorator keywords pass straight through to ``jax.jit``; the
static-argument layout is visible to amlint's tracer rules exactly as it
was on a bare ``@partial(jax.jit, ...)``)::

    @profiled_jit("paging.apply_ops", static_argnames=("page_size",),
                  donate_argnums=(0,))
    def paged_apply_ops(slab, ...):
        ...
"""
from __future__ import annotations

import jax

from ..obs.prof import ProfiledProgram, get_observatory


def profiled_jit(name: str, **jit_kwargs):
    """Decorator: jits ``fn`` and registers it on the process observatory
    under ``name``. Returns the :class:`ProfiledProgram` wrapper (calls
    fall through to the jitted function while the observatory is
    disabled)."""

    def wrap(fn) -> ProfiledProgram:
        return get_observatory().register(name, jax.jit(fn, **jit_kwargs))

    return wrap
