"""Host-side transcoding between Automerge change ops and dense op tensors.

The variable-length columnar encodings (LEB128/RLE, backend/encoding.js) are
hostile to fixed-width SIMD, so the TPU engine works on dense interned
tensors: actors, keys and values are interned into per-batch tables on the
host, and ops become int32/int64 rows (SURVEY.md §7 'Architecture mapping').

Nested objects (maps inside maps, tables of rows — reference semantics in
frontend/context.js createNestedObjects:230 and backend/new.js objectMeta)
need no new device kernels: the engine's sort key is an opaque int32, so the
transcoder interns the *(objectId, key)* pair into one "slot" id. Rows of one
(object, key) stay contiguous under the sort, succ/visibility/conflict
resolution are per-slot and therefore per-(object, key), exactly like the
reference's (objectId, key) op grouping (new.js:1153-1224). makeMap/makeTable
ops become set-ops whose value is a child reference; the host rebuilds the
tree from the flat winner rows."""
from __future__ import annotations

from typing import NamedTuple

import numpy as np

from .engine import (
    ACTION_DEL,
    ACTION_INC,
    ACTION_SET,
    ACTOR_BITS,
    ACTOR_MASK,
    PAD_KEY,
    _MKEY_OP_BITS as _SLOT_SHIFT,
    ChangeOpsBatch,
    changes_from_numpy,
)
from ..common import parse_op_id
from ..errors import EncodeError, PackingLimitError
from ..obs.metrics import get_metrics

_COUNTER_TAG = object()

_M_ROWS = get_metrics().counter(
    "transcode.rows", "ops packed into dense rows by BatchTranscoder"
)

# Slot ids ride the high bits of the engine's packed int64 merge key
# (slot << 44 | opid): 63 value bits - 44 opid bits = 19 bits of slot before
# the sign bit flips and the sorted-table invariant silently breaks. The
# opid field itself is (counter << 20 | actor), so counters are capped at
# 2^24 and actor intern indexes at 2^20.
_MAX_SLOTS = 1 << 19
_MAX_COUNTER = 1 << 24


class ChildRef(NamedTuple):
    """Interned value marking 'this key holds the object with this id'."""

    object_id: str


def actor_rank_table(actors, pad_to=None):
    """int32 table: actor intern index -> lexicographic rank of the actor id
    string, so packed-opId comparisons tie-break like the reference
    (new.js:146, apply_patch.js:33). `pad_to` pads the table (ranks repeat
    the identity for unused slots) so jitted kernels see fewer shapes."""
    n = len(actors)
    size = max(pad_to or n, n, 1)
    ranks = np.arange(size, dtype=np.int32)  # identity for unused slots
    # amlint: disable=AM105 — actor-table-sized and cached per interner
    # size by the farm (not per row, not per call): the callback sort is
    # off the hot path by construction
    order = sorted(range(n), key=lambda i: actors[i])
    for rank, i in enumerate(order):
        ranks[i] = rank
    return ranks


class _Interner:
    """Append-only value->int table. `max_size` guards packing ranges: slot
    ids ride the high bits of the engine's int64 merge key, so an unchecked
    table would silently corrupt the sorted-table invariant past 2^19."""

    def __init__(self, max_size=None, name="intern"):
        self.table = []
        self.index = {}
        self.max_size = max_size
        self.name = name

    def intern(self, value) -> int:
        # Key by (class, value): Python equates 1 == True and
        # tuple == NamedTuple (so a user tuple could collide with a ChildRef
        # under plain value keying), but distinct classes must intern apart.
        try:
            key = (value.__class__, value)
            idx = self.index.get(key)
        except TypeError:  # unhashable (lists/dicts) — identity-intern
            key = id(value)
            idx = self.index.get(key)
        if idx is None:
            idx = len(self.table)
            if self.max_size is not None and idx >= self.max_size:
                raise PackingLimitError(
                    f"{self.name} table overflow: more than {self.max_size} "
                    "distinct entries in batch"
                )
            self.table.append(value)
            self.index[key] = idx
        return idx

    def lookup(self, idx: int):
        return self.table[idx]

    def find(self, value):
        """Index of an already-interned value (None if absent): a pure
        lookup that never grows the table, for hot paths that must not
        perturb packed-id assignment."""
        try:
            return self.index.get((value.__class__, value))
        except TypeError:  # unhashable — identity-interned
            return self.index.get(id(value))


# ---------------------------------------------------------------------- #
# column helpers for vectorized patch assembly (tpu/farm._build_diffs):
# per-slot work expressed as array operations over the host row mirror.

def lamport_keys(ops, actor_rank):
    """int64 column of reference-comparable lamport keys for packed opIds:
    the actor intern index is replaced by its lexicographic rank
    (actor_rank_table), so int64 comparison == (counter, actorId-string)
    comparison — the walk's tie-break — without a per-row sort callback."""
    return (ops >> ACTOR_BITS << ACTOR_BITS) | actor_rank[ops & ACTOR_MASK]


def ragged_spans(sorted_mkey, slots):
    """Row spans of `slots` (ascending int64 slot ids) in a merge-key-sorted
    row table: returns (starts, counts, idx, grp) where `idx` flat-indexes
    every row of every requested slot and ``grp[i]`` is the position in
    `slots` that ``idx[i]`` belongs to. One batched searchsorted pair
    replaces a per-slot binary-search loop."""
    lo = np.searchsorted(sorted_mkey, slots << _SLOT_SHIFT)
    hi = np.searchsorted(sorted_mkey, (slots + 1) << _SLOT_SHIFT)
    counts = hi - lo
    total = int(counts.sum())
    idx = np.repeat(
        lo - np.concatenate(([0], counts.cumsum()[:-1])), counts
    ) + np.arange(total)
    grp = np.repeat(np.arange(slots.shape[0]), counts)
    return lo, counts, idx, grp


#: gate_verdicts dep-column sentinels: a dep that is already committed in
#: the doc, and a dep that is neither committed nor in this delivery.
DEP_COMMITTED = -1
DEP_UNKNOWN = -2


def gate_verdicts(dep_idx, dep_counts):
    """Causal-gate verdicts for a whole delivery as one column program.

    ``dep_counts[i]`` is the number of deps of delivery entry ``i`` (entries
    are one doc's pending changes in arrival order); ``dep_idx`` is the flat
    int64 dep column — for each dep either the global entry index of the
    in-delivery change it names, ``DEP_COMMITTED`` for a dep already in the
    doc's change index, or ``DEP_UNKNOWN`` for a dep nobody has seen.

    Returns the int64 ``batch`` column: 0 = deferred (some dep chain ends in
    an unknown hash), else the 1-based gate round the entry commits in —
    exactly the round ``_gate_round`` would admit it, because the scalar
    gate scans pending in order and counts a same-round *earlier* entry as
    satisfied: ``batch[c] = max(1, max over deps d of
    (batch[d] + (d > c)))`` with committed deps contributing 1.

    The relaxation is a fixpoint sweep: batches only grow and the deferred
    set only grows among reachable entries, so ``n + 1`` sweeps always
    converge (each sweep settles at least one more entry of the longest
    dep chain)."""
    dep_idx = np.asarray(dep_idx, dtype=np.int64)
    dep_counts = np.asarray(dep_counts, dtype=np.int64)
    n = dep_counts.shape[0]
    if n == 0:
        return np.zeros(0, dtype=np.int64)
    owner = np.repeat(np.arange(n, dtype=np.int64), dep_counts)
    in_delivery = dep_idx >= 0
    unknown = dep_idx == DEP_UNKNOWN
    same_round_ok = dep_idx < owner  # earlier entry satisfies in-round
    batch = np.ones(n, dtype=np.int64)
    for _ in range(n + 1):
        target = batch[np.maximum(dep_idx, 0)]
        dep_batch = np.where(
            in_delivery,
            target + np.where(same_round_ok, 0, 1),
            1,  # DEP_COMMITTED; DEP_UNKNOWN is masked out via `bad` below
        )
        bad_dep = unknown | (in_delivery & (target == 0))
        new = np.ones(n, dtype=np.int64)
        np.maximum.at(new, owner, dep_batch)
        bad = np.zeros(n, dtype=bool)
        np.logical_or.at(bad, owner, bad_dep)
        new[bad] = 0
        if np.array_equal(new, batch):
            break
        batch = new
    return batch


class BatchTranscoder:
    """Interns actors/(object, key) slots/values for one document batch and
    packs change ops into ChangeOpsBatch tensors."""

    def __init__(self):
        self.actors = _Interner(max_size=1 << ACTOR_BITS, name="actor")
        self.slots = _Interner(max_size=_MAX_SLOTS, name="slot")
        # amlint: disable=AM103 — value ids are payloads, never packed into
        # merge keys, so the table has no bit-field cap
        self.values = _Interner()
        self.object_types = {"_root": "map"}  # objectId -> map | table

    def pack_opid_str(self, op_id: str) -> int:
        p = parse_op_id(op_id)
        if p.counter >= _MAX_COUNTER:
            raise PackingLimitError(
                f"op counter {p.counter} exceeds the merge-key packing range"
            )
        return (p.counter << ACTOR_BITS) | self.actors.intern(p.actor_id)

    def slot_id(self, obj: str, key: str) -> int:
        return self.slots.intern((obj, key))

    def op_row(self, op: dict, op_counter: int, actor: str):
        """Converts one map-family change op dict (frontend format) into a
        dense row (slot, op, action, value, pred). Supports set/inc/del on
        maps and table rows, plus makeMap/makeTable child creation."""
        if op_counter >= _MAX_COUNTER:
            raise PackingLimitError(
                f"op counter {op_counter} exceeds the merge-key packing range"
            )
        packed_id = (op_counter << ACTOR_BITS) | self.actors.intern(actor)
        slot = self.slot_id(op.get("obj", "_root"), op["key"])
        pred = self.pack_opid_str(op["pred"][0]) if op.get("pred") else -1
        action = op["action"]
        if action == "set":
            if op.get("datatype") == "counter":
                return slot, packed_id, ACTION_SET, int(op["value"]), pred
            return slot, packed_id, ACTION_SET, self.values.intern(op.get("value")), pred
        if action in ("makeMap", "makeTable"):
            child_id = f"{op_counter}@{actor}"
            self.object_types[child_id] = "map" if action == "makeMap" else "table"
            value = self.values.intern(ChildRef(child_id))
            return slot, packed_id, ACTION_SET, value, pred
        if action == "inc":
            return slot, packed_id, ACTION_INC, int(op["value"]), pred
        if action == "del":
            return slot, packed_id, ACTION_DEL, 0, pred
        raise EncodeError(f"Unsupported op action for the dense engine: {action}")

    def changes_to_batch(self, per_doc_ops, width=None) -> ChangeOpsBatch:
        """`per_doc_ops` is a list (one entry per document) of lists of
        (op_dict, op_counter, actor) tuples. Returns a padded ChangeOpsBatch."""
        num_docs = len(per_doc_ops)
        if _M_ROWS.enabled:
            _M_ROWS.inc(sum(len(ops) for ops in per_doc_ops))
        m = width or max((len(ops) for ops in per_doc_ops), default=1) or 1
        keys = np.full((num_docs, m), PAD_KEY, np.int32)
        ops = np.zeros((num_docs, m), np.int64)
        actions = np.zeros((num_docs, m), np.int32)
        values = np.zeros((num_docs, m), np.int64)
        preds = np.full((num_docs, m), -1, np.int64)
        for d, doc_ops in enumerate(per_doc_ops):
            for i, (op, ctr, actor) in enumerate(doc_ops):
                keys[d, i], ops[d, i], actions[d, i], values[d, i], preds[d, i] = (
                    self.op_row(op, ctr, actor)
                )
        return changes_from_numpy(keys, ops, actions, values, preds)

    def decode_visible(self, keys, ops, winners, values, counter_slots=()):
        """Converts one document's per-row visibility tensors (from
        batched_visible_state) back into the document's Python tree, rooted
        at `_root`. `counter_slots` is the set of slot ids whose winning
        value is a raw counter total rather than an interned ref. Nested
        maps/table rows appear as nested dicts, reconstructed by following
        ChildRef winner values — the host-side analogue of the reference's
        objectMeta tree walk (new.js:1461, setupPatches)."""
        counter_slots = set(counter_slots)
        keys = np.asarray(keys)
        winners = np.asarray(winners)
        values = np.asarray(values)
        # flat winner table: objectId -> {key: scalar | ChildRef}
        objects = {}
        for i in np.nonzero(winners)[0]:
            slot = int(keys[i])
            if slot == PAD_KEY:
                continue
            obj, key = self.slots.lookup(slot)
            if slot in counter_slots:
                value = int(values[i])
            else:
                value = self.values.lookup(int(values[i]))
            objects.setdefault(obj, {})[key] = value

        def build(object_id):
            out = {}
            for key, value in objects.get(object_id, {}).items():
                out[key] = build(value.object_id) if isinstance(value, ChildRef) else value
            return out

        return build("_root")
