"""Host-side transcoding between Automerge change ops and dense op tensors.

The variable-length columnar encodings (LEB128/RLE, backend/encoding.js) are
hostile to fixed-width SIMD, so the TPU engine works on dense interned
tensors: actors, keys and values are interned into per-batch tables on the
host, and ops become int32/int64 rows (SURVEY.md §7 'Architecture mapping').
"""
from __future__ import annotations

import numpy as np

from .engine import (
    ACTION_DEL,
    ACTION_INC,
    ACTION_SET,
    PAD_KEY,
    ChangeOpsBatch,
    changes_from_numpy,
)
from ..common import parse_op_id

_COUNTER_TAG = object()


class _Interner:
    def __init__(self):
        self.table = []
        self.index = {}

    def intern(self, value) -> int:
        key = value if isinstance(value, (str, int, float, bool, bytes, type(None))) else id(value)
        idx = self.index.get(key)
        if idx is None:
            idx = len(self.table)
            self.table.append(value)
            self.index[key] = idx
        return idx

    def lookup(self, idx: int):
        return self.table[idx]


class BatchTranscoder:
    """Interns actors/keys/values for one document batch and packs change ops
    into ChangeOpsBatch tensors."""

    def __init__(self):
        self.actors = _Interner()
        self.keys = _Interner()
        self.values = _Interner()

    def pack_opid_str(self, op_id: str) -> int:
        p = parse_op_id(op_id)
        return (p.counter << 20) | self.actors.intern(p.actor_id)

    def op_row(self, op: dict, op_counter: int, actor: str):
        """Converts one root-map change op dict (frontend format) into a dense
        row (key, op, action, value, pred)."""
        packed_id = (op_counter << 20) | self.actors.intern(actor)
        key_id = self.keys.intern(op["key"])
        pred = self.pack_opid_str(op["pred"][0]) if op.get("pred") else -1
        action = op["action"]
        if action == "set":
            if op.get("datatype") == "counter":
                return key_id, packed_id, ACTION_SET, int(op["value"]), pred
            return key_id, packed_id, ACTION_SET, self.values.intern(op.get("value")), pred
        if action == "inc":
            return key_id, packed_id, ACTION_INC, int(op["value"]), pred
        if action == "del":
            return key_id, packed_id, ACTION_DEL, 0, pred
        raise ValueError(f"Unsupported op action for the dense engine: {action}")

    def changes_to_batch(self, per_doc_ops, width=None) -> ChangeOpsBatch:
        """`per_doc_ops` is a list (one entry per document) of lists of
        (op_dict, op_counter, actor) tuples. Returns a padded ChangeOpsBatch."""
        num_docs = len(per_doc_ops)
        m = width or max((len(ops) for ops in per_doc_ops), default=1) or 1
        keys = np.full((num_docs, m), PAD_KEY, np.int32)
        ops = np.zeros((num_docs, m), np.int64)
        actions = np.zeros((num_docs, m), np.int32)
        values = np.zeros((num_docs, m), np.int64)
        preds = np.full((num_docs, m), -1, np.int64)
        for d, doc_ops in enumerate(per_doc_ops):
            for i, (op, ctr, actor) in enumerate(doc_ops):
                keys[d, i], ops[d, i], actions[d, i], values[d, i], preds[d, i] = (
                    self.op_row(op, ctr, actor)
                )
        return changes_from_numpy(keys, ops, actions, values, preds)

    def decode_visible(self, keys, ops, winners, values, counter_keys=()):
        """Converts one document's per-row visibility tensors (from
        batched_visible_state) back into a Python dict. `counter_keys` is the
        set of interned key ids whose winning value is a raw counter total
        rather than an interned ref."""
        result = {}
        counter_keys = set(counter_keys)
        keys = np.asarray(keys)
        winners = np.asarray(winners)
        values = np.asarray(values)
        for i in np.nonzero(winners)[0]:
            key_id = int(keys[i])
            if key_id == PAD_KEY:
                continue
            key = self.keys.lookup(key_id)
            if key_id in counter_keys:
                result[key] = int(values[i])
            else:
                result[key] = self.values.lookup(int(values[i]))
        return result
