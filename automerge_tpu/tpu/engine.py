"""Batched merge kernels: the TPU equivalent of the OpSet engine's hot loop.

The reference merge (mergeDocChangeOps, /root/reference/backend/new.js:1052)
is a sequential two-pointer walk per document. Here the same result is
computed as a data-parallel array program over a whole batch of documents:

  1. concatenate existing doc ops with incoming change ops
  2. lexsort rows into the canonical op order: (key, opId counter, opId actor)
     -- the same total order the columnar engine maintains
  3. resolve succ/overwrite relationships: an op is overwritten when another
     (non-increment) op names it in `pred` (matched with a sorted binary
     search, no scatter loops)
  4. visibility = zero successors; the winning value per key is the visible
     op with the greatest Lamport opId (segmented max over the sorted keys);
     counter increments accumulate onto their target set op instead of
     hiding it (new.js:937-965)

Everything is static-shape and jit/vmap/shard_map friendly: padded rows carry
key = PAD_KEY and sort to the end. Map objects and counters are supported in
this v1 engine (benchmark configs 1 and 3); list/text RGA ordering stays on
the sequential engine for now (see SURVEY.md §7 step 5).

Lamport opIds are packed into a single int64 as (counter << 20 | actor_num),
which preserves (counter, actor) ordering for up to 2^20 actors and 2^43 ops.
"""
from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..obs.metrics import get_metrics
from ..testing.faults import fire as _fault_point

PAD_KEY = jnp.iinfo(jnp.int32).max
ACTOR_BITS = 20
ACTOR_MASK = (1 << ACTOR_BITS) - 1
_NEG_INF = jnp.int64(-(2**62))

ACTION_SET = 0
ACTION_INC = 1
ACTION_DEL = 2

# engine metrics (process-wide registry, disabled unless a workload opts
# in — obs/metrics.py). Dispatch accounting lives in the HOST wrappers
# below, never inside traced code (amlint AM303).
_METRICS = get_metrics()
_M_DISPATCHES = _METRICS.counter(
    "engine.device.dispatches",
    "batched device programs dispatched (merge + visibility)",
)
_M_JIT_HITS = _METRICS.counter(
    "engine.jit.cache_hits",
    "dispatches served by an already-compiled program",
)
_M_JIT_RECOMPILES = _METRICS.counter(
    "engine.jit.recompiles",
    "dispatches that triggered an XLA compile (shape-bucket misses)",
)
_M_STATE_GROWS = _METRICS.counter(
    "engine.state.grows",
    "capacity doublings of the dense device state",
)


def _dispatch(jitted, *args):
    """Runs a jitted entry point, classifying the call as a jit cache hit
    or a recompile by the growth of the function's compile cache across the
    call. This is the single device-dispatch funnel for the engine, so the
    recompile-storm and dispatch-count metrics cover every merge and
    visibility program; with metrics disabled it degrades to a plain call."""
    if not _METRICS.enabled:
        return jitted(*args)
    size_fn = getattr(jitted, "_cache_size", None)
    before = size_fn() if size_fn is not None else -1
    out = jitted(*args)
    _M_DISPATCHES.inc()
    if size_fn is not None:
        grew = size_fn() - before
        if grew > 0:
            _M_JIT_RECOMPILES.inc(grew)
        else:
            _M_JIT_HITS.inc()
    return out


def pack_opid(counter, actor):
    """Packs (counter, actorNum) into one int64 preserving Lamport order."""
    counter = jnp.asarray(counter)
    actor = jnp.asarray(actor)
    return (counter.astype(jnp.int64) << ACTOR_BITS) | actor.astype(jnp.int64)


def unpack_opid(opid):
    return opid >> ACTOR_BITS, opid & ACTOR_MASK


def remap_opid_actors(opid, actor_rank):
    """Rebuilds packed opIds with the actor index replaced by its
    lexicographic rank, so int64 comparison == (counter, actorId-string)
    comparison (the reference's tie-break, new.js:146, apply_patch.js:33)."""
    actor_rank = jnp.asarray(actor_rank)
    counter = opid >> ACTOR_BITS
    actor = (opid & ACTOR_MASK).astype(jnp.int32)
    rank = actor_rank[jnp.minimum(actor, actor_rank.shape[0] - 1)]
    return (counter << ACTOR_BITS) | rank.astype(jnp.int64)


class BatchedDocState(NamedTuple):
    """Dense op storage for a batch of map documents.

    All row arrays have shape [docs, capacity], sorted by (key, opId);
    padded slots have key == PAD_KEY and sort last. `overwritten` marks ops
    with at least one non-increment successor (the dense analogue of
    succNum > 0); `pred` is the packed opId each op overwrites/increments
    (-1 if none), from which full succ lists are recovered host-side when
    transcoding back to the columnar format.
    """

    key: jax.Array          # int32 interned key id
    op: jax.Array           # int64 packed opId
    action: jax.Array       # int32 (ACTION_SET / ACTION_INC / ACTION_DEL)
    value: jax.Array        # int64 value payload (interned ref or small int)
    pred: jax.Array         # int64 packed opId, -1 if none
    overwritten: jax.Array  # bool
    num_ops: jax.Array      # int32 [docs] live op count


class ChangeOpsBatch(NamedTuple):
    """One batch of incoming change ops per document, shape [docs, m]."""

    key: jax.Array
    op: jax.Array
    action: jax.Array
    value: jax.Array
    pred: jax.Array


def make_empty_state(num_docs: int, capacity: int) -> BatchedDocState:
    return BatchedDocState(
        key=jnp.full((num_docs, capacity), PAD_KEY, jnp.int32),
        op=jnp.zeros((num_docs, capacity), jnp.int64),
        action=jnp.zeros((num_docs, capacity), jnp.int32),
        value=jnp.zeros((num_docs, capacity), jnp.int64),
        pred=jnp.full((num_docs, capacity), -1, jnp.int64),
        overwritten=jnp.zeros((num_docs, capacity), jnp.bool_),
        num_ops=jnp.zeros((num_docs,), jnp.int32),
    )


# Merge keys pack (key, opId) into one int64: key in the top 20 bits, the
# packed opId (counter << 20 | actor) in the low 44. Requires counter < 2^24.
_MKEY_OP_BITS = 44
_I64_MAX = jnp.iinfo(jnp.int64).max


def _merge_key(key, op):
    return jnp.where(
        key == PAD_KEY,
        _I64_MAX,
        (key.astype(jnp.int64) << _MKEY_OP_BITS) | op,
    )


def _merge_one_doc(s_key, s_op, s_action, s_value, s_pred, s_over, num_ops,
                   c_key, c_op, c_action, c_value, c_pred):
    """Merges one document's change ops into its sorted op table (vmapped
    over the batch).

    The doc state is invariant-sorted by (key, opId), so instead of
    re-sorting the whole table (the naive O(N log N) per merge), only the
    small change batch is sorted and merged in by insertion position:
    searchsorted gives each change op's slot, and every row moves to its
    final position with one scatter -- O(N) memory traffic + O(M log N)
    compute, the TPU analogue of the reference's two-pointer merge
    (mergeDocChangeOps, new.js:1052).
    """
    n = s_key.shape[0]
    m = c_key.shape[0]
    s_mkey = _merge_key(s_key, s_op)

    # sort the change ops into canonical order
    c_mkey = _merge_key(c_key, c_op)
    c_order = jnp.argsort(c_mkey)
    c_mkey = c_mkey[c_order]
    c_key = c_key[c_order]
    c_op = c_op[c_order]
    c_action = c_action[c_order]
    c_value = c_value[c_order]
    c_pred = c_pred[c_order]

    # insertion positions: new row j lands at pos[j] + j. The output is then
    # built by pure gathers (TPU scatters serialize; gathers vectorise):
    # output slot t holds new row k-1 if new_pos[k-1] == t, else old row
    # t - k, where k = |{j : new_pos[j] <= t}|.
    pos = jnp.searchsorted(s_mkey, c_mkey)
    new_pos = pos + jnp.arange(m)
    t = jnp.arange(n)
    k = jnp.searchsorted(new_pos, t, side="right")
    is_new = (k > 0) & (new_pos[jnp.maximum(k - 1, 0)] == t)
    new_idx = jnp.maximum(k - 1, 0)
    old_idx = jnp.minimum(t - k, n - 1)

    def place(s_arr, c_arr):
        return jnp.where(is_new, c_arr[new_idx], s_arr[old_idx])

    out_key = place(s_key, c_key)
    out_op = place(s_op, c_op)
    out_action = place(s_action, c_action)
    out_value = place(s_value, c_value)
    out_pred = place(s_pred, c_pred)
    out_over = place(s_over, jnp.zeros((m,), jnp.bool_))

    # succ resolution: a non-increment change op overwrites its pred
    # (increments are successors that keep the counter visible,
    # new.js:937-965). pred ops share the change op's key, so the target row
    # is identified exactly by its merge key; membership is a sorted lookup.
    hides = (c_action != ACTION_INC) & (c_pred >= 0)
    hide_mkey = jnp.sort(jnp.where(
        hides,
        (c_key.astype(jnp.int64) << _MKEY_OP_BITS) | jnp.where(c_pred >= 0, c_pred, 0),
        _I64_MAX,
    ))
    out_mkey = _merge_key(out_key, out_op)
    p = jnp.minimum(jnp.searchsorted(hide_mkey, out_mkey), m - 1)
    out_over = out_over | ((hide_mkey[p] == out_mkey) & (out_mkey != _I64_MAX))

    new_num = num_ops + jnp.sum(c_key != PAD_KEY).astype(jnp.int32)
    return out_key, out_op, out_action, out_value, out_pred, out_over, new_num


@partial(jax.jit, donate_argnums=(0,))
def batched_apply_ops(state: BatchedDocState, changes: ChangeOpsBatch) -> BatchedDocState:
    """applyChanges over a whole document batch: one fused XLA program,
    vmapped over the doc axis."""
    key, op, action, value, pred, over, num = jax.vmap(_merge_one_doc)(
        state.key, state.op, state.action, state.value, state.pred,
        state.overwritten, state.num_ops,
        changes.key, changes.op, changes.action, changes.value, changes.pred,
    )
    return BatchedDocState(key, op, action, value, pred, over, num)


def _visible_state_one_doc(key, op, action, value, pred, over, cmp):
    """Computes per-row visibility for one document.

    Returns (key, op, visible, winner, value_total):
    - `visible[i]`: row i is a visible set op (no non-increment successor) —
      the rows that populate a conflict map (new.js:112-130);
    - `winner[i]`: row i is the winning visible set op of its key (the
      visible set op with the greatest Lamport opId, apply_patch.js:33-42);
    - `value_total[i]` at a visible row: the row's value plus the sum of
      live increments targeting *that row* (per-target succ accumulation,
      new.js:937-965), so conflicting counters each carry their own total.

    `cmp` is the comparison opId per row: the packed opId itself, or its
    actor bits remapped to lexicographic actor ranks (rga.remap_opid_actors)
    so counter ties break on the actor *string* like the reference
    (new.js:146, apply_patch.js:33).

    Per-key reductions exploit the sorted key column: a run ends where the
    key differs from its right neighbour; each row's run-end index is one
    suffix min over the end positions, and the segmented max rides a single
    global cummax by packing the (ascending) key into the high bits — no
    scatters in the winner path (TPU scatters serialise) and no deep scan
    graphs.
    """
    n = key.shape[0]
    is_real = key != PAD_KEY
    is_set = is_real & (action == ACTION_SET)
    is_inc = is_real & (action == ACTION_INC)
    visible_set = is_set & ~over

    iota = jnp.arange(n, dtype=jnp.int32)
    is_end = jnp.concatenate([key[:-1] != key[1:], jnp.ones((1,), jnp.bool_)])
    run_end = jax.lax.cummin(
        jnp.where(is_end, iota, jnp.iinfo(jnp.int32).max), reverse=True
    )

    # winner: the visible set row with the greatest cmp in its key run.
    packed = jnp.where(
        visible_set, (key.astype(jnp.int64) << _MKEY_OP_BITS) | cmp, jnp.int64(-1)
    )
    run_max = jax.lax.cummax(packed)[run_end]
    winner = visible_set & (packed == run_max)

    # live increments: an inc is live iff its target set op is not
    # overwritten. The target shares the inc's key, so locate it by merge
    # key within the sorted rows.
    mkey = _merge_key(key, op)
    target_mkey = jnp.where(
        is_inc & (pred >= 0),
        (key.astype(jnp.int64) << _MKEY_OP_BITS) | jnp.where(pred >= 0, pred, 0),
        _I64_MAX,
    )
    tpos = jnp.minimum(jnp.searchsorted(mkey, target_mkey), n - 1)
    target_live = (mkey[tpos] == target_mkey) & ~over[tpos]
    inc_live = is_inc & target_live

    # per-target accumulation: each live inc adds its value onto the row it
    # names in pred (a segment-sum scatter-add over target positions).
    inc_vals = jnp.where(inc_live, value, 0)
    row_inc = jax.ops.segment_sum(inc_vals, tpos, num_segments=n)
    value_total = jnp.where(visible_set, value + row_inc, 0)
    return key, op, visible_set, winner, value_total


@jax.jit
def _batched_visible_state_cmp(state: BatchedDocState, cmp):
    return jax.vmap(_visible_state_one_doc)(
        state.key, state.op, state.action, state.value, state.pred,
        state.overwritten, cmp,
    )


def batched_visible_state(state: BatchedDocState, actor_rank=None):
    """Materialises the visible state of every document: the device-side
    equivalent of documentPatch (new.js:1604). Returns per-row
    (key, op, visible, winner, value_total) arrays of shape
    [docs, capacity].

    `actor_rank` (int32[A], actor intern index -> lexicographic rank) makes
    counter-tied conflicts resolve on the actor id string exactly like the
    reference; without it, ties break on actor intern order (sufficient for
    single-engine convergence, not for cross-engine parity).
    """
    if actor_rank is None:
        cmp = state.op
    else:
        cmp = remap_opid_actors(state.op, actor_rank)
    return _dispatch(_batched_visible_state_cmp, state, cmp)


@jax.jit
def _gather_rows(visible, totals, idx):
    """Row gather for the incremental readback path: `idx` is a flat array
    of ``doc * capacity + row`` indices (padded to a power-of-two length so
    jit shapes are bucketed; the host trims the padding)."""
    return visible.reshape(-1)[idx], totals.reshape(-1)[idx]


class BatchedMapEngine:
    """Host-side driver for the batched map/counter engine.

    Maintains the dense device state for a batch of documents. The capacity
    doubles when a merge would overflow, bucketing shapes by powers of two so
    recompiles are amortised. ``version`` counts committed merges; the
    visibility pytree is memoised per version so that repeated reads between
    merges (patch assembly, whole-doc scans, scoped readbacks) cost one
    device dispatch per merge, not one per read.
    """

    def __init__(self, num_docs: int, capacity: int = 1024):
        self.num_docs = num_docs
        self.capacity = capacity
        self.state = make_empty_state(num_docs, capacity)
        self.version = 0
        self._vis_memo = None  # ((version, rank_bytes), visibility pytree)

    def apply_batch(self, changes: ChangeOpsBatch) -> BatchedDocState:
        _fault_point("engine.apply_batch", changes=changes)
        needed = int(jnp.max(self.state.num_ops)) + changes.key.shape[1]
        while needed > self.capacity:
            self.capacity *= 2
            self.state = _grow_state(self.state, self.capacity)
            _M_STATE_GROWS.inc()
        self.state = _dispatch(batched_apply_ops, self.state, changes)
        self.version += 1
        self._vis_memo = None
        return self.state

    def visible_state(self, actor_rank=None):
        """Device-resident visibility pytree (see batched_visible_state),
        memoised per (state version, actor-rank table)."""
        _fault_point("engine.visible_state")
        rank_key = (
            None if actor_rank is None else np.asarray(actor_rank).tobytes()
        )
        key = (self.version, rank_key)
        if self._vis_memo is not None and self._vis_memo[0] == key:
            return self._vis_memo[1]
        out = batched_visible_state(self.state, actor_rank=actor_rank)
        self._vis_memo = (key, out)
        return out

    def read_visibility_rows(self, flat_idx, actor_rank=None):
        """Scoped device→host visibility readback: (visible, value_total)
        numpy arrays for just the rows named by `flat_idx` (flattened
        ``doc * capacity + row`` indices), via one padded device gather and
        ONE jax.device_get — the transfer is O(rows requested), not O(whole
        farm state)."""
        n = int(flat_idx.shape[0])
        if n == 0:
            return np.zeros(0, bool), np.zeros(0, np.int64)
        _, _, visible, _, totals = self.visible_state(actor_rank)
        padded = 1 << max(0, n - 1).bit_length()
        idx = np.zeros(padded, np.int32)
        idx[:n] = flat_idx
        v, t = _dispatch(_gather_rows, visible, totals, jnp.asarray(idx))
        v, t = jax.device_get((v, t))
        return v[:n], t[:n]


def _grow_state(state: BatchedDocState, capacity: int) -> BatchedDocState:
    num_docs, old_cap = state.key.shape
    pad = capacity - old_cap

    def grow(arr, fill):
        return jnp.concatenate(
            [arr, jnp.full((num_docs, pad), fill, arr.dtype)], axis=1
        )

    return BatchedDocState(
        key=grow(state.key, PAD_KEY),
        op=grow(state.op, 0),
        action=grow(state.action, 0),
        value=grow(state.value, 0),
        pred=grow(state.pred, -1),
        overwritten=grow(state.overwritten, False),
        num_ops=state.num_ops,
    )


def changes_from_numpy(keys, ops, actions, values, preds) -> ChangeOpsBatch:
    return ChangeOpsBatch(
        key=jnp.asarray(keys, jnp.int32),
        op=jnp.asarray(ops, jnp.int64),
        action=jnp.asarray(actions, jnp.int32),
        value=jnp.asarray(values, jnp.int64),
        pred=jnp.asarray(preds, jnp.int64),
    )
