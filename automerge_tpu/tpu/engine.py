"""Batched merge kernels: the TPU equivalent of the OpSet engine's hot loop.

The reference merge (mergeDocChangeOps, /root/reference/backend/new.js:1052)
is a sequential two-pointer walk per document. Here the same result is
computed as a data-parallel array program over a whole batch of documents:

  1. concatenate existing doc ops with incoming change ops
  2. lexsort rows into the canonical op order: (key, opId counter, opId actor)
     -- the same total order the columnar engine maintains
  3. resolve succ/overwrite relationships: an op is overwritten when another
     (non-increment) op names it in `pred` (matched with a sorted binary
     search, no scatter loops)
  4. visibility = zero successors; the winning value per key is the visible
     op with the greatest Lamport opId (segmented max over the sorted keys);
     counter increments accumulate onto their target set op instead of
     hiding it (new.js:937-965)

Everything is static-shape and jit/vmap/shard_map friendly: padded rows carry
key = PAD_KEY and sort to the end. Map objects and counters are supported in
this v1 engine (benchmark configs 1 and 3); list/text RGA ordering stays on
the sequential engine for now (see SURVEY.md §7 step 5).

Lamport opIds are packed into a single int64 as (counter << 20 | actor_num),
which preserves (counter, actor) ordering for up to 2^20 actors and 2^43 ops.
"""
from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..obs.flight import get_flight
from ..obs.metrics import get_metrics
from ..obs.prof import get_observatory
from ..testing.faults import fire as _fault_point
from .jitprof import profiled_jit

PAD_KEY = jnp.iinfo(jnp.int32).max
ACTOR_BITS = 20
ACTOR_MASK = (1 << ACTOR_BITS) - 1
_NEG_INF = jnp.int64(-(2**62))

ACTION_SET = 0
ACTION_INC = 1
ACTION_DEL = 2

# engine metrics (process-wide registry, disabled unless a workload opts
# in — obs/metrics.py). Dispatch accounting lives in the HOST wrappers
# below, never inside traced code (amlint AM303).
_METRICS = get_metrics()
_M_DISPATCHES = _METRICS.counter(
    "engine.device.dispatches",
    "batched device programs dispatched (merge + visibility)",
)
_M_JIT_HITS = _METRICS.counter(
    "engine.jit.cache_hits",
    "dispatches served by an already-compiled program",
)
_M_JIT_RECOMPILES = _METRICS.counter(
    "engine.jit.recompiles",
    "dispatches that triggered an XLA compile (shape-bucket misses)",
)
_M_STATE_GROWS = _METRICS.counter(
    "engine.state.grows",
    "capacity doublings of the dense device state",
)

# flight-recorder hook (obs/flight.py): recompiles and slab growth are the
# two engine events worth a postmortem timeline entry — a steady-state
# recompile storm or a surprise slab doubling explains a latency cliff.
_FLIGHT = get_flight()

# amprof observatory (obs/prof.py): every jit program below registers a
# named ProfiledProgram via tpu/jitprof.py, so recompiles carry program
# identity and dispatches get per-program latency attribution.
_OBSERVATORY = get_observatory()


def _dispatch(prog, *args, **kwargs):
    """Runs a named profiled program (tpu/jitprof.py), classifying the
    call as a jit cache hit or a recompile by the growth of the program's
    compile cache across the call. This is the single device-dispatch
    funnel for the engine, so the recompile-storm and dispatch-count
    metrics cover every merge and visibility program. Per-program
    attribution (compile/dispatch tallies, shape buckets, the
    ``engine.recompile`` flight event with program identity) lives in
    ``ProfiledProgram.call_profiled``; with both metrics and the
    observatory disabled this degrades to a plain call."""
    if not _METRICS.enabled and not _OBSERVATORY.enabled:
        return prog.fn(*args, **kwargs)
    out, grew, _dt = prog.call_profiled(args, kwargs)
    if _METRICS.enabled:
        _M_DISPATCHES.inc()
        if grew > 0:
            _M_JIT_RECOMPILES.inc(grew)
        elif grew == 0:
            _M_JIT_HITS.inc()
    return out


def pack_opid(counter, actor):
    """Packs (counter, actorNum) into one int64 preserving Lamport order."""
    counter = jnp.asarray(counter)
    actor = jnp.asarray(actor)
    return (counter.astype(jnp.int64) << ACTOR_BITS) | actor.astype(jnp.int64)


def unpack_opid(opid):
    return opid >> ACTOR_BITS, opid & ACTOR_MASK


def remap_opid_actors(opid, actor_rank):
    """Rebuilds packed opIds with the actor index replaced by its
    lexicographic rank, so int64 comparison == (counter, actorId-string)
    comparison (the reference's tie-break, new.js:146, apply_patch.js:33)."""
    actor_rank = jnp.asarray(actor_rank)
    counter = opid >> ACTOR_BITS
    actor = (opid & ACTOR_MASK).astype(jnp.int32)
    rank = actor_rank[jnp.minimum(actor, actor_rank.shape[0] - 1)]
    return (counter << ACTOR_BITS) | rank.astype(jnp.int64)


class BatchedDocState(NamedTuple):
    """Dense op storage for a batch of map documents.

    All row arrays have shape [docs, capacity], sorted by (key, opId);
    padded slots have key == PAD_KEY and sort last. `overwritten` marks ops
    with at least one non-increment successor (the dense analogue of
    succNum > 0); `pred` is the packed opId each op overwrites/increments
    (-1 if none), from which full succ lists are recovered host-side when
    transcoding back to the columnar format.
    """

    key: jax.Array          # int32 interned key id
    op: jax.Array           # int64 packed opId
    action: jax.Array       # int32 (ACTION_SET / ACTION_INC / ACTION_DEL)
    value: jax.Array        # int64 value payload (interned ref or small int)
    pred: jax.Array         # int64 packed opId, -1 if none
    overwritten: jax.Array  # bool
    num_ops: jax.Array      # int32 [docs] live op count


class ChangeOpsBatch(NamedTuple):
    """One batch of incoming change ops per document, shape [docs, m]."""

    key: jax.Array
    op: jax.Array
    action: jax.Array
    value: jax.Array
    pred: jax.Array


def make_empty_state(num_docs: int, capacity: int) -> BatchedDocState:
    return BatchedDocState(
        key=jnp.full((num_docs, capacity), PAD_KEY, jnp.int32),
        op=jnp.zeros((num_docs, capacity), jnp.int64),
        action=jnp.zeros((num_docs, capacity), jnp.int32),
        value=jnp.zeros((num_docs, capacity), jnp.int64),
        pred=jnp.full((num_docs, capacity), -1, jnp.int64),
        overwritten=jnp.zeros((num_docs, capacity), jnp.bool_),
        num_ops=jnp.zeros((num_docs,), jnp.int32),
    )


# Merge keys pack (key, opId) into one int64: key in the top 20 bits, the
# packed opId (counter << 20 | actor) in the low 44. Requires counter < 2^24.
_MKEY_OP_BITS = 44
_I64_MAX = jnp.iinfo(jnp.int64).max


def _merge_key(key, op):
    return jnp.where(
        key == PAD_KEY,
        _I64_MAX,
        (key.astype(jnp.int64) << _MKEY_OP_BITS) | op,
    )


def _merge_one_doc(s_key, s_op, s_action, s_value, s_pred, s_over, num_ops,
                   c_key, c_op, c_action, c_value, c_pred):
    """Merges one document's change ops into its sorted op table (vmapped
    over the batch).

    The doc state is invariant-sorted by (key, opId), so instead of
    re-sorting the whole table (the naive O(N log N) per merge), only the
    small change batch is sorted and merged in by insertion position:
    searchsorted gives each change op's slot, and every row moves to its
    final position with one scatter -- O(N) memory traffic + O(M log N)
    compute, the TPU analogue of the reference's two-pointer merge
    (mergeDocChangeOps, new.js:1052).
    """
    n = s_key.shape[0]
    m = c_key.shape[0]
    s_mkey = _merge_key(s_key, s_op)

    # sort the change ops into canonical order
    c_mkey = _merge_key(c_key, c_op)
    c_order = jnp.argsort(c_mkey)
    c_mkey = c_mkey[c_order]
    c_key = c_key[c_order]
    c_op = c_op[c_order]
    c_action = c_action[c_order]
    c_value = c_value[c_order]
    c_pred = c_pred[c_order]

    # insertion positions: new row j lands at pos[j] + j. The output is then
    # built by pure gathers (TPU scatters serialize; gathers vectorise):
    # output slot t holds new row k-1 if new_pos[k-1] == t, else old row
    # t - k, where k = |{j : new_pos[j] <= t}|.
    pos = jnp.searchsorted(s_mkey, c_mkey)
    new_pos = pos + jnp.arange(m)
    t = jnp.arange(n)
    k = jnp.searchsorted(new_pos, t, side="right")
    is_new = (k > 0) & (new_pos[jnp.maximum(k - 1, 0)] == t)
    new_idx = jnp.maximum(k - 1, 0)
    old_idx = jnp.minimum(t - k, n - 1)

    def place(s_arr, c_arr):
        return jnp.where(is_new, c_arr[new_idx], s_arr[old_idx])

    out_key = place(s_key, c_key)
    out_op = place(s_op, c_op)
    out_action = place(s_action, c_action)
    out_value = place(s_value, c_value)
    out_pred = place(s_pred, c_pred)
    out_over = place(s_over, jnp.zeros((m,), jnp.bool_))

    # succ resolution: a non-increment change op overwrites its pred
    # (increments are successors that keep the counter visible,
    # new.js:937-965). pred ops share the change op's key, so the target row
    # is identified exactly by its merge key; membership is a sorted lookup.
    hides = (c_action != ACTION_INC) & (c_pred >= 0)
    hide_mkey = jnp.sort(jnp.where(
        hides,
        (c_key.astype(jnp.int64) << _MKEY_OP_BITS) | jnp.where(c_pred >= 0, c_pred, 0),
        _I64_MAX,
    ))
    out_mkey = _merge_key(out_key, out_op)
    p = jnp.minimum(jnp.searchsorted(hide_mkey, out_mkey), m - 1)
    out_over = out_over | ((hide_mkey[p] == out_mkey) & (out_mkey != _I64_MAX))

    new_num = num_ops + jnp.sum(c_key != PAD_KEY).astype(jnp.int32)
    return out_key, out_op, out_action, out_value, out_pred, out_over, new_num


@profiled_jit("engine.apply_ops", donate_argnums=(0,))
def batched_apply_ops(state: BatchedDocState, changes: ChangeOpsBatch) -> BatchedDocState:
    """applyChanges over a whole document batch: one fused XLA program,
    vmapped over the doc axis."""
    key, op, action, value, pred, over, num = jax.vmap(_merge_one_doc)(
        state.key, state.op, state.action, state.value, state.pred,
        state.overwritten, state.num_ops,
        changes.key, changes.op, changes.action, changes.value, changes.pred,
    )
    return BatchedDocState(key, op, action, value, pred, over, num)


def _visible_state_one_doc(key, op, action, value, pred, over, cmp):
    """Computes per-row visibility for one document.

    Returns (key, op, visible, winner, value_total):
    - `visible[i]`: row i is a visible set op (no non-increment successor) —
      the rows that populate a conflict map (new.js:112-130);
    - `winner[i]`: row i is the winning visible set op of its key (the
      visible set op with the greatest Lamport opId, apply_patch.js:33-42);
    - `value_total[i]` at a visible row: the row's value plus the sum of
      live increments targeting *that row* (per-target succ accumulation,
      new.js:937-965), so conflicting counters each carry their own total.

    `cmp` is the comparison opId per row: the packed opId itself, or its
    actor bits remapped to lexicographic actor ranks (rga.remap_opid_actors)
    so counter ties break on the actor *string* like the reference
    (new.js:146, apply_patch.js:33).

    Per-key reductions exploit the sorted key column: a run ends where the
    key differs from its right neighbour; each row's run-end index is one
    suffix min over the end positions, and the segmented max rides a single
    global cummax by packing the (ascending) key into the high bits — no
    scatters in the winner path (TPU scatters serialise) and no deep scan
    graphs.
    """
    n = key.shape[0]
    is_real = key != PAD_KEY
    is_set = is_real & (action == ACTION_SET)
    is_inc = is_real & (action == ACTION_INC)
    visible_set = is_set & ~over

    iota = jnp.arange(n, dtype=jnp.int32)
    is_end = jnp.concatenate([key[:-1] != key[1:], jnp.ones((1,), jnp.bool_)])
    run_end = jax.lax.cummin(
        jnp.where(is_end, iota, jnp.iinfo(jnp.int32).max), reverse=True
    )

    # winner: the visible set row with the greatest cmp in its key run.
    packed = jnp.where(
        visible_set, (key.astype(jnp.int64) << _MKEY_OP_BITS) | cmp, jnp.int64(-1)
    )
    run_max = jax.lax.cummax(packed)[run_end]
    winner = visible_set & (packed == run_max)

    # live increments: an inc is live iff its target set op is not
    # overwritten. The target shares the inc's key, so locate it by merge
    # key within the sorted rows.
    mkey = _merge_key(key, op)
    target_mkey = jnp.where(
        is_inc & (pred >= 0),
        (key.astype(jnp.int64) << _MKEY_OP_BITS) | jnp.where(pred >= 0, pred, 0),
        _I64_MAX,
    )
    tpos = jnp.minimum(jnp.searchsorted(mkey, target_mkey), n - 1)
    target_live = (mkey[tpos] == target_mkey) & ~over[tpos]
    inc_live = is_inc & target_live

    # per-target accumulation: each live inc adds its value onto the row it
    # names in pred (a segment-sum scatter-add over target positions).
    inc_vals = jnp.where(inc_live, value, 0)
    row_inc = jax.ops.segment_sum(inc_vals, tpos, num_segments=n)
    value_total = jnp.where(visible_set, value + row_inc, 0)
    return key, op, visible_set, winner, value_total


@profiled_jit("engine.visible_cmp")
def _batched_visible_state_cmp(state: BatchedDocState, cmp):
    return jax.vmap(_visible_state_one_doc)(
        state.key, state.op, state.action, state.value, state.pred,
        state.overwritten, cmp,
    )


def batched_visible_state(state: BatchedDocState, actor_rank=None):
    """Materialises the visible state of every document: the device-side
    equivalent of documentPatch (new.js:1604). Returns per-row
    (key, op, visible, winner, value_total) arrays of shape
    [docs, capacity].

    `actor_rank` (int32[A], actor intern index -> lexicographic rank) makes
    counter-tied conflicts resolve on the actor id string exactly like the
    reference; without it, ties break on actor intern order (sufficient for
    single-engine convergence, not for cross-engine parity).
    """
    if actor_rank is None:
        cmp = state.op
    else:
        cmp = remap_opid_actors(state.op, actor_rank)
    return _dispatch(_batched_visible_state_cmp, state, cmp)


@profiled_jit("engine.gather_rows")
def _gather_rows(visible, totals, idx):
    """Row gather for the incremental readback path: `idx` is a flat array
    of ``doc * capacity + row`` indices (padded to a power-of-two length so
    jit shapes are bucketed; the host trims the padding)."""
    return visible.reshape(-1)[idx], totals.reshape(-1)[idx]


# page-storage metrics: the slab's figure of merit (farm.pages.occupancy
# replaces pad-waste as the HBM measure — see paging.py)
_M_PAGES_ALLOC = _METRICS.gauge(
    "farm.pages.allocated", "slab pages currently owned by documents"
)
_M_PAGES_FREE = _METRICS.gauge(
    "farm.pages.free", "slab pages on the allocator free list"
)
_M_PAGES_OCC = _METRICS.gauge(
    "farm.pages.occupancy", "live op rows / allocated page cells"
)

# imported mid-module: paging.py needs the kernel functions above, the
# driver below needs paging's slab programs — the split keeps kernels and
# storage layout in separate files without a third module
from .paging import (  # noqa: E402
    PageAllocator,
    grow_slab,
    make_empty_slab,
    paged_adopt_rows,
    paged_apply_ops,
    paged_dense_view,
    paged_probe_ops,
    paged_visible_plain,
    paged_visible_ranked,
    patch_column_rows,
)


class BatchedMapEngine:
    """Host-side driver for the batched map/counter engine over ragged
    paged op storage (paging.py).

    Documents' op rows live in fixed-size pages of one shared device slab
    (per-doc page table + length on the host). A merge gathers only the
    ACTIVE documents' rows into a pow2-bucketed dense working view, runs
    the unchanged merge kernel, and scatters the result back through the
    new page map — one XLA program, shapes bucketed by (active docs,
    largest active doc), so a farm of wildly different doc sizes neither
    pays largest-doc HBM per doc nor recompiles the whole farm when one
    document grows. ``version`` counts committed merges; visibility
    pytrees are memoised per (version, doc subset, actor rank) so repeated
    reads between merges cost one dispatch each.
    """

    def __init__(self, num_docs: int, capacity: int = 1024,
                 page_size: int | None = None):
        import os

        self.num_docs = num_docs
        self.capacity = capacity  # legacy sizing hint; storage is paged
        # the dense WORKING width (gather/merge/visibility views) never
        # shrinks below the caller's sizing hint and ratchets up with the
        # largest doc: stable pow2 shapes keep the program cache warm (the
        # hint does NOT reserve HBM — the slab allocates by page)
        self._width_floor = self._pow2(min(capacity, 1 << 13))
        page_size = page_size or int(os.environ.get("AM_PAGE_SIZE", "64"))
        # the slab starts at the caller's sizing hint (num_docs x capacity
        # rows) and grows in pow2 jumps: every distinct slab size is a
        # compiled-program shape, so a hint-sized farm never recompiles in
        # the steady state, while farms of mostly-small docs simply leave
        # pages on the free list (allocation is per page, the hint only
        # sizes the arena)
        hint_pages = (num_docs * min(capacity, 1 << 13)) // page_size
        self.pages = PageAllocator(
            page_size, initial_pages=max(4, min(hint_pages, 1 << 17))
        )
        self.slab = make_empty_slab(self.pages.num_pages * page_size)
        self.page_table: list[list] = [[] for _ in range(num_docs)]
        self.lengths = np.zeros(num_docs, np.int64)
        self.version = 0
        self._vis_memo: dict = {}

    @staticmethod
    def _pow2(n) -> int:
        return 1 << max(0, int(n) - 1).bit_length()

    def _width(self, needed: int) -> int:
        """Dense working width for `needed` rows: pow2-bucketed (never
        below one page) with the never-shrinking floor, so steady-state
        dispatches reuse one compiled shape instead of recompiling at
        every doubling."""
        width = max(self._pow2(needed), self._width_floor,
                    self.pages.page_size)
        self._width_floor = width
        return width

    def _page_map(self, tables, width, a_pad, fill):
        """[a_pad, width / P] PAGE indices: slot j of doc k names the slab
        page holding its rows [j*P, (j+1)*P), else `fill` (0 = the PAD
        page for gathers, num_pages = dropped for scatters). Device moves
        are whole contiguous pages; the page-tail invariant (paging.py)
        makes per-row masking unnecessary."""
        npg = width // self.pages.page_size
        mat = np.full((a_pad, npg), fill, np.int32)
        for k, pt in enumerate(tables):
            n = min(len(pt), npg)
            if n:
                mat[k, :n] = pt[:n]
        return mat

    def apply_batch(self, changes: ChangeOpsBatch, docs=None, counts=None):
        """Merges `changes` into the slab. `docs` names the documents the
        batch rows belong to (None = all docs, the legacy full-farm shape);
        rows past ``len(docs)`` are pow2 padding. `counts` gives each doc's
        real (non-pad) row count — passed by the farm, derived from the
        batch otherwise."""
        _fault_point("engine.apply_batch", changes=changes)
        docs = (
            list(range(self.num_docs)) if docs is None
            else [int(d) for d in docs]
        )
        if not docs:
            return
        a_pad, m = changes.key.shape
        assert a_pad >= len(docs)
        if counts is None:
            counts = np.asarray(changes.key != PAD_KEY).sum(axis=1)[: len(docs)]
        counts = np.asarray(counts, np.int64)
        old_lens = self.lengths[docs]
        new_lens = old_lens + counts
        width = self._width(int(old_lens.max()) + m)
        P = self.pages.page_size

        old_tables = [self.page_table[d] for d in docs]
        gidx = self._page_map(old_tables, width, a_pad, fill=0)

        extra = [
            self.pages.pages_for(int(n)) - len(t)
            for n, t in zip(new_lens, old_tables)
        ]
        if self.pages.ensure(sum(e for e in extra if e > 0)):
            self.slab = grow_slab(self.slab, self.pages.num_pages * P)
            _M_STATE_GROWS.inc()
            if _FLIGHT.enabled:
                _FLIGHT.record("engine.slab.grow",
                               pages=self.pages.num_pages,
                               rows=self.pages.num_pages * P)
        fresh: list = []
        new_tables = []
        for t, e in zip(old_tables, extra):
            if e > 0:
                pages = self.pages.alloc(e)
                fresh.extend(pages)
                new_tables.append(list(t) + pages)
            else:
                new_tables.append(list(t))
        dest = self._page_map(new_tables, width, a_pad,
                              fill=self.pages.num_pages)
        try:
            self.slab = _dispatch(
                paged_apply_ops, self.slab, jnp.asarray(gidx), changes,
                jnp.asarray(dest), page_size=P,
            )
        except Exception:
            # nothing committed: hand the delta pages back so a failed
            # dispatch (degraded mode) leaks no slab capacity
            self.pages.free(fresh)
            raise
        for d, t, n in zip(docs, new_tables, new_lens):
            self.page_table[d] = t
            self.lengths[d] = int(n)
        self.version += 1
        self._vis_memo.clear()
        self._update_page_metrics()

    def probe_apply(self, changes: ChangeOpsBatch, docs, counts=None):
        """Runs the merge for `docs` on a throwaway basis (no scatter, no
        donation, no state advance): the bisection probe for device-fault
        isolation."""
        docs = [int(d) for d in docs]
        a_pad, m = changes.key.shape
        lens = self.lengths[docs] if docs else np.zeros(0, np.int64)
        width = self._width((int(lens.max()) if docs else 0) + m)
        tables = [self.page_table[d] for d in docs]
        gidx = self._page_map(tables, width, a_pad, fill=0)
        out = paged_probe_ops(
            self.slab, jnp.asarray(gidx), changes,
            page_size=self.pages.page_size,
        )
        jax.block_until_ready(out)

    def visible_state(self, actor_rank=None, docs=None):
        """Device-resident visibility pytree for `docs` (None = every
        document): per-row (key, op, visible, winner, value_total) arrays
        of shape [len(docs), W], W = pow2 bucket of the largest requested
        doc. Memoised per (state version, doc subset, actor-rank table)."""
        _fault_point("engine.visible_state")
        docs_t = (
            tuple(range(self.num_docs)) if docs is None
            else tuple(int(d) for d in docs)
        )
        rank_key = (
            None if actor_rank is None else np.asarray(actor_rank).tobytes()
        )
        key = (docs_t, rank_key)
        hit = self._vis_memo.get(key)
        if hit is not None:
            return hit
        lens = (
            self.lengths[list(docs_t)] if docs_t else np.zeros(0, np.int64)
        )
        width = self._width(int(lens.max()) if len(lens) else 1)
        a_pad = self._pow2(len(docs_t))
        tables = [self.page_table[d] for d in docs_t]
        gidx = self._page_map(tables, width, a_pad, fill=0)
        if actor_rank is None:
            out = _dispatch(
                paged_visible_plain, self.slab, jnp.asarray(gidx),
                page_size=self.pages.page_size,
            )
        else:
            out = _dispatch(
                paged_visible_ranked, self.slab, jnp.asarray(gidx),
                jnp.asarray(actor_rank), page_size=self.pages.page_size,
            )
        out = jax.tree_util.tree_map(lambda a: a[: len(docs_t)], out)
        if len(self._vis_memo) > 16:
            self._vis_memo.clear()
        self._vis_memo[key] = out
        return out

    def read_visibility_rows(self, plan, actor_rank=None):
        """Scoped device→host visibility readback: `plan` is a list of
        ``(doc, row_idx array)`` pairs; returns (visible, value_total)
        numpy arrays concatenated in plan order. Visibility is computed
        for ONLY the planned docs' rows, then one padded device gather and
        ONE jax.device_get move exactly the requested rows — O(rows
        requested), not O(whole farm state)."""
        plan = [
            (int(d), np.asarray(idx, np.int64))
            for d, idx in plan if len(idx)
        ]
        if not plan:
            return np.zeros(0, bool), np.zeros(0, np.int64)
        docs_t = tuple(sorted({d for d, _ in plan}))
        _k, _o, visible, _w, totals = self.visible_state(
            actor_rank, docs=docs_t
        )
        w = visible.shape[1]
        pos = {d: i for i, d in enumerate(docs_t)}
        flat = np.concatenate([pos[d] * w + idx for d, idx in plan])
        n = int(flat.shape[0])
        padded = 1 << max(0, n - 1).bit_length()
        idx = np.zeros(padded, np.int64)
        idx[:n] = flat
        v, t = _dispatch(_gather_rows, visible, totals, jnp.asarray(idx))
        v, t = jax.device_get((v, t))
        return v[:n], t[:n]

    def read_patch_columns(self, plan, actor_rank):
        """Scoped readback + device patch-column emission: `plan` is a
        list of ``(doc, row_idx array, cut array)`` triples, where `cut`
        holds each requested row's walk cutoff as a rank-packed int64
        (``-1`` = the row's slot is outside the delivery's cutoff set,
        int64 max = walk to the end of the key run). Returns
        (visible, value_total, emit) numpy arrays concatenated in plan
        order. Visibility comes from the memoised stable-shape program
        (visible_state), then paging.patch_column_rows gathers exactly
        the requested rows and decides patch emission on device — the
        shape-varying half compiles in milliseconds, so growing readback
        sizes never re-pay the visibility kernel's compile."""
        plan = [
            (int(d), np.asarray(idx, np.int64), np.asarray(cut, np.int64))
            for d, idx, cut in plan if len(idx)
        ]
        if not plan:
            return (
                np.zeros(0, bool), np.zeros(0, np.int64), np.zeros(0, bool)
            )
        docs_t = tuple(sorted({d for d, _, _ in plan}))
        _k, op, visible, _w, totals = self.visible_state(
            actor_rank, docs=docs_t
        )
        w = visible.shape[1]
        pos = {d: i for i, d in enumerate(docs_t)}
        flat = np.concatenate([pos[d] * w + idx for d, idx, _ in plan])
        cuts = np.concatenate([cut for _, _, cut in plan])
        n = int(flat.shape[0])
        padded = 1 << max(0, n - 1).bit_length()
        idx = np.zeros(padded, np.int64)
        idx[:n] = flat
        cut = np.full(padded, -1, np.int64)  # pad rows never emit
        cut[:n] = cuts
        v, t, e = _dispatch(
            patch_column_rows, visible, totals, op,
            jnp.asarray(actor_rank), jnp.asarray(idx), jnp.asarray(cut),
        )
        v, t, e = jax.device_get((v, t, e))
        return v[:n], t[:n], e[:n]

    def dense_view(self, docs=None):
        """Host copies of the six op columns as dense [D, W] arrays (the
        whole-state debug/parity readback — production paths stay paged)."""
        docs_t = (
            tuple(range(self.num_docs)) if docs is None
            else tuple(int(d) for d in docs)
        )
        lens = self.lengths[list(docs_t)] if docs_t else np.zeros(0, np.int64)
        width = self._width(int(lens.max()) if len(lens) else 1)
        gidx = self._page_map(
            [self.page_table[d] for d in docs_t], width,
            self._pow2(len(docs_t)), fill=0,
        )
        out = paged_dense_view(
            self.slab, jnp.asarray(gidx), page_size=self.pages.page_size
        )
        return jax.device_get(
            jax.tree_util.tree_map(lambda a: a[: len(docs_t)], out)
        )

    def restore_doc(self, d: int, pages, length: int) -> None:
        """Rolls doc `d`'s page allocation back to a snapshot, returning
        pages acquired since to the free list. No device rows are
        rewritten: rollback always precedes the commit that would have
        used them (or that commit's dispatch failed and already freed its
        delta pages)."""
        keep = set(pages)
        self.pages.free([p for p in self.page_table[d] if p not in keep])
        self.page_table[d] = list(pages)
        self.lengths[d] = int(length)
        self._update_page_metrics()

    def adopt_rows(self, d: int, key, op, action, value, pred, over) -> None:
        """Installs a migrated document's op rows as doc `d`'s pages (the
        destination half of cross-farm page-granular migration). Doc `d`
        must be empty; rows arrive as host arrays already translated into
        THIS engine's id space and sorted by merge key. Pages are
        allocated fresh and written by one whole-page scatter program —
        host padding keeps the page-tail invariant."""
        assert not self.page_table[d], "adopt_rows into an occupied doc"
        n = int(np.asarray(key).shape[0])
        self.lengths[d] = n
        self.version += 1
        self._vis_memo.clear()
        if n == 0:
            self._update_page_metrics()
            return
        P = self.pages.page_size
        npg = self.pages.pages_for(n)
        if self.pages.ensure(npg):
            self.slab = grow_slab(self.slab, self.pages.num_pages * P)
            _M_STATE_GROWS.inc()
        pages = self.pages.alloc(npg)
        npg_pad = self._pow2(npg)
        dest = np.full(npg_pad, self.pages.num_pages, np.int32)
        dest[:npg] = pages
        w = npg_pad * P

        def pad(col, fill, dtype):
            out = np.full(w, fill, dtype)
            out[:n] = col
            return out

        self.slab = _dispatch(
            paged_adopt_rows, self.slab, jnp.asarray(dest),
            jnp.asarray(pad(key, PAD_KEY, np.int32)),
            jnp.asarray(pad(op, 0, np.int64)),
            jnp.asarray(pad(action, 0, np.int32)),
            jnp.asarray(pad(value, 0, np.int64)),
            jnp.asarray(pad(pred, -1, np.int64)),
            jnp.asarray(pad(over, False, np.bool_)),
            page_size=P,
        )
        self.page_table[d] = pages
        self._update_page_metrics()

    def evict_doc(self, d: int) -> None:
        """Releases doc `d`'s pages to the free list and zeroes its length
        (the source half of migration). No device rows are wiped: freed
        pages are fully overwritten at their next allocation — every
        scatter (paged_apply_ops / paged_adopt_rows) writes whole pages,
        the same reasoning that lets restore_doc return pages untouched."""
        self.pages.free(self.page_table[d])
        self.page_table[d] = []
        self.lengths[d] = 0
        self.version += 1
        self._vis_memo.clear()
        self._update_page_metrics()

    def _update_page_metrics(self) -> None:
        if not _METRICS.enabled:
            return
        allocated = self.pages.allocated
        _M_PAGES_ALLOC.set(allocated)
        _M_PAGES_FREE.set(self.pages.free_count)
        if allocated:
            _M_PAGES_OCC.set(
                float(self.lengths.sum()) / (allocated * self.pages.page_size)
            )


def _grow_state(state: BatchedDocState, capacity: int) -> BatchedDocState:
    num_docs, old_cap = state.key.shape
    pad = capacity - old_cap

    def grow(arr, fill):
        return jnp.concatenate(
            [arr, jnp.full((num_docs, pad), fill, arr.dtype)], axis=1
        )

    return BatchedDocState(
        key=grow(state.key, PAD_KEY),
        op=grow(state.op, 0),
        action=grow(state.action, 0),
        value=grow(state.value, 0),
        pred=grow(state.pred, -1),
        overwritten=grow(state.overwritten, False),
        num_ops=state.num_ops,
    )


def changes_from_numpy(keys, ops, actions, values, preds) -> ChangeOpsBatch:
    return ChangeOpsBatch(
        key=jnp.asarray(keys, jnp.int32),
        op=jnp.asarray(ops, jnp.int64),
        action=jnp.asarray(actions, jnp.int32),
        value=jnp.asarray(values, jnp.int64),
        pred=jnp.asarray(preds, jnp.int64),
    )
