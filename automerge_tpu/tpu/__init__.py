"""TPU-native batched CRDT merge engine.

The reference engine (backend/new.js) merges one change into one document at
a time with data-dependent control flow. This package re-architects the hot
path for TPU execution: documents become fixed-width dense op tensors, and
applyChanges becomes a batched array program (sort + segmented scans) that
merges changes into thousands of documents in parallel, vmapped over the doc
axis and sharded over a jax.sharding.Mesh.
"""
import jax

# Packed int64 Lamport opIds require 64-bit array support
jax.config.update("jax_enable_x64", True)

from .engine import (  # noqa: E402
    ACTION_DEL,
    ACTION_INC,
    ACTION_SET,
    BatchedDocState,
    BatchedMapEngine,
    ChangeOpsBatch,
    PAD_KEY,
    batched_apply_ops,
    batched_visible_state,
    make_empty_state,
    pack_opid,
    unpack_opid,
)
from . import decode  # noqa: E402, F401  (registers the vectorized decode backend)
from .transcode import BatchTranscoder  # noqa: E402
