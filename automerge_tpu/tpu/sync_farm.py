"""Batched peer-wise sync for a farm of documents.

`SyncFarm` runs the reference sync protocol (backend/sync.js, wire format
unchanged — see automerge_tpu/sync.py) for many (document, peer) channels at
once over a `TpuDocFarm`:

- `generate_messages` builds every channel's `have` Bloom filter in ONE
  batched device program (sync_batch.build_filters) and evaluates every
  channel's changes-to-send Bloom queries in ONE batched device program
  (sync_batch.query_filters) — the batched analogue of makeBloomFilter
  (sync.js:234) and getChangesToSend's containsHash loop (sync.js:246-289).
- `receive_messages` decodes the messages, applies all channels' changes
  through the farm's single batched applyChanges, and advances per-channel
  sharedHeads exactly like receiveSyncMessage (sync.js:420).

Channels negotiated onto sync v2 (range-based reconciliation,
automerge_tpu/sync_v2.py) ride the same batched calls via the
``protocols`` parameter: every v2 channel's fingerprint queries for the
round — inbound-range checks, median splits, fresh probes — concatenate
into ONE ``sync.fingerprint_ranges`` device reduction
(tpu/fingerprint.FingerprintIndex), and inbound payloads route on their
leading type byte, so one sweep mixes v1 and v2 channels freely.

Messages are byte-identical to the sequential protocol's (asserted by
tests/test_sync_farm.py against sync.py driving per-doc backends), so a
farm can sync against any reference-compatible peer.

Hash-graph traversals (changes since lastSync, dependents closure) stay on
the host: the graphs are tiny per document and pointer-chasing shaped. The
device does the bit-parallel work: B filters built and B x C candidate
probes evaluated per call.
"""
from __future__ import annotations

from math import ceil

import numpy as np

from ..columnar import decode_change_meta_cached
from ..errors import SyncProtocolError
from ..obs.metrics import get_metrics
from ..sync import (
    BITS_PER_ENTRY,
    NUM_PROBES,
    decode_sync_message,
    encode_sync_message,
    init_sync_state,
    _advance_heads,
)
from ..sync_v2 import (
    MESSAGE_TYPE_SYNC_V2,
    decode_sync_message_v2,
    finish_generate_v2,
    plan_generate_v2,
    post_receive_v2,
)
from .fingerprint import FingerprintIndex
from .sync_batch import (
    WORD_BITS,
    build_filters,
    filters_to_bytes,
    hash_to_xyz,
    pack_hashes,
    query_filters,
)

# Batched sync records into the SAME named instruments as the sequential
# protocol (sync.py): one set of totals whichever driver runs. The device
# query kernel evaluates all NUM_PROBES bits per candidate (no early
# exit), so its probe count is candidates x NUM_PROBES.
_METRICS = get_metrics()
_M_MSGS_GEN = _METRICS.counter("sync.messages.generated")
_M_MSGS_RECV = _METRICS.counter("sync.messages.received")
_M_BYTES_SENT = _METRICS.counter("sync.bytes.sent")
_M_BYTES_RECV = _METRICS.counter("sync.bytes.received")
_M_CHANGES_SENT = _METRICS.counter("sync.changes.sent")
_M_CHANGES_RECV = _METRICS.counter("sync.changes.received")
_M_NEED_REQUESTED = _METRICS.counter("sync.changes.need_requested")
_M_BLOOM_PROBES = _METRICS.counter("sync.bloom.probes")
_M_BLOOM_HITS = _METRICS.counter("sync.bloom.hits")
_M_BLOOM_FP = _METRICS.counter("sync.bloom.false_positives")
_M_REJECTED = _METRICS.counter("sync.messages.rejected")
_M2_MSGS_RECV = _METRICS.counter("sync.v2.messages.received")
_M2_REJECTED = _METRICS.counter("sync.v2.messages.rejected")
_M_SHED_QUARANTINED = _METRICS.counter(
    "sync.messages.shed_quarantined",
    "sync channels skipped in generate_messages because the doc farm has "
    "their document quarantined (release_quarantine restores them)",
)


def _pow2(n: int) -> int:
    """Smallest power of two >= n (min 1): the shape-bucket grid for the
    batched filter kernels. A serving pump calls generate_messages with a
    different channel count every sweep; without bucketing, every distinct
    (batch, width) pair costs a fresh XLA compile."""
    return 1 << (max(n, 1) - 1).bit_length()


def filters_from_bytes(blobs):
    """Parses wire-format Bloom filters into padded device tensors:
    (words [B, W] uint32, modulo [B] int32, counts [B] int32). Inverse of
    filters_to_bytes for same-parameter filters; a zero-entry filter maps
    to an all-zero row with count 0. The device query kernel hardcodes the
    default probe count, so filters with other wire parameters must take
    the host path (see _plan_generate) — passing one here is an error."""
    from ..sync import NUM_PROBES, BloomFilter

    parsed = [BloomFilter(b) for b in blobs]
    for p in parsed:
        if p.num_entries and (
            p.num_probes != NUM_PROBES or p.num_bits_per_entry != BITS_PER_ENTRY
        ):
            raise SyncProtocolError(
                "non-default Bloom parameters require the host BloomFilter path"
            )
    num_words = max(
        (ceil(len(p.bits) / 4) for p in parsed if p.num_entries), default=1
    ) or 1
    words = np.zeros((len(parsed), num_words), np.uint32)
    modulo = np.zeros(len(parsed), np.int32)
    counts = np.zeros(len(parsed), np.int32)
    for i, p in enumerate(parsed):
        if p.num_entries == 0:
            continue
        bits = bytes(p.bits)
        padded = bits + b"\0" * (-len(bits) % 4)
        row = np.frombuffer(padded, np.uint32)
        words[i, : row.shape[0]] = row
        modulo[i] = 8 * len(p.bits)
        counts[i] = p.num_entries
    return words, modulo, counts


class SyncFarm:
    """Batched sync driver over a TpuDocFarm. Channels are (doc index,
    sync_state dict) pairs; sync_state is the reference's shape
    (initSyncState, sync.js:308) and remains encode/decode-compatible."""

    def __init__(self, farm):
        self.farm = farm
        # outcome report of the most recent receive_messages farm dispatch
        # (a FarmApplyResult, or None when the call applied no changes) —
        # the serve batcher reads .applied/.quarantined off it per flush
        self.last_apply = None
        # per-doc range-fingerprint indexes for v2 channels, refreshed
        # lazily from the farm's change graph (cheap count-compare no-op
        # once current; rebuild_from_store re-hydrates after a restart)
        self.fingerprints = FingerprintIndex()

    def _v2_view(self, d):
        """The doc's fingerprint view, refreshed against the farm."""
        self.fingerprints.sync_from_farm(self.farm, d)
        return self.fingerprints.view(d)

    @staticmethod
    def init_state():
        return init_sync_state()

    def make_session(self, d, *, clock=None, rng=None, config=None,
                     state=None):
        """A supervised ``SyncSession`` (sync_session.py) for document
        ``d``'s channel to one peer: seq/ack framing, retransmission with
        backoff, peer-restart detection and the convergence watchdog, over
        this farm's batched generate/receive."""
        from ..sync_session import FarmDriver, SyncSession

        return SyncSession(FarmDriver(self, d), clock=clock, rng=rng,
                           config=config, state=state)

    def restore_session(self, d, blob, *, clock=None, rng=None, config=None):
        """Resumes a persisted supervised channel (``SyncSession.save()``)
        for document ``d``."""
        from ..sync_session import FarmDriver, SyncSession

        return SyncSession.restore(blob, FarmDriver(self, d), clock=clock,
                                   rng=rng, config=config)

    # -------------------------------------------------------------- #
    # generate (sync.js:327, batched)

    def _changes_since(self, d, since_hashes):
        changes = self.farm.get_changes(d, list(since_hashes))
        return [decode_change_meta_cached(c) for c in changes]

    def generate_messages(self, channels, protocols=None):
        """channels: [(doc, sync_state)]. Returns [(new_state, bytes|None)]
        in channel order. All Bloom builds and queries run as one device
        batch each; all v2 channels' fingerprint queries run as ONE
        batched ``sync.fingerprint_ranges`` reduction.

        ``protocols``, when given, aligns with ``channels``: an entry of
        ``"v2"`` routes that channel through range-based reconciliation
        (sync_v2), anything else through the Bloom protocol. One sweep
        mixes both freely."""
        n = len(channels)
        plans = []
        v2_queries = []  # (doc, lo, hi) across ALL v2 channels this sweep
        # a doc quarantined by the farm's per-doc isolation (PR 3) must not
        # be offered over sync: its host state is the pre-fault snapshot,
        # so advertising heads/filters from it would invite deliveries the
        # farm will shed anyway. The channel resumes after
        # release_quarantine.
        quarantined = self.farm.quarantine
        for i, (d, state) in enumerate(channels):
            if d in quarantined:
                plans.append({"shed": True})
                _M_SHED_QUARANTINED.inc()
                continue
            if protocols is not None and protocols[i] == "v2":
                view = self._v2_view(d)
                our_heads = self.farm.get_heads(d)
                our_need = self.farm.get_missing_deps(
                    d, state.get("theirHeads") or []
                )
                v2_plan, queries = plan_generate_v2(state, view, our_heads)
                plans.append({
                    "v2": True, "plan": v2_plan, "q0": len(v2_queries),
                    "nq": len(queries), "our_heads": our_heads,
                    "our_need": our_need,
                })
                v2_queries.extend((d, lo, hi) for lo, hi in queries)
                continue
            plans.append(self._plan_generate(d, state))

        # ALL v2 channels' fingerprints — inbound-range checks, median
        # splits, fresh probes — resolve in one pow2-bucketed device
        # reduction; each channel then slices its contiguous span back out
        v2_fps = self.fingerprints.fingerprint_ranges(v2_queries)

        # batched `have` filter construction, pow2-padded in batch and
        # width so every sweep size shares a few compiled programs (the
        # padding is masked: zero-count rows serialise to empty filters)
        build_idx = [i for i, p in enumerate(plans) if p.get("build_hashes") is not None]
        if build_idx:
            lists = [plans[i]["build_hashes"] for i in build_idx]
            width = _pow2(max((len(h) for h in lists), default=1))
            xyz, counts = pack_hashes(lists, width=width)
            pad = _pow2(len(lists)) - len(lists)
            if pad:
                xyz = np.concatenate(
                    [xyz, np.zeros((pad,) + xyz.shape[1:], xyz.dtype)]
                )
                counts = np.concatenate([counts, np.zeros(pad, counts.dtype)])
            num_words = int(ceil(width * BITS_PER_ENTRY / WORD_BITS)) or 1
            # amlint: disable=AM701 — pad-to-bucket idiom: `pad` is
            # _pow2(len(lists)) - len(lists), dynamic on its own, but the
            # concatenate grows the batch TO the pow2 bucket, so the
            # leading dim build_filters sees is _pow2(n) — shape-stable by
            # construction. The dataflow engine cannot prove the sum.
            words, modulo = build_filters(xyz, counts, num_words)
            blooms = filters_to_bytes(words, modulo, counts)
            for i, bloom in zip(build_idx, blooms):
                plans[i]["our_have"] = [
                    {"lastSync": plans[i]["shared_heads"], "bloom": bloom}
                ]

        # batched changes-to-send Bloom queries: flatten every channel's
        # (their-filter, candidate-hash) pairs into one [B, C] query
        query_idx = [i for i, p in enumerate(plans) if p.get("query") is not None]
        if query_idx:
            blobs, cand_lists = [], []
            for i in query_idx:
                blobs.append(plans[i]["query"]["bloom"])
                cand_lists.append(plans[i]["query"]["hashes"])
            words, modulo, counts = filters_from_bytes(blobs)
            # pow2 shape buckets (batch, candidate width, filter words):
            # padded rows/slots are masked by counts and never read back
            batch = _pow2(len(blobs))
            width = _pow2(max((len(c) for c in cand_lists), default=1))
            w_words = _pow2(words.shape[1])
            padded_words = np.zeros((batch, w_words), words.dtype)
            padded_words[: words.shape[0], : words.shape[1]] = words
            padded_modulo = np.zeros(batch, modulo.dtype)
            padded_modulo[: modulo.shape[0]] = modulo
            padded_counts = np.zeros(batch, counts.dtype)
            padded_counts[: counts.shape[0]] = counts
            q = np.zeros((batch, width, 3), np.uint32)
            for b, hashes in enumerate(cand_lists):
                for c, h in enumerate(hashes):
                    q[b, c] = hash_to_xyz(h)
            contained = np.asarray(query_filters(
                padded_words, padded_modulo, padded_counts, q
            ))
            total_hits = 0
            for b, i in enumerate(query_idx):
                hits = {
                    h
                    for c, h in enumerate(cand_lists[b])
                    if contained[b, c]
                }
                total_hits += len(hits)
                plans[i]["bloom_positive"] = hits
            if _METRICS.enabled:
                _M_BLOOM_PROBES.inc(
                    NUM_PROBES * sum(len(c) for c in cand_lists)
                )
                _M_BLOOM_HITS.inc(total_hits)

        results = []
        for (d, state), plan in zip(channels, plans):
            if plan.get("v2"):
                fps = v2_fps[plan["q0"]: plan["q0"] + plan["nq"]]
                results.append(finish_generate_v2(
                    state, plan["plan"], fps,
                    lambda h, d=d: self.farm.get_change_by_hash(d, h),
                    plan["our_heads"], plan["our_need"],
                ))
                continue
            results.append(self._finish_generate(d, state, plan))
        assert len(results) == n
        return results

    def _plan_generate(self, d, state):
        """Host phase 1: everything except the device filter ops."""
        farm = self.farm
        shared_heads = state["sharedHeads"]
        their_heads = state["theirHeads"]
        their_have = state["theirHave"]
        their_need = state["theirNeed"]
        our_heads = farm.get_heads(d)
        our_need = farm.get_missing_deps(d, their_heads or [])
        plan = {
            "shared_heads": shared_heads,
            "our_heads": our_heads,
            "our_need": our_need,
            "our_have": [],
        }

        if their_heads is None or all(h in their_heads for h in our_need):
            plan["build_hashes"] = [
                c["hash"] for c in self._changes_since(d, shared_heads)
            ]

        if their_have:
            last_sync = their_have[0]["lastSync"]
            if not all(farm.get_change_by_hash(d, h) for h in last_sync):
                plan["reset"] = True
                return plan

        if (
            isinstance(their_have, list)
            and isinstance(their_need, list)
            and their_have  # have=[] is served from `need` alone (sync.py:183)
        ):
            # candidates for the Bloom-negative scan: changes since the
            # union of the peer's lastSync hashes (sync.js:246)
            last_sync_hashes = []
            seen = set()
            for h in their_have:
                for hash_ in h["lastSync"]:
                    if hash_ not in seen:
                        seen.add(hash_)
                        last_sync_hashes.append(hash_)
            metas = self._changes_since(d, last_sync_hashes)
            plan["candidates"] = metas
            # one wire filter per have entry; entries beyond [0] — and any
            # filter with non-default wire parameters, which the device
            # kernel cannot evaluate — take the host BloomFilter path
            from ..sync import NUM_PROBES, BloomFilter

            first = BloomFilter(their_have[0]["bloom"])
            conforming = first.num_entries == 0 or (
                first.num_probes == NUM_PROBES
                and first.num_bits_per_entry == BITS_PER_ENTRY
            )
            if conforming:
                plan["query"] = {
                    "bloom": their_have[0]["bloom"],
                    "hashes": [m["hash"] for m in metas],
                }
                plan["extra_blooms"] = [h["bloom"] for h in their_have[1:]]
            else:
                plan["bloom_positive"] = set()
                plan["extra_blooms"] = [h["bloom"] for h in their_have]
        return plan

    def _finish_generate(self, d, state, plan):
        """Host phase 2: reference control flow of generateSyncMessage."""
        farm = self.farm
        if plan.get("shed"):
            return state, None
        if plan.get("reset"):
            msg = {
                "heads": plan["our_heads"], "need": [],
                "have": [{"lastSync": [], "bloom": b""}], "changes": [],
            }
            encoded = encode_sync_message(msg)
            _M_MSGS_GEN.inc()
            _M_BYTES_SENT.inc(len(encoded))
            return state, encoded

        their_have = state["theirHave"]
        their_need = state["theirNeed"]
        changes_to_send = []
        if isinstance(their_have, list) and isinstance(their_need, list):
            if not their_have:
                changes_to_send = [
                    c
                    for c in (farm.get_change_by_hash(d, h) for h in their_need)
                    if c is not None
                ]
            else:
                changes_to_send = self._changes_to_send(
                    d, plan, their_have, their_need
                )

        our_heads = plan["our_heads"]
        heads_unchanged = (
            isinstance(state["lastSentHeads"], list)
            and our_heads == state["lastSentHeads"]
        )
        heads_equal = (
            isinstance(state["theirHeads"], list)
            and our_heads == state["theirHeads"]
        )
        if heads_unchanged and heads_equal and not changes_to_send:
            return state, None

        sent_hashes = state["sentHashes"]
        changes_to_send = [
            c
            for c in changes_to_send
            if not sent_hashes.get(decode_change_meta_cached(c)["hash"])
        ]
        msg = {
            "heads": our_heads,
            "have": plan["our_have"],
            "need": plan["our_need"],
            "changes": changes_to_send,
        }
        if changes_to_send:
            sent_hashes = dict(sent_hashes)
            for change in changes_to_send:
                sent_hashes[decode_change_meta_cached(change)["hash"]] = True
        new_state = dict(state, lastSentHeads=our_heads, sentHashes=sent_hashes)
        encoded = encode_sync_message(msg)
        _M_MSGS_GEN.inc()
        _M_BYTES_SENT.inc(len(encoded))
        _M_CHANGES_SENT.inc(len(changes_to_send))
        return new_state, encoded

    def _changes_to_send(self, d, plan, their_have, their_need):
        """Bloom-negative changes + dependents closure + explicit needs
        (getChangesToSend, sync.js:246), with the containsHash loop already
        evaluated on device (plan['bloom_positive'])."""
        from ..sync import BloomFilter

        metas = plan["candidates"]
        positive = plan.get("bloom_positive") or set()
        extra = [BloomFilter(b) for b in plan.get("extra_blooms", ())]

        change_hashes = set()
        dependents = {}
        to_send = set()
        for meta in metas:
            change_hashes.add(meta["hash"])
            for dep in meta["deps"]:
                dependents.setdefault(dep, []).append(meta["hash"])
            missed = meta["hash"] not in positive and all(
                not bloom.contains_hash(meta["hash"]) for bloom in extra
            )
            if missed:
                to_send.add(meta["hash"])

        stack = list(to_send)
        while stack:
            h = stack.pop()
            for dep in dependents.get(h, []):
                if dep not in to_send:
                    to_send.add(dep)
                    stack.append(dep)

        out = []
        _M_NEED_REQUESTED.inc(len(their_need))
        for h in their_need:
            # a needed hash we hold but withheld as Bloom-positive is a
            # detected false positive (same accounting as sync.py)
            if h in change_hashes and h not in to_send:
                _M_BLOOM_FP.inc()
            to_send.add(h)
            if h not in change_hashes:
                change = self.farm.get_change_by_hash(d, h)
                if change is not None:
                    out.append(change)
        for meta in metas:
            if meta["hash"] in to_send:
                out.append(meta["change"])
        return out

    # -------------------------------------------------------------- #
    # receive (sync.js:420, batched apply)

    def receive_messages(self, channels_msgs, protocols=None):
        """channels_msgs: [(doc, sync_state, message_bytes)]. Applies every
        channel's changes through ONE batched farm.applyChanges call (docs
        repeated across channels fall back to per-channel application to
        preserve per-message head accounting). Returns
        [(new_state, patch|None)] in channel order.

        Payloads route on their leading type byte — a sync v2 frame
        (``MESSAGE_TYPE_SYNC_V2``) decodes and post-processes through the
        range-reconciliation path, anything else through the reference
        protocol — so mixed-protocol sweeps and mid-session transitions
        need no caller-side branching. ``protocols`` is accepted for
        symmetry with ``generate_messages`` and forward compatibility;
        routing itself is self-describing.

        One bad peer must not abort the batched round: a channel whose
        message fails to decode is rejected in place — its result is
        ``(unchanged state, None)``, counted on ``sync.messages.rejected``
        (``sync.v2.messages.rejected`` for v2 frames) — and a channel
        whose changes poison its document is handled by the farm's per-doc
        isolation (the doc quarantines, the patch is a no-op, every other
        channel proceeds)."""
        del protocols  # inbound routing is by payload type byte
        farm = self.farm
        decoded = []
        is_v2 = []
        rejected = rejected_v2 = received_v2 = 0
        for _, _, m in channels_msgs:
            v2 = bool(m) and m[0] == MESSAGE_TYPE_SYNC_V2
            is_v2.append(v2)
            try:
                decoded.append(
                    decode_sync_message_v2(m) if v2 else decode_sync_message(m)
                )
                received_v2 += v2
            except (SyncProtocolError, ValueError, TypeError, IndexError):
                decoded.append(None)
                if v2:
                    rejected_v2 += 1
                else:
                    rejected += 1
        if _METRICS.enabled:
            _M_MSGS_RECV.inc(len(channels_msgs) - rejected - rejected_v2
                             - received_v2)
            _M_REJECTED.inc(rejected)
            _M2_MSGS_RECV.inc(received_v2)
            _M2_REJECTED.inc(rejected_v2)
            _M_BYTES_RECV.inc(sum(
                len(m)
                for (_, _, m), msg in zip(channels_msgs, decoded)
                if msg is not None
            ))
            _M_CHANGES_RECV.inc(
                sum(len(m["changes"]) for m in decoded if m is not None)
            )
        docs = [d for d, _, _ in channels_msgs]
        live_docs = [
            d for (d, _, _), msg in zip(channels_msgs, decoded)
            if msg is not None
        ]
        self.last_apply = None
        if len(set(live_docs)) != len(live_docs):
            return [
                (s, None) if msg is None else self._receive_one(d, s, msg, v2)
                for (d, s, _), msg, v2 in zip(channels_msgs, decoded, is_v2)
            ]

        before = {d: farm.get_heads(d) for d in docs}
        patches = [None] * farm.num_docs
        if any(msg and msg["changes"] for msg in decoded):
            per_doc = [[] for _ in range(farm.num_docs)]
            for d, msg in zip(docs, decoded):
                if msg is not None:
                    per_doc[d] = list(msg["changes"])
            patches = farm.apply_changes(per_doc)
            self.last_apply = patches

        results = []
        for (d, state, _), msg, v2 in zip(channels_msgs, decoded, is_v2):
            if msg is None:
                results.append((state, None))
                continue
            patch = patches[d] if msg["changes"] else None
            if v2:
                results.append((
                    self._post_receive_v2(d, state, msg, before[d]), patch,
                ))
            else:
                results.append(
                    self._post_receive(d, state, msg, before[d], patch)
                )
        return results

    def _receive_one(self, d, state, msg, v2=False):
        farm = self.farm
        before = farm.get_heads(d)
        patch = None
        if msg["changes"]:
            per_doc = [[] for _ in range(farm.num_docs)]
            per_doc[d] = list(msg["changes"])
            result = farm.apply_changes(per_doc)
            self.last_apply = result
            patch = result[d]
        if v2:
            return self._post_receive_v2(d, state, msg, before), patch
        return self._post_receive(d, state, msg, before, patch)

    def _post_receive_v2(self, d, state, msg, before_heads):
        """The batched twin of receive_sync_message_v2's bookkeeping: the
        fingerprint view re-syncs from the farm (picking up the changes
        the batched apply just committed) before the item-range diffs."""
        farm = self.farm
        return post_receive_v2(
            state, msg, before_heads, farm.get_heads(d),
            lambda h: farm.get_change_by_hash(d, h) is not None,
            self._v2_view(d),
        )

    def _post_receive(self, d, state, msg, before_heads, patch):
        farm = self.farm
        shared_heads = state["sharedHeads"]
        last_sent_heads = state["lastSentHeads"]
        sent_hashes = state["sentHashes"]
        if msg["changes"]:
            shared_heads = _advance_heads(
                before_heads, farm.get_heads(d), shared_heads
            )
        if not msg["changes"] and msg["heads"] == before_heads:
            last_sent_heads = msg["heads"]
        known = [h for h in msg["heads"] if farm.get_change_by_hash(d, h)]
        if len(known) == len(msg["heads"]):
            shared_heads = msg["heads"]
            if len(msg["heads"]) == 0:
                last_sent_heads = []
                sent_hashes = {}
        else:
            shared_heads = sorted(set(known + shared_heads))
        new_state = {
            "sharedHeads": shared_heads,
            "lastSentHeads": last_sent_heads,
            "theirHave": msg["have"],
            "theirHeads": msg["heads"],
            "theirNeed": msg["need"],
            "sentHashes": sent_hashes,
        }
        return new_state, patch
