"""Range-fingerprint index for sync v2: batched XOR reductions on device.

The v2 reconciliation driver (automerge_tpu/sync_v2.py) compares change-hash
sets range-by-range using XOR-of-hash fingerprints. Per document the
arithmetic is trivial; what the farm needs is the batch shape: a serving
sweep holds hundreds of live v2 channels, and EVERY channel's fingerprint
queries for the round — inbound-range checks, median splits, fresh probes —
must resolve as ONE device dispatch, not one per channel (the columnar
playbook of the Bloom kernels in sync_batch.py).

``FingerprintIndex`` keeps one sorted hash array per document on the host
(incrementally extended on every commit, rebuildable from the amstore hash
graph after a restart via ``rebuild_from_store``) and packs the queried
documents into a pow2-bucketed ``[B, E, 8]`` uint32 tensor; the
``sync.fingerprint_ranges`` program — registered with the amprof
observatory like every compiled program in this package — masks each row
to its [start, end) span and XOR-reduces along the entry axis. Counts come
from host-side bisection (they are index arithmetic, not data reduction).

Fingerprints are canonical: XOR over 256-bit hash integers, returned as
64-char hex, bit-identical to the host ``HashIndex`` prefix-XOR path —
asserted by tests/test_sync_v2.py so the two implementations can never
drift.
"""
from __future__ import annotations

from bisect import bisect_left, insort

import jax
import jax.numpy as jnp
import numpy as np

from ..columnar import decode_change_meta_cached
from ..errors import SyncProtocolError
from ..sync import HASH_SIZE

#: one SHA-256 hash as big-endian uint32 words
HASH_WORDS = HASH_SIZE // 4


def _pow2(n: int) -> int:
    """Smallest power of two >= n (min 1): the shape-bucket grid for the
    batched reduction, so every sweep's (batch, entries) pair lands on a
    few compiled programs instead of one per distinct shape."""
    return 1 << (max(n, 1) - 1).bit_length()


from .jitprof import profiled_jit


@profiled_jit("sync.fingerprint_ranges")
def fingerprint_ranges_kernel(words, starts, ends):
    """XOR-reduces each row's [start, end) span: words [B, E, 8] uint32,
    starts/ends [B] int32 -> [B, 8] uint32. Padded rows (start == end == 0)
    reduce to zero."""
    idx = jnp.arange(words.shape[1], dtype=jnp.int32)[None, :]
    mask = (idx >= starts[:, None]) & (idx < ends[:, None])
    masked = jnp.where(mask[:, :, None], words, jnp.uint32(0))
    return jax.lax.reduce(
        masked, jnp.uint32(0), jax.lax.bitwise_xor, dimensions=(1,)
    )


def _hash_words(h: str) -> list[int]:
    return [int(h[8 * k: 8 * k + 8], 16) for k in range(HASH_WORDS)]


class _DocIndex:
    """One document's sorted hash array plus its packed device words."""

    __slots__ = ("hashes", "members", "words", "dirty")

    def __init__(self):
        self.hashes: list[str] = []
        self.members: set[str] = set()
        self.words: np.ndarray | None = None
        self.dirty = True

    def insert(self, h: str) -> bool:
        if h in self.members:
            return False
        if len(h) != 2 * HASH_SIZE:
            raise SyncProtocolError(f"not a 256-bit hash: {h!r}")
        self.members.add(h)
        insort(self.hashes, h)
        self.dirty = True
        return True

    def packed(self, width: int) -> np.ndarray:
        if self.dirty or self.words is None or self.words.shape[0] < width:
            words = np.zeros((width, HASH_WORDS), np.uint32)
            for e, h in enumerate(self.hashes):
                words[e] = _hash_words(h)
            self.words = words
            self.dirty = False
        return self.words[:width]


class _DocView:
    """Host-side set view of one document (the ``view`` protocol the v2
    driver's plan/receive phases consume: count/items/contains plus
    incremental insert)."""

    __slots__ = ("_doc",)

    def __init__(self, doc: _DocIndex):
        self._doc = doc

    def __len__(self) -> int:
        return len(self._doc.hashes)

    def contains(self, h: str) -> bool:
        return h in self._doc.members

    def insert_many(self, hashes) -> None:
        for h in hashes:
            self._doc.insert(h)

    def count(self, lo: str, hi: str) -> int:
        hashes = self._doc.hashes
        return bisect_left(hashes, hi) - bisect_left(hashes, lo)

    def items(self, lo: str, hi: str) -> list[str]:
        hashes = self._doc.hashes
        return hashes[bisect_left(hashes, lo):bisect_left(hashes, hi)]


class FingerprintIndex:
    """Per-document range-fingerprint indexes with batched resolution.

    Lifecycle: ``note_commit`` extends a document's set incrementally on
    every applied change; ``sync_doc`` reconciles against an authoritative
    hash list (cheap no-op when counts agree — change sets only grow);
    ``rebuild_from_store`` re-hydrates every document from a ShardStore's
    persisted hash graph after a restart, so the index survives crashes
    without a full history walk."""

    def __init__(self):
        self._docs: dict[int, _DocIndex] = {}

    def _doc(self, d: int) -> _DocIndex:
        doc = self._docs.get(d)
        if doc is None:
            doc = self._docs[d] = _DocIndex()
        return doc

    def view(self, d: int) -> _DocView:
        return _DocView(self._doc(d))

    def note_commit(self, d: int, hashes) -> None:
        """Incremental update: the hashes of changes just committed."""
        doc = self._doc(d)
        for h in hashes:
            doc.insert(h)

    def sync_doc(self, d: int, hashes) -> None:
        """Reconciles document ``d`` against an authoritative hash list."""
        doc = self._doc(d)
        if len(hashes) != len(doc.hashes):
            for h in hashes:
                doc.insert(h)

    def sync_from_farm(self, farm, d: int) -> None:
        """Refreshes document ``d`` from a TpuDocFarm's change graph."""
        self.sync_doc(d, [
            decode_change_meta_cached(c)["hash"]
            for c in farm.get_changes(d, [])
        ])

    def rebuild_from_store(self, store) -> None:
        """Re-hydrates from the amstore hash graph (ShardStore's per-doc
        footer hash lists) — the restart path: the store already proved
        these hashes against its checksummed segments."""
        for d, hashes in store.footer_hashes.items():
            self.sync_doc(int(d), hashes)

    # -------------------------------------------------------------- #

    def fingerprint_ranges(self, queries) -> list[tuple[int, str]]:
        """Resolves [(doc, lo, hi)] -> [(count, xor_hex)] in query order.

        ALL queries reduce in one pow2-bucketed device dispatch: the
        batch axis is the query list (documents repeat freely), the entry
        axis is the largest queried document padded to a power of two.
        An empty query list dispatches nothing."""
        if not queries:
            return []
        spans = []
        for d, lo, hi in queries:
            doc = self._doc(d)
            i = bisect_left(doc.hashes, lo)
            j = bisect_left(doc.hashes, hi)
            spans.append((doc, i, j))
        width = _pow2(max((len(doc.hashes) for doc, _, _ in spans), default=1))
        batch = _pow2(len(queries))
        words = np.zeros((batch, width, HASH_WORDS), np.uint32)
        starts = np.zeros(batch, np.int32)
        ends = np.zeros(batch, np.int32)
        for b, (doc, i, j) in enumerate(spans):
            words[b] = doc.packed(width)
            starts[b] = i
            ends[b] = j
        fp_words = np.asarray(fingerprint_ranges_kernel(words, starts, ends))
        out = []
        for b, (_doc, i, j) in enumerate(spans):
            fp = "".join(format(int(w), "08x") for w in fp_words[b])
            out.append((j - i, fp))
        return out
