"""Batched Text/list engine: RGA sequences for a batch of documents.

Division of labour (SURVEY.md §7 'Architecture mapping'):

- **Host**: RGA insertion ordering. Each element's document position follows
  the reference rule "insert after the reference element, skipping concurrent
  elements with greater opId" (new.js:144-163). The host maintains the
  element order per document and assigns each element a dense rank; runs of
  consecutive insertions (typing) are located once per run.
- **Device**: everything per-element: update/delete visibility (succ
  marking), conflict resolution (max-opId winner per element), and the
  visible-text extraction, batched over all documents with the same
  gather/scan kernels as the map engine (engine.py) using the element rank
  as the key.

This covers benchmark config 2 (concurrent insert/delete on Text). The rank
keys are rebuilt per flush; order-maintenance labels (skip lists) are the
planned upgrade for very long documents.
"""
from __future__ import annotations

import numpy as np

from ..common import parse_op_id
from .engine import (
    ACTION_DEL,
    ACTION_SET,
    BatchedMapEngine,
    ChangeOpsBatch,
    PAD_KEY,
    changes_from_numpy,
)


class _DocOrder:
    """Host-side RGA order for one document's list object."""

    __slots__ = ("elems", "pos", "dirty")

    def __init__(self):
        self.elems = []  # elemId strings in document order
        self.pos = {}  # elemId -> index (lazily rebuilt)
        self.dirty = False

    def _rebuild(self):
        if self.dirty:
            self.pos = {e: i for i, e in enumerate(self.elems)}
            self.dirty = False

    def insert(self, elem_id: str, ref: str):
        """Inserts elem_id after `ref` ('_head' for the front), skipping
        concurrent elements with greater opId (RGA convergence rule)."""
        self._rebuild()
        if ref == "_head":
            index = 0
        else:
            index = self.pos[ref] + 1
        new = parse_op_id(elem_id)
        while index < len(self.elems):
            other = parse_op_id(self.elems[index])
            if (other.counter, other.actor_id) > (new.counter, new.actor_id):
                index += 1
            else:
                break
        self.elems.insert(index, elem_id)
        self.dirty = True

    def ranks(self):
        self._rebuild()
        return self.pos


class BatchedTextEngine:
    """Driver for a batch of Text documents (one list object per doc)."""

    def __init__(self, num_docs: int, capacity: int = 256):
        self.num_docs = num_docs
        self.orders = [_DocOrder() for _ in range(num_docs)]
        self.engine = BatchedMapEngine(num_docs, capacity)
        self.values = []  # interned element values
        self._value_index = {}
        self.elem_rank = [dict() for _ in range(num_docs)]  # packed elemId -> key used on device
        self._rank_alloc = [0] * num_docs
        self.actors = []
        self._actor_index = {}

    def _actor(self, actor_id):
        idx = self._actor_index.get(actor_id)
        if idx is None:
            idx = len(self.actors)
            self.actors.append(actor_id)
            self._actor_index[actor_id] = idx
        return idx

    def _value(self, v):
        idx = self._value_index.get(v)
        if idx is None:
            idx = len(self.values)
            self.values.append(v)
            self._value_index[v] = idx
        return idx

    def _pack(self, op_id: str) -> int:
        p = parse_op_id(op_id)
        return (p.counter << 20) | self._actor(p.actor_id)

    def apply_batch(self, per_doc_ops):
        """Applies one round of change ops per document. Each op is a tuple
        (op_dict, op_counter, actor). Supported actions: insert 'set',
        non-insert 'set' (element overwrite), and 'del'."""
        rows = []
        for d, doc_ops in enumerate(per_doc_ops):
            order = self.orders[d]
            doc_rows = []
            for op, ctr, actor in doc_ops:
                op_id = f"{ctr}@{actor}"
                packed = (ctr << 20) | self._actor(actor)
                if op.get("insert"):
                    ref = op.get("elemId", "_head")
                    order.insert(op_id, ref)
                    key = self._rank_alloc[d]
                    self._rank_alloc[d] += 1
                    self.elem_rank[d][op_id] = key
                    doc_rows.append(
                        (key, packed, ACTION_SET, self._value(op.get("value")), -1)
                    )
                elif op["action"] == "set":
                    elem = op["elemId"]
                    key = self.elem_rank[d][elem]
                    pred = self._pack(op["pred"][0]) if op.get("pred") else -1
                    doc_rows.append(
                        (key, packed, ACTION_SET, self._value(op.get("value")), pred)
                    )
                elif op["action"] == "del":
                    elem = op["elemId"]
                    key = self.elem_rank[d][elem]
                    pred = self._pack(op["pred"][0]) if op.get("pred") else -1
                    doc_rows.append((key, packed, ACTION_DEL, 0, pred))
                else:
                    raise ValueError(f"Unsupported text op: {op['action']}")
            rows.append(doc_rows)

        width = max((len(r) for r in rows), default=1) or 1
        keys = np.full((self.num_docs, width), PAD_KEY, np.int32)
        ops = np.zeros((self.num_docs, width), np.int64)
        actions = np.zeros((self.num_docs, width), np.int32)
        values = np.zeros((self.num_docs, width), np.int64)
        preds = np.full((self.num_docs, width), -1, np.int64)
        for d, doc_rows in enumerate(rows):
            for i, (k, o, a, v, p) in enumerate(doc_rows):
                keys[d, i] = k
                ops[d, i] = o
                actions[d, i] = a
                values[d, i] = v
                preds[d, i] = p
        self.engine.apply_batch(changes_from_numpy(keys, ops, actions, values, preds))

    def visible_texts(self):
        """Extracts each document's visible element values in document order
        (device visibility + host rank ordering)."""
        keys, _ops, winners, vals = self.engine.visible_state()
        keys = np.asarray(keys)
        winners = np.asarray(winners)
        vals = np.asarray(vals)
        texts = []
        for d in range(self.num_docs):
            # visible value per rank key
            by_rank = {}
            for i in np.nonzero(winners[d])[0]:
                by_rank[int(keys[d, i])] = self.values[int(vals[d, i])]
            ranks = self.elem_rank[d]
            out = []
            for elem_id in self.orders[d].elems:
                rank = ranks[elem_id]
                if rank in by_rank:
                    out.append(by_rank[rank])
            texts.append(out)
        return texts
