"""Batched Text/list engine: RGA sequences for a batch of documents.

Division of labour (SURVEY.md §7 'Architecture mapping'):

- **Host**: transcoding only. Each insert op is assigned a stable slot in a
  per-document element table; elemId strings resolve to slots through a
  dict. No ordering work happens on the host.
- **Device**: everything else, batched over documents --
  * document order: the RGA insertion order ("insert after the reference
    element, skipping concurrent elements with greater opId",
    /root/reference/backend/new.js:144-163) computed as a parallel rank
    over the insertion tree (rga.batched_rga_rank: sort + pointer doubling,
    O(log E) depth);
  * visibility and conflicts: update/delete succ marking and max-opId
    winner per element via the map-engine kernels (engine.py), keyed by the
    element's slot;
  * counter-tie conflict resolution on the actor id *string* via the
    actor-rank remap (new.js:146, apply_patch.js:33).

This covers benchmark config 2 (concurrent insert/delete on Text). The host
scan-based order (`HostDocOrder`) is retained purely as a differential-test
oracle for the device kernel.
"""
from __future__ import annotations

import numpy as np

from ..common import parse_op_id
from ..errors import EncodeError, PackingLimitError
from .engine import (
    ACTION_DEL,
    ACTION_SET,
    ACTOR_BITS,
    BatchedMapEngine,
    PAD_KEY,
    changes_from_numpy,
)
from . import rga
from .rga import batched_rga_rank


class HostDocOrder:
    """Host-side RGA order for one document's list object — the sequential
    reference scan (new.js:144-163), kept as the oracle the device rank
    kernel is differentially tested against."""

    __slots__ = ("elems", "pos", "dirty")

    def __init__(self):
        self.elems = []  # elemId strings in document order
        self.pos = {}  # elemId -> index (lazily rebuilt)
        self.dirty = False

    def _rebuild(self):
        if self.dirty:
            self.pos = {e: i for i, e in enumerate(self.elems)}
            self.dirty = False

    def insert(self, elem_id: str, ref: str):
        """Inserts elem_id after `ref` ('_head' for the front), skipping
        concurrent elements with greater opId (RGA convergence rule)."""
        self._rebuild()
        if ref == "_head":
            index = 0
        else:
            index = self.pos[ref] + 1
        new = parse_op_id(elem_id)
        while index < len(self.elems):
            other = parse_op_id(self.elems[index])
            if (other.counter, other.actor_id) > (new.counter, new.actor_id):
                index += 1
            else:
                break
        self.elems.insert(index, elem_id)
        self.dirty = True

    def ranks(self):
        self._rebuild()
        return self.pos


def _next_pow2(n: int) -> int:
    return 1 << max(int(n - 1).bit_length(), 0) if n > 1 else 1


class BatchedTextEngine:
    """Driver for a batch of Text documents (one list object per doc)."""

    def __init__(self, num_docs: int, capacity: int = 256):
        self.num_docs = num_docs
        self.engine = BatchedMapEngine(num_docs, capacity)
        self.values = []  # interned element values
        self._value_index = {}
        self.actors = []
        self._actor_index = {}
        # element tables: stable slot per insert op, in arrival order
        self.elem_capacity = capacity
        self.elem_opid = np.zeros((num_docs, capacity), np.int64)
        self.elem_parent = np.full((num_docs, capacity), -1, np.int32)
        self.num_elems = np.zeros(num_docs, np.int32)
        self.elem_slot = [dict() for _ in range(num_docs)]  # elemId -> slot

    def _actor(self, actor_id):
        idx = self._actor_index.get(actor_id)
        if idx is None:
            idx = len(self.actors)
            self.actors.append(actor_id)
            self._actor_index[actor_id] = idx
        return idx

    def _value(self, v):
        idx = self._value_index.get(v)
        if idx is None:
            idx = len(self.values)
            self.values.append(v)
            self._value_index[v] = idx
        return idx

    def _pack(self, op_id: str) -> int:
        p = parse_op_id(op_id)
        return (p.counter << ACTOR_BITS) | self._actor(p.actor_id)

    def _actor_rank(self) -> np.ndarray:
        """Lexicographic rank per actor intern index, padded to a power of
        two so the jitted kernels see few distinct shapes."""
        from .transcode import actor_rank_table

        return actor_rank_table(self.actors, pad_to=_next_pow2(max(len(self.actors), 1)))

    def _grow_elems(self, needed: int):
        if needed > rga.MAX_ELEMS:
            raise PackingLimitError(
                f"text document exceeds {rga.MAX_ELEMS} elements (incl. "
                "tombstones): beyond the rank kernel's key-packing range"
            )
        while needed > self.elem_capacity:
            pad = self.elem_capacity
            self.elem_opid = np.concatenate(
                [self.elem_opid, np.zeros((self.num_docs, pad), np.int64)], axis=1
            )
            self.elem_parent = np.concatenate(
                [self.elem_parent, np.full((self.num_docs, pad), -1, np.int32)],
                axis=1,
            )
            self.elem_capacity *= 2

    def apply_batch(self, per_doc_ops):
        """Applies one round of change ops per document. Each op is a tuple
        (op_dict, op_counter, actor). Supported actions: insert 'set',
        non-insert 'set' (element overwrite), and 'del'."""
        max_new = max(
            (sum(1 for op, _, _ in doc_ops if op.get("insert"))
             for doc_ops in per_doc_ops),
            default=0,
        )
        self._grow_elems(int(self.num_elems.max(initial=0)) + max_new)

        rows = []
        for d, doc_ops in enumerate(per_doc_ops):
            slots = self.elem_slot[d]
            doc_rows = []
            for op, ctr, actor in doc_ops:
                if ctr >= rga.MAX_COUNTER:
                    raise PackingLimitError(
                        f"op counter {ctr} exceeds the merge-key "
                        "packing range"
                    )
                op_id = f"{ctr}@{actor}"
                packed = (ctr << ACTOR_BITS) | self._actor(actor)
                if op.get("insert"):
                    ref = op.get("elemId", "_head")
                    slot = int(self.num_elems[d])
                    self.num_elems[d] += 1
                    self.elem_opid[d, slot] = packed
                    self.elem_parent[d, slot] = -1 if ref == "_head" else slots[ref]
                    slots[op_id] = slot
                    doc_rows.append(
                        (slot, packed, ACTION_SET, self._value(op.get("value")), -1)
                    )
                elif op["action"] == "set":
                    key = slots[op["elemId"]]
                    pred = self._pack(op["pred"][0]) if op.get("pred") else -1
                    doc_rows.append(
                        (key, packed, ACTION_SET, self._value(op.get("value")), pred)
                    )
                elif op["action"] == "del":
                    key = slots[op["elemId"]]
                    pred = self._pack(op["pred"][0]) if op.get("pred") else -1
                    doc_rows.append((key, packed, ACTION_DEL, 0, pred))
                else:
                    raise EncodeError(f"Unsupported text op: {op['action']}")
            rows.append(doc_rows)

        width = max((len(r) for r in rows), default=1) or 1
        keys = np.full((self.num_docs, width), PAD_KEY, np.int32)
        ops = np.zeros((self.num_docs, width), np.int64)
        actions = np.zeros((self.num_docs, width), np.int32)
        values = np.zeros((self.num_docs, width), np.int64)
        preds = np.full((self.num_docs, width), -1, np.int64)
        for d, doc_rows in enumerate(rows):
            for i, (k, o, a, v, p) in enumerate(doc_rows):
                keys[d, i] = k
                ops[d, i] = o
                actions[d, i] = a
                values[d, i] = v
                preds[d, i] = p
        self.engine.apply_batch(changes_from_numpy(keys, ops, actions, values, preds))

    def document_ranks(self, actor_rank=None) -> np.ndarray:
        """Device-computed RGA document order: rank[d, slot] = position of
        the element in doc d's sequence (tombstones included), or E for
        empty slots."""
        if actor_rank is None:
            actor_rank = self._actor_rank()
        valid = np.arange(self.elem_capacity)[None, :] < self.num_elems[:, None]
        return np.asarray(
            batched_rga_rank(self.elem_parent, self.elem_opid, valid, actor_rank)
        )

    def visible_texts(self):
        """Extracts each document's visible element values in document order
        (device rank kernel + device visibility)."""
        actor_rank = self._actor_rank()
        ranks = self.document_ranks(actor_rank)
        keys, _ops, _visible, winners, vals = self.engine.visible_state(actor_rank=actor_rank)
        keys = np.asarray(keys)
        winners = np.asarray(winners)
        vals = np.asarray(vals)
        texts = []
        for d in range(self.num_docs):
            # visible value per element slot
            by_slot = {}
            for i in np.nonzero(winners[d])[0]:
                by_slot[int(keys[d, i])] = self.values[int(vals[d, i])]
            n = int(self.num_elems[d])
            order = np.argsort(ranks[d, : self.elem_capacity])
            row = []
            for slot in order[:n]:
                if int(slot) in by_slot:
                    row.append(by_slot[int(slot)])
            texts.append(row)
        return texts
