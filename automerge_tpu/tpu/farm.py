"""Batched document farm: the backend contract over the device merge engine.

`TpuDocFarm` manages N documents and speaks the reference backend's
applyChanges -> patch protocol (backend/backend.js:27, new.js:1796) for all
of them at once: binary changes in, reference-format patches out, with the
merge + visibility/conflict computation running as one batched device
program per call (engine.batched_apply_ops / batched_visible_state).

Division of labour:
- **Host**: change decoding (columnar -> op dicts), the causal gate
  (dedup by hash, dependency check, per-actor seq contiguity — the port of
  new.js:1550-1597), op transcoding to dense rows, and patch *assembly*
  from device-computed visibility.
- **Device**: the op-table merge (succ/overwrite resolution) and the
  visibility/winner/counter-total computation for every document in the
  batch — the work the reference does per-doc in mergeDocChangeOps
  (new.js:1052) and updatePatchProperty (new.js:884).

Patch assembly reproduces the reference's patch shape exactly (verified by
the differential suite in tests/test_farm.py): per touched key a conflict
map of every visible op {opId: valueDiff}, child objects linked through
parent props up to the root (setupPatches, new.js:1461), counters emitted
with per-target accumulated totals (new.js:937-965), deleted keys as empty
conflict maps.

Map-family keys (maps, tables, counters, nested trees) get reference-exact
patch parity via the batched device path. List/text objects additionally
run through the reference merge walk (the sequential engine in opset.py,
embedded lazily per document): the reference's incremental list edit
stream is an order-dependent state machine (listIndex increments only
after updatePatchProperty at insert boundaries, propState action
conversions, appendUpdate conflict popping — new.js:747-1033) whose output
is NOT a function of (old state, new state) alone, so no state diff can
reproduce it byte-for-byte. Documents that have never seen a list op pay
nothing for this; the first list op replays that doc's committed changes
through the walk once, and from then on its incremental patches are
byte-exact by construction. The device engine still carries every doc's
rows (including list rows: element forests feed the batched RGA rank
kernel in rga.py) for whole-document visibility, conflict winners,
counter totals, and the sync kernels at batch scale.
"""
from __future__ import annotations

from typing import NamedTuple

import numpy as np

from ..columnar import decode_change, decode_change_meta
from ..common import utf16_key
from ..obs.metrics import get_metrics
from ..opset import OpSet
from .engine import (
    ACTION_DEL,
    ACTION_INC,
    ACTION_SET,
    ACTOR_BITS,
    ACTOR_MASK,
    BatchedMapEngine,
    PAD_KEY,
    changes_from_numpy,
)
from .transcode import _Interner, _MAX_SLOTS, actor_rank_table


class ValueCell(NamedTuple):
    """Interned scalar payload of a set op: raw value + optional datatype."""

    value: object
    datatype: object


class ChildObj(NamedTuple):
    """Interned value marking 'this key holds the object with this id'."""

    object_id: str


_ROOT_META = {"parentObj": None, "parentKey": None, "type": "map"}

# farm metrics (process-wide registry, disabled unless a workload opts in —
# obs/metrics.py). All recording is host-side, outside the device phases.
_METRICS = get_metrics()
_M_ROWS = _METRICS.counter(
    "farm.rows.transcoded", "dense op rows produced by gate+transcode"
)
_M_PAD_ROWS = _METRICS.counter(
    "farm.rows.padding", "wasted (padded) cells in packed device batches"
)
_M_PAD_RATIO = _METRICS.gauge(
    "farm.pad_waste_ratio", "padding fraction of the last packed batch"
)
_M_OCCUPANCY = _METRICS.histogram(
    "farm.batch.occupancy", "rows / cells fill ratio per packed batch"
)
_M_ABORTS = _METRICS.counter(
    "farm.prevalidation.aborts",
    "apply_changes calls rejected batch-wide by the packing-limit pre-pass",
)
_M_APPLIED = _METRICS.counter(
    "farm.changes.applied", "changes committed by the causal gate"
)
_M_DEFERRALS = _METRICS.counter(
    "farm.gate.deferrals",
    "delivered changes left causally pending (queued) by the gate",
)
_M_WALKS = _METRICS.counter(
    "farm.exact.walks", "documents served by the embedded reference walk"
)

_MAKE_TYPES = {
    "makeMap": "map",
    "makeTable": "table",
    "makeList": "list",
    "makeText": "text",
}


def _empty_object_patch(object_id, type_):
    if type_ in ("list", "text"):
        return {"objectId": object_id, "type": type_, "edits": []}
    return {"objectId": object_id, "type": type_, "props": {}}


class TpuDocFarm:
    """N documents, one device engine. See module docstring."""

    def __init__(self, num_docs: int, capacity: int = 1024):
        self.num_docs = num_docs
        self.engine = BatchedMapEngine(num_docs, capacity)
        # interners are shared across the batch: actor ids, (objectId, key)
        # slots and scalar values are global tables, document state is not.
        # Caps guard the merge-key packing ranges (slot << 44 | ctr << 20 |
        # actor): an overflowing table would silently corrupt sort order.
        self.actors = _Interner(max_size=1 << ACTOR_BITS, name="actor")
        self.slots = _Interner(max_size=_MAX_SLOTS, name="slot")
        # amlint: disable=AM103 — value ids are payloads, never packed into
        # merge keys, so the table has no bit-field cap
        self.values = _Interner()
        # per-document host state
        self.object_meta = [{"_root": dict(_ROOT_META)} for _ in range(num_docs)]
        self.clock = [{} for _ in range(num_docs)]
        self.heads = [[] for _ in range(num_docs)]
        self.queue = [[] for _ in range(num_docs)]
        self.changes = [[] for _ in range(num_docs)]  # raw change buffers
        self.change_index_by_hash = [{} for _ in range(num_docs)]
        self.hashes_by_actor = [{} for _ in range(num_docs)]
        # hash graph (computeHashGraph, new.js:1879) — maintained eagerly
        self.dependencies_by_hash = [{} for _ in range(num_docs)]
        self.dependents_by_hash = [{} for _ in range(num_docs)]
        self.max_op = [0] * num_docs
        self.counter_ops = [set() for _ in range(num_docs)]  # packed opids
        # max inc opId per counter (Lamport tuple) — gates counter emission
        self.inc_max = [{} for _ in range(num_docs)]
        # counters named by a multi-pred inc as a non-highest pred: the
        # reference registers each inc to its highest-opId pred only
        # (counterStates overwrite, new.js:621-628), so these counters'
        # succ lists never drain and they never emit
        self.starved = [set() for _ in range(num_docs)]
        # per-(obj, key) cache of 'visible values at last walk' (the
        # reference's objectMeta children map, new.js:426) used by the
        # setupPatches ancestor-linking walk
        self.children = [{} for _ in range(num_docs)]
        # list/text element tables (rank-kernel inputs): one forest per doc
        # spanning ALL of its list objects — per-object document order is
        # the global RGA preorder filtered by owning object (rga.py)
        self.elem_capacity = 64
        self.elem_opid = np.zeros((num_docs, self.elem_capacity), np.int64)
        self.elem_parent = np.full((num_docs, self.elem_capacity), -1, np.int32)
        self.num_elems = np.zeros(num_docs, np.int32)
        self.elem_index = [{} for _ in range(num_docs)]  # elemId -> local idx
        self.elem_ids = [[] for _ in range(num_docs)]  # local idx -> elemId
        self.elem_object = [[] for _ in range(num_docs)]  # local idx -> objectId
        # reference merge walk per doc, created lazily on the first op that
        # targets a list/text object (see module docstring): authoritative
        # for that doc's incremental patch stream from then on
        self.exact: list[OpSet | None] = [None] * num_docs

    # ------------------------------------------------------------------ #
    # transcoding

    def _pack_opid(self, op_id: str) -> int:
        ctr, actor = op_id.split("@")
        return (int(ctr) << ACTOR_BITS) | self.actors.intern(actor)

    def _opid_str(self, packed: int) -> str:
        return f"{packed >> ACTOR_BITS}@{self.actors.lookup(packed & ACTOR_MASK)}"

    def _op_rows(self, d: int, op: dict, ctr: int, actor: str):
        """Dense rows for one decoded backend-form op (columnar.decode_ops
        output). Multi-pred ops emit one primary row plus marker rows (one
        per extra pred) that exist purely to record the extra succ edges;
        markers share the primary's opId and sort directly after it (stable
        sort + left-searchsorted), so opId lookups always hit the primary."""
        if "key" not in op or op.get("insert") or op.get("elemId") is not None:
            return self._list_op_rows(d, op, ctr, actor)
        obj, key = op["obj"], op["key"]
        if obj not in self.object_meta[d]:
            raise ValueError(f"op for missing object {obj}")
        slot = self.slots.intern((obj, key))
        packed = (ctr << ACTOR_BITS) | self.actors.intern(actor)
        preds = [self._pack_opid(p) for p in op.get("pred", ())]
        action = op["action"]
        if action == "set":
            datatype = op.get("datatype")
            if datatype == "counter":
                self.counter_ops[d].add(packed)
                value = int(op["value"])
            else:
                value = self.values.intern(ValueCell(op["value"], datatype))
            rows = [(slot, packed, ACTION_SET, value, preds[0] if preds else -1)]
        elif action in _MAKE_TYPES:
            value = self._register_child(d, obj, key, action, ctr, actor)
            rows = [(slot, packed, ACTION_SET, value, preds[0] if preds else -1)]
        elif action == "inc":
            lam = (ctr, actor)
            for target in op.get("pred", ()):
                t = self._pack_opid(target)
                if t not in self.inc_max[d] or self.inc_max[d][t] < lam:
                    self.inc_max[d][t] = lam
            # A multi-pred inc adds its value to only ONE target in the
            # reference: counterStates[incOp] is overwritten by each walked
            # counter, so the highest-opId pred wins (new.js:621-628). The
            # primary row carries the value to preds[-1] (preds are sorted
            # ascending); the rest get zero-valued inc markers, which keep
            # the extra counters visible (inc successors never hide,
            # new.js:937-944) without contributing.
            rows = [(slot, packed, ACTION_INC, int(op["value"]), preds[-1] if preds else -1)]
            for extra in preds[:-1]:
                self.starved[d].add(extra)
                rows.append((slot, packed, ACTION_INC, 0, extra))
            return rows
        elif action == "del":
            rows = [(slot, packed, ACTION_DEL, 0, preds[0] if preds else -1)]
        else:
            raise NotImplementedError(f"op action {action!r} not supported by the farm")
        for extra in preds[1:]:
            rows.append((slot, packed, ACTION_DEL, 0, extra))
        return rows

    def _register_child(self, d, obj, parent_key, action, ctr, actor):
        child_id = f"{ctr}@{actor}"
        self.object_meta[d][child_id] = {
            "parentObj": obj,
            "parentKey": parent_key,
            "type": _MAKE_TYPES[action],
        }
        return self.values.intern(ChildObj(child_id))

    def _grow_elems(self, needed: int):
        from . import rga

        if needed > rga.MAX_ELEMS:
            raise ValueError(
                f"document exceeds {rga.MAX_ELEMS} list elements (incl. "
                "tombstones): beyond the rank kernel's key-packing range"
            )
        while needed > self.elem_capacity:
            pad = self.elem_capacity
            self.elem_opid = np.concatenate(
                [self.elem_opid, np.zeros((self.num_docs, pad), np.int64)], axis=1
            )
            self.elem_parent = np.concatenate(
                [self.elem_parent, np.full((self.num_docs, pad), -1, np.int32)],
                axis=1,
            )
            self.elem_capacity *= 2

    def _list_op_rows(self, d: int, op: dict, ctr: int, actor: str):
        """Dense rows for one list/text op. Inserts register the element in
        the doc's forest (parent = the referenced element, -1 for _head) and
        key all engine rows by the element's id, so per-element conflict
        resolution rides the same device kernels as map keys; document order
        comes from the batched RGA rank kernel (rga.py)."""
        from . import rga

        obj = op["obj"]
        meta = self.object_meta[d].get(obj)
        if meta is None:
            raise ValueError(f"op for missing object {obj}")
        if meta["type"] not in ("list", "text"):
            raise ValueError(f"list op for non-list object {obj}")
        packed = (ctr << ACTOR_BITS) | self.actors.intern(actor)
        preds = [self._pack_opid(p) for p in op.get("pred", ())]
        action = op["action"]

        if op.get("insert"):
            # counter range is enforced batch-wide by _prevalidate_limits
            # before any transcoding starts (the single enforcement point);
            # this only restates the invariant for direct-row callers
            assert ctr < rga.MAX_COUNTER, "op counter outside merge-key packing range"
            elem_id = f"{ctr}@{actor}"
            ref = op.get("elemId") or "_head"
            idx = int(self.num_elems[d])
            self._grow_elems(idx + 1)
            self.num_elems[d] += 1
            self.elem_opid[d, idx] = packed
            if ref == "_head":
                self.elem_parent[d, idx] = -1
            else:
                self.elem_parent[d, idx] = self.elem_index[d][ref]
            self.elem_index[d][elem_id] = idx
            self.elem_ids[d].append(elem_id)
            self.elem_object[d].append(obj)
            key_elem = elem_id
        else:
            key_elem = op["elemId"]
            if key_elem not in self.elem_index[d]:
                raise ValueError(f"unknown list element {key_elem}")
        slot = self.slots.intern((obj, key_elem))

        if action == "set":
            datatype = op.get("datatype")
            if datatype == "counter":
                self.counter_ops[d].add(packed)
                value = int(op["value"])
            else:
                value = self.values.intern(ValueCell(op.get("value"), datatype))
            rows = [(slot, packed, ACTION_SET, value, preds[0] if preds else -1)]
        elif action in _MAKE_TYPES:
            value = self._register_child(d, obj, key_elem, action, ctr, actor)
            rows = [(slot, packed, ACTION_SET, value, preds[0] if preds else -1)]
        elif action == "inc":
            lam = (ctr, actor)
            for target in op.get("pred", ()):
                t = self._pack_opid(target)
                if t not in self.inc_max[d] or self.inc_max[d][t] < lam:
                    self.inc_max[d][t] = lam
            rows = [(slot, packed, ACTION_INC, int(op["value"]),
                     preds[-1] if preds else -1)]
            for extra in preds[:-1]:
                self.starved[d].add(extra)
                rows.append((slot, packed, ACTION_INC, 0, extra))
            return rows
        elif action == "del":
            rows = [(slot, packed, ACTION_DEL, 0, preds[0] if preds else -1)]
        else:
            raise NotImplementedError(f"list op action {action!r}")
        for extra in preds[1:]:
            rows.append((slot, packed, ACTION_DEL, 0, extra))
        return rows

    def _element_ranks(self):
        """Device RGA document order over every doc's element forest."""
        from .rga import batched_rga_rank
        from .text_engine import _next_pow2

        valid = np.arange(self.elem_capacity)[None, :] < self.num_elems[:, None]
        rank = actor_rank_table(
            self.actors.table,
            pad_to=_next_pow2(max(len(self.actors.table), 1)),
        )
        return np.asarray(
            batched_rga_rank(self.elem_parent, self.elem_opid, valid, rank)
        )

    def _actor_rank(self):
        return actor_rank_table(self.actors.table)

    def _lamport(self, packed: int):
        return (packed >> ACTOR_BITS, self.actors.lookup(packed & ACTOR_MASK))

    # ------------------------------------------------------------------ #
    # run segmentation and patch cutoffs
    #
    # The sequential merge (mergeDocChangeOps, new.js:1052) walks doc ops of
    # a key only while that key's change ops are pending; once the run's
    # batching advances to a later key, the rest of the key's ops are copied
    # without patch emission. Each walk also RESETS the key's conflict map
    # (first_op => props[key] = {}, new.js:1000). Net effect: a touched
    # key's final conflict map equals the LAST touching run's walk — the
    # final visible ops of the key whose opId is <= that run's cutoff for
    # the key (+inf when the key is the run's last batch, because the stale
    # change-op comparison keeps the walk going to the end of the key run).
    # Counters additionally require every inc successor to be walked
    # (new.js:1124-1133), i.e. max inc opId <= cutoff.

    _INF = (float("inf"), "")

    def _compute_cutoffs(self, d, applied_ops):
        """applied_ops: in-order [(op_dict, ctr, actor, gate_batch)] of every
        map-family op applied this call. Returns {slot: lamport-cutoff}
        where later touching runs overwrite earlier ones. Runs may span
        consecutive changes of one actor within a causal gate batch (the
        reference's change_state walks all ops of a batch in sequence) but
        never a gate-batch boundary (each batch is a separate merge pass,
        new.js:1816-1822)."""
        cutoffs = {}
        run = None  # {"actor", "obj", "last_key", "batches": [(key, release)]}

        def close(run):
            if run is None:
                return
            last = len(run["batches"]) - 1
            for i, (key, release) in enumerate(run["batches"]):
                slot = self.slots.intern((run["obj"], key))
                cutoffs[slot] = self._INF if i == last else release

        last_batch = None
        for op, ctr, actor, gate_batch in applied_ops:
            if gate_batch != last_batch:
                close(run)
                run = None
                last_batch = gate_batch
            key = op.get("key")
            if key is None or op.get("insert") or op.get("elemId") is not None:
                # list/text ops never produce map-key cutoffs (docs touching
                # them are served by the reference walk); a list op here can
                # only mean a new op kind leaked in — close the run safely
                close(run)
                run = None
                continue
            obj = op["obj"]
            lam = (ctr, actor)
            preds = []
            for p in op.get("pred", ()):
                pctr, pactor = p.split("@")
                preds.append((int(pctr), pactor))
            # a del op leaves the pending batch when its last pred is walked
            release = max(preds, default=lam) if op["action"] == "del" else lam

            if run is not None and run["actor"] == actor and run["obj"] == obj:
                bkey, brel = run["batches"][-1]
                overwrite = any(p in run["batch_ids"] for p in preds)
                if key == bkey and not overwrite:
                    run["batches"][-1] = (bkey, max(brel, release))
                    run["batch_ids"].add(lam)
                    run["last_key"] = key
                    continue
                if utf16_key(run["last_key"]) < utf16_key(key):
                    run["batches"].append((key, release))
                    run["batch_ids"] = {lam}
                    run["last_key"] = key
                    continue
            close(run)
            run = {"actor": actor, "obj": obj, "last_key": key,
                   "batches": [(key, release)], "batch_ids": {lam}}
        close(run)
        return cutoffs

    # ------------------------------------------------------------------ #
    # causal gate (port of the applyChanges function, new.js:1550)

    def _gate_round(self, d: int, pending):
        heads = set(self.heads[d])
        clock = dict(self.clock[d])
        round_hashes = set()
        applied, enqueued = [], []
        for change in pending:
            if (
                change["hash"] in self.change_index_by_hash[d]
                or change["hash"] in round_hashes
            ):
                continue
            expected_seq = clock.get(change["actor"], 0) + 1
            ready = all(
                dep in self.change_index_by_hash[d] or dep in round_hashes
                for dep in change["deps"]
            )
            if not ready:
                enqueued.append(change)
            elif change["seq"] < expected_seq:
                raise ValueError(
                    f"Reuse of sequence number {change['seq']} for actor {change['actor']}"
                )
            elif change["seq"] > expected_seq:
                raise ValueError(
                    f"Skipped sequence number {expected_seq} for actor {change['actor']}"
                )
            else:
                clock[change["actor"]] = change["seq"]
                round_hashes.add(change["hash"])
                for dep in change["deps"]:
                    heads.discard(dep)
                heads.add(change["hash"])
                applied.append(change)
        if applied:
            self.heads[d] = sorted(heads)
            self.clock[d] = clock
        return applied, enqueued

    # ------------------------------------------------------------------ #
    # the reference merge walk (lazily embedded per doc)

    def _ensure_exact(self, d: int) -> OpSet:
        """Bootstraps the reference walk for doc `d` by replaying its
        committed change log (and re-delivering its queued changes), so the
        walk's state matches the farm's exactly from this call onward."""
        if self.exact[d] is None:
            opset = OpSet()
            if self.changes[d]:
                opset.apply_changes(list(self.changes[d]))
            for change in self.queue[d]:
                opset.apply_changes([change["buffer"]])
            self.exact[d] = opset
        return self.exact[d]

    @staticmethod
    def _targets_list(decoded_changes) -> bool:
        return any(
            op.get("insert") or op.get("elemId") is not None
            for change in decoded_changes
            for op in change["ops"]
        )

    def _prevalidate_limits(self, d: int, decoded_changes) -> None:
        """Raises the farm's packing-limit errors BEFORE anything commits, so
        a failed apply leaves all state untouched.

        Every op counter must stay below 2^24: the merge key packs
        (slot << 44 | ctr << 20 | actor) for ALL ops (engine._merge_key), not
        only inserts. The element-capacity estimate counts inserts from this
        delivery plus the queue (queued changes may become ready and apply in
        the same call), and skips changes already applied (duplicate
        deliveries never re-apply, so their inserts must not trigger a
        spurious rejection).

        Abort semantics are BATCH-WIDE: the pre-pass runs for every doc
        before any doc's ops are transcoded or committed, so one over-limit
        document fails the whole apply_changes call and every document in
        the batch stays untouched. The queue estimate is deliberately
        conservative — a permanently-stuck queued change with inserts keeps
        shrinking the doc's effective element budget (readiness is
        unknowable without running the causal gate), which can reject a
        delivery that would have fit; split the batch to isolate such a
        doc."""
        from . import rga

        inserts = 0
        seen = set()
        for change in list(decoded_changes) + list(self.queue[d]):
            if change["hash"] in self.change_index_by_hash[d] or change["hash"] in seen:
                continue
            seen.add(change["hash"])
            ctr = change["startOp"]
            for op in change["ops"]:
                if ctr >= rga.MAX_COUNTER:
                    raise ValueError(
                        f"op counter {ctr} exceeds the merge-key "
                        "packing range"
                    )
                if op.get("insert"):
                    inserts += 1
                ctr += 1
        if int(self.num_elems[d]) + inserts > rga.MAX_ELEMS:
            raise ValueError(
                f"document exceeds {rga.MAX_ELEMS} list elements (incl. "
                "tombstones): beyond the rank kernel's key-packing range"
            )

    # ------------------------------------------------------------------ #
    # the batched applyChanges step

    def apply_changes(self, per_doc_buffers, is_local=False):
        """Applies binary changes to every document (one device merge for
        the whole batch) and returns one reference-format patch per doc.
        `per_doc_buffers` is a list of num_docs lists of change buffers.

        Phases (recorded on the ambient PhaseProfile, SURVEY §5.1):
        decode -> walk (exact docs) -> gate+transcode -> pack ->
        device_dispatch -> visibility -> patch_assembly."""
        from ..profiling import get_profile

        prof = get_profile()
        assert len(per_doc_buffers) == self.num_docs
        per_doc_rows = [[] for _ in range(self.num_docs)]
        applied_ops = [[] for _ in range(self.num_docs)]
        touched_objects = [set() for _ in range(self.num_docs)]
        applied_changes = [[] for _ in range(self.num_docs)]
        exact_patches: dict[int, dict] = {}

        with prof.phase("decode"):
            per_doc_decoded = []
            for buffers in per_doc_buffers:
                decoded = []
                for buffer in buffers:
                    change = decode_change(buffer)
                    change["buffer"] = bytes(buffer)
                    decoded.append(change)
                per_doc_decoded.append(decoded)

        # Docs receiving no changes this call skip prevalidation entirely:
        # their queue was already validated at its original delivery and a
        # queued change can only become ready when a NEW change for the same
        # doc commits, so re-scanning the queue would be O(queue ops) of
        # redundant work per call (ADVICE round 5). Docs that do receive
        # changes still re-scan their queue inside _prevalidate_limits.
        try:
            for d, decoded in enumerate(per_doc_decoded):
                if decoded:
                    self._prevalidate_limits(d, decoded)
        except ValueError:
            _M_ABORTS.inc()
            raise

        # list/text-targeting docs route through the reference walk, whose
        # patch is authoritative for them (byte-exact edit streams; see
        # module docstring). Run it BEFORE the farm's own gate so error
        # behaviour (seq reuse, missing objects) matches the sequential
        # engine's.
        with prof.phase("walk"):
            for d, decoded in enumerate(per_doc_decoded):
                if decoded and (
                    self.exact[d] is not None or self._targets_list(decoded)
                ):
                    self._ensure_exact(d)
                    exact_patches[d] = self.exact[d].apply_changes(
                        [c["buffer"] for c in decoded], is_local
                    )

        with prof.phase("gate+transcode"):
            for d, decoded in enumerate(per_doc_decoded):
                pending = decoded + self.queue[d] if self.queue[d] else decoded
                gate_batch = 0
                while True:
                    applied, pending = self._gate_round(d, pending)
                    if not applied:
                        break
                    gate_batch += 1
                    for change in applied:
                        ctr = change["startOp"]
                        for op in change["ops"]:
                            rows = self._op_rows(d, op, ctr, change["actor"])
                            per_doc_rows[d].extend(rows)
                            applied_ops[d].append(
                                (op, ctr, change["actor"], gate_batch)
                            )
                            touched_objects[d].add(op["obj"])
                            ctr += 1
                        self.max_op[d] = max(self.max_op[d], ctr - 1)
                        applied_changes[d].append(change)
                        # commit immediately so later gate rounds (and later
                        # calls) see this hash as a satisfied dependency
                        self.changes[d].append(change["buffer"])
                        self.change_index_by_hash[d][change["hash"]] = (
                            len(self.changes[d]) - 1
                        )
                        by_actor = self.hashes_by_actor[d].setdefault(
                            change["actor"], []
                        )
                        while len(by_actor) < change["seq"]:
                            by_actor.append(None)
                        by_actor[change["seq"] - 1] = change["hash"]
                        self.dependencies_by_hash[d][change["hash"]] = list(
                            change["deps"]
                        )
                        self.dependents_by_hash[d].setdefault(change["hash"], [])
                        for dep in change["deps"]:
                            self.dependents_by_hash[d].setdefault(dep, []).append(
                                change["hash"]
                            )
                    if not pending:
                        break
                self.queue[d] = pending

        if _METRICS.enabled:
            _M_WALKS.inc(len(exact_patches))
            _M_APPLIED.inc(sum(len(c) for c in applied_changes))
            delivered = {
                c["hash"] for decoded in per_doc_decoded for c in decoded
            }
            _M_DEFERRALS.inc(sum(
                1
                for d in range(self.num_docs)
                for c in self.queue[d]
                if c["hash"] in delivered
            ))

        # one device merge for the whole batch
        width = max((len(r) for r in per_doc_rows), default=0)
        if width > 0:
            if _METRICS.enabled:
                rows = sum(len(r) for r in per_doc_rows)
                cells = self.num_docs * width
                _M_ROWS.inc(rows)
                _M_PAD_ROWS.inc(cells - rows)
                _M_PAD_RATIO.set(1.0 - rows / cells)
                _M_OCCUPANCY.observe(rows / cells)
            with prof.phase("pack"):
                keys = np.full((self.num_docs, width), PAD_KEY, np.int32)
                ops = np.zeros((self.num_docs, width), np.int64)
                actions = np.zeros((self.num_docs, width), np.int32)
                values = np.zeros((self.num_docs, width), np.int64)
                preds = np.full((self.num_docs, width), -1, np.int64)
                for d, rows in enumerate(per_doc_rows):
                    for i, (slot, packed, action, value, pred) in enumerate(rows):
                        keys[d, i] = slot
                        ops[d, i] = packed
                        actions[d, i] = action
                        values[d, i] = value
                        preds[d, i] = pred
            with prof.phase("device_dispatch"):
                self.engine.apply_batch(
                    changes_from_numpy(keys, ops, actions, values, preds)
                )

        # no-op deliveries (all queued or duplicates) need no device work
        need_device_patch = [
            d for d in range(self.num_docs) if d not in exact_patches
        ]
        with prof.phase("visibility"):
            vis = (
                self._read_visibility()
                if width > 0 and need_device_patch
                else None
            )
        with prof.phase("patch_assembly"):
            patches = []
            for d in range(self.num_docs):
                if d in exact_patches:
                    patches.append(exact_patches[d])
                    continue
                cutoffs = self._compute_cutoffs(d, applied_ops[d])
                diffs = self._build_diffs(d, vis, cutoffs, touched_objects[d])
                patch = {
                    "maxOp": self.max_op[d],
                    "clock": self.clock[d],
                    "deps": self.heads[d],
                    "pendingChanges": len(self.queue[d]),
                    "diffs": diffs,
                }
                if (
                    is_local
                    and len(per_doc_buffers[d]) == 1
                    and applied_changes[d]
                ):
                    patch["actor"] = applied_changes[d][0]["actor"]
                    patch["seq"] = applied_changes[d][0]["seq"]
                patches.append(patch)
        return patches

    # ------------------------------------------------------------------ #
    # patch assembly from device visibility

    def _read_visibility(self):
        keys, ops, visible, _winners, totals = self.engine.visible_state(
            actor_rank=self._actor_rank() if self.actors.table else None
        )
        return (
            np.asarray(keys),
            np.asarray(ops),
            np.asarray(visible),
            np.asarray(totals),
            np.asarray(self.engine.state.action),
        )

    def _slot_rows(self, d, vis, slot):
        """All walkable rows of one slot in ascending opId order (the row
        sort order): [(packed, action, visible, total)]. Deletion rows and
        multi-pred marker rows are skipped — the reference stores deletions
        only as succ entries, so its walk never visits them."""
        keys, ops, visible, totals, actions = vis
        row_keys = keys[d]
        lo = np.searchsorted(row_keys, slot, side="left")
        hi = np.searchsorted(row_keys, slot, side="right")
        out = []
        for i in range(lo, hi):
            if actions[d, i] == ACTION_DEL:
                continue
            out.append(
                (int(ops[d, i]), int(actions[d, i]), bool(visible[d, i]),
                 int(totals[d, i]))
            )
        # the engine table sorts by actor intern index; the reference walk
        # order ties same-counter ops on the actor id string
        out.sort(key=lambda r: self._lamport(r[0]))
        return out

    def _visible_rows(self, d, vis, slot):
        """[(packed_opid, value_total)] of visible set rows for one slot."""
        return [
            (packed, total)
            for packed, action, visible, total in self._slot_rows(d, vis, slot)
            if visible and action == ACTION_SET
        ]

    def _value_diff(self, d, patches, packed, total):
        """The valueDiff for one visible row (updatePatchProperty's values,
        new.js:884-1033)."""
        if packed in self.counter_ops[d]:
            return {"type": "value", "datatype": "counter", "value": total}
        cell = self.values.lookup(total)
        if isinstance(cell, ChildObj):
            child = cell.object_id
            if child not in patches:
                patches[child] = _empty_object_patch(
                    child, self.object_meta[d][child]["type"]
                )
            return patches[child]
        diff = {"type": "value", "value": cell.value}
        if cell.datatype is not None:
            diff["datatype"] = cell.datatype
        return diff

    def _ensure_patch(self, d, patches, object_id):
        if object_id not in patches:
            patches[object_id] = _empty_object_patch(
                object_id, self.object_meta[d][object_id]["type"]
            )
        return patches[object_id]

    def _emitted_rows(self, d, rows, cutoff):
        """The visible set rows (from _slot_rows) the sequential walk would
        have emitted under `cutoff` (see _compute_cutoffs): opId <= cutoff,
        counters only when every inc successor was walked too."""
        out = []
        for packed, action, visible, total in rows:
            if not visible or action != ACTION_SET:
                continue
            if self._lamport(packed) > cutoff:
                continue
            if packed in self.counter_ops[d] and not self._counter_emits(
                d, packed, cutoff
            ):
                continue
            out.append((packed, total))
        return out

    def _counter_emits(self, d, packed, cutoff):
        """A counter emits only when its succ list drains during the walk:
        every inc targeting it must be walked (<= cutoff) and actually
        registered to it (not to a higher-opId conflicting counter)."""
        if packed in self.starved[d]:
            return False
        max_inc = self.inc_max[d].get(packed)
        return max_inc is None or max_inc <= cutoff

    def _cache_spec(self, d, packed, total):
        """Children-cache entry for one emitted row: the reference caches
        raw decoded values (counters with inc successors are filtered out by
        the caller, so `total` here is the raw value) and object stubs
        (new.js:426, updatePatchProperty's `values`)."""
        if packed in self.counter_ops[d]:
            return {"type": "value", "value": total, "datatype": "counter"}
        cell = self.values.lookup(total)
        if isinstance(cell, ChildObj):
            return ("child", cell.object_id)
        diff = {"type": "value", "value": cell.value}
        if cell.datatype is not None:
            diff["datatype"] = cell.datatype
        return diff

    def _update_children_cache(self, d, slot, cutoff, rows):
        """Replays the walk's per-op cache updates for one slot.

        The reference re-evaluates `hasChild or prev_children` at EVERY
        walked op, reading the cache live (new.js:923-935): once a walk
        shrinks the cache to empty, later ops of the same walk can no longer
        update it (the gate reads the now-empty cache), so the final cache
        is order-dependent. Counters with inc successors never enter
        visibleOps (their succNum > 0), and inc ops enter visibleOps but
        not the cached values."""
        cache = self.children[d].get(slot)
        specs = []  # cached (opId, spec) accumulated in walk order
        has_child = False
        updated = False
        for packed, action, visible, total in rows:
            if self._lamport(packed) > cutoff:
                break  # rows are in ascending opId order; the rest unwalked
            if action == ACTION_SET:
                ref_overwritten = (not visible) or (
                    packed in self.counter_ops[d] and packed in self.inc_max[d]
                )
                if not ref_overwritten:
                    spec = self._cache_spec(d, packed, total)
                    specs.append((self._opid_str(packed), spec))
                    has_child = has_child or isinstance(spec, tuple)
            if has_child or cache:
                cache = dict(specs)
                updated = True
        if updated:
            self.children[d][slot] = cache

    def _visible_sequence(self, d, vis, ranks, obj):
        """One list object's visible elements in document order:
        [(elemId, winner_packed, total)] — device ranks give the order,
        device visibility/winners give each element's surviving value."""
        n = int(self.num_elems[d])
        if n == 0:
            return []
        order = np.argsort(ranks[d, :n], kind="stable")
        seq = []
        for idx in order:
            idx = int(idx)
            if self.elem_object[d][idx] != obj:
                continue
            elem_id = self.elem_ids[d][idx]
            slot = self.slots.intern((obj, elem_id))
            best = None
            for packed, action, visible, total in self._slot_rows(d, vis, slot):
                if not visible or action != ACTION_SET:
                    continue
                if packed in self.counter_ops[d] and packed in self.starved[d]:
                    continue
                if best is None or self._lamport(packed) > self._lamport(best[0]):
                    best = (packed, total)
            if best is not None:
                seq.append((elem_id, best[0], best[1]))
        return seq

    def _build_diffs(self, d, vis, cutoffs, touched_objects):
        """Patch assembly for map-family docs from device visibility. Docs
        that touch list/text objects never reach this path (they are served
        by the embedded reference walk; see apply_changes)."""
        patches = {"_root": _empty_object_patch("_root", "map")}

        for slot in sorted(cutoffs):
            obj, key = self.slots.lookup(slot)
            if obj not in self.object_meta[d]:
                continue
            patch = self._ensure_patch(d, patches, obj)
            rows = self._slot_rows(d, vis, slot)
            emitted = self._emitted_rows(d, rows, cutoffs[slot])
            # each walk resets the key's conflict map (new.js:1000)
            props = patch["props"][key] = {}
            for packed, total in emitted:
                props[self._opid_str(packed)] = self._value_diff(
                    d, patches, packed, total
                )
            self._update_children_cache(d, slot, cutoffs[slot], rows)

        # link touched objects up to the root (setupPatches, new.js:1461)
        for object_id in sorted(touched_objects):
            meta = self.object_meta[d].get(object_id)
            if meta is None:
                continue
            child_meta = None
            patch_exists = False
            while True:
                values = None
                if child_meta is not None:
                    slot = self.slots.intern((object_id, child_meta["parentKey"]))
                    values = self.children[d].get(slot) or {}
                has_children = child_meta is not None and len(values) > 0
                self._ensure_patch(d, patches, object_id)
                if child_meta is not None and has_children:
                    props = patches[object_id]["props"].setdefault(
                        child_meta["parentKey"], {}
                    )
                    for op_id, spec in values.items():
                        if op_id in props:
                            patch_exists = True
                        elif isinstance(spec, tuple):  # ("child", id)
                            child = spec[1]
                            if child not in patches:
                                patches[child] = _empty_object_patch(
                                    child, self.object_meta[d][child]["type"]
                                )
                            props[op_id] = patches[child]
                        else:
                            props[op_id] = spec
                if (
                    patch_exists
                    or not meta["parentObj"]
                    or (child_meta is not None and not has_children)
                ):
                    break
                child_meta = dict(meta, opId=object_id)
                object_id = meta["parentObj"]
                meta = self.object_meta[d][object_id]

        return patches["_root"]

    # ------------------------------------------------------------------ #
    # whole-document patch (getPatch, new.js:2052)

    def get_patch(self, d: int):
        vis = self._read_visibility()
        ranks = (
            self._element_ranks() if int(self.num_elems[d]) > 0 else None
        )
        keys = vis[0][d]
        patches = {"_root": _empty_object_patch("_root", "map")}
        list_objects = set()
        slots_here = sorted({int(s) for s in keys if s != PAD_KEY})
        for slot in slots_here:
            obj, key = self.slots.lookup(slot)
            if obj not in self.object_meta[d]:
                continue
            if self.object_meta[d][obj]["type"] in ("list", "text"):
                list_objects.add(obj)
                continue
            rows = [
                (packed, total)
                for packed, total in self._visible_rows(d, vis, slot)
                if packed not in self.counter_ops[d]
                or self._counter_emits(d, packed, self._INF)
            ]
            if not rows:
                continue  # whole-doc patches omit empty props (new.js:1604)
            patch = self._ensure_patch(d, patches, obj)
            props = patch["props"].setdefault(key, {})
            for packed, total in rows:
                props[self._opid_str(packed)] = self._value_diff(
                    d, patches, packed, total
                )
        # list objects materialise as a full insert script in document
        # order (the whole-doc scan's edits, new.js:1604)
        from ..opset import append_edit

        for obj in sorted(list_objects):
            patch = self._ensure_patch(d, patches, obj)
            for index, (elem_id, packed, total) in enumerate(
                self._visible_sequence(d, vis, ranks, obj)
            ):
                append_edit(patch["edits"], {
                    "action": "insert", "index": index, "elemId": elem_id,
                    "opId": self._opid_str(packed),
                    "value": self._value_diff(d, patches, packed, total),
                })
        return {
            "maxOp": self.max_op[d],
            "clock": self.clock[d],
            "deps": self.heads[d],
            "pendingChanges": len(self.queue[d]),
            "diffs": patches["_root"],
        }

    # ------------------------------------------------------------------ #
    # hash-graph queries (backend.js facade parity)

    def get_heads(self, d: int):
        return list(self.heads[d])

    def get_all_changes(self, d: int):
        return list(self.changes[d])

    def get_change_by_hash(self, d: int, hash_: str):
        index = self.change_index_by_hash[d].get(hash_)
        return self.changes[d][index] if index is not None else None

    def get_changes(self, d: int, have_deps):
        """Changes a replica holding `have_deps` is missing (getChanges,
        new.js:1913): walk forward from have_deps through the dependents
        graph; if that cannot reach all heads, fall back to everything not
        in have_deps' ancestor closure."""
        if not have_deps:
            return list(self.changes[d])
        stack, seen, to_return = [], set(), []
        for h in have_deps:
            seen.add(h)
            successors = self.dependents_by_hash[d].get(h)
            if successors is None:
                raise ValueError(f"hash not found: {h}")
            stack.extend(successors)
        while stack:
            h = stack.pop()
            seen.add(h)
            to_return.append(h)
            if not all(dep in seen for dep in self.dependencies_by_hash[d][h]):
                break
            stack.extend(self.dependents_by_hash[d][h])
        if not stack and all(head in seen for head in self.heads[d]):
            return [self.changes[d][self.change_index_by_hash[d][h]] for h in to_return]
        stack, seen = list(have_deps), set()
        while stack:
            h = stack.pop()
            if h not in seen:
                deps = self.dependencies_by_hash[d].get(h)
                if deps is None:
                    raise ValueError(f"hash not found: {h}")
                stack.extend(deps)
                seen.add(h)
        return [
            change for change in self.changes[d]
            if decode_change_meta(change, True)["hash"] not in seen
        ]

    def get_missing_deps(self, d: int, heads=()):
        """Dependencies needed before queued changes can apply, plus any
        requested heads we lack (getMissingDeps, new.js:2006)."""
        missing = set()
        in_queue = {change["hash"] for change in self.queue[d]}
        for change in self.queue[d]:
            for dep in change["deps"]:
                if dep not in self.change_index_by_hash[d] and dep not in in_queue:
                    missing.add(dep)
        for head in heads:
            if head not in self.change_index_by_hash[d] and head not in in_queue:
                missing.add(head)
        return sorted(missing)
