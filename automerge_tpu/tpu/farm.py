"""Batched document farm: the backend contract over the device merge engine.

`TpuDocFarm` manages N documents and speaks the reference backend's
applyChanges -> patch protocol (backend/backend.js:27, new.js:1796) for all
of them at once: binary changes in, reference-format patches out, with the
merge + visibility/conflict computation running as one batched device
program per call (engine.batched_apply_ops / batched_visible_state).

Division of labour:
- **Host**: change decoding (columnar -> op dicts, memoised in a bounded
  LRU so a change gossiped to N documents is parsed once), the causal gate
  (dedup by hash, dependency check, per-actor seq contiguity — the port of
  new.js:1550-1597), op transcoding to dense rows, and patch *assembly*
  from device-computed visibility. Assembly reads a host ROW MIRROR of the
  device op table (static columns replicated with zero transfers; the
  merge-dependent visibility/total columns cached per (doc, slot) and
  refreshed from the device only for spans a commit invalidated) and runs
  as column operations — see README "Performance".
- **Device**: the op-table merge (succ/overwrite resolution) and the
  visibility/winner/counter-total computation for every document in the
  batch — the work the reference does per-doc in mergeDocChangeOps
  (new.js:1052) and updatePatchProperty (new.js:884).

Patch assembly reproduces the reference's patch shape exactly (verified by
the differential suite in tests/test_farm.py): per touched key a conflict
map of every visible op {opId: valueDiff}, child objects linked through
parent props up to the root (setupPatches, new.js:1461), counters emitted
with per-target accumulated totals (new.js:937-965), deleted keys as empty
conflict maps.

Map-family keys (maps, tables, counters, nested trees) get reference-exact
patch parity via the batched device path. List/text objects additionally
run through the reference merge walk (the sequential engine in opset.py,
embedded lazily per document): the reference's incremental list edit
stream is an order-dependent state machine (listIndex increments only
after updatePatchProperty at insert boundaries, propState action
conversions, appendUpdate conflict popping — new.js:747-1033) whose output
is NOT a function of (old state, new state) alone, so no state diff can
reproduce it byte-for-byte. Documents that have never seen a list op pay
nothing for this; the first list op replays that doc's committed changes
through the walk once, and from then on its incremental patches are
byte-exact by construction. The device engine still carries every doc's
rows (including list rows: element forests feed the batched RGA rank
kernel in rga.py) for whole-document visibility, conflict winners,
counter totals, and the sync kernels at batch scale.

Fault isolation: under the default ``isolation="doc"`` every document is
its own fault domain — a poisoned delivery (corrupt bytes, causal
violations, packing overflows) quarantines only that doc, with its host
state rolled back to a pre-call snapshot and the failure classified by the
error taxonomy (errors.py) in the call's outcome report. Repeat offenders
enter a traffic-shedding quarantine set (release_quarantine restores
them), and a failing device dispatch degrades to the sequential reference
walk after bisecting out the poison docs. ``isolation="batch"`` keeps the
historical all-or-nothing contract. See README "Fault isolation".
"""
from __future__ import annotations

import os
import pickle
import time
from collections import OrderedDict
from typing import NamedTuple

import numpy as np

from ..columnar import decode_change_cached, decode_change_meta_cached
from .decode import warm_decode_cache
from ..common import utf16_key
from ..errors import (
    CausalityError,
    DeviceFaultError,
    PackingLimitError,
    QuarantinedError,
    error_kind,
)
from ..obs.flight import get_flight
from ..obs.metrics import get_metrics
from ..obs.scope import current_exemplar
from ..opset import OpSet
from ..testing.faults import fire as _fault_point
from .engine import (
    ACTION_DEL,
    ACTION_INC,
    ACTION_SET,
    ACTOR_BITS,
    ACTOR_MASK,
    BatchedMapEngine,
    PAD_KEY,
    _MKEY_OP_BITS,
    changes_from_numpy,
)
from .transcode import (
    DEP_COMMITTED,
    DEP_UNKNOWN,
    _Interner,
    _MAX_SLOTS,
    actor_rank_table,
    gate_verdicts,
    lamport_keys,
    ragged_spans,
)


class ValueCell(NamedTuple):
    """Interned scalar payload of a set op: raw value + optional datatype."""

    value: object
    datatype: object


class ChildObj(NamedTuple):
    """Interned value marking 'this key holds the object with this id'."""

    object_id: str


_ROOT_META = {"parentObj": None, "parentKey": None, "type": "map"}


def _remap_packed(col, amap):
    """Rewrites the actor field of a packed-opid column through `amap`
    (source actor id -> destination actor id); -1 sentinels pass through.
    The counter field is actor-independent and survives unchanged."""
    out = np.asarray(col, np.int64).copy()
    live = out >= 0
    ops = out[live]
    out[live] = (ops & ~np.int64(ACTOR_MASK)) | amap[ops & np.int64(ACTOR_MASK)]
    return out


def _remap_packed_one(packed: int, amap) -> int:
    return int((packed & ~ACTOR_MASK) | int(amap[packed & ACTOR_MASK]))

# farm metrics (process-wide registry, disabled unless a workload opts in —
# obs/metrics.py). All recording is host-side, outside the device phases.
_METRICS = get_metrics()
_M_ROWS = _METRICS.counter(
    "farm.rows.transcoded", "dense op rows produced by gate+transcode"
)
_M_PAD_ROWS = _METRICS.counter(
    "farm.rows.padding", "wasted (padded) cells in packed device batches"
)
_M_PAD_RATIO = _METRICS.gauge(
    "farm.pad_waste_ratio", "padding fraction of the last packed batch"
)
_M_OCCUPANCY = _METRICS.histogram(
    "farm.batch.occupancy", "rows / cells fill ratio per packed batch"
)
_M_ABORTS = _METRICS.counter(
    "farm.prevalidation.aborts",
    "apply_changes calls rejected batch-wide by the packing-limit pre-pass",
)
_M_APPLIED = _METRICS.counter(
    "farm.changes.applied", "changes committed by the causal gate"
)
_M_DEFERRALS = _METRICS.counter(
    "farm.gate.deferrals",
    "delivered changes left causally pending (queued) by the gate",
)
_M_WALKS = _METRICS.counter(
    "farm.exact.walks", "documents served by the embedded reference walk"
)
_M_Q_ENTERED = _METRICS.counter(
    "farm.quarantine.entered",
    "documents moved into the quarantine set after repeated failures",
)
_M_Q_RELEASED = _METRICS.counter(
    "farm.quarantine.released", "documents returned to service"
)
_M_Q_SHED = _METRICS.counter(
    "farm.quarantine.shed",
    "deliveries dropped unprocessed because the target doc is quarantined",
)
_M_Q_ACTIVE = _METRICS.gauge(
    "farm.quarantine.active", "documents currently quarantined"
)
_M_FB_CALLS = _METRICS.counter(
    "farm.fallback.calls",
    "apply_changes calls that lost the batched device path mid-dispatch",
)
_M_FB_DOCS = _METRICS.counter(
    "farm.fallback.docs",
    "documents served by the sequential reference walk after a device failure",
)
_M_BISECT = _METRICS.counter(
    "farm.bisect.rounds",
    "bisection probes run to isolate device-poison documents",
)
_M_RB_ROWS = _METRICS.counter(
    "farm.readback.rows",
    "rows transferred device→host by the scoped visibility readback",
)
_M_RB_SKIPPED = _METRICS.counter(
    "farm.readback.rows_skipped",
    "live rows NOT transferred because their cached visibility was fresh "
    "(what the old full readback would have paid)",
)
_M_RB_HITS = _METRICS.counter(
    "farm.readback.cache_hits",
    "(doc, slot) spans served from the host visibility cache",
)
_M_VECTOR_ROWS = _METRICS.counter(
    "farm.assembly.vector_rows",
    "rows processed by the vectorized (column-mask) assembly path",
)
_M_VEC_CHANGES = _METRICS.counter(
    "farm.gate.vector_changes",
    "changes gated by the columnar verdict program (transcode.gate_verdicts)",
)
_M_DEV_COLS = _METRICS.counter(
    "farm.patch.device_columns",
    "patch rows whose emit mask was computed on device by the fused "
    "visibility+patch-columns program",
)
_M_GATE_ORACLE = _METRICS.counter(
    "farm.gate.oracle_docs",
    "docs routed to the scalar gate oracle before verdicts (uncacheable "
    "ops or in-delivery duplicate hashes)",
)
_M_TC_ORACLE = _METRICS.counter(
    "farm.transcode.oracle_docs",
    "docs re-routed to the scalar chain after verdicts (seq/ref anomalies "
    "whose canonical error the oracle owns)",
)
# amscope hooks: the dispatch/readback latency histograms carry the
# ambient serve DispatchSpan id as their bucket exemplar, so a farm-side
# latency spike links back to the batched request traces it served.
_M_DISPATCH_MS = _METRICS.histogram(
    "farm.dispatch.latency_ms",
    "host-measured batched device merge dispatch latency; exemplars name "
    "the owning serve dispatch span",
)
_M_READBACK_MS = _METRICS.histogram(
    "farm.readback.latency_ms",
    "host-measured scoped visibility readback latency; exemplars name "
    "the owning serve dispatch span",
)
# flight-recorder hook (obs/flight.py): quarantine transitions and device
# faults leave timeline events (with the offending change hashes) and
# auto-dump the ring for postmortems.
_FLIGHT = get_flight()

# One counter family for every per-doc quarantine cause, dimensioned by the
# taxonomy's error_kind (decode/checksum/causality/packing/device/...): the
# single funnel for "why did a doc lose this delivery", replacing the old
# split where only prevalidation aborts were counted (the batch-wide
# `farm.prevalidation.aborts` counter still tracks isolation="batch" aborts).
_QUARANTINE_CAUSES: dict[str, object] = {}


def _quarantine_cause(kind: str):
    counter = _QUARANTINE_CAUSES.get(kind)
    if counter is None:
        counter = _METRICS.counter(
            f"farm.quarantine.causes.{kind}",
            f"per-doc quarantined deliveries with error_kind={kind}",
        )
        _QUARANTINE_CAUSES[kind] = counter
    return counter

_MAKE_TYPES = {
    "makeMap": "map",
    "makeTable": "table",
    "makeList": "list",
    "makeText": "text",
}


def _empty_object_patch(object_id, type_):
    if type_ in ("list", "text"):
        return {"objectId": object_id, "type": type_, "edits": []}
    return {"objectId": object_id, "type": type_, "props": {}}


class DocOutcome(NamedTuple):
    """Per-document result of one apply_changes call (isolation="doc")."""

    status: str                       # "applied" | "quarantined"
    error: BaseException | None = None
    error_kind: str | None = None     # taxonomy dimension (errors.error_kind)
    offending_hashes: tuple = ()      # change hashes implicated, if known
    fallback: bool = False            # served by the sequential walk


_APPLIED = DocOutcome("applied")
_APPLIED_FALLBACK = DocOutcome("applied", fallback=True)


class FarmApplyResult(list):
    """apply_changes' return value: the per-doc patch list every existing
    caller indexes into, plus the per-doc outcome report."""

    def __init__(self, patches, outcomes):
        super().__init__(patches)
        self.outcomes = list(outcomes)

    @property
    def quarantined(self):
        """{doc index: DocOutcome} of the docs that lost this delivery."""
        return {
            d: o for d, o in enumerate(self.outcomes) if o.status == "quarantined"
        }

    @property
    def applied(self):
        """{doc index: DocOutcome} of the docs whose delivery committed
        (including fallback-walk-served docs) — the symmetric accessor to
        ``quarantined``, so callers like the serve batcher account
        outcomes without re-filtering ``outcomes`` by status string."""
        return {
            d: o for d, o in enumerate(self.outcomes) if o.status == "applied"
        }


# ---------------------------------------------------------------------- #
# wire frames: the picklable shipping format a mesh worker process uses
# to return one FarmApplyResult over a pipe (parallel/workers.py).
# Patches are double-pickled — the whole per-doc patch list rides as ONE
# opaque blob inside the response — so the controller can defer (or
# skip) materializing thousands of patch dicts it may never index into;
# outcomes travel as flat tuples with the exception safely pickled
# (exceptions can carry unpicklable payloads, e.g. wrapped runtime
# errors — those degrade to a same-taxonomy stand-in carrying the repr).

def exc_to_blob(exc: BaseException | None) -> bytes | None:
    """Pickles an exception, degrading unpicklable ones to a
    DeviceFaultError-taxonomy stand-in that preserves kind + repr."""
    if exc is None:
        return None
    try:
        blob = pickle.dumps(exc)
        pickle.loads(blob)  # some exceptions pickle but fail to rebuild
        return blob
    except Exception:
        stand_in = DeviceFaultError(
            f"[unpicklable {type(exc).__name__}] {exc!r}"
        )
        stand_in.kind = error_kind(exc)
        return pickle.dumps(stand_in)


def exc_from_blob(blob: bytes | None) -> BaseException | None:
    return None if blob is None else pickle.loads(blob)


def outcome_to_wire(o: DocOutcome) -> tuple:
    return (
        o.status, exc_to_blob(o.error), o.error_kind,
        tuple(o.offending_hashes), o.fallback,
    )


def outcome_from_wire(w: tuple) -> DocOutcome:
    status, blob, kind, offending, fallback = w
    if status == "applied" and blob is None and not offending:
        return _APPLIED_FALLBACK if fallback else _APPLIED
    return DocOutcome(status, exc_from_blob(blob), kind, offending, fallback)


def result_to_wire(result: FarmApplyResult) -> dict:
    """{patches: blob, outcomes: [wire tuples]} — see block comment."""
    return {
        "patches": pickle.dumps(
            list(result), protocol=pickle.HIGHEST_PROTOCOL
        ),
        "outcomes": [outcome_to_wire(o) for o in result.outcomes],
    }


def result_from_wire(frame: dict) -> FarmApplyResult:
    return FarmApplyResult(
        pickle.loads(frame["patches"]),
        [outcome_from_wire(w) for w in frame["outcomes"]],
    )


#: cache sentinel for changes the columnar builder cannot express
_UNCACHEABLE = object()


class _ChangeCols:
    """One decoded change transcoded ONCE into column form (cached per
    change hash): the dense row array plus every per-doc side effect of
    `_op_rows` recorded as replayable data. A change gossiped to N
    documents builds its columns a single time; committing it to a doc
    replays the recorded effects (counter registration, inc max-merge,
    child metas) without any per-op Python. List/text ops and unknown
    actions are uncacheable (the builder returns None): they mutate
    order-dependent per-doc element state, so their docs route through
    the scalar oracle chain."""

    __slots__ = (
        "hash", "actor", "seq", "deps", "max_ctr", "arr", "counter_packed",
        "inc_updates", "starved", "children", "objs", "external_refs",
        "cut_slots", "cut_packed", "_sorted",
    )

    def __init__(self, change, max_ctr, arr, counter_packed, inc_updates,
                 starved, children, objs, external_refs, cut_slots,
                 cut_packed):
        self.hash = change["hash"]
        self.actor = change["actor"]
        self.seq = change["seq"]
        self.deps = tuple(change["deps"])
        self.max_ctr = max_ctr
        self.arr = arr
        self.counter_packed = counter_packed
        self.inc_updates = inc_updates
        self.starved = starved
        self.children = children
        self.objs = objs
        self.external_refs = external_refs
        self.cut_slots = cut_slots
        self.cut_packed = cut_packed
        self._sorted = None

    def sorted_cols(self):
        """Mirror-weave columns in merge-key order, lazily sorted once and
        shared by every doc the change merges into:
        (mkey sorted, key32, op, action32, unique slots)."""
        if self._sorted is None:
            arr = self.arr
            mkey = (arr[:, 0] << _MKEY_OP_BITS) | arr[:, 1]
            order = np.argsort(mkey, kind="stable")
            self._sorted = (
                mkey[order],
                arr[order, 0].astype(np.int32),
                arr[order, 1],
                arr[order, 2].astype(np.int32),
                np.unique(arr[:, 0]),
            )
        return self._sorted


class TpuDocFarm:
    """N documents, one device engine. See module docstring.

    `quarantine_threshold`: consecutive failed deliveries after which a
    document enters the quarantine set and sheds its traffic until
    `release_quarantine` (None disables the set; every failure still
    quarantines that one delivery)."""

    def __init__(self, num_docs: int, capacity: int = 1024,
                 quarantine_threshold: int | None = 3,
                 page_size: int | None = None,
                 gate_mode: str | None = None):
        # "columnar" gates whole deliveries with verdict columns
        # (transcode.gate_verdicts) and commits ready changes from cached
        # column arrays; "oracle" pins every doc to the scalar gate chain
        # (the parity oracle the columnar path re-routes anomalies to).
        gate_mode = gate_mode or os.environ.get("AM_GATE_MODE", "columnar")
        if gate_mode not in ("columnar", "oracle"):
            raise ValueError(f"unknown gate mode: {gate_mode!r}")  # amlint: disable=AM401 — API-usage validation
        self.gate_mode = gate_mode
        self.num_docs = num_docs
        self.engine = BatchedMapEngine(num_docs, capacity, page_size=page_size)
        # optional crash-consistent persistence tier (automerge_tpu/store):
        # attach_store routes every committed delivery through the WAL and
        # a group-commit fsync barrier before its patches are acked
        self.store = None
        # interners are shared across the batch: actor ids, (objectId, key)
        # slots and scalar values are global tables, document state is not.
        # Caps guard the merge-key packing ranges (slot << 44 | ctr << 20 |
        # actor): an overflowing table would silently corrupt sort order.
        self.actors = _Interner(max_size=1 << ACTOR_BITS, name="actor")
        self.slots = _Interner(max_size=_MAX_SLOTS, name="slot")
        # amlint: disable=AM103 — value ids are payloads, never packed into
        # merge keys, so the table has no bit-field cap
        self.values = _Interner()
        # per-document host state
        self.object_meta = [{"_root": dict(_ROOT_META)} for _ in range(num_docs)]
        self.clock = [{} for _ in range(num_docs)]
        self.heads = [[] for _ in range(num_docs)]
        self.queue = [[] for _ in range(num_docs)]
        self.changes = [[] for _ in range(num_docs)]  # raw change buffers
        self.change_index_by_hash = [{} for _ in range(num_docs)]
        self.hashes_by_actor = [{} for _ in range(num_docs)]
        # hash graph (computeHashGraph, new.js:1879) — maintained eagerly
        self.dependencies_by_hash = [{} for _ in range(num_docs)]
        self.dependents_by_hash = [{} for _ in range(num_docs)]
        self.max_op = [0] * num_docs
        self.counter_ops = [set() for _ in range(num_docs)]  # packed opids
        # max inc opId per counter (Lamport tuple) — gates counter emission
        self.inc_max = [{} for _ in range(num_docs)]
        # counters named by a multi-pred inc as a non-highest pred: the
        # reference registers each inc to its highest-opId pred only
        # (counterStates overwrite, new.js:621-628), so these counters'
        # succ lists never drain and they never emit
        self.starved = [set() for _ in range(num_docs)]
        # per-(obj, key) cache of 'visible values at last walk' (the
        # reference's objectMeta children map, new.js:426) used by the
        # setupPatches ancestor-linking walk
        self.children = [{} for _ in range(num_docs)]
        # list/text element tables (rank-kernel inputs): one forest per doc
        # spanning ALL of its list objects — per-object document order is
        # the global RGA preorder filtered by owning object (rga.py)
        self.elem_capacity = 64
        self.elem_opid = np.zeros((num_docs, self.elem_capacity), np.int64)
        self.elem_parent = np.full((num_docs, self.elem_capacity), -1, np.int32)
        self.num_elems = np.zeros(num_docs, np.int32)
        self.elem_index = [{} for _ in range(num_docs)]  # elemId -> local idx
        self.elem_ids = [[] for _ in range(num_docs)]  # local idx -> elemId
        self.elem_object = [[] for _ in range(num_docs)]  # local idx -> objectId
        # reference merge walk per doc, created lazily on the first op that
        # targets a list/text object (see module docstring): authoritative
        # for that doc's incremental patch stream from then on
        self.exact: list[OpSet | None] = [None] * num_docs
        # fault-isolation state (isolation="doc"): consecutive failure
        # streaks, the quarantine set (doc -> last cause), and docs pinned
        # to the sequential walk after a device-path failure
        self.quarantine_threshold = quarantine_threshold
        self.fault_counts = [0] * num_docs
        self.quarantine: dict[int, BaseException] = {}
        self.degraded: set[int] = set()
        # host mirror of the device op table (incremental readback, README
        # "Performance"): per doc, the live rows in exact device order —
        # the host produced every row and the merge insert position is
        # deterministic (engine._merge_one_doc), so key/op/action never
        # need a device transfer. visible/total are a per-(doc, slot)
        # cache refreshed from the device only for slots invalidated by a
        # commit; steady-state sync rounds read back only deltas.
        self._vis_mkey = [np.empty(0, np.int64) for _ in range(num_docs)]
        self._vis_key = [np.empty(0, np.int32) for _ in range(num_docs)]
        self._vis_op = [np.empty(0, np.int64) for _ in range(num_docs)]
        self._vis_action = [np.empty(0, np.int32) for _ in range(num_docs)]
        self._vis_visible = [np.empty(0, bool) for _ in range(num_docs)]
        self._vis_total = [np.empty(0, np.int64) for _ in range(num_docs)]
        self._vis_stale = [set() for _ in range(num_docs)]  # slot ids to re-read
        self._vis_all_stale = [False] * num_docs
        # actor-rank table cached per interner size (it only ever grows)
        self._rank_cache = (0, np.zeros(0, np.int32))
        # interned value ids that hold ChildObj cells (child detection in
        # the vectorized children-cache update without a lookup per row)
        self._child_value_ids: set[int] = set()
        # columnar-gate caches: change hash -> _ChangeCols (a change
        # gossiped to N docs transcodes once), packed opid -> "ctr@actor",
        # value id -> leaf valueDiff template (device-column assembly)
        self._cols_cache: OrderedDict = OrderedDict()
        self._opid_strs: dict[int, str] = {}
        self._leaf_tpls: dict[int, dict] = {}

    # ------------------------------------------------------------------ #
    # transcoding

    def _pack_opid(self, op_id: str) -> int:
        ctr, actor = op_id.split("@")
        return (int(ctr) << ACTOR_BITS) | self.actors.intern(actor)

    def _opid_str(self, packed: int) -> str:
        return f"{packed >> ACTOR_BITS}@{self.actors.lookup(packed & ACTOR_MASK)}"

    def _op_rows(self, d: int, op: dict, ctr: int, actor: str):
        """Dense rows for one decoded backend-form op (columnar.decode_ops
        output). Multi-pred ops emit one primary row plus marker rows (one
        per extra pred) that exist purely to record the extra succ edges;
        markers share the primary's opId and sort directly after it (stable
        sort + left-searchsorted), so opId lookups always hit the primary."""
        if "key" not in op or op.get("insert") or op.get("elemId") is not None:
            return self._list_op_rows(d, op, ctr, actor)
        obj, key = op["obj"], op["key"]
        if obj not in self.object_meta[d]:
            raise CausalityError(f"op for missing object {obj}")
        slot = self.slots.intern((obj, key))
        packed = (ctr << ACTOR_BITS) | self.actors.intern(actor)
        preds = [self._pack_opid(p) for p in op.get("pred", ())]
        action = op["action"]
        if action == "set":
            datatype = op.get("datatype")
            if datatype == "counter":
                self.counter_ops[d].add(packed)
                value = int(op["value"])
            else:
                value = self.values.intern(ValueCell(op["value"], datatype))
            rows = [(slot, packed, ACTION_SET, value, preds[0] if preds else -1)]
        elif action in _MAKE_TYPES:
            value = self._register_child(d, obj, key, action, ctr, actor)
            rows = [(slot, packed, ACTION_SET, value, preds[0] if preds else -1)]
        elif action == "inc":
            lam = (ctr, actor)
            for target in op.get("pred", ()):
                t = self._pack_opid(target)
                if t not in self.inc_max[d] or self.inc_max[d][t] < lam:
                    self.inc_max[d][t] = lam
            # A multi-pred inc adds its value to only ONE target in the
            # reference: counterStates[incOp] is overwritten by each walked
            # counter, so the highest-opId pred wins (new.js:621-628). The
            # primary row carries the value to preds[-1] (preds are sorted
            # ascending); the rest get zero-valued inc markers, which keep
            # the extra counters visible (inc successors never hide,
            # new.js:937-944) without contributing.
            rows = [(slot, packed, ACTION_INC, int(op["value"]), preds[-1] if preds else -1)]
            for extra in preds[:-1]:
                self.starved[d].add(extra)
                rows.append((slot, packed, ACTION_INC, 0, extra))
            return rows
        elif action == "del":
            rows = [(slot, packed, ACTION_DEL, 0, preds[0] if preds else -1)]
        else:
            raise NotImplementedError(f"op action {action!r} not supported by the farm")
        for extra in preds[1:]:
            rows.append((slot, packed, ACTION_DEL, 0, extra))
        return rows

    def _register_child(self, d, obj, parent_key, action, ctr, actor):
        child_id = f"{ctr}@{actor}"
        self.object_meta[d][child_id] = {
            "parentObj": obj,
            "parentKey": parent_key,
            "type": _MAKE_TYPES[action],
        }
        value = self.values.intern(ChildObj(child_id))
        self._child_value_ids.add(value)
        return value

    def _grow_elems(self, needed: int):
        from . import rga

        if needed > rga.MAX_ELEMS:
            raise PackingLimitError(
                f"document exceeds {rga.MAX_ELEMS} list elements (incl. "
                "tombstones): beyond the rank kernel's key-packing range"
            )
        while needed > self.elem_capacity:
            pad = self.elem_capacity
            self.elem_opid = np.concatenate(
                [self.elem_opid, np.zeros((self.num_docs, pad), np.int64)], axis=1
            )
            self.elem_parent = np.concatenate(
                [self.elem_parent, np.full((self.num_docs, pad), -1, np.int32)],
                axis=1,
            )
            self.elem_capacity *= 2

    def _list_op_rows(self, d: int, op: dict, ctr: int, actor: str):
        """Dense rows for one list/text op. Inserts register the element in
        the doc's forest (parent = the referenced element, -1 for _head) and
        key all engine rows by the element's id, so per-element conflict
        resolution rides the same device kernels as map keys; document order
        comes from the batched RGA rank kernel (rga.py)."""
        from . import rga

        obj = op["obj"]
        meta = self.object_meta[d].get(obj)
        if meta is None:
            raise CausalityError(f"op for missing object {obj}")
        if meta["type"] not in ("list", "text"):
            raise CausalityError(f"list op for non-list object {obj}")
        packed = (ctr << ACTOR_BITS) | self.actors.intern(actor)
        preds = [self._pack_opid(p) for p in op.get("pred", ())]
        action = op["action"]

        if op.get("insert"):
            # counter range is enforced batch-wide by _prevalidate_limits
            # before any transcoding starts (the single enforcement point);
            # this only restates the invariant for direct-row callers
            assert ctr < rga.MAX_COUNTER, "op counter outside merge-key packing range"
            elem_id = f"{ctr}@{actor}"
            ref = op.get("elemId") or "_head"
            idx = int(self.num_elems[d])
            self._grow_elems(idx + 1)
            self.num_elems[d] += 1
            self.elem_opid[d, idx] = packed
            if ref == "_head":
                self.elem_parent[d, idx] = -1
            elif ref in self.elem_index[d]:
                self.elem_parent[d, idx] = self.elem_index[d][ref]
            else:
                raise CausalityError(f"unknown list element {ref}")
            self.elem_index[d][elem_id] = idx
            self.elem_ids[d].append(elem_id)
            self.elem_object[d].append(obj)
            key_elem = elem_id
        else:
            key_elem = op["elemId"]
            if key_elem not in self.elem_index[d]:
                raise CausalityError(f"unknown list element {key_elem}")
        slot = self.slots.intern((obj, key_elem))

        if action == "set":
            datatype = op.get("datatype")
            if datatype == "counter":
                self.counter_ops[d].add(packed)
                value = int(op["value"])
            else:
                value = self.values.intern(ValueCell(op.get("value"), datatype))
            rows = [(slot, packed, ACTION_SET, value, preds[0] if preds else -1)]
        elif action in _MAKE_TYPES:
            value = self._register_child(d, obj, key_elem, action, ctr, actor)
            rows = [(slot, packed, ACTION_SET, value, preds[0] if preds else -1)]
        elif action == "inc":
            lam = (ctr, actor)
            for target in op.get("pred", ()):
                t = self._pack_opid(target)
                if t not in self.inc_max[d] or self.inc_max[d][t] < lam:
                    self.inc_max[d][t] = lam
            rows = [(slot, packed, ACTION_INC, int(op["value"]),
                     preds[-1] if preds else -1)]
            for extra in preds[:-1]:
                self.starved[d].add(extra)
                rows.append((slot, packed, ACTION_INC, 0, extra))
            return rows
        elif action == "del":
            rows = [(slot, packed, ACTION_DEL, 0, preds[0] if preds else -1)]
        else:
            raise NotImplementedError(f"list op action {action!r}")
        for extra in preds[1:]:
            rows.append((slot, packed, ACTION_DEL, 0, extra))
        return rows

    def _element_ranks(self):
        """Device RGA document order over every doc's element forest."""
        from .rga import batched_rga_rank
        from .text_engine import _next_pow2

        valid = np.arange(self.elem_capacity)[None, :] < self.num_elems[:, None]
        rank = actor_rank_table(
            self.actors.table,
            pad_to=_next_pow2(max(len(self.actors.table), 1)),
        )
        return np.asarray(
            batched_rga_rank(self.elem_parent, self.elem_opid, valid, rank)
        )

    def _actor_rank(self):
        n = len(self.actors.table)
        if self._rank_cache[0] != n:  # the interner only ever grows
            self._rank_cache = (n, actor_rank_table(self.actors.table))
        return self._rank_cache[1]

    def _lamport(self, packed: int):
        return (packed >> ACTOR_BITS, self.actors.lookup(packed & ACTOR_MASK))

    # ------------------------------------------------------------------ #
    # run segmentation and patch cutoffs
    #
    # The sequential merge (mergeDocChangeOps, new.js:1052) walks doc ops of
    # a key only while that key's change ops are pending; once the run's
    # batching advances to a later key, the rest of the key's ops are copied
    # without patch emission. Each walk also RESETS the key's conflict map
    # (first_op => props[key] = {}, new.js:1000). Net effect: a touched
    # key's final conflict map equals the LAST touching run's walk — the
    # final visible ops of the key whose opId is <= that run's cutoff for
    # the key (+inf when the key is the run's last batch, because the stale
    # change-op comparison keeps the walk going to the end of the key run).
    # Counters additionally require every inc successor to be walked
    # (new.js:1124-1133), i.e. max inc opId <= cutoff.

    _INF = (float("inf"), "")

    def _compute_cutoffs(self, d, applied_ops):
        """applied_ops: in-order [(op_dict, ctr, actor, gate_batch)] of every
        map-family op applied this call. Returns {slot: lamport-cutoff}
        where later touching runs overwrite earlier ones. Runs may span
        consecutive changes of one actor within a causal gate batch (the
        reference's change_state walks all ops of a batch in sequence) but
        never a gate-batch boundary (each batch is a separate merge pass,
        new.js:1816-1822)."""
        cutoffs = {}
        run = None  # {"actor", "obj", "last_key", "batches": [(key, release)]}

        def close(run):
            if run is None:
                return
            last = len(run["batches"]) - 1
            for i, (key, release) in enumerate(run["batches"]):
                slot = self.slots.intern((run["obj"], key))
                cutoffs[slot] = self._INF if i == last else release

        last_batch = None
        # amlint: disable=AM107 — scalar-oracle cutoff walk: the columnar
        # path precomputes cut columns once per distinct change hash
        for op, ctr, actor, gate_batch in applied_ops:
            if gate_batch != last_batch:
                close(run)
                run = None
                last_batch = gate_batch
            key = op.get("key")
            if key is None or op.get("insert") or op.get("elemId") is not None:
                # list/text ops never produce map-key cutoffs (docs touching
                # them are served by the reference walk); a list op here can
                # only mean a new op kind leaked in — close the run safely
                close(run)
                run = None
                continue
            obj = op["obj"]
            lam = (ctr, actor)
            preds = []
            for p in op.get("pred", ()):
                pctr, pactor = p.split("@")
                preds.append((int(pctr), pactor))
            # a del op leaves the pending batch when its last pred is walked
            release = max(preds, default=lam) if op["action"] == "del" else lam

            if run is not None and run["actor"] == actor and run["obj"] == obj:
                bkey, brel = run["batches"][-1]
                overwrite = any(p in run["batch_ids"] for p in preds)
                if key == bkey and not overwrite:
                    run["batches"][-1] = (bkey, max(brel, release))
                    run["batch_ids"].add(lam)
                    run["last_key"] = key
                    continue
                if utf16_key(run["last_key"]) < utf16_key(key):
                    run["batches"].append((key, release))
                    run["batch_ids"] = {lam}
                    run["last_key"] = key
                    continue
            close(run)
            run = {"actor": actor, "obj": obj, "last_key": key,
                   "batches": [(key, release)], "batch_ids": {lam}}
        close(run)
        return cutoffs

    # ------------------------------------------------------------------ #
    # causal gate (port of the applyChanges function, new.js:1550)

    def _gate_round(self, d: int, pending):
        heads = set(self.heads[d])
        clock = dict(self.clock[d])
        round_hashes = set()
        applied, enqueued = [], []
        # amlint: disable=AM107 — the scalar causal gate IS the parity
        # oracle the columnar verdicts are tested against; anomalous docs
        # re-route here for the canonical result/error
        for change in pending:
            if (
                change["hash"] in self.change_index_by_hash[d]
                or change["hash"] in round_hashes
            ):
                continue
            expected_seq = clock.get(change["actor"], 0) + 1
            ready = all(
                dep in self.change_index_by_hash[d] or dep in round_hashes
                for dep in change["deps"]
            )
            if not ready:
                enqueued.append(change)
            elif change["seq"] < expected_seq:
                exc = CausalityError(
                    f"Reuse of sequence number {change['seq']} for actor {change['actor']}"
                )
                exc.offending_hashes = (change["hash"],)
                raise exc
            elif change["seq"] > expected_seq:
                exc = CausalityError(
                    f"Skipped sequence number {expected_seq} for actor {change['actor']}"
                )
                exc.offending_hashes = (change["hash"],)
                raise exc
            else:
                clock[change["actor"]] = change["seq"]
                round_hashes.add(change["hash"])
                for dep in change["deps"]:
                    heads.discard(dep)
                heads.add(change["hash"])
                applied.append(change)
        if applied:
            self.heads[d] = sorted(heads)
            self.clock[d] = clock
        return applied, enqueued

    # ------------------------------------------------------------------ #
    # columnar causal gate (gate_mode="columnar"): verdict columns for a
    # whole delivery at once (transcode.gate_verdicts) + per-change column
    # arrays cached across docs, with the scalar chain above as the
    # bit-for-bit parity oracle for anything the columns cannot express

    def _build_change_cols(self, change):
        """Columnar form of one decoded change, or None when any op falls
        outside the cacheable map-family subset. Mirrors `_op_rows` row
        for row (primary + marker rows); doc-independent because map-family
        rows only consult the shared interners, never per-doc state.
        Interner entries created here survive even if the change never
        commits — they are append-only lookup tables, never doc state
        (same policy as rollback)."""
        rows = []
        counter_packed = []
        inc_updates = []
        starved = []
        children = []
        local_children = set()
        external = []
        objs = set()
        actor = change["actor"]
        actor_idx = self.actors.intern(actor)
        ctr = change["startOp"]
        # amlint: disable=AM107 — columnar-cache builder: runs ONCE per
        # distinct change hash (LRU across the whole farm), not per
        # (doc, op) delivery; every doc replays the recorded columns
        for op in change["ops"]:
            if "key" not in op or op.get("insert") or op.get("elemId") is not None:
                return None
            obj, key = op["obj"], op["key"]
            objs.add(obj)
            if obj != "_root" and obj not in local_children:
                external.append(obj)
            slot = self.slots.intern((obj, key))
            packed = (ctr << ACTOR_BITS) | actor_idx
            preds = [self._pack_opid(p) for p in op.get("pred", ())]
            action = op["action"]
            if action == "set":
                datatype = op.get("datatype")
                if datatype == "counter":
                    counter_packed.append(packed)
                    value = int(op["value"])
                else:
                    value = self.values.intern(ValueCell(op["value"], datatype))
                rows.append((slot, packed, ACTION_SET, value,
                             preds[0] if preds else -1))
            elif action in _MAKE_TYPES:
                child_id = f"{ctr}@{actor}"
                value = self.values.intern(ChildObj(child_id))
                self._child_value_ids.add(value)
                children.append((child_id, {
                    "parentObj": obj,
                    "parentKey": key,
                    "type": _MAKE_TYPES[action],
                }))
                local_children.add(child_id)
                rows.append((slot, packed, ACTION_SET, value,
                             preds[0] if preds else -1))
            elif action == "inc":
                lam = (ctr, actor)
                for target in op.get("pred", ()):
                    inc_updates.append((self._pack_opid(target), lam))
                rows.append((slot, packed, ACTION_INC, int(op["value"]),
                             preds[-1] if preds else -1))
                for extra in preds[:-1]:
                    starved.append(extra)
                    rows.append((slot, packed, ACTION_INC, 0, extra))
                ctr += 1
                continue
            elif action == "del":
                rows.append((slot, packed, ACTION_DEL, 0,
                             preds[0] if preds else -1))
            else:
                return None
            for extra in preds[1:]:
                rows.append((slot, packed, ACTION_DEL, 0, extra))
            ctr += 1
        max_ctr = ctr - 1
        arr = np.asarray(rows, np.int64).reshape(-1, 5)
        # single-change cutoffs are doc-independent too (`_compute_cutoffs`
        # only consults slots/keys/actor): cache them as rank-translatable
        # columns — ctr << ACTOR_BITS | actor INDEX, int64 max = walk to end
        applied_ops = [
            (op, change["startOp"] + i, actor, 1)
            for i, op in enumerate(change["ops"])
        ]
        cut_items = sorted(self._compute_cutoffs(None, applied_ops).items())
        cut_slots = np.asarray([s for s, _ in cut_items], np.int64)
        cut_packed = np.empty(len(cut_items), np.int64)
        inf = np.iinfo(np.int64).max
        for k, (_s, cut) in enumerate(cut_items):
            if cut[0] == float("inf"):
                cut_packed[k] = inf
            else:
                cut_packed[k] = (int(cut[0]) << ACTOR_BITS) | self.actors.intern(cut[1])
        return _ChangeCols(
            change, max_ctr, arr, counter_packed, inc_updates, starved,
            children, objs, tuple(dict.fromkeys(external)), cut_slots,
            cut_packed,
        )

    def _change_cols(self, change):
        """LRU-cached `_build_change_cols`. Builder exceptions cache as
        uncacheable — the scalar oracle chain owns the canonical error."""
        cache = self._cols_cache
        h = change["hash"]
        cols = cache.get(h)
        if cols is not None:
            cache.move_to_end(h)
            return None if cols is _UNCACHEABLE else cols
        try:
            cols = self._build_change_cols(change)
        except Exception:
            cols = None
        cache[h] = _UNCACHEABLE if cols is None else cols
        if len(cache) > 4096:
            cache.popitem(last=False)
        return cols

    def _gate_verdict_columns(self, per_doc_decoded):
        """Causal-gate verdicts for the whole delivery as column programs:
        per doc, assemble dep-index columns over (decoded + queued) entries
        and run `transcode.gate_verdicts` for commit order / deferrals in
        one pass. Returns (plans, scalar_docs): plans[d] =
        (pend, cols_list, batch, order); scalar_docs re-route through the
        scalar oracle (uncacheable ops, in-delivery duplicate hashes, or
        seq/ref anomalies whose canonical error the oracle owns)."""
        plans = {}
        scalar_docs = []
        vec_changes = 0
        for d, decoded in enumerate(per_doc_decoded):
            if not decoded:
                # no new changes: queued entries cannot become ready (their
                # missing deps only arrive with a commit), and the queue
                # holds no committed duplicates — the scalar loop would be
                # a no-op for this doc
                continue
            pend0 = decoded + self.queue[d] if self.queue[d] else decoded
            index = self.change_index_by_hash[d]
            pend = []
            positions = {}
            dup = False
            for c in pend0:
                h = c["hash"]
                if h in index:
                    continue  # committed duplicate: silently dropped
                if h in positions:
                    dup = True  # in-delivery duplicate: oracle owns dedup
                    break
                positions[h] = len(pend)
                pend.append(c)
            if dup:
                scalar_docs.append(d)
                _M_GATE_ORACLE.inc()
                continue
            if not pend:
                self.queue[d] = []
                continue
            cols_list = [self._change_cols(c) for c in pend]
            if any(cols is None for cols in cols_list):
                scalar_docs.append(d)
                _M_GATE_ORACLE.inc()
                continue
            if all(dep in index for c in pend for dep in c["deps"]):
                # every dep already committed (the steady-state shape:
                # deliveries extending known heads) — gate_verdicts would
                # assign batch 1 everywhere and keep delivery order
                batch = np.ones(len(pend), np.int64)
                order = np.arange(len(pend))
            else:
                dep_idx = []
                dep_counts = np.empty(len(pend), np.int64)
                for i, c in enumerate(pend):
                    deps = c["deps"]
                    dep_counts[i] = len(deps)
                    for dep in deps:
                        if dep in index:
                            dep_idx.append(DEP_COMMITTED)
                        else:
                            dep_idx.append(positions.get(dep, DEP_UNKNOWN))
                batch = gate_verdicts(dep_idx, dep_counts)
                committed = np.nonzero(batch > 0)[0]
                order = committed[np.argsort(batch[committed], kind="stable")]
            if not self._validate_commit(d, pend, cols_list, order):
                scalar_docs.append(d)
                _M_TC_ORACLE.inc()
                continue
            plans[d] = (pend, cols_list, batch, order)
            vec_changes += len(pend)
        if _METRICS.enabled and vec_changes:
            _M_VEC_CHANGES.inc(vec_changes)
        return plans, scalar_docs

    def _validate_commit(self, d, pend, cols_list, order):
        """Checks the anomalies the scalar gate/transcode raises on —
        per-actor seq contiguity over the commit order, and external object
        refs resolving against committed state + earlier-committed makes.
        Returns False to re-route the doc through the scalar chain, which
        owns the canonical error (and its offending_hashes)."""
        seqs = {}
        known = self.object_meta[d]
        made = set()
        for i in order:
            c = pend[int(i)]
            cols = cols_list[int(i)]
            actor = c["actor"]
            expected = seqs.get(actor)
            if expected is None:
                expected = self.clock[d].get(actor, 0) + 1
            if c["seq"] != expected:
                return False
            seqs[actor] = expected + 1
            for obj in cols.external_refs:
                if obj not in known and obj not in made:
                    return False
            for child_id, _meta in cols.children:
                made.add(child_id)
        return True

    def _transcode_columns(self, d, plan, per_doc_arrays, applied_ops,
                           touched_objects, applied_changes, col_cuts,
                           mirror_pre):
        """Commits one doc's gate verdicts: replays each ready change's
        cached column side effects (the bookkeeping the scalar loop does
        per op) and takes the doc's dense row array straight from the
        cached column blocks — zero per-op Python on this path."""
        pend, cols_list, batch, order = plan
        deferred = [pend[i] for i in range(len(pend)) if batch[i] == 0]
        if len(deferred) == len(pend):
            self.queue[d] = deferred
            return
        clock = dict(self.clock[d])
        heads = set(self.heads[d])
        arrays = []
        multi = len(pend) - len(deferred) > 1
        for i in order:
            change = pend[int(i)]
            cols = cols_list[int(i)]
            clock[change["actor"]] = change["seq"]
            for dep in change["deps"]:
                heads.discard(dep)
            heads.add(change["hash"])
            arrays.append(cols.arr)
            touched_objects[d] |= cols.objs
            self.max_op[d] = max(self.max_op[d], cols.max_ctr)
            applied_changes[d].append(change)
            self.changes[d].append(change["buffer"])
            self.change_index_by_hash[d][change["hash"]] = (
                len(self.changes[d]) - 1
            )
            by_actor = self.hashes_by_actor[d].setdefault(change["actor"], [])
            while len(by_actor) < change["seq"]:
                by_actor.append(None)
            by_actor[change["seq"] - 1] = change["hash"]
            self.dependencies_by_hash[d][change["hash"]] = list(change["deps"])
            self.dependents_by_hash[d].setdefault(change["hash"], [])
            for dep in change["deps"]:
                self.dependents_by_hash[d].setdefault(dep, []).append(
                    change["hash"]
                )
            if cols.counter_packed:
                self.counter_ops[d].update(cols.counter_packed)
            for target, lam in cols.inc_updates:
                cur = self.inc_max[d].get(target)
                if cur is None or cur < lam:
                    self.inc_max[d][target] = lam
            if cols.starved:
                self.starved[d].update(cols.starved)
            for child_id, meta in cols.children:
                self.object_meta[d][child_id] = dict(meta)
            if multi:
                ctr = change["startOp"]
                gb = int(batch[int(i)])
                # amlint: disable=AM107 — multi-change cutoff
                # materialisation: bounded by delivery size; single-change
                # deliveries (the steady state) reuse the cached cutoff
                # columns and never run this
                for op in change["ops"]:
                    applied_ops[d].append((op, ctr, change["actor"], gb))
                    ctr += 1
        self.clock[d] = clock
        self.heads[d] = sorted(heads)
        self.queue[d] = deferred
        arr = arrays[0] if len(arrays) == 1 else np.vstack(arrays)
        if arr.shape[0]:
            per_doc_arrays[d] = arr
            if not multi:
                cols = cols_list[int(order[0])]
                col_cuts[d] = (cols.cut_slots, cols.cut_packed)
                mirror_pre[d] = cols.sorted_cols()

    def _cutoffs_from_cols(self, cuts):
        """Rebuilds the {slot: lamport-cutoff} dict `_build_diffs` expects
        from cached cutoff columns (actor-INDEX packed; int64 max = walk
        to the end of the key run)."""
        cut_slots, cut_packed = cuts
        inf = np.iinfo(np.int64).max
        out = {}
        for slot, cut in zip(cut_slots.tolist(), cut_packed.tolist()):
            out[slot] = self._INF if cut == inf else (
                cut >> ACTOR_BITS, self.actors.lookup(cut & ACTOR_MASK)
            )
        return out

    # ------------------------------------------------------------------ #
    # the reference merge walk (lazily embedded per doc)

    def _ensure_exact(self, d: int) -> OpSet:
        """Bootstraps the reference walk for doc `d` by replaying its
        committed change log (and re-delivering its queued changes), so the
        walk's state matches the farm's exactly from this call onward."""
        if self.exact[d] is None:
            opset = OpSet()
            if self.changes[d]:
                opset.apply_changes(list(self.changes[d]))
            # amlint: disable=AM107 — cold path: one-time OpSet rebuild
            # when a doc first needs the reference walk
            for change in self.queue[d]:
                opset.apply_changes([change["buffer"]])
            self.exact[d] = opset
        return self.exact[d]

    @staticmethod
    def _targets_list(decoded_changes) -> bool:
        return any(
            op.get("insert") or op.get("elemId") is not None
            for change in decoded_changes
            for op in change["ops"]
        )

    def _prevalidate_limits(self, d: int, decoded_changes) -> None:
        """Raises the farm's packing-limit errors BEFORE anything commits, so
        a failed apply leaves all state untouched.

        Every op counter must stay below 2^24: the merge key packs
        (slot << 44 | ctr << 20 | actor) for ALL ops (engine._merge_key), not
        only inserts. The element-capacity estimate counts inserts from this
        delivery plus the queue (queued changes may become ready and apply in
        the same call), and skips changes already applied (duplicate
        deliveries never re-apply, so their inserts must not trigger a
        spurious rejection).

        Abort semantics depend on the isolation mode (apply_changes): under
        the default isolation="doc", an over-limit document quarantines ONLY
        its own delivery (state untouched, reported in the call's outcome
        list) while the rest of the batch proceeds; under the
        isolation="batch" escape hatch the pre-pass keeps the historical
        all-or-nothing contract — it runs for every doc before any doc's
        ops are transcoded or committed, so one over-limit document fails
        the whole call and every document stays untouched. The queue
        estimate is deliberately conservative — a permanently-stuck queued
        change with inserts keeps shrinking the doc's effective element
        budget (readiness is unknowable without running the causal gate),
        which can reject a delivery that would have fit; under "doc"
        isolation such a doc quarantines itself (see release_quarantine)
        instead of poisoning its batch neighbours."""
        from . import rga

        inserts = 0
        insert_hashes = set()
        seen = set()
        # amlint: disable=AM107 — packing-limit prevalidation must walk
        # every candidate op to count list inserts BEFORE any commit;
        # it guards the quarantine boundary, not a throughput phase
        for change in list(decoded_changes) + list(self.queue[d]):
            if change["hash"] in self.change_index_by_hash[d] or change["hash"] in seen:
                continue
            seen.add(change["hash"])
            ctr = change["startOp"]
            # amlint: disable=AM107 — same prevalidation walk, op level
            for op in change["ops"]:
                if ctr >= rga.MAX_COUNTER:
                    exc = PackingLimitError(
                        f"op counter {ctr} exceeds the merge-key "
                        "packing range"
                    )
                    exc.offending_hashes = (change["hash"],)
                    raise exc
                if op.get("insert"):
                    inserts += 1
                    insert_hashes.add(change["hash"])
                ctr += 1
        if int(self.num_elems[d]) + inserts > rga.MAX_ELEMS:
            exc = PackingLimitError(
                f"document exceeds {rga.MAX_ELEMS} list elements (incl. "
                "tombstones): beyond the rank kernel's key-packing range"
            )
            exc.offending_hashes = tuple(sorted(insert_hashes))
            raise exc

    # ------------------------------------------------------------------ #
    # the batched applyChanges step

    def apply_changes(self, per_doc_buffers, is_local=False, isolation="doc"):
        """Applies binary changes to every document (one device merge for
        the whole batch) and returns one reference-format patch per doc
        (a FarmApplyResult: a plain list of patches carrying a per-doc
        `outcomes` report). `per_doc_buffers` is a list of num_docs lists
        of change buffers.

        Isolation modes:
        - ``"doc"`` (default): decode, prevalidation, walk and gate
          failures are captured PER DOCUMENT — healthy docs proceed
          through transcode/pack/device dispatch in the same call, the
          failing doc's state stays untouched (snapshot/rollback around
          the commit phase) and its outcome reports
          ``quarantined(error, offending_hashes)``. Docs failing
          `quarantine_threshold` consecutive deliveries enter the
          quarantine set and shed traffic until `release_quarantine`.
          If the batched device program itself fails mid-dispatch, the
          batch is bisected to isolate the poison doc(s) and the
          survivors are served through the sequential reference walk
          (degraded mode), so the call still returns patches.
        - ``"batch"``: the historical all-or-nothing contract — the first
          failure raises out of the call (prevalidation aborts the whole
          batch before anything commits).

        Phases (recorded on the ambient PhaseProfile, SURVEY §5.1):
        decode -> walk (exact docs) -> gate+transcode -> pack ->
        device_dispatch -> visibility (host mirror merge + scoped
        device readback of stale spans) -> patch_assembly (vectorized
        over the mirror)."""
        from ..profiling import get_profile

        if isolation not in ("doc", "batch"):
            raise ValueError(f"unknown isolation mode: {isolation!r}")  # amlint: disable=AM401 — API-usage validation
        doc_mode = isolation == "doc"

        prof = get_profile()
        assert len(per_doc_buffers) == self.num_docs
        per_doc_rows = [[] for _ in range(self.num_docs)]
        per_doc_arrays = [None] * self.num_docs
        applied_ops = [[] for _ in range(self.num_docs)]
        touched_objects = [set() for _ in range(self.num_docs)]
        applied_changes = [[] for _ in range(self.num_docs)]
        exact_patches: dict[int, dict] = {}
        # fault-domain state for this call (isolation="doc")
        failures: dict[int, BaseException] = {}
        snapshots: dict[int, dict] = {}
        fallback_docs: set[int] = set()
        attempted = [d for d in range(self.num_docs) if per_doc_buffers[d]]
        # WAL capture: remember each attempted doc's committed-change count.
        # The delta at return is exactly what this call committed — uniform
        # across the columnar gate, the scalar oracle and the fallback walk,
        # and naturally zero for docs a quarantine rollback restored.
        store_marks = (
            {d: len(self.changes[d]) for d in attempted}
            if self.store is not None else None
        )

        def quarantine(d, exc):
            """Captures one doc's failure: rolls its state back, drops its
            rows/patch work, and counts the cause by error_kind."""
            if d in snapshots:
                # the rolled-back delivery never reached the mirror or the
                # device (the merge replays only after every doc committed,
                # and a failed dispatch advances nothing), so only the
                # spans it MEANT to touch need a re-read
                arr = per_doc_arrays[d]
                if arr is not None:
                    stale = np.unique(arr[:, 0]).tolist()
                elif per_doc_rows[d]:
                    stale = {int(r[0]) for r in per_doc_rows[d]}
                else:
                    stale = ()
                self._restore_doc(d, snapshots.pop(d), stale_slots=stale)
            failures[d] = exc
            per_doc_decoded[d] = []
            per_doc_rows[d] = []
            per_doc_arrays[d] = None
            applied_ops[d] = []
            touched_objects[d] = set()
            applied_changes[d] = []
            exact_patches.pop(d, None)
            _quarantine_cause(error_kind(exc)).inc()
            self.fault_counts[d] += 1
            if (
                self.quarantine_threshold is not None
                and self.fault_counts[d] >= self.quarantine_threshold
                and d not in self.quarantine
            ):
                self.quarantine[d] = exc
                _M_Q_ENTERED.inc()
                _M_Q_ACTIVE.set(len(self.quarantine))
                if _FLIGHT.enabled:
                    _FLIGHT.record(
                        "farm.quarantine.enter", doc=d,
                        kind=error_kind(exc),
                        offending_hashes=list(
                            getattr(exc, "offending_hashes", ())
                        ),
                        failures=self.fault_counts[d],
                    )
                    _FLIGHT.trigger("farm.quarantine", doc=d)

        # quarantined docs shed their traffic before any work happens
        if doc_mode and self.quarantine:
            per_doc_buffers = list(per_doc_buffers)
            for d, cause in self.quarantine.items():
                if per_doc_buffers[d]:
                    per_doc_buffers[d] = []
                    failures[d] = QuarantinedError(
                        f"document {d} is quarantined after "
                        f"{self.fault_counts[d]} failed deliveries (last "
                        f"cause: {cause}); release_quarantine({d}) to "
                        "restore traffic"
                    )
                    _M_Q_SHED.inc()

        with prof.phase("decode"):
            # batched first-touch decode: every distinct cache miss in the
            # delivery parses in ONE vector pass (tpu/decode) — the per-doc
            # loop below then hits the shared LRU. Buffers the batch pass
            # cannot decode stay uncached and raise their canonical error
            # inside the owning doc's fault domain.
            warm_decode_cache(
                [b for buffers in per_doc_buffers for b in buffers]
            )
            per_doc_decoded = []
            for d, buffers in enumerate(per_doc_buffers):
                decoded = []
                try:
                    _fault_point("farm.decode", doc=d, buffers=buffers)
                    for buffer in buffers:
                        # LRU-backed: one parse per distinct change however
                        # many documents it is gossiped to (shallow copy per
                        # doc; the shared ops list is never mutated)
                        change = decode_change_cached(buffer)
                        change["buffer"] = bytes(buffer)
                        decoded.append(change)
                except Exception as exc:
                    if not doc_mode:
                        raise
                    decoded = []
                    per_doc_decoded.append(decoded)
                    quarantine(d, exc)
                    continue
                per_doc_decoded.append(decoded)

        # Docs receiving no changes this call skip prevalidation entirely:
        # their queue was already validated at its original delivery and a
        # queued change can only become ready when a NEW change for the same
        # doc commits, so re-scanning the queue would be O(queue ops) of
        # redundant work per call (ADVICE round 5). Docs that do receive
        # changes still re-scan their queue inside _prevalidate_limits.
        for d, decoded in enumerate(per_doc_decoded):
            if not decoded:
                continue
            try:
                self._prevalidate_limits(d, decoded)
            except ValueError as exc:
                if not doc_mode:
                    _M_ABORTS.inc()
                    raise
                quarantine(d, exc)

        # list/text-targeting docs route through the reference walk, whose
        # patch is authoritative for them (byte-exact edit streams; see
        # module docstring). Run it BEFORE the farm's own gate so error
        # behaviour (seq reuse, missing objects) matches the sequential
        # engine's.
        with prof.phase("walk"):
            for d, decoded in enumerate(per_doc_decoded):
                if decoded and (
                    self.exact[d] is not None or self._targets_list(decoded)
                ):
                    try:
                        self._ensure_exact(d)
                        exact_patches[d] = self.exact[d].apply_changes(
                            [c["buffer"] for c in decoded], is_local
                        )
                    except Exception as exc:
                        if not doc_mode:
                            raise
                        # the walk bootstrap/apply may be mid-flight;
                        # rebuild lazily from the committed log
                        self.exact[d] = None
                        quarantine(d, exc)

        # snapshot + columnar verdicts: the whole delivery's gate decisions
        # (commit order / deferrals) come from one dep-column program per
        # doc (transcode.gate_verdicts); docs the columns cannot express
        # re-route through the scalar oracle below, which owns the
        # canonical result/error. Batch isolation keeps the historical
        # all-scalar behaviour (one raise aborts the call).
        use_columnar = doc_mode and self.gate_mode == "columnar"
        col_cuts: dict[int, tuple] = {}
        mirror_pre: dict[int, tuple] = {}
        with prof.phase("gate_verdicts"):
            if doc_mode:
                for d, decoded in enumerate(per_doc_decoded):
                    if decoded:
                        snapshots[d] = self._snapshot_doc(d)
            if use_columnar:
                plans, scalar_docs = self._gate_verdict_columns(per_doc_decoded)
            else:
                plans, scalar_docs = {}, range(self.num_docs)

        with prof.phase("transcode_columns"):
            for d, plan in plans.items():
                try:
                    self._transcode_columns(
                        d, plan, per_doc_arrays, applied_ops,
                        touched_objects, applied_changes, col_cuts,
                        mirror_pre,
                    )
                except Exception as exc:
                    self.exact[d] = None
                    col_cuts.pop(d, None)
                    mirror_pre.pop(d, None)
                    quarantine(d, exc)

        with prof.phase("gate+transcode"):
            for d in scalar_docs:
                decoded = per_doc_decoded[d]
                pending = decoded + self.queue[d] if self.queue[d] else decoded
                gate_batch = 0
                try:
                    while True:
                        applied, pending = self._gate_round(d, pending)
                        if not applied:
                            break
                        gate_batch += 1
                        # amlint: disable=AM107 — scalar-oracle transcode:
                        # docs land here only on gate_mode="oracle" or an
                        # anomaly re-route; the chain owns the canonical
                        # result and its offending_hashes
                        for change in applied:
                            ctr = change["startOp"]
                            # amlint: disable=AM107 — same oracle chain
                            for op in change["ops"]:
                                rows = self._op_rows(d, op, ctr, change["actor"])
                                per_doc_rows[d].extend(rows)
                                applied_ops[d].append(
                                    (op, ctr, change["actor"], gate_batch)
                                )
                                touched_objects[d].add(op["obj"])
                                ctr += 1
                            self.max_op[d] = max(self.max_op[d], ctr - 1)
                            applied_changes[d].append(change)
                            # commit immediately so later gate rounds (and
                            # later calls) see this hash as a satisfied
                            # dependency
                            self.changes[d].append(change["buffer"])
                            self.change_index_by_hash[d][change["hash"]] = (
                                len(self.changes[d]) - 1
                            )
                            by_actor = self.hashes_by_actor[d].setdefault(
                                change["actor"], []
                            )
                            while len(by_actor) < change["seq"]:
                                by_actor.append(None)
                            by_actor[change["seq"] - 1] = change["hash"]
                            self.dependencies_by_hash[d][change["hash"]] = list(
                                change["deps"]
                            )
                            self.dependents_by_hash[d].setdefault(change["hash"], [])
                            for dep in change["deps"]:
                                self.dependents_by_hash[d].setdefault(dep, []).append(
                                    change["hash"]
                                )
                        if not pending:
                            break
                    self.queue[d] = pending
                except Exception as exc:
                    if not doc_mode:
                        raise
                    # exact walk state (if any) committed the delivery the
                    # farm is rolling back; rebuild it lazily
                    self.exact[d] = None
                    quarantine(d, exc)

        if _METRICS.enabled:
            _M_WALKS.inc(len(exact_patches))
            _M_APPLIED.inc(sum(len(c) for c in applied_changes))
            delivered = {
                c["hash"] for decoded in per_doc_decoded for c in decoded
            }
            _M_DEFERRALS.inc(sum(
                1
                for d in range(self.num_docs)
                for c in self.queue[d]
                if c["hash"] in delivered
            ))

        # one device merge for the ACTIVE docs only: the paged engine
        # gathers just their rows from the slab, so idle documents cost
        # neither HBM traffic nor kernel work. Columnar-gated docs already
        # carry their dense row arrays (cached column blocks); scalar-gated
        # docs densify their row lists here.
        device_failed = False
        for d, rows in enumerate(per_doc_rows):
            if rows and per_doc_arrays[d] is None:
                per_doc_arrays[d] = np.asarray(rows, np.int64)
        width = max(
            (a.shape[0] for a in per_doc_arrays if a is not None), default=0
        )
        active = ()
        if width > 0:
            active = tuple(
                d for d in range(self.num_docs)
                if per_doc_arrays[d] is not None
            )
            if _METRICS.enabled:
                # pad waste is measured over the ACTIVE docs' cells: idle
                # documents no longer ride the dispatch at all (the paged
                # engine gathers only active rows), and the pow2 doc-count
                # bucket is the bounded price of shape caching, not waste
                rows = sum(per_doc_arrays[d].shape[0] for d in active)
                cells = len(active) * width
                _M_ROWS.inc(rows)
                _M_PAD_ROWS.inc(cells - rows)
                _M_PAD_RATIO.set(1.0 - rows / cells)
                _M_OCCUPANCY.observe(rows / cells)
            with prof.phase("pack"):
                batch, counts = self._pack_subset(
                    per_doc_arrays, active, width=width
                )
            with prof.phase("device_dispatch"):
                try:
                    _fault_point("farm.device_dispatch", docs=active)
                    dispatch_t0 = time.perf_counter()
                    self.engine.apply_batch(batch, docs=active, counts=counts)
                    if _METRICS.enabled:
                        _M_DISPATCH_MS.observe(
                            (time.perf_counter() - dispatch_t0) * 1000.0,
                            exemplar=current_exemplar(),
                        )
                except Exception as exc:
                    if not doc_mode:
                        raise
                    # Degraded mode: the batched device path is gone for
                    # this call. Bisect to find the doc(s) whose rows
                    # poison the program; quarantine them (host state
                    # rolled back) and serve every survivor through the
                    # sequential reference walk below.
                    device_failed = True
                    _M_FB_CALLS.inc()
                    if _FLIGHT.enabled:
                        _FLIGHT.record("farm.device_fault",
                                       docs=list(active), error=str(exc))
                        _FLIGHT.trigger("farm.device_fault")
                    poison = self._bisect_device_faults(per_doc_arrays, active)
                    for d in sorted(poison):
                        quarantine(d, DeviceFaultError(
                            f"batched device dispatch fails with document "
                            f"{d}'s rows in the batch: {exc}"
                        ))
                    fallback_docs.update(d for d in active if d not in poison)

        if device_failed:
            with prof.phase("fallback_walk"):
                for d in sorted(fallback_docs):
                    try:
                        if d in exact_patches:
                            # the walk already produced this call's patch;
                            # just pin the doc to walk-served mode
                            self.degraded.add(d)
                        else:
                            exact_patches[d] = self._fallback_walk(
                                d,
                                snapshots.get(d),
                                [c["buffer"] for c in per_doc_decoded[d]],
                                is_local,
                            )
                        _M_FB_DOCS.inc()
                    except Exception as exc:
                        quarantine(d, exc)

        # no-op deliveries (all queued or duplicates) need no device work;
        # after a device failure nothing may touch the device again this
        # call (every doc with applied rows is fallback- or quarantine-
        # served, so the remaining docs' patches are device-independent)
        need_device_patch = [
            d for d in range(self.num_docs)
            if d not in exact_patches and d not in failures
        ]
        emit_info: dict[int, tuple] = {}
        with prof.phase("visibility"):
            if width > 0 and not device_failed:
                # replicate the committed merge on the host mirror (exact
                # device row order, no transfer), then refresh the stale
                # (doc, slot) visibility spans with one scoped gather.
                # Docs whose whole delivery is a single cached columnar
                # change on counter-free, child-free state take the FUSED
                # program: visibility + row gather + patch emit mask in one
                # dispatch (engine.read_patch_columns), leaving only
                # column->JSON materialisation for patch assembly.
                for d, arr in enumerate(per_doc_arrays):
                    if arr is not None:
                        self._merge_mirror(d, arr, pre=mirror_pre.get(d))
                vis_docs = [
                    d for d in need_device_patch
                    if per_doc_arrays[d] is not None
                ]
                fast = []
                if not self._child_value_ids:
                    fast = [
                        d for d in vis_docs
                        if d in col_cuts
                        and not self.counter_ops[d]
                        and not self.children[d]
                    ]
                if fast:
                    emit_info = self._refresh_patch_columns(fast, col_cuts)
                self._refresh_visibility(
                    [d for d in vis_docs if d not in emit_info]
                )
        with prof.phase("patch_assembly"):
            patches = []
            outcomes = []
            for d in range(self.num_docs):
                if d in failures:
                    exc = failures[d]
                    patches.append(self._noop_patch(d))
                    outcomes.append(DocOutcome(
                        "quarantined",
                        error=exc,
                        error_kind=error_kind(exc),
                        offending_hashes=tuple(
                            getattr(exc, "offending_hashes", ())
                        ),
                    ))
                    continue
                if d in attempted:
                    self.fault_counts[d] = 0  # a clean delivery ends the streak
                outcomes.append(
                    _APPLIED_FALLBACK if d in fallback_docs else _APPLIED
                )
                if d in exact_patches:
                    patches.append(exact_patches[d])
                    continue
                if d in emit_info:
                    idx_e, emit_e = emit_info[d]
                    diffs = self._build_diffs_columns(
                        d, idx_e, emit_e, col_cuts[d][0], touched_objects[d]
                    )
                elif d in col_cuts:
                    diffs = self._build_diffs(
                        d, self._cutoffs_from_cols(col_cuts[d]),
                        touched_objects[d],
                    )
                else:
                    cutoffs = self._compute_cutoffs(d, applied_ops[d])
                    diffs = self._build_diffs(d, cutoffs, touched_objects[d])
                patch = {
                    "maxOp": self.max_op[d],
                    "clock": self.clock[d],
                    "deps": self.heads[d],
                    "pendingChanges": len(self.queue[d]),
                    "diffs": diffs,
                }
                if (
                    is_local
                    and len(per_doc_buffers[d]) == 1
                    and applied_changes[d]
                ):
                    patch["actor"] = applied_changes[d][0]["actor"]
                    patch["seq"] = applied_changes[d][0]["seq"]
                patches.append(patch)
        if self.store is not None:
            # acked ⇒ durable: commits reach the WAL and the group-commit
            # fsync barrier BEFORE patches leave this call. A store failure
            # here raises out of apply_changes — the caller never sees an
            # ack the log cannot replay.
            with prof.phase("store_commit"):
                for d in attempted:
                    tail = self.changes[d][store_marks[d]:]
                    if tail:
                        self.store.append_commit(d, tail)
                self.store.commit_barrier(self._store_quarantine_snapshot())
        return FarmApplyResult(patches, outcomes)

    # ------------------------------------------------------------------ #
    # persistence (automerge_tpu/store): the WAL rides the ack boundary

    def attach_store(self, store) -> None:
        """Attaches a ``ShardStore``: every committed delivery is appended
        to its WAL and made durable before ``apply_changes`` returns, and
        quarantine transitions persist to the store's sidecar. Hydrate the
        farm from the store FIRST (``store.hydrate.open_farm`` does both in
        order) — attached commits are logged, hydration must not be."""
        self.store = store
        # seed the sidecar so pre-existing quarantine state survives even
        # if no further delivery ever arrives
        store.save_quarantine(self._store_quarantine_snapshot())

    def _store_quarantine_snapshot(self) -> dict:
        from ..store.hydrate import quarantine_snapshot

        return quarantine_snapshot(self)

    # ------------------------------------------------------------------ #
    # fault domains: snapshot/rollback, quarantine, degraded-mode fallback

    def _snapshot_doc(self, d: int) -> dict:
        """Captures doc `d`'s mutable host state before the commit phase.
        Containers the gate replaces wholesale (heads/clock/queue) are kept
        by reference; containers it mutates in place are shallow-copied.
        The element arrays need only their live count: rows past
        num_elems[d] are dead (masked by the valid range) and the next
        insert overwrites them."""
        return {
            "object_meta": dict(self.object_meta[d]),
            "clock": self.clock[d],
            "heads": self.heads[d],
            "queue": self.queue[d],
            "changes_len": len(self.changes[d]),
            "change_index": dict(self.change_index_by_hash[d]),
            "hashes_by_actor": {
                k: list(v) for k, v in self.hashes_by_actor[d].items()
            },
            "deps_by_hash": {
                k: list(v) for k, v in self.dependencies_by_hash[d].items()
            },
            "dependents": {
                k: list(v) for k, v in self.dependents_by_hash[d].items()
            },
            "max_op": self.max_op[d],
            "counter_ops": set(self.counter_ops[d]),
            "inc_max": dict(self.inc_max[d]),
            "starved": set(self.starved[d]),
            "num_elems": int(self.num_elems[d]),
            "elem_index": dict(self.elem_index[d]),
            "elem_ids": list(self.elem_ids[d]),
            "elem_object": list(self.elem_object[d]),
            # paged op storage: the doc's slab pages + live row count, so
            # rollback returns any since-acquired pages to the allocator
            # instead of leaking them
            "pages": tuple(self.engine.page_table[d]),
            "page_rows": int(self.engine.lengths[d]),
        }

    def _restore_doc(self, d: int, snap: dict,
                     stale_slots=None) -> None:
        """Rolls doc `d` back to its snapshot (quarantine path). Shared
        interner entries created by the rolled-back transcode are left
        behind deliberately: they are append-only lookup tables, never
        document state.

        `stale_slots` scopes the visibility invalidation to the slots the
        failed delivery actually touched: the delivery never reached the
        mirror or the device (both commit only after every doc's gate), so
        the rest of the doc's cached spans are still exact. None keeps the
        conservative whole-doc invalidation for callers without span
        knowledge."""
        self.object_meta[d] = snap["object_meta"]
        self.clock[d] = snap["clock"]
        self.heads[d] = snap["heads"]
        self.queue[d] = snap["queue"]
        del self.changes[d][snap["changes_len"]:]
        self.change_index_by_hash[d] = snap["change_index"]
        self.hashes_by_actor[d] = snap["hashes_by_actor"]
        self.dependencies_by_hash[d] = snap["deps_by_hash"]
        self.dependents_by_hash[d] = snap["dependents"]
        self.max_op[d] = snap["max_op"]
        self.counter_ops[d] = snap["counter_ops"]
        self.inc_max[d] = snap["inc_max"]
        self.starved[d] = snap["starved"]
        self.num_elems[d] = snap["num_elems"]
        self.elem_index[d] = snap["elem_index"]
        self.elem_ids[d] = snap["elem_ids"]
        self.elem_object[d] = snap["elem_object"]
        self.engine.restore_doc(d, snap["pages"], snap["page_rows"])
        # a rolled-back delivery must never be served stale visibility
        if stale_slots is None:
            self._vis_all_stale[d] = True
            self._vis_stale[d].clear()
        elif not self._vis_all_stale[d]:
            self._vis_stale[d].update(int(s) for s in stale_slots)

    def _noop_patch(self, d: int) -> dict:
        """The patch of a delivery that changed nothing (quarantined/shed):
        current clock/heads, empty diffs."""
        return {
            "maxOp": self.max_op[d],
            "clock": self.clock[d],
            "deps": self.heads[d],
            "pendingChanges": len(self.queue[d]),
            "diffs": _empty_object_patch("_root", "map"),
        }

    def _pack_subset(self, per_doc_arrays, docs, width=None):
        """Packs the given docs' dense row column arrays ([n, 5] int64 of
        (slot, op, action, value, pred); None for empty docs) into a
        pow2-doc-padded ChangeOpsBatch [A_pad, width] by whole-column
        assignment. Returns (batch, per-doc real row counts) — the paged
        engine needs the counts to size page allocations host-side."""
        docs = list(docs)
        arrays = [per_doc_arrays[d] for d in docs]
        if width is None:
            width = max(
                (a.shape[0] for a in arrays if a is not None), default=0
            ) or 1
        a_pad = 1 << max(0, len(docs) - 1).bit_length()
        keys = np.full((a_pad, width), PAD_KEY, np.int32)
        ops = np.zeros((a_pad, width), np.int64)
        actions = np.zeros((a_pad, width), np.int32)
        values = np.zeros((a_pad, width), np.int64)
        preds = np.full((a_pad, width), -1, np.int64)
        counts = np.zeros(len(docs), np.int64)
        for k, arr in enumerate(arrays):
            if arr is None:
                continue
            n = arr.shape[0]
            counts[k] = n
            keys[k, :n] = arr[:, 0]
            ops[k, :n] = arr[:, 1]
            actions[k, :n] = arr[:, 2]
            values[k, :n] = arr[:, 3]
            preds[k, :n] = arr[:, 4]
        return changes_from_numpy(keys, ops, actions, values, preds), counts

    def _bisect_device_faults(self, per_doc_arrays, active):
        """Isolates the doc(s) whose rows crash the batched device program
        by bisection: each probe runs a subset's rows through the merge on
        a throwaway basis (engine.probe_apply — no scatter, the slab is
        never advanced). Returns the poison doc set; `farm.bisect.rounds`
        counts probes."""

        def probe_ok(group):
            _M_BISECT.inc()
            try:
                _fault_point("farm.device_dispatch", docs=tuple(group))
                batch, counts = self._pack_subset(per_doc_arrays, group)
                self.engine.probe_apply(batch, group, counts)
                return True
            except Exception:
                return False

        poison = set()
        stack = [sorted(active)]
        while stack:
            group = stack.pop()
            if probe_ok(group):
                continue
            if len(group) == 1:
                poison.add(group[0])
                continue
            mid = len(group) // 2
            stack.append(group[:mid])
            stack.append(group[mid:])
        if poison == set(active):
            # every doc "poison" means the device itself is down, not the
            # data: blame nobody and serve the whole batch sequentially
            return set()
        return poison

    def _fallback_walk(self, d, snap, delivered_buffers, is_local):
        """Serves one device-failure survivor through the sequential
        reference walk: replays the doc's pre-call committed log and queue
        into a fresh OpSet, applies this call's delivery for the patch, and
        pins the doc to walk-served (degraded) mode from now on — its
        device rows are stale after the lost dispatch, so the embedded
        walk becomes authoritative for patches AND whole-doc reads
        (get_patch)."""
        opset = OpSet()
        committed = (
            self.changes[d][: snap["changes_len"]]
            if snap is not None
            else list(self.changes[d])
        )
        if committed:
            opset.apply_changes(list(committed))
        queued = snap["queue"] if snap is not None else self.queue[d]
        # amlint: disable=AM107 — reference-walk parity replay, cold by
        # construction (list/text docs only)
        for change in queued:
            opset.apply_changes([change["buffer"]])
        patch = opset.apply_changes(list(delivered_buffers), is_local)
        self.exact[d] = opset
        self.degraded.add(d)
        return patch

    def release_quarantine(self, doc: int | None = None):
        """Returns quarantined doc(s) to service (all of them when `doc` is
        None) and resets their failure streaks. Returns the released doc
        indexes."""
        docs = list(self.quarantine) if doc is None else [doc]
        released = []
        for d in docs:
            if d in self.quarantine:
                del self.quarantine[d]
                self.fault_counts[d] = 0
                released.append(d)
                _M_Q_RELEASED.inc()
        _M_Q_ACTIVE.set(len(self.quarantine))
        if released and _FLIGHT.enabled:
            _FLIGHT.record("farm.quarantine.release", docs=released)
        if released and self.store is not None:
            self.store.save_quarantine(self._store_quarantine_snapshot())
        return released

    # ------------------------------------------------------------------ #
    # cross-farm migration (parallel/meshfarm.py): a document moves between
    # farms as whole pages. Interner id spaces are farm-local, so the
    # export carries the source tables and adopt translates every id —
    # actors by whole-table remap (the same union a reconcile pass
    # produces), slots/values only where the doc references them (their
    # tables are packing ranges / unbounded payload tables that must not
    # import other docs' entries).

    def export_doc(self, d: int) -> dict:
        """Self-contained snapshot of doc `d` for migration to another
        farm. Row columns and packed-opid host state are in THIS farm's id
        space; the interner tables ride along by reference (they are
        append-only and the importer only reads them). Mutable host
        containers are copied, so the export stays valid after
        ``evict_doc``."""
        keys, ops, actions, values, preds, overs = self.engine.dense_view([d])
        n = int(self.engine.lengths[d])
        return {
            "rows": {
                "key": np.asarray(keys[0][:n], np.int64),
                "op": np.asarray(ops[0][:n], np.int64),
                "action": np.asarray(actions[0][:n], np.int64),
                "value": np.asarray(values[0][:n], np.int64),
                "pred": np.asarray(preds[0][:n], np.int64),
                "overwritten": np.asarray(overs[0][:n], bool),
            },
            "actor_table": list(self.actors.table),
            "slot_table": list(self.slots.table),
            "value_table": list(self.values.table),
            "object_meta": dict(self.object_meta[d]),
            "clock": dict(self.clock[d]),
            "heads": list(self.heads[d]),
            "queue": list(self.queue[d]),
            "changes": list(self.changes[d]),
            "change_index": dict(self.change_index_by_hash[d]),
            "hashes_by_actor": {
                k: list(v) for k, v in self.hashes_by_actor[d].items()
            },
            "deps_by_hash": {
                k: list(v) for k, v in self.dependencies_by_hash[d].items()
            },
            "dependents": {
                k: list(v) for k, v in self.dependents_by_hash[d].items()
            },
            "max_op": self.max_op[d],
            "counter_ops": set(self.counter_ops[d]),
            "inc_max": dict(self.inc_max[d]),
            "starved": set(self.starved[d]),
            # children re-keyed symbolically: slot ids are farm-local but
            # the interned (objectId, key) tuples are globally meaningful
            "children": {
                self.slots.lookup(s): dict(v)
                for s, v in self.children[d].items()
            },
            "num_elems": int(self.num_elems[d]),
            "elem_opid": self.elem_opid[d, : int(self.num_elems[d])].copy(),
            "elem_parent": self.elem_parent[d, : int(self.num_elems[d])].copy(),
            "elem_index": dict(self.elem_index[d]),
            "elem_ids": list(self.elem_ids[d]),
            "elem_object": list(self.elem_object[d]),
            "exact": self.exact[d],
            "fault_count": self.fault_counts[d],
            "quarantine": self.quarantine.get(d),
            "degraded": d in self.degraded,
        }

    def adopt_doc(self, d: int, export: dict) -> None:
        """Installs an exported document as doc `d` (which must be empty):
        translates every interner id into this farm's tables, re-sorts the
        rows by the destination merge key (stable, so multi-pred marker
        rows keep sorting directly after their primary), scatters them
        into freshly allocated pages, and rebuilds the host mirror. The
        visible/total cache starts stale and refreshes on the next read."""
        assert not self.changes[d] and not self.engine.page_table[d], (
            "adopt_doc target must be an empty doc slot"
        )
        rows = export["rows"]
        n = int(rows["key"].shape[0])
        src_actors = export["actor_table"]
        amap = np.fromiter(
            (self.actors.intern(a) for a in src_actors),
            np.int64, count=len(src_actors),
        )
        slot_table = export["slot_table"]
        used_s = np.unique(rows["key"]) if n else np.zeros(0, np.int64)
        smap = np.zeros(
            int(used_s.max()) + 1 if used_s.size else 1, np.int64
        )
        smap[used_s] = np.fromiter(
            (self.slots.intern(slot_table[s]) for s in used_s.tolist()),
            np.int64, count=used_s.size,
        )
        # value ids live only in non-counter SET primaries — markers carry
        # zero, counter SET/INC rows carry raw integers (see _op_rows)
        value_table = export["value_table"]
        op_col = np.asarray(rows["op"], np.int64)
        action = np.asarray(rows["action"], np.int64)
        ctr_ops = export["counter_ops"]
        if ctr_ops and n:
            is_ctr = np.isin(
                op_col, np.fromiter(ctr_ops, np.int64, count=len(ctr_ops))
            )
        else:
            is_ctr = np.zeros(n, bool)
        val_mask = (action == ACTION_SET) & ~is_ctr
        value = np.asarray(rows["value"], np.int64).copy()
        used_v = (
            np.unique(value[val_mask]) if val_mask.any()
            else np.zeros(0, np.int64)
        )
        vmap = np.zeros(
            int(used_v.max()) + 1 if used_v.size else 1, np.int64
        )
        for v in used_v.tolist():
            cell = value_table[v]
            nid = self.values.intern(cell)
            if isinstance(cell, ChildObj):
                self._child_value_ids.add(nid)
            vmap[v] = nid
        value[val_mask] = vmap[value[val_mask]]
        key = smap[np.asarray(rows["key"], np.int64)]
        op = _remap_packed(op_col, amap)
        pred = _remap_packed(np.asarray(rows["pred"], np.int64), amap)
        over = np.asarray(rows["overwritten"], bool)
        mkey = (key << _MKEY_OP_BITS) | op
        order = np.argsort(mkey, kind="stable")
        self.engine.adopt_rows(
            d, key[order].astype(np.int32), op[order],
            action[order].astype(np.int32), value[order], pred[order],
            over[order],
        )
        # symbolic host state moves as-is; packed-opid fields ride the
        # actor remap; children re-key to this farm's slot ids
        self.object_meta[d] = export["object_meta"]
        self.clock[d] = export["clock"]
        self.heads[d] = export["heads"]
        self.queue[d] = export["queue"]
        self.changes[d] = export["changes"]
        self.change_index_by_hash[d] = export["change_index"]
        self.hashes_by_actor[d] = export["hashes_by_actor"]
        self.dependencies_by_hash[d] = export["deps_by_hash"]
        self.dependents_by_hash[d] = export["dependents"]
        self.max_op[d] = export["max_op"]
        if ctr_ops:
            ctr_arr = _remap_packed(
                np.fromiter(ctr_ops, np.int64, count=len(ctr_ops)), amap
            )
            self.counter_ops[d] = set(ctr_arr.tolist())
        else:
            self.counter_ops[d] = set()
        self.inc_max[d] = {
            _remap_packed_one(k, amap): v
            for k, v in export["inc_max"].items()
        }
        self.starved[d] = {
            _remap_packed_one(k, amap) for k in export["starved"]
        }
        self.children[d] = {
            self.slots.intern(sk): dict(v)
            for sk, v in export["children"].items()
        }
        ne = export["num_elems"]
        self._grow_elems(ne)
        self.num_elems[d] = ne
        self.elem_opid[d, :ne] = _remap_packed(export["elem_opid"], amap)
        self.elem_parent[d, :ne] = export["elem_parent"]
        self.elem_index[d] = export["elem_index"]
        self.elem_ids[d] = export["elem_ids"]
        self.elem_object[d] = export["elem_object"]
        self.exact[d] = export["exact"]
        self.fault_counts[d] = export["fault_count"]
        if export["quarantine"] is not None:
            self.quarantine[d] = export["quarantine"]
        else:
            self.quarantine.pop(d, None)
        if export["degraded"]:
            self.degraded.add(d)
        else:
            self.degraded.discard(d)
        # host mirror: static columns from the translated rows, the
        # visible/total cache conservatively marked whole-doc stale
        self._vis_mkey[d] = mkey[order]
        self._vis_key[d] = key[order].astype(np.int32)
        self._vis_op[d] = op[order]
        self._vis_action[d] = action[order].astype(np.int32)
        self._vis_visible[d] = np.zeros(n, bool)
        self._vis_total[d] = np.zeros(n, np.int64)
        self._vis_all_stale[d] = bool(n)
        self._vis_stale[d] = set()

    def evict_doc(self, d: int) -> None:
        """Resets doc `d` to the fresh-document state and returns its slab
        pages to the allocator (the source half of migration; the export
        was taken first). Interner entries stay — they are append-only
        shared lookup tables, never document state."""
        self.engine.evict_doc(d)
        self.object_meta[d] = {"_root": dict(_ROOT_META)}
        self.clock[d] = {}
        self.heads[d] = []
        self.queue[d] = []
        self.changes[d] = []
        self.change_index_by_hash[d] = {}
        self.hashes_by_actor[d] = {}
        self.dependencies_by_hash[d] = {}
        self.dependents_by_hash[d] = {}
        self.max_op[d] = 0
        self.counter_ops[d] = set()
        self.inc_max[d] = {}
        self.starved[d] = set()
        self.children[d] = {}
        self.num_elems[d] = 0
        self.elem_index[d] = {}
        self.elem_ids[d] = []
        self.elem_object[d] = []
        self.exact[d] = None
        self.fault_counts[d] = 0
        self.quarantine.pop(d, None)
        self.degraded.discard(d)
        self._vis_mkey[d] = np.empty(0, np.int64)
        self._vis_key[d] = np.empty(0, np.int32)
        self._vis_op[d] = np.empty(0, np.int64)
        self._vis_action[d] = np.empty(0, np.int32)
        self._vis_visible[d] = np.empty(0, bool)
        self._vis_total[d] = np.empty(0, np.int64)
        self._vis_stale[d] = set()
        self._vis_all_stale[d] = False

    # ------------------------------------------------------------------ #
    # incremental visibility: host row mirror + scoped device readback
    #
    # The host transcoded every dispatched row and the device merge insert
    # position is a pure function of the sorted merge keys
    # (engine._merge_one_doc: left-searchsorted + stable order), so the
    # static row columns (key, packed opId, action) are replicated on the
    # host with zero device traffic. Only the merge-DEPENDENT columns —
    # per-row visibility and counter totals — come from the device, and
    # only for the (doc, slot) spans invalidated since they were last read:
    # a delivery touching 3 objects in 2 documents reads back a handful of
    # rows, not the whole farm state.

    def _merge_mirror(self, d, arr, pre=None):
        """Replays a committed device merge on doc `d`'s host mirror.
        `arr` is the [n, 5] (slot, op, action, value, pred) column array
        this call dispatched; rows land at exactly the device's insert
        positions (stable sort + left-searchsorted, so multi-pred marker
        rows keep sorting directly after their primary).

        `pre` optionally carries the change's cached merge-key-sorted
        columns (_ChangeCols.sorted_cols) so the sort and column casts are
        amortised across every doc the change was gossiped to; the weave
        itself is two whole-column fills per column instead of six
        np.inserts."""
        if pre is None:
            mkey = (arr[:, 0] << _MKEY_OP_BITS) | arr[:, 1]
            order = np.argsort(mkey, kind="stable")
            pre = (
                mkey[order],
                arr[order, 0].astype(np.int32),
                arr[order, 1],
                arr[order, 2].astype(np.int32),
                np.unique(arr[:, 0]),
            )
        mkey_s, key32, opcol, act32, uniq = pre
        old = self._vis_mkey[d]
        m = mkey_s.shape[0]
        if old.shape[0] == 0:
            # fresh doc: the cached sorted columns ARE the mirror (shared
            # across docs; mirror columns are only ever replaced wholesale
            # or scatter-written into visible/total, which are fresh here)
            self._vis_mkey[d] = mkey_s
            self._vis_key[d] = key32
            self._vis_op[d] = opcol
            self._vis_action[d] = act32
            self._vis_visible[d] = np.zeros(m, bool)
            self._vis_total[d] = np.zeros(m, np.int64)
        else:
            pos = np.searchsorted(old, mkey_s)
            total = old.shape[0] + m
            new_pos = pos + np.arange(m)
            keep = np.ones(total, bool)
            keep[new_pos] = False

            def weave(old_col, new_col, dtype):
                out = np.empty(total, dtype)
                out[keep] = old_col
                out[new_pos] = new_col
                return out

            self._vis_mkey[d] = weave(old, mkey_s, np.int64)
            self._vis_key[d] = weave(self._vis_key[d], key32, np.int32)
            self._vis_op[d] = weave(self._vis_op[d], opcol, np.int64)
            self._vis_action[d] = weave(self._vis_action[d], act32, np.int32)
            # placeholders until the scoped readback refreshes these spans
            self._vis_visible[d] = weave(self._vis_visible[d], False, bool)
            self._vis_total[d] = weave(self._vis_total[d], 0, np.int64)
        if not self._vis_all_stale[d]:
            self._vis_stale[d].update(uniq.tolist())

    def _refresh_visibility(self, docs):
        """Brings the visibility cache of `docs` up to date: ONE batched
        device gather covering exactly the stale (doc, slot) spans. Fresh
        docs cost nothing; in the steady state only the rows a delivery
        touched cross the device boundary."""
        plan = []
        gathered = 0
        live = 0
        for d in docs:
            mkey = self._vis_mkey[d]
            if mkey.shape[0] == 0:
                self._vis_all_stale[d] = False
                self._vis_stale[d].clear()
                continue
            live += mkey.shape[0]
            if self._vis_all_stale[d]:
                idx = np.arange(mkey.shape[0])
            elif self._vis_stale[d]:
                slots = np.fromiter(
                    self._vis_stale[d], np.int64, len(self._vis_stale[d])
                )
                slots.sort()
                _, _, idx, _ = ragged_spans(mkey, slots)
            else:
                if _METRICS.enabled:
                    _M_RB_HITS.inc(self._live_slot_count(d))
                continue
            if _METRICS.enabled:
                fresh = self._live_slot_count(d) - (
                    0 if self._vis_all_stale[d] else len(self._vis_stale[d])
                )
                _M_RB_HITS.inc(max(fresh, 0))
            plan.append((d, idx))
            gathered += idx.shape[0]
        if _METRICS.enabled:
            _M_RB_ROWS.inc(gathered)
            _M_RB_SKIPPED.inc(live - gathered)
        if not plan:
            return
        rank = self._actor_rank() if self.actors.table else None
        readback_t0 = time.perf_counter()
        visible, totals = self.engine.read_visibility_rows(
            plan, actor_rank=rank
        )
        if _METRICS.enabled:
            _M_READBACK_MS.observe(
                (time.perf_counter() - readback_t0) * 1000.0,
                exemplar=current_exemplar(),
            )
        offset = 0
        for d, idx in plan:
            n = idx.shape[0]
            self._vis_visible[d][idx] = visible[offset:offset + n]
            self._vis_total[d][idx] = totals[offset:offset + n]
            offset += n
            self._vis_all_stale[d] = False
            self._vis_stale[d].clear()

    def _refresh_patch_columns(self, docs, col_cuts):
        """The fused fast path of `_refresh_visibility`: one device program
        (engine.read_patch_columns) refreshes the stale spans AND emits the
        patch mask for this delivery's cutoff slots, so patch assembly
        needs no host-side walk-order sort or visibility filter. Per
        refreshed row the walk cutoff rides along as a rank-packed int64
        (-1 = the row's slot is outside the delivery's cutoff set; int64
        max = walk to the end of the key run). Returns {doc: (idx, emit)}
        for the docs actually refreshed."""
        plan = []
        gathered = 0
        live = 0
        rank = self._actor_rank()
        inf = np.iinfo(np.int64).max
        for d in docs:
            mkey = self._vis_mkey[d]
            if mkey.shape[0] == 0:
                self._vis_all_stale[d] = False
                self._vis_stale[d].clear()
                continue
            live += mkey.shape[0]
            if self._vis_all_stale[d]:
                idx = np.arange(mkey.shape[0])
            elif self._vis_stale[d]:
                slots = np.fromiter(
                    self._vis_stale[d], np.int64, len(self._vis_stale[d])
                )
                slots.sort()
                _, _, idx, _ = ragged_spans(mkey, slots)
            else:
                # unreachable in practice (_merge_mirror just marked this
                # delivery's slots stale), kept for interface symmetry
                if _METRICS.enabled:
                    _M_RB_HITS.inc(self._live_slot_count(d))
                continue
            if _METRICS.enabled:
                fresh = self._live_slot_count(d) - (
                    0 if self._vis_all_stale[d] else len(self._vis_stale[d])
                )
                _M_RB_HITS.inc(max(fresh, 0))
            cut_slots, cut_packed = col_cuts[d]
            keys = self._vis_key[d][idx].astype(np.int64)
            pos = np.minimum(
                np.searchsorted(cut_slots, keys), len(cut_slots) - 1
            )
            matched = cut_slots[pos] == keys
            cp = cut_packed[pos]
            # cached cutoffs pack the actor INDEX; the device compares
            # lamport keys with actor RANK low bits — translate, keeping
            # the walk-to-end sentinel intact (its index bits are clipped:
            # np.where evaluates both branches)
            ai = np.minimum(cp & ACTOR_MASK, len(rank) - 1)
            cp = np.where(cp == inf, cp, (cp & ~ACTOR_MASK) | rank[ai])
            cut = np.where(matched, cp, -1)
            plan.append((d, idx, cut))
            gathered += idx.shape[0]
        if _METRICS.enabled:
            _M_RB_ROWS.inc(gathered)
            _M_RB_SKIPPED.inc(live - gathered)
        if not plan:
            return {}
        readback_t0 = time.perf_counter()
        visible, totals, emit = self.engine.read_patch_columns(
            plan, actor_rank=rank
        )
        if _METRICS.enabled:
            _M_READBACK_MS.observe(
                (time.perf_counter() - readback_t0) * 1000.0,
                exemplar=current_exemplar(),
            )
        out = {}
        offset = 0
        for d, idx, _cut in plan:
            n = idx.shape[0]
            self._vis_visible[d][idx] = visible[offset:offset + n]
            self._vis_total[d][idx] = totals[offset:offset + n]
            out[d] = (idx, emit[offset:offset + n])
            offset += n
            self._vis_all_stale[d] = False
            self._vis_stale[d].clear()
        if _METRICS.enabled:
            _M_DEV_COLS.inc(int(emit.sum()))
        return out

    def _live_slot_count(self, d):
        keys = self._vis_key[d]
        if keys.shape[0] == 0:
            return 0
        return int((keys[1:] != keys[:-1]).sum()) + 1

    # ------------------------------------------------------------------ #
    # patch assembly from the visibility mirror

    def _read_visibility(self):
        """Full-state readback — the reference path the incremental mirror
        is verified against (tests/test_parity_incremental.py): one batched
        ``jax.device_get`` of the whole visibility pytree plus a dense
        gather of the action column from the paged slab. Production paths
        use the mirror; this exists for whole-state debugging and the
        parity suite."""
        import jax

        keys, ops, visible, _winners, totals = self.engine.visible_state(
            actor_rank=self._actor_rank() if self.actors.table else None
        )
        keys, ops, visible, totals = jax.device_get(
            (keys, ops, visible, totals)
        )
        actions = self.engine.dense_view()[2]
        return keys, ops, visible, totals, actions

    def _slot_span(self, d, slot):
        mkey = self._vis_mkey[d]
        lo = np.searchsorted(mkey, np.int64(slot) << _MKEY_OP_BITS)
        hi = np.searchsorted(mkey, (np.int64(slot) + 1) << _MKEY_OP_BITS)
        return int(lo), int(hi)

    def _slot_rows(self, d, slot):
        """All walkable rows of one slot in reference walk order:
        [(packed, action, visible, total)], served from the host mirror
        (callers refresh first). Deletion rows and multi-pred marker rows
        are dropped as a column mask BEFORE any per-row materialisation —
        the reference stores deletions only as succ entries, so its walk
        never visits them. Walk order ties same-counter ops on the actor id
        STRING via the precomputed rank table, not a per-row sort key."""
        lo, hi = self._slot_span(d, slot)
        if lo == hi:
            return []
        span = slice(lo, hi)
        act = self._vis_action[d][span]
        keep = act != ACTION_DEL
        ops = self._vis_op[d][span][keep]
        if ops.shape[0] == 0:
            return []
        act = act[keep]
        vis = self._vis_visible[d][span][keep]
        tot = self._vis_total[d][span][keep]
        order = np.argsort(
            lamport_keys(ops, self._actor_rank()), kind="stable"
        )
        return [
            (int(o), int(a), bool(v), int(t))
            for o, a, v, t in zip(ops[order], act[order], vis[order], tot[order])
        ]

    def _visible_rows(self, d, slot):
        """[(packed_opid, value_total)] of visible set rows for one slot —
        the visible/action filters run as column masks before any rows are
        materialised into Python tuples."""
        lo, hi = self._slot_span(d, slot)
        if lo == hi:
            return []
        span = slice(lo, hi)
        mask = self._vis_visible[d][span] & (
            self._vis_action[d][span] == ACTION_SET
        )
        if not mask.any():
            return []
        ops = self._vis_op[d][span][mask]
        tot = self._vis_total[d][span][mask]
        order = np.argsort(
            lamport_keys(ops, self._actor_rank()), kind="stable"
        )
        return [(int(o), int(t)) for o, t in zip(ops[order], tot[order])]

    def _value_diff(self, d, patches, packed, total):
        """The valueDiff for one visible row (updatePatchProperty's values,
        new.js:884-1033)."""
        if packed in self.counter_ops[d]:
            return {"type": "value", "datatype": "counter", "value": total}
        cell = self.values.lookup(total)
        if isinstance(cell, ChildObj):
            child = cell.object_id
            if child not in patches:
                patches[child] = _empty_object_patch(
                    child, self.object_meta[d][child]["type"]
                )
            return patches[child]
        diff = {"type": "value", "value": cell.value}
        if cell.datatype is not None:
            diff["datatype"] = cell.datatype
        return diff

    def _ensure_patch(self, d, patches, object_id):
        if object_id not in patches:
            patches[object_id] = _empty_object_patch(
                object_id, self.object_meta[d][object_id]["type"]
            )
        return patches[object_id]

    def _counter_emits(self, d, packed, cutoff):
        """A counter emits only when its succ list drains during the walk:
        every inc targeting it must be walked (<= cutoff) and actually
        registered to it (not to a higher-opId conflicting counter)."""
        if packed in self.starved[d]:
            return False
        max_inc = self.inc_max[d].get(packed)
        return max_inc is None or max_inc <= cutoff

    def _cache_spec(self, d, packed, total):
        """Children-cache entry for one emitted row: the reference caches
        raw decoded values (counters with inc successors are filtered out by
        the caller, so `total` here is the raw value) and object stubs
        (new.js:426, updatePatchProperty's `values`)."""
        if packed in self.counter_ops[d]:
            return {"type": "value", "value": total, "datatype": "counter"}
        cell = self.values.lookup(total)
        if isinstance(cell, ChildObj):
            return ("child", cell.object_id)
        diff = {"type": "value", "value": cell.value}
        if cell.datatype is not None:
            diff["datatype"] = cell.datatype
        return diff

    def _children_cache_segment(self, d, slot, seg, ops, tot, spec, walked,
                                is_ctr):
        """Replays the walk's per-op children-cache updates for one slot
        from the assembly column masks.

        The reference re-evaluates `hasChild or prev_children` at EVERY
        walked op, reading the cache live (new.js:923-935): once a walk
        shrinks the cache to empty, later ops of the same walk can no
        longer update it (the gate reads the now-empty cache), so the final
        cache is order-dependent. Because the cached spec set only ever
        GROWS during one walk, the whole state machine collapses to three
        outcomes: a walked child spec anywhere re-opens the gate for good
        (cache := all walked specs); otherwise a truthy pre-existing cache
        updates to all walked specs when the FIRST walked op produced a
        spec, and sticks shut at {} when it did not; an absent/empty cache
        with no child stays untouched. Counters with inc successors never
        enter visibleOps (their succNum > 0) and inc ops enter visibleOps
        but not the cached values — both already excluded from `spec`."""
        s, e = seg
        if e == s or not walked[s]:
            return  # walked is a prefix of the lamport-ordered segment
        spec_idx = np.nonzero(spec[s:e])[0] + s
        has_child = False
        for j in spec_idx:
            if (is_ctr is None or not is_ctr[j]) and (
                int(tot[j]) in self._child_value_ids
            ):
                has_child = True
                break
        cache = self.children[d].get(slot)
        if has_child or (cache and spec[s]):
            self.children[d][slot] = {
                self._opid_str(int(ops[j])): self._cache_spec(
                    d, int(ops[j]), int(tot[j])
                )
                for j in spec_idx
            }
        elif cache:
            self.children[d][slot] = {}

    def _pack_lamport(self, cutoff, rank):
        """A (counter, actorId) lamport cutoff as an int64 comparable
        against the remapped lamport key column; _INF maps to int64 max."""
        ctr, actor = cutoff
        if ctr == float("inf"):
            return np.iinfo(np.int64).max
        idx = self.actors.find(actor)
        assert idx is not None, f"cutoff actor {actor!r} never interned"
        return (int(ctr) << ACTOR_BITS) | int(rank[idx])

    def _visible_sequence(self, d, ranks, obj):
        """One list object's visible elements in document order:
        [(elemId, winner_packed, total)] — device ranks give the order, the
        visibility mirror gives each element's surviving value."""
        n = int(self.num_elems[d])
        if n == 0:
            return []
        order = np.argsort(ranks[d, :n], kind="stable")
        seq = []
        for idx in order:
            idx = int(idx)
            if self.elem_object[d][idx] != obj:
                continue
            elem_id = self.elem_ids[d][idx]
            slot = self.slots.intern((obj, elem_id))
            best = None
            for packed, action, visible, total in self._slot_rows(d, slot):
                if not visible or action != ACTION_SET:
                    continue
                if packed in self.counter_ops[d] and packed in self.starved[d]:
                    continue
                if best is None or self._lamport(packed) > self._lamport(best[0]):
                    best = (packed, total)
            if best is not None:
                seq.append((elem_id, best[0], best[1]))
        return seq

    def _build_diffs(self, d, cutoffs, touched_objects):
        """Patch assembly for map-family docs from the visibility mirror.
        Docs that touch list/text objects never reach this path (they are
        served by the embedded reference walk; see apply_changes).

        The old per-slot inner loops are column operations here: slot spans
        come from one batched searchsorted pair (ragged_spans), walk order
        from a precomputed lamport sort-key column (lamport_keys — actor
        bits remapped to lexicographic ranks, replacing the per-row
        ``sort(key=...)`` callback), and the action/visibility/cutoff
        filters are boolean masks — per-row Python runs only for the rows
        that actually land in the patch."""
        patches = {"_root": _empty_object_patch("_root", "map")}

        if cutoffs:
            slot_list = sorted(cutoffs)
            slots = np.asarray(slot_list, np.int64)
            _, _, idx, grp = ragged_spans(self._vis_mkey[d], slots)
            act = self._vis_action[d][idx]
            # the reference walk never visits deletion/marker rows
            keep = act != ACTION_DEL
            idx = idx[keep]
            grp = grp[keep]
            act = act[keep]
            ops = self._vis_op[d][idx]
            vis = self._vis_visible[d][idx]
            tot = self._vis_total[d][idx]
            rank = self._actor_rank()
            lam = lamport_keys(ops, rank)
            order = np.argsort(
                (grp.astype(np.int64) << _MKEY_OP_BITS) | lam, kind="stable"
            )
            grp = grp[order]
            ops = ops[order]
            act = act[order]
            vis = vis[order]
            tot = tot[order]
            lam = lam[order]
            if _METRICS.enabled:
                _M_VECTOR_ROWS.inc(int(ops.shape[0]))

            cut = np.empty(len(slot_list), np.int64)
            for i, slot in enumerate(slot_list):
                cut[i] = self._pack_lamport(cutoffs[slot], rank)
            walked = lam <= cut[grp]
            emit = vis & (act == ACTION_SET) & walked
            spec = emit.copy()
            is_ctr = None
            if self.counter_ops[d]:
                ctr_arr = np.fromiter(
                    self.counter_ops[d], np.int64, len(self.counter_ops[d])
                )
                is_ctr = np.isin(ops, ctr_arr)
                # counters emit only once their succ list drains; the
                # children cache drops counters with ANY registered inc
                for j in np.nonzero(is_ctr & emit)[0]:
                    if not self._counter_emits(
                        d, int(ops[j]), cutoffs[slot_list[int(grp[j])]]
                    ):
                        emit[j] = False
                for j in np.nonzero(is_ctr & spec)[0]:
                    if int(ops[j]) in self.inc_max[d]:
                        spec[j] = False

            bounds = np.searchsorted(
                grp, np.arange(slots.shape[0] + 1)
            )
            # with no ChildObj ever interned the cache gate can never open
            # (has_child is impossible and no truthy cache can exist), so
            # the per-slot replay is skipped wholesale
            track_children = bool(self._child_value_ids) or bool(
                self.children[d]
            )
            for i, slot in enumerate(slot_list):
                obj, key = self.slots.lookup(slot)
                if obj not in self.object_meta[d]:
                    continue
                patch = self._ensure_patch(d, patches, obj)
                # each walk resets the key's conflict map (new.js:1000)
                props = patch["props"][key] = {}
                s, e = int(bounds[i]), int(bounds[i + 1])
                for j in np.nonzero(emit[s:e])[0] + s:
                    packed = int(ops[j])
                    props[self._opid_str(packed)] = self._value_diff(
                        d, patches, packed, int(tot[j])
                    )
                if track_children:
                    self._children_cache_segment(
                        d, slot, (s, e), ops, tot, spec, walked, is_ctr
                    )

        self._link_ancestors(d, patches, touched_objects)
        return patches["_root"]

    def _link_ancestors(self, d, patches, touched_objects):
        """Links touched objects up to the root (setupPatches, new.js:1461)
        — shared tail of `_build_diffs` and `_build_diffs_columns`."""
        for object_id in sorted(touched_objects):
            meta = self.object_meta[d].get(object_id)
            if meta is None:
                continue
            child_meta = None
            patch_exists = False
            while True:
                values = None
                if child_meta is not None:
                    slot = self.slots.intern((object_id, child_meta["parentKey"]))
                    values = self.children[d].get(slot) or {}
                has_children = child_meta is not None and len(values) > 0
                self._ensure_patch(d, patches, object_id)
                if child_meta is not None and has_children:
                    props = patches[object_id]["props"].setdefault(
                        child_meta["parentKey"], {}
                    )
                    for op_id, spec in values.items():
                        if op_id in props:
                            patch_exists = True
                        elif isinstance(spec, tuple):  # ("child", id)
                            child = spec[1]
                            if child not in patches:
                                patches[child] = _empty_object_patch(
                                    child, self.object_meta[d][child]["type"]
                                )
                            props[op_id] = patches[child]
                        else:
                            props[op_id] = spec
                if (
                    patch_exists
                    or not meta["parentObj"]
                    or (child_meta is not None and not has_children)
                ):
                    break
                child_meta = dict(meta, opId=object_id)
                object_id = meta["parentObj"]
                meta = self.object_meta[d][object_id]

    def _opid_str_cached(self, packed):
        s = self._opid_strs.get(packed)
        if s is None:
            s = f"{packed >> ACTOR_BITS}@{self.actors.lookup(packed & ACTOR_MASK)}"
            if len(self._opid_strs) < (1 << 16):
                self._opid_strs[packed] = s
        return s

    def _leaf_diff(self, value_id):
        """valueDiff for a plain (non-counter, non-ChildObj) interned value
        — the only kind the device-column path can emit (its eligibility
        gate excludes counter docs and farms with child values, making
        this equivalent to `_value_diff`). Templates are cached per value
        id and copied per emission (patch consumers may mutate them)."""
        tpl = self._leaf_tpls.get(value_id)
        if tpl is None:
            cell = self.values.lookup(value_id)
            tpl = {"type": "value", "value": cell.value}
            if cell.datatype is not None:
                tpl["datatype"] = cell.datatype
            if len(self._leaf_tpls) < (1 << 16):
                self._leaf_tpls[value_id] = tpl
        return dict(tpl)

    def _build_diffs_columns(self, d, idx, emit, cut_slots, touched_objects):
        """Patch assembly from DEVICE-emitted patch columns — the fast path
        for single-change columnar commits on counter-free, child-free
        state. The emit mask arrived with the fused visibility readback
        (engine.read_patch_columns), so the walk-order sort and the
        visibility/action/cutoff filters of `_build_diffs` have already
        happened on device; what remains is column -> JSON
        materialisation."""
        patches = {"_root": _empty_object_patch("_root", "map")}
        eidx = idx[emit]
        # mirror rows are merge-key (slot-major) ordered, so the emitted
        # keys arrive pre-grouped for the span searchsorted below
        keys = self._vis_key[d][eidx].astype(np.int64)
        ops = self._vis_op[d][eidx]
        tot = self._vis_total[d][eidx]
        if _METRICS.enabled:
            _M_VECTOR_ROWS.inc(int(idx.shape[0]))
        lo = np.searchsorted(keys, cut_slots).tolist()
        hi = np.searchsorted(keys, cut_slots + 1).tolist()
        ops_l = ops.tolist()
        tot_l = tot.tolist()
        meta = self.object_meta[d]
        opid_str = self._opid_str_cached
        leaf = self._leaf_diff
        for i, slot in enumerate(cut_slots.tolist()):
            obj, key = self.slots.lookup(slot)
            if obj not in meta:
                continue
            patch = self._ensure_patch(d, patches, obj)
            # each walk resets the key's conflict map (new.js:1000)
            props = patch["props"][key] = {}
            for j in range(lo[i], hi[i]):
                props[opid_str(ops_l[j])] = leaf(tot_l[j])
        self._link_ancestors(d, patches, touched_objects)
        return patches["_root"]

    # ------------------------------------------------------------------ #
    # whole-document patch (getPatch, new.js:2052)

    def get_patch(self, d: int):
        # degraded docs lost device rows to a failed dispatch; their
        # embedded walk is authoritative for whole-doc reads too
        if d in self.degraded and self.exact[d] is not None:
            return self.exact[d].get_patch()
        # whole-doc reads ride the same mirror: only this doc's stale
        # spans (if any) cross the device boundary
        self._refresh_visibility([d])
        ranks = (
            self._element_ranks() if int(self.num_elems[d]) > 0 else None
        )
        patches = {"_root": _empty_object_patch("_root", "map")}
        list_objects = set()
        slots_here = np.unique(self._vis_key[d]).tolist()
        for slot in slots_here:
            obj, key = self.slots.lookup(slot)
            if obj not in self.object_meta[d]:
                continue
            if self.object_meta[d][obj]["type"] in ("list", "text"):
                list_objects.add(obj)
                continue
            rows = [
                (packed, total)
                for packed, total in self._visible_rows(d, slot)
                if packed not in self.counter_ops[d]
                or self._counter_emits(d, packed, self._INF)
            ]
            if not rows:
                continue  # whole-doc patches omit empty props (new.js:1604)
            patch = self._ensure_patch(d, patches, obj)
            props = patch["props"].setdefault(key, {})
            for packed, total in rows:
                props[self._opid_str(packed)] = self._value_diff(
                    d, patches, packed, total
                )
        # list objects materialise as a full insert script in document
        # order (the whole-doc scan's edits, new.js:1604)
        from ..opset import append_edit

        for obj in sorted(list_objects):
            patch = self._ensure_patch(d, patches, obj)
            for index, (elem_id, packed, total) in enumerate(
                self._visible_sequence(d, ranks, obj)
            ):
                append_edit(patch["edits"], {
                    "action": "insert", "index": index, "elemId": elem_id,
                    "opId": self._opid_str(packed),
                    "value": self._value_diff(d, patches, packed, total),
                })
        return {
            "maxOp": self.max_op[d],
            "clock": self.clock[d],
            "deps": self.heads[d],
            "pendingChanges": len(self.queue[d]),
            "diffs": patches["_root"],
        }

    # ------------------------------------------------------------------ #
    # hash-graph queries (backend.js facade parity)

    def get_heads(self, d: int):
        return list(self.heads[d])

    def get_all_changes(self, d: int):
        return list(self.changes[d])

    def get_change_by_hash(self, d: int, hash_: str):
        index = self.change_index_by_hash[d].get(hash_)
        return self.changes[d][index] if index is not None else None

    def get_changes(self, d: int, have_deps):
        """Changes a replica holding `have_deps` is missing (getChanges,
        new.js:1913): walk forward from have_deps through the dependents
        graph; if that cannot reach all heads, fall back to everything not
        in have_deps' ancestor closure."""
        if not have_deps:
            return list(self.changes[d])
        stack, seen, to_return = [], set(), []
        for h in have_deps:
            seen.add(h)
            successors = self.dependents_by_hash[d].get(h)
            if successors is None:
                raise CausalityError(f"hash not found: {h}")
            stack.extend(successors)
        while stack:
            h = stack.pop()
            seen.add(h)
            to_return.append(h)
            if not all(dep in seen for dep in self.dependencies_by_hash[d][h]):
                break
            stack.extend(self.dependents_by_hash[d][h])
        if not stack and all(head in seen for head in self.heads[d]):
            return [self.changes[d][self.change_index_by_hash[d][h]] for h in to_return]
        stack, seen = list(have_deps), set()
        while stack:
            h = stack.pop()
            if h not in seen:
                deps = self.dependencies_by_hash[d].get(h)
                if deps is None:
                    raise CausalityError(f"hash not found: {h}")
                stack.extend(deps)
                seen.add(h)
        return [
            change for change in self.changes[d]
            if decode_change_meta_cached(change)["hash"] not in seen
        ]

    def get_missing_deps(self, d: int, heads=()):
        """Dependencies needed before queued changes can apply, plus any
        requested heads we lack (getMissingDeps, new.js:2006)."""
        missing = set()
        in_queue = {change["hash"] for change in self.queue[d]}
        # amlint: disable=AM107 — sync-protocol API over the (small)
        # undeliverable queue, not a throughput phase
        for change in self.queue[d]:
            for dep in change["deps"]:
                if dep not in self.change_index_by_hash[d] and dep not in in_queue:
                    missing.add(dep)
        for head in heads:
            if head not in self.change_index_by_hash[d] and head not in in_queue:
                missing.add(head)
        return sorted(missing)
