"""Device-side batched RGA ordering: document order as a parallel rank
computation.

The reference determines a list element's position with a sequential scan:
insert after the reference element, skipping over existing elements with a
greater opId (/root/reference/backend/new.js:144-163, the loop "Skip over any
list elements with greater ID than the new one"). SURVEY.md §7 flags this as
the main algorithmic redesign for a TPU build: the scan must become a rank
computation.

The redesign rests on the tree equivalence of RGA:

- Every element names the element it was inserted after (its *parent*; the
  virtual head for position 0), so the elements of a list object form a
  forest rooted at the head.
- By causal delivery, an element's Lamport opId is strictly greater than its
  parent's (you can only insert after an element you have already seen, and
  new opIds exceed every opId seen so far -- maxOp tracking,
  /root/reference/backend/new.js:1818). Hence every element of a subtree has
  a greater opId than the subtree's root.
- Therefore the reference's skip rule ("skip elements with greater opId")
  skips exactly the subtrees of the new element's greater-opId siblings, and
  the resulting document order is the depth-first preorder of the tree with
  each node's children ordered by **descending** opId.

That preorder is computed here entirely on device, batched over documents,
with O(log E) depth per document of E elements:

  1. one sort groups siblings contiguously in descending-opId order
     (jnp.argsort over a packed (parent, ~opId) key),
  2. `next sibling` / `first child` come from neighbours and binary searches
     in the sorted order,
  3. `next sibling of the nearest ancestor` resolves by pointer doubling up
     the parent chain (log2 E gather rounds),
  4. each node's DFS successor = first child, else that ancestor sibling --
     giving the document order as a linked list, whose ranks are computed by
     Wyllie's pointer-doubling list ranking (log2 E gather rounds).

Ties between concurrent opIds with equal counters are broken by the actor id
-- compared as *strings* in the reference (new.js:146: `nextIdActor >
idActor`). Packed opIds carry an interned actor index, so callers pass an
`actor_rank` table mapping intern index -> lexicographic rank, and the
kernel compares remapped opIds (see `remap_opid_actors`).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..errors import PackingLimitError
from .engine import remap_opid_actors
from .jitprof import profiled_jit

# Packed opIds are (counter << 20 | actor), 44 significant bits. The
# sibling-sort composite packs (parent+1) above them, so documents are
# limited to MAX_ELEMS elements (tombstones included) and op counters to
# 2^24; callers must guard (text_engine._grow_elems does).
_OP_BITS = 44
_OP_MASK = (1 << _OP_BITS) - 1
_I64_MAX = jnp.iinfo(jnp.int64).max
MAX_ELEMS = 1 << 19
MAX_COUNTER = 1 << 24


def _rga_rank_one_doc(parent, opid, valid):
    """Ranks one document's elements in RGA document order.

    parent: int32[E] slot index of the insertion reference (-1 = head).
    opid:   int64[E] packed opId, already actor-rank-remapped for ties.
    valid:  bool[E].
    Returns int32[E]: 0-based document order; invalid slots get E.
    """
    e = parent.shape[0]
    doubling_rounds = max(int(e - 1).bit_length(), 1)
    sent = e  # sentinel node: end-of-list / virtual root's "no next"

    # --- 1. sibling sort: group by parent, descending opId within a group.
    # Composite key: (parent+1) in the high bits, bitwise-complemented opId
    # low, so ascending sort = (parent asc, opId desc). parent+1 <= E needs
    # E < 2^19 to stay within int64 alongside 44 opId bits.
    comp = jnp.where(
        valid,
        ((parent.astype(jnp.int64) + 1) << _OP_BITS) | (_OP_MASK - (opid & _OP_MASK)),
        _I64_MAX,
    )
    order = jnp.argsort(comp)          # sorted pos -> slot
    comp_sorted = comp[order]
    parent_sorted = jnp.where(valid[order], parent[order], jnp.int32(-2))
    inv_order = jnp.argsort(order)     # slot -> sorted pos

    # --- 2. neighbours in sorted space.
    # next sibling: the following row when it shares the parent.
    nxt_parent = jnp.roll(parent_sorted, -1)
    has_next_sib = (jnp.arange(e) + 1 < e) & (nxt_parent == parent_sorted) & (
        parent_sorted != -2
    )
    next_sib = jnp.where(has_next_sib, jnp.arange(e) + 1, sent)

    # first child of slot s: leftmost sorted row whose parent key is s
    # (search the sorted composite's high bits).
    pc = comp_sorted >> _OP_BITS       # parent+1 per sorted row (huge for pads)
    slots = jnp.arange(e, dtype=jnp.int64)
    fc_pos = jnp.searchsorted(pc, slots + 1)
    has_child = (fc_pos < e) & (pc[jnp.minimum(fc_pos, e - 1)] == slots + 1)
    first_child = jnp.where(has_child, fc_pos, sent).astype(jnp.int32)  # slot -> sorted pos

    # --- 3. next-sibling-of-nearest-ancestor by pointer doubling.
    # State per sorted row: res = resolved successor (or sent=unresolved yet
    # exhausted), up = sorted pos of the parent (sent once past the root).
    # Rows are extended by one sentinel row that resolves to itself.
    parent_pos = jnp.where(
        parent_sorted >= 0, inv_order[jnp.maximum(parent_sorted, 0)], sent
    )
    res = jnp.where(has_next_sib, next_sib, jnp.where(parent_pos == sent, sent, -1))
    res = jnp.append(res, sent)        # sentinel row
    up = jnp.append(parent_pos, sent)

    def anc_step(_, carry):
        res, up = carry
        unresolved = res == -1
        res2 = jnp.where(unresolved, res[up], res)
        # res[up] may itself be -1; keep climbing
        up2 = jnp.where(res2 == -1, up[up], up)
        return res2, up2

    res, up = jax.lax.fori_loop(0, doubling_rounds, anc_step, (res, up))
    anc_next = jnp.where(res[:e] == -1, sent, res[:e])

    # --- 4. DFS successor, then Wyllie list ranking.
    slot_of_row = order                       # sorted pos -> slot
    fc_of_row = first_child[slot_of_row]      # this row's first child (sorted pos)
    succ = jnp.where(fc_of_row != sent, fc_of_row, anc_next)
    succ = jnp.where(valid[slot_of_row], succ, sent)
    succ = jnp.append(succ, sent)

    dist = jnp.append(
        jnp.where(valid[slot_of_row], jnp.int32(1), jnp.int32(0)), jnp.int32(0)
    )

    def rank_step(_, carry):
        dist, ptr = carry
        return dist + dist[ptr], ptr[ptr]

    dist, _ = jax.lax.fori_loop(0, doubling_rounds + 1, rank_step, (dist, succ))

    # dist[row] = #elements from this row (inclusive) to the end of the list.
    n_valid = jnp.sum(valid.astype(jnp.int32))
    rank_sorted = jnp.where(valid[slot_of_row], n_valid - dist[:e], e)
    return rank_sorted[inv_order].astype(jnp.int32)


@profiled_jit("rga.rank")
def batched_rga_rank(parent, opid, valid, actor_rank):
    """Document-order ranks for a batch of list objects.

    parent: int32[docs, E] insertion-reference slot (-1 = head).
    opid:   int64[docs, E] packed opIds (counter << 20 | actor intern index).
    valid:  bool[docs, E].
    actor_rank: int32[A] lexicographic rank per actor intern index.
    Returns int32[docs, E] ranks; invalid slots get E.
    """
    if parent.shape[-1] > MAX_ELEMS:
        raise PackingLimitError(
            f"document element table exceeds the rank kernel's "
            f"MAX_ELEMS={MAX_ELEMS}; the sibling-sort key packing would "
            "overflow int64"
        )
    remapped = remap_opid_actors(opid, actor_rank)
    return jax.vmap(_rga_rank_one_doc)(parent, remapped, valid)


def patch_emit_columns(visible, lam, cut):
    """Device-side patch-emit mask: a gathered row lands in the patch iff
    it is visible (visibility implies a live SET row — DEL/INC rows never
    win) and its rank-remapped lamport key is within its slot's walk
    cutoff. ``cut`` carries the cutoff per gathered row as an int64:
    ``-1`` = the row's slot is outside this delivery's cutoff set, int64
    max = walk to the end of the key run (the farm's +inf sentinel).
    Traced inside paging.patch_column_rows, so the row readback and the
    emit decision are one device program."""
    return visible & (lam <= cut) & (cut >= 0)
