"""Batched sync-protocol kernels: Bloom filter construction and querying for
thousands of (document, peer) pairs on device.

The wire format is unchanged from the single-document protocol
(automerge_tpu/sync.py, reference backend/sync.js): 10 bits/entry, 7 probes,
triple hashing from the first 12 bytes of each SHA-256 change hash
(sync.js:88, Dillinger & Manolios FMCAD 2004). What changes is the execution
shape: a replica farm syncing B documents against their peers evaluates all
filters in one batched XLA program instead of B sequential loops.

Filters are padded to a common word capacity; each filter's true bit count
(`modulo` = 8 * ceil(entries * 10 / 8)) rides along as data, so documents
with different change counts share one compiled program.
"""
from __future__ import annotations

from math import ceil

import jax
import jax.numpy as jnp
import numpy as np

from ..codecs import hex_to_bytes
from ..obs.metrics import get_metrics
from ..sync import BITS_PER_ENTRY, NUM_PROBES

WORD_BITS = 32

# Host-side accounting only: the jitted kernels below must stay free of
# instrument calls (amlint AM303); serialisation is the one funnel every
# device-built filter passes through.
_M_FILTERS_BUILT = get_metrics().counter(
    "sync.filters.built", "Bloom filters built on device and serialised"
)
_M_FILTER_BYTES = get_metrics().counter(
    "sync.filters.bytes", "wire bytes of serialised device-built filters"
)


def hash_to_xyz(hash_hex: str) -> tuple[int, int, int]:
    """First 12 bytes of the hash as three little-endian uint32s."""
    data = hex_to_bytes(hash_hex)
    return (
        int.from_bytes(data[0:4], "little"),
        int.from_bytes(data[4:8], "little"),
        int.from_bytes(data[8:12], "little"),
    )


def pack_hashes(hash_lists, width=None):
    """Packs per-filter hash lists into [B, E, 3] uint32 xyz tensors plus a
    [B] count vector. Padded entries are zero and masked by the count."""
    batch = len(hash_lists)
    width = width or max((len(h) for h in hash_lists), default=1) or 1
    xyz = np.zeros((batch, width, 3), np.uint32)
    counts = np.zeros((batch,), np.int32)
    for b, hashes in enumerate(hash_lists):
        counts[b] = len(hashes)
        for e, h in enumerate(hashes):
            xyz[b, e] = hash_to_xyz(h)
    return jnp.asarray(xyz), jnp.asarray(counts)


def filter_modulo(num_entries):
    """Bit size of a filter with the given entry count (sync.js:45)."""
    num_bytes = jnp.ceil(num_entries * BITS_PER_ENTRY / 8).astype(jnp.int32)
    return 8 * num_bytes


def _probe_positions(xyz, modulo):
    """Probe bit positions for one entry: triple hashing x_{i+1} = x_i + y_i,
    y_{i+1} = y_i + z (all mod filter size). xyz: [..., 3] uint32."""
    modulo = jnp.maximum(modulo, 1).astype(jnp.uint32)
    x = xyz[..., 0] % modulo
    y = xyz[..., 1] % modulo
    z = xyz[..., 2] % modulo

    def step(carry, _):
        x, y = carry
        nx = (x + y) % modulo
        ny = (y + z) % modulo
        return (nx, ny), nx

    (_, _), rest = jax.lax.scan(step, (x, y), None, length=NUM_PROBES - 1)
    return jnp.concatenate([x[None], rest], axis=0)  # [NUM_PROBES, ...]


from .jitprof import profiled_jit


@profiled_jit("sync.build_filters", static_argnums=(2,))
def build_filters(xyz, counts, num_words: int = None):
    """Builds B Bloom filters at once. xyz: [B, E, 3] uint32; counts: [B].
    Returns (words [B, W] uint32, modulo [B] int32)."""
    batch, width, _ = xyz.shape
    modulo = filter_modulo(counts)
    if num_words is None:
        num_words = int(ceil(width * BITS_PER_ENTRY / WORD_BITS)) or 1

    # probe positions for every entry: [P, B, E]
    probes = _probe_positions(xyz, modulo[:, None].astype(jnp.uint32))
    entry_mask = (jnp.arange(width)[None, :] < counts[:, None])  # [B, E]

    word_idx = (probes // WORD_BITS).astype(jnp.int32)  # [P, B, E]
    bit_idx = (probes % WORD_BITS).astype(jnp.uint32)

    # dense OR-accumulation per word: words[b, w] = OR over probes with
    # word_idx == w (one-hot contraction; no scatters)
    w_range = jnp.arange(num_words, dtype=jnp.int32)  # [W]
    hit = (word_idx[..., None] == w_range) & entry_mask[None, :, :, None]  # [P,B,E,W]
    contrib = jnp.where(hit, (jnp.uint32(1) << bit_idx)[..., None], jnp.uint32(0))
    words = jax.lax.reduce(
        contrib, jnp.uint32(0), jax.lax.bitwise_or, dimensions=(0, 2)
    )  # [B, W]
    return words, modulo


@profiled_jit("sync.query_filters")
def query_filters(words, modulo, counts, query_xyz):
    """Tests C candidate hashes against each of B filters in one shot.
    query_xyz: [B, C, 3] uint32. Returns contained: [B, C] bool (False for
    empty filters, matching BloomFilter.containsHash on zero entries)."""
    probes = _probe_positions(query_xyz, modulo[:, None].astype(jnp.uint32))  # [P, B, C]
    word_idx = (probes // WORD_BITS).astype(jnp.int32)
    bit_idx = (probes % WORD_BITS).astype(jnp.uint32)
    gathered = jnp.take_along_axis(
        words[None, :, :], jnp.minimum(word_idx, words.shape[1] - 1), axis=2
    )  # [P, B, C]
    bit_set = (gathered >> bit_idx) & jnp.uint32(1)
    contained = jnp.all(bit_set == 1, axis=0)
    return contained & (counts[:, None] > 0)


def filters_to_bytes(words, modulo, counts):
    """Serialises device filters into the reference wire format
    (sync.js:68: numEntries, bitsPerEntry, numProbes, bits)."""
    from ..codecs import Encoder

    words = np.asarray(words)
    modulo = np.asarray(modulo)
    counts = np.asarray(counts)
    out = []
    for b in range(words.shape[0]):
        if counts[b] == 0:
            out.append(b"")
            continue
        encoder = Encoder()
        encoder.append_uint32(int(counts[b]))
        encoder.append_uint32(BITS_PER_ENTRY)
        encoder.append_uint32(NUM_PROBES)
        num_bytes = int(modulo[b]) // 8
        encoder.append_raw_bytes(words[b].tobytes()[:num_bytes])
        out.append(encoder.buffer)
    if _M_FILTERS_BUILT.enabled:
        _M_FILTERS_BUILT.inc(sum(1 for blob in out if blob))
        _M_FILTER_BYTES.inc(sum(len(blob) for blob in out))
    return out


def batched_have_filters(backends, last_syncs):
    """Host driver: builds the `have` Bloom filters for a batch of documents
    in one device program (the batched analogue of makeBloomFilter,
    sync.js:234)."""
    from .. import backend as Backend
    from ..columnar import decode_change_meta_cached

    hash_lists = []
    for backend, last_sync in zip(backends, last_syncs):
        changes = Backend.get_changes(backend, list(last_sync))
        hash_lists.append([decode_change_meta_cached(c)["hash"] for c in changes])
    xyz, counts = pack_hashes(hash_lists)
    num_words = int(ceil(xyz.shape[1] * BITS_PER_ENTRY / WORD_BITS)) or 1
    words, modulo = build_filters(xyz, counts, num_words)
    blooms = filters_to_bytes(words, modulo, counts)
    return [
        {"lastSync": list(last_sync), "bloom": bloom}
        for last_sync, bloom in zip(last_syncs, blooms)
    ]
