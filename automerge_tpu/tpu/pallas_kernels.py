"""Pallas TPU kernels for the sync protocol's Bloom filter hot path.

The sync protocol probes every candidate change hash against every peer's
`have` filter (reference backend/sync.js: getProbes:88, containsHash:116,
addHash:107). At replica-farm scale that is B filters x C candidates x 7
probes of bit tests — a bandwidth-bound bitwise workload that XLA executes
as a chain of gathers. These kernels fuse the whole probe sequence in VMEM:

- probe positions are computed with the reference's triple-hashing recurrence
  (x += y; y += z, all mod filter size) unrolled NUM_PROBES times;
- the word gather `words[probe >> 5]` is expressed as a one-hot matmul so it
  rides the MXU instead of serialising into scalar gathers. uint32 words are
  split into two uint16 halves so the f32 matmul is exact (one-hot rows sum
  a single term < 2^16);
- the grid tiles the entry/query axis and the word axis, OR-accumulating
  into revisited output blocks, so every VMEM block stays a few MB no matter
  how large the filter or candidate set grows (a 10k-change filter is ~3200
  words; one-shot one-hots over that would be ~1 GB).

On CPU the kernels run in the Pallas interpreter (tests); on TPU they are
compiled. Results are bit-identical to the XLA reference implementations in
sync_batch.py, which remain the default host API.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..sync import NUM_PROBES
from .jitprof import profiled_jit

WORD_BITS = 32
_LANES = 128
# VMEM budgets: the one-hot intermediates are [P, ENTRY/QUERY_TILE, WORD_TILE]
# f32 — 7 * 256 * 512 * 4 B = 3.5 MB, comfortably under ~16 MB VMEM.
_ENTRY_TILE = 256
_QUERY_TILE = 256
_WORD_TILE = 512


def _pad_to(n: int, m: int) -> int:
    return ((n + m - 1) // m) * m or m


def _probe_rows(xyz, modulo):
    """Unrolled triple-hash probe positions. xyz: [C, 3] uint32, modulo
    scalar uint32. Returns [NUM_PROBES, C] uint32."""
    modulo = jnp.maximum(modulo, jnp.uint32(1))
    x = xyz[:, 0] % modulo
    y = xyz[:, 1] % modulo
    z = xyz[:, 2] % modulo
    rows = [x]
    for _ in range(NUM_PROBES - 1):
        x = (x + y) % modulo
        y = (y + z) % modulo
        rows.append(x)
    return jnp.stack(rows)


def _gather_words_mxu(words_u32, word_idx, num_words):
    """words[word_idx] as a one-hot MXU contraction.

    words_u32: [W] uint32, word_idx: [P, C] int32 (must be in [0, W)) ->
    [P, C] uint32. The one-hot rows select exactly one element, and uint16
    halves keep every f32 product exactly representable."""
    lo = (words_u32 & jnp.uint32(0xFFFF)).astype(jnp.float32)  # [W]
    hi = (words_u32 >> 16).astype(jnp.float32)
    onehot = (word_idx[..., None] == jnp.arange(num_words, dtype=jnp.int32)).astype(
        jnp.float32
    )  # [P, C, W]
    g_lo = jnp.einsum("pcw,w->pc", onehot, lo, preferred_element_type=jnp.float32)
    g_hi = jnp.einsum("pcw,w->pc", onehot, hi, preferred_element_type=jnp.float32)
    return g_lo.astype(jnp.uint32) | (g_hi.astype(jnp.uint32) << 16)


def _bloom_query_kernel(words_ref, modulo_ref, xyz_ref, out_ref, *, num_words):
    """One (filter, query-tile, word-tile) cell. Blocks: words [1, W_T],
    modulo [1, 1] (SMEM), xyz [1, C_T, 3], out [1, P, C_T] int32 holding the
    probed bit per (probe, query), OR-accumulated across word tiles (each
    probe's word lives in exactly one tile, so the OR is exact). word_idx is
    clamped to num_words - 1 exactly like sync_batch.query_filters' gather,
    keeping the two implementations bit-identical even for over-sized moduli
    (possible only when a caller undersizes num_words for the filter count)."""
    w_idx = pl.program_id(2)
    w_t = words_ref.shape[1]
    modulo = modulo_ref[0, 0].astype(jnp.uint32)
    probes = _probe_rows(xyz_ref[0], modulo)  # [P, C_T]
    word_idx = jnp.minimum((probes // WORD_BITS).astype(jnp.int32), num_words - 1)
    bit_idx = probes % WORD_BITS
    local = word_idx - w_idx * w_t
    in_tile = (local >= 0) & (local < w_t)
    gathered = _gather_words_mxu(
        words_ref[0], jnp.where(in_tile, local, 0), w_t
    )
    bit_set = jnp.where(in_tile, (gathered >> bit_idx) & jnp.uint32(1), 0).astype(
        jnp.int32
    )

    @pl.when(w_idx == 0)
    def _init():
        out_ref[0] = bit_set

    @pl.when(w_idx > 0)
    def _accumulate():
        out_ref[0] = out_ref[0] | bit_set


@profiled_jit("pallas.bloom_query", static_argnames=("interpret",))
def bloom_query(words, modulo, counts, query_xyz, *, interpret=False):
    """Pallas analogue of sync_batch.query_filters.

    words: [B, W] uint32, modulo: [B] int32, counts: [B] int32,
    query_xyz: [B, C, 3] uint32. Returns [B, C] bool."""
    batch, num_words = words.shape
    _, c, _ = query_xyz.shape
    w_t = min(_pad_to(num_words, _LANES), _WORD_TILE)
    c_t = min(_pad_to(c, _LANES), _QUERY_TILE)
    w_pad = _pad_to(num_words, w_t)
    c_pad = _pad_to(c, c_t)
    words = jnp.pad(words, ((0, 0), (0, w_pad - num_words)))
    query_xyz = jnp.pad(query_xyz, ((0, 0), (0, c_pad - c), (0, 0)))

    bits = pl.pallas_call(
        partial(_bloom_query_kernel, num_words=num_words),
        grid=(batch, c_pad // c_t, w_pad // w_t),
        in_specs=[
            pl.BlockSpec((1, w_t), lambda b, q, w: (b, w), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1), lambda b, q, w: (b, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec(
                (1, c_t, 3), lambda b, q, w: (b, q, 0), memory_space=pltpu.VMEM
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, NUM_PROBES, c_t), lambda b, q, w: (b, 0, q), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((batch, NUM_PROBES, c_pad), jnp.int32),
        interpret=interpret,
    )(
        words,
        modulo.reshape(batch, 1).astype(jnp.int32),
        query_xyz,
    )
    all_set = jnp.min(bits[:, :, :c], axis=1)
    return jnp.where(counts[:, None] > 0, all_set, 0).astype(jnp.bool_)


def _bloom_build_kernel(xyz_ref, modulo_ref, count_ref, out_ref):
    """One (filter, word-tile, entry-tile) cell. Blocks: xyz [1, E_T, 3],
    modulo/count [1, 1] (SMEM), out words [1, W_T] int32, OR-accumulated
    across entry tiles (the innermost grid axis, so the block is revisited
    consecutively)."""
    w_idx = pl.program_id(1)
    e_idx = pl.program_id(2)
    e_t = xyz_ref.shape[1]
    w_t = out_ref.shape[1]
    modulo = modulo_ref[0, 0].astype(jnp.uint32)
    count = count_ref[0, 0]
    probes = _probe_rows(xyz_ref[0], modulo)  # [P, E_T]
    word_idx = (probes // WORD_BITS).astype(jnp.int32)
    bit = jnp.uint32(1) << (probes % WORD_BITS)
    global_e = e_idx * e_t + jax.lax.broadcasted_iota(
        jnp.int32, (NUM_PROBES, e_t), 1
    )
    entry_ok = global_e < count
    # OR-accumulate per word without scatters: for each word lane w of this
    # tile, fold together the bits of every probe that lands in w.
    local = word_idx - w_idx * w_t
    hit = (local[..., None] == jnp.arange(w_t, dtype=jnp.int32)) & entry_ok[..., None]
    contrib = jnp.where(hit, bit[..., None], jnp.uint32(0))
    words = jax.lax.reduce(
        contrib, jnp.uint32(0), jax.lax.bitwise_or, dimensions=(0, 1)
    ).astype(jnp.int32)  # [W_T]

    @pl.when(e_idx == 0)
    def _init():
        out_ref[0, :] = words

    @pl.when(e_idx > 0)
    def _accumulate():
        out_ref[0, :] = out_ref[0, :] | words


_SEG_TILE = 128
_BYTE_TILE = 512


def _leb_segsum_kernel(planes_ref, seg_ref, out_ref):
    """One (varint-tile, byte-tile) cell of the LEB128 segmented sum.

    Blocks: planes [B_T, P] f32 (14-bit payload planes per byte), seg
    [B_T, 1] int32 (varint id per byte, -1 for padding), out [V_T, P] f32.
    Each byte belongs to exactly one varint, so accumulating partial
    one-hot matmuls over byte tiles reconstructs the exact per-varint
    plane sums (every product is an integer < 2^17, exact in f32)."""
    v_idx = pl.program_id(1)
    b_idx = pl.program_id(2)
    v_t = out_ref.shape[0]
    seg = seg_ref[:, 0]  # [B_T]
    local = seg - v_idx * v_t
    onehot = (
        jax.lax.broadcasted_iota(jnp.int32, (v_t, seg.shape[0]), 0) == local[None, :]
    ).astype(jnp.float32)  # [V_T, B_T]
    partial_sums = jnp.dot(
        onehot, planes_ref[...], preferred_element_type=jnp.float32
    )

    @pl.when(b_idx == 0)
    def _init():
        out_ref[...] = partial_sums

    @pl.when(b_idx > 0)
    def _accumulate():
        out_ref[...] = out_ref[...] + partial_sums


@profiled_jit("pallas.leb128_segment_sum",
              static_argnames=("num_segments", "interpret"))
def leb128_segment_sum(planes, seg_ids, num_segments: int, *, interpret=False):
    """Per-varint payload-plane sums for the vectorized LEB128 decode
    (tpu/decode.leb128_scan_device): ``out[v, p] = sum(planes[i, p] for i
    with seg_ids[i] == v)``.

    planes: [N, P] f32, seg_ids: [N] int32 in [0, num_segments). XLA
    lowers this reduction to serialised scatters on TPU; here it rides the
    MXU as a tiled one-hot contraction, the same pattern as the Bloom
    word gather above."""
    n, p = planes.shape
    b_t = min(_pad_to(n, 8), _BYTE_TILE)
    v_t = min(_pad_to(num_segments, 8), _SEG_TILE)
    n_pad = _pad_to(n, b_t)
    v_pad = _pad_to(num_segments, v_t)
    planes = jnp.pad(planes, ((0, n_pad - n), (0, 0)))
    seg_ids = jnp.pad(
        seg_ids.astype(jnp.int32), (0, n_pad - n), constant_values=-1
    )

    out = pl.pallas_call(
        _leb_segsum_kernel,
        grid=(1, v_pad // v_t, n_pad // b_t),
        in_specs=[
            pl.BlockSpec((b_t, p), lambda g, v, b: (b, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((b_t, 1), lambda g, v, b: (b, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (v_t, p), lambda g, v, b: (v, 0), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((v_pad, p), jnp.float32),
        interpret=interpret,
    )(planes, seg_ids.reshape(n_pad, 1))
    return out[:num_segments]


@profiled_jit("pallas.bloom_build", static_argnames=("num_words", "interpret"))
def bloom_build(xyz, counts, num_words: int, *, interpret=False):
    """Pallas analogue of sync_batch.build_filters.

    xyz: [B, E, 3] uint32, counts: [B] int32. Returns (words [B, num_words]
    uint32, modulo [B] int32) exactly like sync_batch.build_filters."""
    from .sync_batch import filter_modulo

    batch, e, _ = xyz.shape
    modulo = filter_modulo(counts)
    e_t = min(_pad_to(e, 8), _ENTRY_TILE)
    w_t = min(_pad_to(num_words, _LANES), _WORD_TILE)
    e_pad = _pad_to(e, e_t)
    w_pad = _pad_to(num_words, w_t)
    xyz = jnp.pad(xyz, ((0, 0), (0, e_pad - e), (0, 0)))

    words = pl.pallas_call(
        _bloom_build_kernel,
        grid=(batch, w_pad // w_t, e_pad // e_t),
        in_specs=[
            pl.BlockSpec(
                (1, e_t, 3), lambda b, w, ei: (b, ei, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec((1, 1), lambda b, w, ei: (b, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 1), lambda b, w, ei: (b, 0), memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec(
            (1, w_t), lambda b, w, ei: (b, w), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((batch, w_pad), jnp.int32),
        interpret=interpret,
    )(
        xyz,
        modulo.reshape(batch, 1).astype(jnp.int32),
        counts.reshape(batch, 1).astype(jnp.int32),
    )
    return words[:, :num_words].astype(jnp.uint32), modulo
