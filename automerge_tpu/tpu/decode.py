"""Vectorized columnar decode: chunk byte tensors in, struct-of-arrays out.

The scalar codecs (codecs.py) walk one byte at a time through a per-op
state machine — ~5.5 s of pure Python per bench round on hosts without the
native library (BENCH_r05). This module re-expresses the change-chunk
column codecs as data-parallel transforms over concatenated chunk byte
tensors, the control-flow-duplication-for-columnar-arrays technique
(PAPERS.md: arxiv 2302.10098): every branch of the decode state machine
becomes a masked vector pass over the whole batch.

- **LEB128** becomes one pass: the continuation bit (``byte & 0x80``)
  masks value boundaries, a prefix scan over the boundary mask assigns
  each byte its varint id and in-varint position, and the payload
  contributions (``(byte & 0x7f) << 7*pos``) reduce segment-wise
  (``np.add.reduceat`` — exact int64). One scan covers EVERY varint
  column of EVERY chunk in the batch.
- **RLE / Delta** become a record-level walk (O(runs) Python, not
  O(bytes)) emitting (kind, count, value-index) triples, expanded to rows
  by segment-id gather + ``np.repeat``; Delta adds one cumulative-sum
  pass over the null-masked deltas.
- **Boolean** columns are a single ``np.repeat`` of alternating values
  over the run-length varints.

The scalar decoders remain the parity oracle: whenever a vector pass
meets bytes it cannot prove well-formed (truncated varints, bad run
structure, out-of-range values), the affected chunk is re-decoded through
the scalar path, which produces the canonical result or raises the
canonical ``DecodeError``/``ChecksumError``. The byte-corpus suite
(tests/test_decode_vectorized.py) pins bit-for-bit parity over the
reference corpus, fuzzed changes and corrupt inputs.

Importing this module registers the single-chunk vector pass as
columnar.decode_change's fallback backend (after the native library,
before the per-op decoder chain). The farm's delivery hot path and the
sync receive paths call ``warm_decode_cache`` to decode all cache misses
of a delivery together in one batch.

A jnp/Pallas assist (``leb128_scan_device`` + the segmented-sum MXU
kernel in pallas_kernels.py) exists for device-resident byte tensors,
where XLA's scatter-based segment sums serialise; the NumPy host path is
the default everywhere.
"""
# amlint: hot-path
from __future__ import annotations

import numpy as np

from .. import columnar, native
from ..codecs import MAX_SAFE_INTEGER, Decoder
from ..columnar import ColumnType
from ..native import NULL_SENTINEL
from ..obs.metrics import get_metrics

_METRICS = get_metrics()
_M_CHUNKS = _METRICS.counter(
    "codecs.vector.chunks", "change chunks decoded by the vectorized passes"
)
_M_BYTES = _METRICS.counter(
    "codecs.vector.bytes", "column bytes decoded by the vectorized passes"
)

#: expansion guard: a corrupt run count must not allocate unbounded rows
#: before validation can reject it — over the cap, the scalar oracle owns
#: the buffer (and its error)
ROW_CAP = 1 << 24


class _Fallback(Exception):
    """Internal: the vector pass met bytes it cannot prove well-formed; the
    caller re-runs the scalar oracle for the exact result or error."""


# ---------------------------------------------------------------------- #
# LEB128: continuation-bit mask + prefix scan

def leb128_scan(data: np.ndarray):
    """One masked vector pass over a byte tensor of back-to-back LEB128
    varints. Returns ``(starts, lengths, unsigned, signed)``: per-varint
    start offsets and byte lengths, and both int64 interpretations (the
    caller picks per column type). Raises _Fallback for streams the pass
    cannot decode exactly in int64 (a trailing continuation byte, or a
    varint wider than 8 bytes — legal values there exceed the 53-bit
    wire range anyway, so the oracle owns them and their errors)."""
    n = data.shape[0]
    if n == 0:
        e = np.empty(0, np.int64)
        return e, e, e, e
    cont = (data & 0x80) != 0
    if cont[-1]:
        raise _Fallback("stream ends inside a varint")
    ends = np.flatnonzero(~cont)
    starts = np.empty(ends.shape[0], np.int64)
    starts[0] = 0
    starts[1:] = ends[:-1] + 1
    lengths = ends + 1 - starts
    if int(lengths.max()) > 8:
        raise _Fallback("varint wider than 8 bytes")
    pos = np.arange(n, dtype=np.int64) - np.repeat(starts, lengths)
    contrib = (data & 0x7F).astype(np.int64) << (7 * pos)
    unsigned = np.add.reduceat(contrib, starts)
    sign = (data[ends] & 0x40) != 0
    signed = unsigned - (sign.astype(np.int64) << (7 * lengths))
    return starts, lengths, unsigned, signed


class _Scan:
    """The shared varint scan over a list of column buffers (one chunk's
    columns, or every varint column of a whole delivery batch): the byte
    tensors concatenate, one leb128_scan covers them all, and each segment
    reads its own varint index range. Buffer boundaries must land on varint
    boundaries (each column decodes independently) — a misaligned boundary
    means some buffer ends mid-varint, and the whole scan defers."""

    __slots__ = ("u", "s", "_vi")

    def __init__(self, bufs):
        sizes = np.fromiter((len(b) for b in bufs), np.int64, len(bufs))
        offsets = np.zeros(len(bufs) + 1, np.int64)
        np.cumsum(sizes, out=offsets[1:])
        data = np.frombuffer(b"".join(bufs), np.uint8)
        starts, _lengths, self.u, self.s = leb128_scan(data)
        nvar = starts.shape[0]
        vi = np.searchsorted(starts, offsets)
        if nvar > 0:
            interior = offsets < data.shape[0]
            aligned = starts[np.minimum(vi, nvar - 1)] == offsets
            if not np.all(aligned | ~interior):
                raise _Fallback("column boundary inside a varint")
        self._vi = vi

    def seg(self, k: int):
        """(lo, hi) varint index range of segment `k`."""
        return int(self._vi[k]), int(self._vi[k + 1])


# ---------------------------------------------------------------------- #
# RLE / Delta / Boolean: record walk + segment-id expansion

_REP, _LIT, _NULL = 0, 1, 2


def _rle_expand(scan: _Scan, lo: int, hi: int, signed: bool,
                row_cap: int = ROW_CAP) -> np.ndarray:
    """Expands one RLE column chunk (varint indexes [lo, hi) of `scan`)
    into an int64 row array with nulls as NULL_SENTINEL.

    The walk is O(records): each iteration consumes a whole repetition,
    literal run or null run. Row materialisation is vectorized — a
    segment-id gather into the varint value array plus one np.repeat.
    Structural violations (the scalar decoder's run-grammar errors) and
    out-of-range values raise _Fallback; the oracle re-raises exactly."""
    u, s = scan.u, scan.s
    vals = s if signed else u
    # the record walk runs on plain ints: local list views of the varint
    # slice beat numpy scalar indexing ~10x at record granularity
    s_l = s[lo:hi].tolist()
    vals_l = vals[lo:hi].tolist()
    kinds, counts, vidx = [], [], []
    i = 0
    n = hi - lo
    state = -1
    last_vi = -1
    while i < n:
        c = s_l[i]
        if c > 1:
            if c > MAX_SAFE_INTEGER or i + 1 >= n:
                raise _Fallback("bad repetition")
            if state in (_REP, _LIT) and vals_l[i + 1] == vals_l[last_vi]:
                raise _Fallback("successive repetitions of one value")
            kinds.append(_REP)
            counts.append(c)
            vidx.append(lo + i + 1)
            state, last_vi = _REP, i + 1
            i += 2
        elif c == 1:
            raise _Fallback("repetition count of 1")
        elif c < 0:
            m = -c
            if m > MAX_SAFE_INTEGER or i + 1 + m > n:
                raise _Fallback("truncated literal run")
            if state == _LIT:
                raise _Fallback("successive literals")
            kinds.append(_LIT)
            counts.append(m)
            vidx.append(lo + i + 1)
            state, last_vi = _LIT, i + m
            i += 1 + m
        else:
            if i + 1 >= n:
                raise _Fallback("truncated null run")
            m = int(u[lo + i + 1])  # null counts read unsigned
            if m == 0 or m > MAX_SAFE_INTEGER or state == _NULL:
                raise _Fallback("bad null run")
            kinds.append(_NULL)
            counts.append(int(m))
            vidx.append(lo)  # never read; keeps the gather in range
            state, last_vi = _NULL, -1
            i += 2
    if not kinds:
        return np.empty(0, np.int64)

    kind_arr = np.asarray(kinds, np.int64)
    count_arr = np.asarray(counts, np.int64)
    total = int(count_arr.sum())
    if total > row_cap:
        raise _Fallback("row cap exceeded")
    rec = np.repeat(np.arange(kind_arr.shape[0]), count_arr)
    rec_start = np.concatenate(([0], np.cumsum(count_arr)[:-1]))
    offset = np.arange(total) - rec_start[rec]
    row_kind = kind_arr[rec]
    is_lit = row_kind == _LIT
    is_null = row_kind == _NULL
    src = np.asarray(vidx, np.int64)[rec] + np.where(is_lit, offset, 0)
    out = np.where(is_null, NULL_SENTINEL, vals[src])

    live = out[~is_null]
    if live.size:
        if signed:
            if int(np.abs(live).max()) > MAX_SAFE_INTEGER:
                raise _Fallback("value out of range")
        elif int(live.max()) > MAX_SAFE_INTEGER:
            raise _Fallback("value out of range")
    # literal grammar: a literal value must differ from its predecessor
    # (the scalar decoder's read-time check), unless that predecessor was
    # a null run (last_value is None there)
    if is_lit.any():
        dup = np.zeros(total, bool)
        dup[1:] = is_lit[1:] & ~is_null[:-1] & (out[1:] == out[:-1])
        if dup.any():
            raise _Fallback("repetition inside literal")
    return out


def _delta_expand(scan: _Scan, lo: int, hi: int,
                  row_cap: int = ROW_CAP) -> np.ndarray:
    """Delta column: signed RLE over successive differences, then one
    cumulative-sum pass (nulls pass through without touching the running
    absolute — exactly DeltaDecoder.read_value)."""
    deltas = _rle_expand(scan, lo, hi, signed=True, row_cap=row_cap)
    nulls = deltas == NULL_SENTINEL
    stepped = np.where(nulls, 0, deltas)
    # |delta| <= 2^53 and rows <= ROW_CAP, but the running sum could still
    # overflow int64 on adversarial input: bound it in float first
    if stepped.size and float(np.abs(stepped, dtype=np.float64).sum()) >= 2.0**62:
        raise _Fallback("absolute value overflow")
    out = np.cumsum(stepped)
    return np.where(nulls, NULL_SENTINEL, out)


def _bool_expand(scan: _Scan, lo: int, hi: int,
                 row_cap: int = ROW_CAP) -> np.ndarray:
    """Boolean column: alternating run lengths starting with false — one
    np.repeat over the run-length varints."""
    counts = scan.u[lo:hi]
    if counts.shape[0] == 0:
        return np.zeros(0, bool)
    if int(counts.max()) > MAX_SAFE_INTEGER:
        raise _Fallback("run length out of range")
    if counts.shape[0] > 1 and int(counts[1:].min()) == 0:
        raise _Fallback("zero-length run")
    total = int(counts.sum())
    if total > row_cap:
        raise _Fallback("row cap exceeded")
    vals = (np.arange(counts.shape[0], dtype=np.int64) & 1) == 1
    return np.repeat(vals, counts)


def _strrle_expand(buf: bytes, row_cap: int = ROW_CAP):
    """utf8 RLE column: value-level walk (strings interleave with the run
    varints, so this column cannot ride the shared varint scan). O(records
    + strings) Python — runs and length prefixes amortise the per-byte
    cost the scalar chain pays. Returns (blob, offsets int64[n, 2]) in
    native.strrle_decode's format: row i is blob[o[i,0]:o[i,1]], null rows
    are (-1, -1)."""
    dec = Decoder(buf)
    n_bytes = len(buf)
    parts = []          # blob fragments, in row order
    rec_rows = []       # per record: (kind, count, start, end) into blob
    blob_len = 0
    total = 0
    state = -1
    last_bytes = None

    def read_str():
        """One length-prefixed string: single-byte prefixes (the common
        case) slice directly; multi-byte prefixes ride the Decoder."""
        o = dec.offset
        if o >= n_bytes:
            raise _Fallback("truncated string run")
        ln = buf[o]
        if ln < 0x80:
            start = o + 1
        else:
            ln = dec.read_uint53()
            start = dec.offset
        end = start + ln
        if end > n_bytes:
            raise _Fallback("string exceeds buffer")
        dec.offset = end
        return buf[start:end]

    try:
        while not dec.done:
            c = dec.read_int53()
            if c > 1:
                raw = read_str()
                if state in (_REP, _LIT) and raw == last_bytes:
                    raise _Fallback("successive repetitions of one value")
                parts.append(raw)
                rec_rows.append((_REP, c, blob_len, blob_len + len(raw)))
                blob_len += len(raw)
                state, last_bytes = _REP, raw
                total += c
            elif c == 1:
                raise _Fallback("repetition count of 1")
            elif c < 0:
                if state == _LIT:
                    raise _Fallback("successive literals")
                for _ in range(-c):
                    raw = read_str()
                    if raw == last_bytes and last_bytes is not None:
                        raise _Fallback("repetition inside literal")
                    parts.append(raw)
                    rec_rows.append((_LIT, 1, blob_len, blob_len + len(raw)))
                    blob_len += len(raw)
                    last_bytes = raw
                state = _LIT
                total += -c
            else:
                m = dec.read_uint53()
                if m == 0 or state == _NULL:
                    raise _Fallback("bad null run")
                rec_rows.append((_NULL, m, -1, -1))
                state, last_bytes = _NULL, None
                total += m
            if total > row_cap:
                raise _Fallback("row cap exceeded")
    except _Fallback:
        raise
    except Exception as exc:  # truncated varint/string: oracle owns the error
        raise _Fallback(str(exc)) from None
    if not rec_rows:
        return b"", np.empty((0, 2), np.int64)
    recs = np.asarray([(r[1], r[2], r[3]) for r in rec_rows], np.int64)
    offs = np.repeat(recs[:, 1:], recs[:, 0], axis=0)
    return b"".join(parts), offs


# ---------------------------------------------------------------------- #
# chunk-level decode: columns -> struct-of-arrays -> ops

def _collect_columns(cols):
    """Splits one chunk's (column_id, buffer) list into varint segments,
    string columns and raw columns, keyed by canonical change-column name.
    Returns None when an unknown column is present (the generic path
    preserves those)."""
    varints, strs, raws = [], {}, {}
    for cid, buf in cols:
        name = columnar._CHANGE_COLUMN_IDS.get(cid)
        if name is None:
            return None
        t = cid & 7
        buf = bytes(buf)
        if t == ColumnType.STRING_RLE:
            strs[name] = buf
        elif t == ColumnType.VALUE_RAW:
            raws[name] = buf
        elif t == ColumnType.INT_DELTA:
            varints.append((name, "delta", buf))
        elif t == ColumnType.BOOLEAN:
            varints.append((name, "bool", buf))
        else:  # GROUP_CARD / ACTOR_ID / INT_RLE / VALUE_LEN: uint RLE
            varints.append((name, "uint", buf))
    return varints, strs, raws


def _soa_from_columns(varints, strs, raws, scan: _Scan, seg_of):
    """Materialises the struct-of-arrays for one chunk: every varint
    column expands through the shared scan (`seg_of` maps the position in
    `varints` to its scan segment), strings and raw columns decode
    locally."""
    arrs = {}
    for j, (name, kind, _buf) in enumerate(varints):
        lo, hi = scan.seg(seg_of(j))
        if kind == "bool":
            arrs[name] = _bool_expand(scan, lo, hi)
        elif kind == "delta":
            arrs[name] = _delta_expand(scan, lo, hi)
        else:
            arrs[name] = _rle_expand(scan, lo, hi, signed=False)
    for name, buf in strs.items():
        if buf and native.available():
            try:
                arrs[name] = native.strrle_decode(buf)
                continue
            except ValueError:
                pass  # the Python walk re-validates and classifies
        arrs[name] = _strrle_expand(buf)
    for name, buf in raws.items():
        arrs[name] = buf
    return arrs


def _count_bytes(varints, strs, raws) -> int:
    return (
        sum(len(b) for _, _, b in varints)
        + sum(len(b) for b in strs.values())
        + sum(len(b) for b in raws.values())
    )


def _vector_change_ops(cols, actor_ids):
    """Single-chunk vectorized change-op decode — the backend registered
    with columnar.set_vector_decoder (same contract as the native path:
    ops list, or None to defer to the generic per-op decoder chain)."""
    grouped = _collect_columns(cols)
    if grouped is None:
        return None
    varints, strs, raws = grouped
    try:
        scan = _Scan([b for _, _, b in varints])
        arrs = _soa_from_columns(varints, strs, raws, scan, lambda j: j)
        ops = columnar.ops_from_column_arrays(arrs, actor_ids)
    except Exception:
        # anything the vector pass cannot decode — structural fallbacks
        # AND real decode errors — defers to the per-op decoder chain,
        # which produces the canonical result or raises the canonical
        # taxonomy error
        return None
    if ops is not None and _M_CHUNKS.enabled:
        _M_CHUNKS.inc()
        _M_BYTES.inc(_count_bytes(varints, strs, raws))
    return ops


def _finish_change(meta, ops):
    """decode_change's tail: attach ops, drop the transport fields."""
    change = dict(meta)
    change["ops"] = ops
    del change["actorIds"]
    del change["columns"]
    return change


def _decode_batch(keys):
    """Decodes a batch of distinct change buffers, sharing ONE varint scan
    across every column of every chunk. Returns one entry per buffer:
    the decoded change dict, or the exception that buffer raises.

    Chunks the vector pass cannot prove well-formed re-decode through
    columnar.decode_change (native/scalar), which produces the canonical
    result or error — corrupt inputs cost one extra parse, the clean bulk
    path stays batched."""
    metas = [None] * len(keys)
    grouped = [None] * len(keys)
    results = [None] * len(keys)
    seg_bufs = []
    seg_base = [0] * len(keys)
    for i, buf in enumerate(keys):
        try:
            metas[i] = columnar.decode_change_columns(buf)
        except Exception as exc:  # per-buffer isolation: header/checksum
            results[i] = exc
            continue
        g = _collect_columns(
            [(c["columnId"], c["buffer"]) for c in metas[i]["columns"]]
        )
        grouped[i] = g
        if g is not None:
            seg_base[i] = len(seg_bufs)
            seg_bufs.extend(b for _, _, b in g[0])

    scan = None
    try:
        scan = _Scan(seg_bufs)
    except _Fallback:
        pass  # some buffer is malformed: every chunk re-scans locally

    decoded_chunks = 0
    decoded_bytes = 0
    for i, buf in enumerate(keys):
        if results[i] is not None or metas[i] is None:
            continue
        ops = None
        if grouped[i] is not None:
            varints, strs, raws = grouped[i]
            try:
                if scan is not None:
                    base = seg_base[i]
                    arrs = _soa_from_columns(
                        varints, strs, raws, scan, lambda j, b=base: b + j
                    )
                else:
                    local = _Scan([b for _, _, b in varints])
                    arrs = _soa_from_columns(
                        varints, strs, raws, local, lambda j: j
                    )
                ops = columnar.ops_from_column_arrays(arrs, metas[i]["actorIds"])
                if ops is not None:
                    decoded_chunks += 1
                    decoded_bytes += _count_bytes(varints, strs, raws)
            except Exception:
                ops = None  # scalar re-decode owns the result AND the error
        if ops is not None:
            results[i] = _finish_change(metas[i], ops)
        else:
            try:
                results[i] = columnar.decode_change(buf)
            except Exception as exc:
                results[i] = exc
    if decoded_chunks and _M_CHUNKS.enabled:
        _M_CHUNKS.inc(decoded_chunks)
        _M_BYTES.inc(decoded_bytes)
    return results


def decode_changes_vector(buffers):
    """Batched `columnar.decode_change` over a list of change buffers:
    misses decode together in one vector pass; the first buffer that fails
    raises its canonical error (list-order semantics, like decoding the
    buffers one by one)."""
    results = _decode_batch([bytes(b) for b in buffers])
    for res in results:
        if isinstance(res, BaseException):
            raise res
    return results


def warm_decode_cache(buffers) -> int:
    """Best-effort batched decode of the delivery's cache misses into the
    shared change LRU (columnar.decode_change_cached then hits for every
    buffer). Buffers that fail to decode are left uncached — the
    per-document delivery path re-raises their exact error inside its own
    fault domain. Returns the number of chunks decoded."""
    cache = columnar._DECODED_CHANGE_CACHE
    misses = []
    seen = set()
    for b in buffers:
        k = bytes(b)
        if k in seen or k in cache._entries:
            continue
        seen.add(k)
        misses.append(k)
    if not misses:
        return 0
    decoded = 0
    for k, res in zip(misses, _decode_batch(misses)):
        if not isinstance(res, BaseException):
            cache.put(k, res)
            decoded += 1
    return decoded


# ---------------------------------------------------------------------- #
# device path: jnp + Pallas assist for device-resident byte tensors

def leb128_scan_device(data, *, interpret: bool | None = None):
    """leb128_scan for a device-resident byte tensor: boundary mask and
    positions in jnp, the payload segment-reduction through the MXU
    one-hot kernel (pallas_kernels.leb128_segment_sum) — XLA lowers that
    reduction to serialised scatters, which is exactly where fusion falls
    short on TPU. Returns the same (starts, lengths, unsigned, signed)
    tuple as the NumPy pass, as host arrays. `interpret` defaults to True
    off-TPU (the Pallas interpreter)."""
    import jax
    import jax.numpy as jnp

    from .pallas_kernels import leb128_segment_sum

    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    data = jnp.asarray(data, jnp.uint8)
    n = int(data.shape[0])
    if n == 0:
        e = np.empty(0, np.int64)
        return e, e, e, e
    cont = (data & 0x80) != 0
    is_end = ~cont
    if bool(jax.device_get(cont[-1])):
        raise _Fallback("stream ends inside a varint")
    seg = jnp.cumsum(is_end.astype(jnp.int32)) - is_end.astype(jnp.int32)
    nvar = int(jax.device_get(seg[-1])) + 1
    ends = jnp.nonzero(is_end, size=nvar)[0]
    starts = jnp.concatenate([jnp.zeros(1, ends.dtype), ends[:-1] + 1])
    lengths = ends + 1 - starts
    if int(jax.device_get(lengths.max())) > 8:
        raise _Fallback("varint wider than 8 bytes")
    pos = jnp.arange(n) - starts[seg]
    contrib = (data & 0x7F).astype(jnp.int64) << (7 * pos)
    # 14-bit planes keep every f32 one-hot product exact in the kernel
    planes = jnp.stack(
        [(contrib >> (14 * k)) & 0x3FFF for k in range(4)], axis=1
    ).astype(jnp.float32)
    sums = leb128_segment_sum(
        planes, seg.astype(jnp.int32), nvar, interpret=interpret
    )
    unsigned = sum(
        sums[:, k].astype(jnp.int64) << (14 * k) for k in range(4)
    )
    sign = (data[ends] & 0x40) != 0
    signed = unsigned - (sign.astype(jnp.int64) << (7 * lengths))
    return jax.device_get((starts, lengths, unsigned, signed))


# register the vectorized backend with the host-only codec layer
columnar.set_vector_decoder(_vector_change_ops)
