"""Ragged paged op storage for the merge farm.

Modeled on Ragged Paged Attention (PAPERS.md: arxiv 2604.15464): the dense
engine state used to be one ``[docs, capacity]`` tensor per column with
``capacity = pow2(largest doc)`` — a farm of wildly different document
sizes pays largest-doc HBM for EVERY doc (the ``farm.pad_waste`` metric
existed to measure exactly that), and every capacity doubling recompiles
every program over the whole farm. Here op rows live in fixed-size pages
allocated from one shared slab; each document owns a page list and a row
count, and kernels address the slab through host-built row maps derived
from ``(page_table, lengths)``:

    row_map[a, r] = page_table[doc_a][r // P] * P + r % P    (r < len_a)
                  = 0                                        (pad row)

Page 0 is reserved as the immutable PAD page — its rows hold PAD values
forever, so gathers of dead rows produce pad rows without branching, and
scatters never target it (``dest == slab_rows`` drops pad writes instead).

The merge program gathers the ACTIVE documents' rows into a dense
``[A, W]`` working view (A = pow2-bucketed active-doc count, W = pow2
bucket of the largest active doc + incoming rows), runs the unchanged
merge kernel from engine.py, and scatters the merged rows back through
the NEW page map inside the same XLA program. A delivery touching 3
documents dispatches 3 documents' rows — not the farm — and a farm of
mixed doc sizes packs the slab at page granularity (the
``farm.pages.occupancy`` gauge replaces pad-waste as the HBM figure of
merit).
"""
from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from .engine import PAD_KEY, _merge_one_doc, _visible_state_one_doc, remap_opid_actors
from .jitprof import profiled_jit


class SlabState(NamedTuple):
    """One shared op slab: flat ``[num_pages * page_size]`` columns."""

    key: jax.Array          # int32 interned key id (PAD_KEY when dead)
    op: jax.Array           # int64 packed opId
    action: jax.Array       # int32
    value: jax.Array        # int64
    pred: jax.Array         # int64 (-1 none)
    overwritten: jax.Array  # bool


def make_empty_slab(rows: int) -> SlabState:
    return SlabState(
        key=jnp.full((rows,), PAD_KEY, jnp.int32),
        op=jnp.zeros((rows,), jnp.int64),
        action=jnp.zeros((rows,), jnp.int32),
        value=jnp.zeros((rows,), jnp.int64),
        pred=jnp.full((rows,), -1, jnp.int64),
        overwritten=jnp.zeros((rows,), jnp.bool_),
    )


def grow_slab(slab: SlabState, rows: int) -> SlabState:
    """Extends the slab to `rows` total rows (new rows are PAD)."""
    old = slab.key.shape[0]
    pad = rows - old
    if pad <= 0:
        return slab

    def grow(arr, fill):
        return jnp.concatenate([arr, jnp.full((pad,), fill, arr.dtype)])

    return SlabState(
        key=grow(slab.key, PAD_KEY),
        op=grow(slab.op, 0),
        action=grow(slab.action, 0),
        value=grow(slab.value, 0),
        pred=grow(slab.pred, -1),
        overwritten=grow(slab.overwritten, False),
    )


class PageAllocator:
    """Host-side free list of fixed-size pages. Page 0 is the reserved PAD
    page and is never handed out. Doubling `num_pages` signals the caller
    to grow the device slab (ensure() returns True when that happened)."""

    __slots__ = ("page_size", "num_pages", "_free")

    def __init__(self, page_size: int = 64, initial_pages: int = 64):
        assert page_size > 0 and (page_size & (page_size - 1)) == 0, (
            "page_size must be a power of two (working widths are pow2-"
            "bucketed and page-aligned)"
        )
        self.page_size = page_size
        self.num_pages = max(2, initial_pages)
        self._free = list(range(self.num_pages - 1, 0, -1))

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def allocated(self) -> int:
        """Pages currently owned by documents (PAD page excluded)."""
        return self.num_pages - 1 - len(self._free)

    def pages_for(self, rows: int) -> int:
        return -(-rows // self.page_size)

    def ensure(self, n: int) -> bool:
        """Guarantees `n` free pages, growing the slab in ONE pow2 jump
        (at least a doubling) when short — every distinct slab size is a
        compiled-program shape, so growth events must stay logarithmic.
        Returns True when `num_pages` changed (caller grows the slab)."""
        if len(self._free) >= n:
            return False
        needed_total = self.num_pages + n - len(self._free)
        old = self.num_pages
        self.num_pages = max(
            1 << (needed_total - 1).bit_length(), old * 2
        )
        self._free.extend(range(self.num_pages - 1, old - 1, -1))
        return True

    def alloc(self, n: int) -> list:
        assert len(self._free) >= n, "alloc without ensure"
        taken = self._free[len(self._free) - n:]
        del self._free[len(self._free) - n:]
        return taken[::-1]

    def free(self, pages) -> None:
        self._free.extend(pages)


# ---------------------------------------------------------------------- #
# device programs: gather -> kernel -> scatter, one XLA program each.
#
# Gathers and scatters move whole PAGES, not rows: the index tensors are
# [A, W/P] page ids (64x fewer indices than row maps) and every move is a
# contiguous page_size-row block — the difference between vectorised block
# copies and scalarised element gathers. Correctness rests on the
# page-tail invariant: rows of a page beyond its document's length always
# hold PAD values. Fresh pages start PAD (make_empty_slab/grow_slab), and
# every scatter writes full pages whose tail rows carry the merge kernel's
# PAD output, so the invariant is inductive; gathering a doc's pages
# therefore yields exactly the dense [len | PAD...] view the kernels
# expect, with no per-row masking.

def _gather_pages(slab: SlabState, page_idx, page_size: int):
    a = page_idx.shape[0]

    def g(col):
        return col.reshape(-1, page_size)[page_idx].reshape(a, -1)

    return tuple(g(col) for col in slab)


@profiled_jit("paging.apply_ops", static_argnames=("page_size",),
              donate_argnums=(0,))
def paged_apply_ops(slab: SlabState, gather_pages, changes, dest_pages, *,
                    page_size: int) -> SlabState:
    """applyChanges over the active documents: gather their pages from the
    slab, merge the change batch with the unchanged per-doc kernel, and
    scatter every merged page to its new slot. `dest_pages` holds
    ``num_pages`` (out of range -> dropped) for pad slots, so dead pages
    never write and the PAD page is never a target."""
    a = gather_pages.shape[0]
    s_key, s_op, s_action, s_value, s_pred, s_over = _gather_pages(
        slab, gather_pages, page_size
    )
    num = jnp.zeros((a,), jnp.int32)  # host tracks lengths
    key, op, action, value, pred, over, _num = jax.vmap(_merge_one_doc)(
        s_key, s_op, s_action, s_value, s_pred, s_over, num,
        changes.key, changes.op, changes.action, changes.value, changes.pred,
    )

    def scatter(col, vals):
        paged = col.reshape(-1, page_size)
        vals = vals.reshape(a, -1, page_size)
        return paged.at[dest_pages].set(vals, mode="drop").reshape(-1)

    return SlabState(
        key=scatter(slab.key, key),
        op=scatter(slab.op, op),
        action=scatter(slab.action, action),
        value=scatter(slab.value, value),
        pred=scatter(slab.pred, pred),
        overwritten=scatter(slab.overwritten, over),
    )


@profiled_jit("paging.probe_ops", static_argnames=("page_size",))
def paged_probe_ops(slab: SlabState, gather_pages, changes, *, page_size: int):
    """The merge WITHOUT the scatter (and without donation): bisection
    probes run the suspect subset against the live slab on a throwaway
    basis — the slab is never advanced."""
    s_key, s_op, s_action, s_value, s_pred, s_over = _gather_pages(
        slab, gather_pages, page_size
    )
    num = jnp.zeros((gather_pages.shape[0],), jnp.int32)
    return jax.vmap(_merge_one_doc)(
        s_key, s_op, s_action, s_value, s_pred, s_over, num,
        changes.key, changes.op, changes.action, changes.value, changes.pred,
    )


@profiled_jit("paging.visible_plain", static_argnames=("page_size",))
def paged_visible_plain(slab: SlabState, gather_pages, *, page_size: int):
    key, op, action, value, pred, over = _gather_pages(
        slab, gather_pages, page_size
    )
    return jax.vmap(_visible_state_one_doc)(key, op, action, value, pred, over, op)


@profiled_jit("paging.visible_ranked", static_argnames=("page_size",))
def paged_visible_ranked(slab: SlabState, gather_pages, actor_rank, *,
                         page_size: int):
    key, op, action, value, pred, over = _gather_pages(
        slab, gather_pages, page_size
    )
    cmp = remap_opid_actors(op, actor_rank)
    return jax.vmap(_visible_state_one_doc)(key, op, action, value, pred, over, cmp)


@profiled_jit("paging.patch_column_rows")
def patch_column_rows(visible, totals, op, actor_rank, idx, cut):
    """Row gather + device patch emission for the scoped readback:
    `visible`/`totals`/`op` are the paged visibility outputs
    (paged_visible_ranked, ``[A_pad, W]``), `idx` flat ``doc * W + row``
    indices host-padded to pow2, `cut` each row's walk cutoff as a
    rank-packed int64 (``-1`` = outside the delivery's cutoff set — pad
    rows never emit; int64 max = walk to the end of the key run). Returns
    (visible, totals, emit) rows. Kept separate from the visibility
    program on purpose: this gather's shape varies with the pow2 idx
    bucket and compiles in milliseconds, while the expensive visibility
    kernel keeps its one ``[A_pad, W]`` shape."""
    from .rga import patch_emit_columns  # rga imports engine: bind lazily

    v = visible.reshape(-1)[idx]
    t = totals.reshape(-1)[idx]
    lam = remap_opid_actors(op.reshape(-1)[idx], actor_rank)
    return v, t, patch_emit_columns(v, lam, cut)


@profiled_jit("paging.dense_view", static_argnames=("page_size",))
def paged_dense_view(slab: SlabState, gather_pages, *, page_size: int):
    """Dense [D, W] gather of all six columns (parity/debug readback)."""
    return _gather_pages(slab, gather_pages, page_size)


@profiled_jit("paging.adopt_rows", static_argnames=("page_size",),
              donate_argnums=(0,))
def paged_adopt_rows(slab: SlabState, dest_pages, key, op, action, value,
                     pred, over, *, page_size: int) -> SlabState:
    """Installs externally prepared rows (a migrated document) into freshly
    allocated pages: a pure whole-page scatter, no merge. The row columns
    arrive host-padded to ``len(dest_pages) * page_size`` with PAD fills,
    so every written page keeps the page-tail invariant; `dest_pages` holds
    ``num_pages`` (out of range -> dropped) for pow2-bucket pad slots."""

    def scatter(col, vals):
        paged = col.reshape(-1, page_size)
        return paged.at[dest_pages].set(
            vals.reshape(-1, page_size), mode="drop"
        ).reshape(-1)

    return SlabState(
        key=scatter(slab.key, key),
        op=scatter(slab.op, op),
        action=scatter(slab.action, action),
        value=scatter(slab.value, value),
        pred=scatter(slab.pred, pred),
        overwritten=scatter(slab.overwritten, over),
    )
