"""amprof — compiled-program observatory and memory sampler.

The stack's perf trajectory is governed by three quantities that were
invisible before this module: XLA recompiles (previously inferred via an
anonymous ``_cache_size`` delta in ``tpu/engine.py``), slab/page memory
behaviour over time, and the mesh pickle tax (measured by the
``mesh.pipe.<s>.*`` family that ``parallel/workers.py`` feeds — see
ROADMAP item 2b). Three pieces live here:

- :class:`ProfiledProgram` / :class:`Observatory` — every jit program in
  the tpu layer registers under a stable name (``tpu/jitprof.py`` is the
  one blessed ``jax.jit`` call site; amlint AM306 enforces it). Each
  dispatch through a profiled program records per-program dispatch
  counts, dispatch-latency histograms, compile counts and compile wall
  time, plus the shape-bucket signature that triggered each compile.
  Recompile flight events carry program identity, and a storm detector
  (>= ``storm_compiles`` compiles of ONE program inside
  ``storm_window_s``) emits ``prof.recompile.storm`` with the offending
  bucket sequence.
- :class:`Sampler` — point-in-time snapshots of slab pages
  (allocated/free/occupancy/fragmentation), DecodeCache pinned bytes and
  cached ``_ChangeCols`` column bytes, exported as ``prof.mem.*`` gauges.
  Everything is cast to plain ``int``/``float`` before it enters a
  sample dict (np.int64 stringifies under ``json.dumps(default=str)``).
- the module-level observatory singleton (:func:`get_observatory`),
  disabled by default with the same one-attribute hot-path guard as the
  metrics registry: a dispatch through a disabled observatory costs one
  attribute read and a branch.

Like the rest of obs/, this module is import-light: no jax, no tpu
imports (it inspects engine/farm objects duck-typed and reaches codecs
via ``sys.modules`` so importing obs never initialises the device
layer).
"""
# amlint: host-only
from __future__ import annotations

import sys
import time
from collections import deque

from .flight import get_flight
from .metrics import get_metrics

#: compiles of one program inside the window that constitute a storm
STORM_COMPILES = 4
#: storm detector window (seconds, on the injected clock)
STORM_WINDOW_S = 10.0
#: shape buckets retained per program (newest last)
RECENT_BUCKETS = 8


def shape_bucket(args, kwargs):
    """The shape signature of a call: sorted, deduplicated shape tuples of
    every array-like leaf in ``(args, kwargs)``. Stdlib-only (NamedTuples
    like SlabState traverse as tuples), so the observatory never imports
    jax."""
    shapes = set()
    stack = [args, kwargs]
    while stack:
        node = stack.pop()
        shape = getattr(node, "shape", None)
        if shape is not None:
            shapes.add(tuple(int(d) for d in shape))
        elif isinstance(node, dict):
            stack.extend(node.values())
        elif isinstance(node, (tuple, list)):
            stack.extend(node)
    return sorted(shapes)


class ProfiledProgram:
    """One named jit program plus its dispatch/compile tallies.

    Calling the wrapper with the observatory disabled falls straight
    through to the jitted function (one attribute read, one branch).
    ``call_profiled`` is the instrumented path used by both the wrapper
    itself and ``engine._dispatch`` (which layers the engine-wide
    hit/recompile counters on top of the returned growth)."""

    __slots__ = ("name", "fn", "_obs", "compiles", "dispatches",
                 "compile_s", "dispatch_s", "recent_buckets",
                 "_storm_times", "_m")

    def __init__(self, name, fn, observatory):
        self.name = name
        self.fn = fn
        self._obs = observatory
        self.compiles = 0
        self.dispatches = 0
        self.compile_s = 0.0
        self.dispatch_s = 0.0
        self.recent_buckets = deque(maxlen=RECENT_BUCKETS)
        self._storm_times = deque()
        self._m = None

    def __call__(self, *args, **kwargs):
        if not self._obs.enabled:
            return self.fn(*args, **kwargs)
        out, _grew, _dt = self.call_profiled(args, kwargs)
        return out

    def cache_size(self) -> int:
        """Entries in the jitted function's tracing cache, -1 when the
        backing callable does not expose one (plain functions in tests)."""
        probe = getattr(self.fn, "_cache_size", None)
        if probe is None:
            return -1
        try:
            return int(probe())
        except Exception:
            return -1

    def _instruments(self):
        m = self._m
        if m is None:
            reg = self._obs.registry
            name = self.name
            m = (
                reg.counter(f"prof.program.{name}.compiles",
                            "XLA compiles attributed to this program"),
                reg.counter(f"prof.program.{name}.dispatches",
                            "dispatches through this program"),
                reg.histogram(f"prof.program.{name}.compile_ms",
                              "wall time of dispatches that compiled"),
                reg.histogram(f"prof.program.{name}.dispatch_ms",
                              "per-dispatch wall time"),
            )
            self._m = m
        return m

    def call_profiled(self, args, kwargs):
        """Dispatches with full accounting; returns ``(out, grew, dt)``
        where ``grew`` is the tracing-cache growth (-1 when unprobeable)
        and ``dt`` the dispatch wall time on the observatory clock."""
        obs = self._obs
        clock = obs.clock
        before = self.cache_size()
        t0 = clock()
        out = self.fn(*args, **kwargs)
        dt = clock() - t0
        after = self.cache_size()
        grew = (after - before) if after >= 0 and before >= 0 else -1
        if grew > 0:
            bucket = shape_bucket(args, kwargs)
            self.recent_buckets.append(bucket)
            flight = obs.flight
            if flight.enabled:
                flight.record(
                    "engine.recompile",
                    program=self.name,
                    fn=getattr(self.fn, "__name__", self.name),
                    shapes=bucket,
                    cache_size=after,
                )
            obs._note_compiles(self, grew)
        if obs.enabled:
            self.dispatches += 1
            self.dispatch_s += dt
            m_compiles, m_dispatches, m_compile_ms, m_dispatch_ms = (
                self._instruments())
            m_dispatches.inc()
            m_dispatch_ms.observe(dt * 1000.0)
            if grew > 0:
                self.compiles += grew
                self.compile_s += dt
                m_compiles.inc(grew)
                m_compile_ms.observe(dt * 1000.0)
        return out, grew, dt

    def stats(self) -> dict:
        return {
            "compiles": int(self.compiles),
            "dispatches": int(self.dispatches),
            "compile_ms": round(self.compile_s * 1000.0, 3),
            "dispatch_ms": round(self.dispatch_s * 1000.0, 3),
            "cache_size": self.cache_size(),
            "buckets": [
                [list(shape) for shape in bucket]
                for bucket in self.recent_buckets
            ],
        }

    def reset(self) -> None:
        self.compiles = 0
        self.dispatches = 0
        self.compile_s = 0.0
        self.dispatch_s = 0.0
        self.recent_buckets.clear()
        self._storm_times.clear()


class Observatory:
    """Registry of named :class:`ProfiledProgram` wrappers plus the
    recompile-storm detector. Disabled by default; enabling is a single
    flag flip (programs read it per dispatch)."""

    def __init__(self, registry=None, flight=None, clock=None,
                 storm_compiles: int = STORM_COMPILES,
                 storm_window_s: float = STORM_WINDOW_S):
        self.enabled = False
        self.registry = registry if registry is not None else get_metrics()
        self.flight = flight if flight is not None else get_flight()
        self.clock = clock if clock is not None else time.monotonic
        self.storm_compiles = storm_compiles
        self.storm_window_s = storm_window_s
        self._programs: dict = {}

    def register(self, name: str, fn) -> ProfiledProgram:
        """Wraps ``fn`` as a named profiled program. Re-registering a name
        (module reload) rebinds the callable but keeps the tallies."""
        prog = self._programs.get(name)
        if prog is None:
            prog = ProfiledProgram(name, fn, self)
            self._programs[name] = prog
        else:
            prog.fn = fn
        return prog

    def program(self, name: str):
        return self._programs.get(name)

    def programs(self) -> dict:
        return dict(self._programs)

    def enable(self) -> None:
        self.enabled = True

    def disable(self) -> None:
        self.enabled = False

    def reset(self) -> None:
        for prog in self._programs.values():
            prog.reset()

    def table(self) -> dict:
        """``{program name: stats dict}`` for every registered program
        that has been dispatched at least once (plain ints/floats)."""
        return {
            name: prog.stats()
            for name, prog in sorted(self._programs.items())
            if prog.dispatches or prog.compiles
        }

    def _note_compiles(self, prog: ProfiledProgram, grew: int) -> None:
        """Feeds the storm detector: ``grew`` compiles of ``prog`` landed
        now. Fires ``prof.recompile.storm`` once per storm, then re-arms."""
        now = self.clock()
        times = prog._storm_times
        for _ in range(grew):
            times.append(now)
        horizon = now - self.storm_window_s
        while times and times[0] < horizon:
            times.popleft()
        if len(times) >= self.storm_compiles:
            flight = self.flight
            if flight.enabled:
                flight.record(
                    "prof.recompile.storm",
                    program=prog.name,
                    compiles=len(times),
                    window_s=self.storm_window_s,
                    buckets=[
                        [list(shape) for shape in bucket]
                        for bucket in prog.recent_buckets
                    ],
                )
            times.clear()


_GLOBAL = Observatory()


def get_observatory() -> Observatory:
    """The process-wide observatory (one per process; workers ship their
    per-program counters through the existing metrics-delta pipe)."""
    return _GLOBAL


class enabled_observatory:
    """Context manager: enables the observatory (and restores the prior
    state on exit). Program tallies are NOT reset — call
    ``get_observatory().reset()`` for a clean slate."""

    def __init__(self, observatory: Observatory | None = None):
        self._obs = observatory if observatory is not None else _GLOBAL
        self._was = False

    def __enter__(self) -> Observatory:
        self._was = self._obs.enabled
        self._obs.enable()
        return self._obs

    def __exit__(self, *exc) -> None:
        self._obs.enabled = self._was


def _longest_free_run(free_pages) -> int:
    """Longest run of consecutive page ids in the free list (the largest
    allocation the slab can satisfy contiguously)."""
    if not free_pages:
        return 0
    ids = sorted(set(int(p) for p in free_pages))
    best = run = 1
    for prev, cur in zip(ids, ids[1:]):
        run = run + 1 if cur == prev + 1 else 1
        if run > best:
            best = run
    return best


class Sampler:
    """Point-in-time memory/occupancy snapshots of a farm or engine.

    ``sample(farm=...)`` (or ``engine=...``) duck-types its way around the
    device layer: slab pages come from ``engine.pages`` (a PageAllocator),
    row occupancy from ``engine.lengths``, cached change columns from
    ``farm._cols_cache`` (entries with an ``.arr`` ndarray), and
    DecodeCache pinned bytes from ``automerge_tpu.codecs`` IF that module
    is already imported (``sys.modules`` probe — sampling never imports
    the device layer). Every value is cast to plain int/float before it
    enters the sample dict or a gauge, so samples survive
    ``json.dumps`` without np.int64 stringification."""

    def __init__(self, registry=None, clock=None, keep: int = 256):
        self.registry = registry if registry is not None else get_metrics()
        self.clock = clock if clock is not None else time.monotonic
        self.samples = deque(maxlen=keep)
        reg = self.registry
        self._g_allocated = reg.gauge(
            "prof.mem.pages.allocated", "slab pages owned by documents")
        self._g_free = reg.gauge(
            "prof.mem.pages.free", "slab pages on the free list")
        self._g_occupancy = reg.gauge(
            "prof.mem.pages.occupancy",
            "live rows / allocated page capacity")
        self._g_fragmentation = reg.gauge(
            "prof.mem.pages.fragmentation",
            "1 - longest contiguous free run / free pages")
        self._g_decode_bytes = reg.gauge(
            "prof.mem.decode_cache.bytes",
            "chunk bytes pinned across DecodeCache instances")
        self._g_cols_bytes = reg.gauge(
            "prof.mem.change_cols.bytes",
            "ndarray bytes held by cached change columns")
        self._g_cols_entries = reg.gauge(
            "prof.mem.change_cols.entries",
            "cached change-column entries (incl. uncacheable sentinels)")

    def sample(self, farm=None, engine=None) -> dict:
        """Takes one snapshot, updates the ``prof.mem.*`` gauges, appends
        to the bounded ring, and returns the sample dict."""
        if engine is None and farm is not None:
            engine = getattr(farm, "engine", None)
        out = {"t": float(self.clock())}

        pages = getattr(engine, "pages", None)
        if pages is not None:
            allocated = int(pages.allocated)
            free = int(pages.free_count)
            page_size = int(pages.page_size)
            rows = 0
            lengths = getattr(engine, "lengths", None)
            if lengths is not None:
                rows = int(sum(int(n) for n in lengths))
            capacity = allocated * page_size
            occupancy = (rows / capacity) if capacity else 0.0
            run = _longest_free_run(getattr(pages, "_free", ()))
            fragmentation = (1.0 - run / free) if free else 0.0
            out.update(
                pages_allocated=allocated,
                pages_free=free,
                page_size=page_size,
                rows=rows,
                occupancy=round(occupancy, 4),
                fragmentation=round(fragmentation, 4),
            )
            self._g_allocated.set(allocated)
            self._g_free.set(free)
            self._g_occupancy.set(occupancy)
            self._g_fragmentation.set(fragmentation)

        codecs = sys.modules.get("automerge_tpu.codecs")
        if codecs is not None:
            decode_bytes = int(sum(
                int(v) for v in codecs.DecodeCache._name_bytes.values()))
            out["decode_cache_bytes"] = decode_bytes
            self._g_decode_bytes.set(decode_bytes)

        cols_cache = getattr(farm, "_cols_cache", None)
        if cols_cache is not None:
            cols_bytes = 0
            entries = 0
            for value in cols_cache.values():
                entries += 1
                arr = getattr(value, "arr", None)
                if arr is None:
                    continue
                cols_bytes += int(arr.nbytes)
                cached_sort = getattr(value, "_sorted", None)
                if cached_sort is not None:
                    cols_bytes += int(sum(
                        int(col.nbytes) for col in cached_sort
                        if hasattr(col, "nbytes")))
            out["change_cols_bytes"] = int(cols_bytes)
            out["change_cols_entries"] = int(entries)
            self._g_cols_bytes.set(cols_bytes)
            self._g_cols_entries.set(entries)

        self.samples.append(out)
        return out
