"""amtrace spans: nested wall-clock span trees with latency histograms.

The original `PhaseProfile` (automerge_tpu/profiling.py, now a shim over
this module) accumulated flat per-name totals behind a *module-global*
ambient slot — unusable once two farms run in different threads or asyncio
tasks. This module replaces it with:

- **Span trees**: `Trace.span(name)` opens a nested span; each distinct
  (parent, name) node accumulates wall time, call count and a fixed-bucket
  latency histogram from which p50/p95/p99 are read. Trees render as an
  indented table (`Trace.tree_table()`) and export/import as JSON lines
  (`Trace.to_jsonl()` / `Trace.from_jsonl()`) so a bench run on one host
  can be inspected on another.
- **Ambient propagation via `contextvars`**: `use_trace(trace)` installs
  the trace for the current *context* (thread / asyncio task), so
  concurrent farms never cross-pollute each other's profiles
  (tests/test_obs.py::test_two_interleaved_contexts_do_not_cross_pollute).
- **Near-zero disabled cost**: `Trace(enabled=False).span(...)` performs a
  single attribute test and never touches the clock or allocates a node
  (asserted by tests/test_obs.py::test_disabled_span_is_attribute_test_only).

Histogram buckets are log2-spaced: bucket i covers
[1µs·2^i, 1µs·2^(i+1)), 28 buckets spanning 1µs to ~134s; out-of-range
durations clamp to the first/last bucket. Quantiles report the upper bound
of the bucket where the cumulative count crosses the quantile — a
deterministic over-estimate, the standard fixed-bucket convention.
"""
# amlint: host-only — pure-host layer: must not import tpu/ or jax
from __future__ import annotations

import contextlib
import contextvars
import json
import math
import time
from typing import Iterator

#: log2-spaced histogram: bucket i covers [FLOOR * 2**i, FLOOR * 2**(i+1))
BUCKET_FLOOR_S = 1e-6
NUM_BUCKETS = 28


def bucket_index(seconds: float) -> int:
    """Histogram bucket for a duration; clamps below-floor and overflow."""
    if seconds < BUCKET_FLOOR_S:
        return 0
    i = int(math.log2(seconds / BUCKET_FLOOR_S))
    # float log2 can land one bucket low at exact powers of two
    if seconds >= BUCKET_FLOOR_S * (1 << (i + 1)):
        i += 1
    return min(i, NUM_BUCKETS - 1)


def bucket_bounds(index: int) -> tuple[float, float]:
    """[lo, hi) duration bounds of one histogram bucket, in seconds."""
    return BUCKET_FLOOR_S * (1 << index), BUCKET_FLOOR_S * (1 << (index + 1))


class SpanNode:
    """One node of a span tree: aggregate stats for a (parent, name) pair."""

    __slots__ = ("name", "total_s", "calls", "buckets", "children")

    def __init__(self, name: str):
        self.name = name
        self.total_s = 0.0
        self.calls = 0
        self.buckets: dict[int, int] = {}  # sparse: bucket index -> count
        self.children: dict[str, SpanNode] = {}

    def child(self, name: str) -> "SpanNode":
        node = self.children.get(name)
        if node is None:
            node = self.children[name] = SpanNode(name)
        return node

    def record(self, elapsed_s: float) -> None:
        self.total_s += elapsed_s
        self.calls += 1
        b = bucket_index(elapsed_s)
        self.buckets[b] = self.buckets.get(b, 0) + 1

    def percentile(self, q: float) -> float | None:
        """Upper bound of the bucket holding the q-quantile (q in [0, 1]),
        or None when the node has no recorded calls."""
        if self.calls == 0:
            return None
        threshold = q * self.calls
        cum = 0
        for b in sorted(self.buckets):
            cum += self.buckets[b]
            if cum >= threshold:
                return bucket_bounds(b)[1]
        return bucket_bounds(max(self.buckets))[1]

    def as_dict(self) -> dict:
        out = {
            "name": self.name,
            "total_s": self.total_s,
            "calls": self.calls,
            "buckets": {str(b): c for b, c in sorted(self.buckets.items())},
        }
        if self.children:
            out["children"] = [
                c.as_dict() for c in self.children.values()
            ]
        return out


class Trace:
    """A span tree plus the enabled flag. See module docstring."""

    __slots__ = ("enabled", "root")

    def __init__(self, enabled: bool = True):
        self.enabled = enabled
        self.root = SpanNode("")

    @contextlib.contextmanager
    def span(self, name: str) -> Iterator[SpanNode | None]:
        if not self.enabled:
            yield None
            return
        state = _STATE.get()
        parent = state[1] if state[0] is self else self.root
        node = parent.child(name)
        token = _STATE.set((self, node))
        start = time.perf_counter()
        try:
            yield node
        finally:
            node.record(time.perf_counter() - start)
            _STATE.reset(token)

    # the historical PhaseProfile spelling; same ambient/nesting semantics
    phase = span

    def reset(self) -> None:
        self.root = SpanNode("")

    # ------------------------------------------------------------------ #
    # aggregation (PhaseProfile compatibility surface)

    def totals_by_name(self) -> dict[str, tuple[float, int]]:
        """{name: (total_s, calls)} summed over every node of that name,
        anywhere in the tree — the flat view the old PhaseProfile kept.
        Distinct-path spans that share a name are MERGED here; renderers
        that must not lose per-path counts use ``totals_by_path``."""
        out: dict[str, tuple[float, int]] = {}
        stack = list(self.root.children.values())
        while stack:
            node = stack.pop()
            t, c = out.get(node.name, (0.0, 0))
            out[node.name] = (t + node.total_s, c + node.calls)
            stack.extend(node.children.values())
        return out

    def totals_by_path(self) -> dict[str, tuple[float, int]]:
        """{"outer/inner": (total_s, calls)} — one entry per distinct tree
        path (root children are bare names). Unlike ``totals_by_name``,
        same-named spans under different parents keep their own totals and
        call counts, so a flat renderer cannot silently merge them."""
        out: dict[str, tuple[float, int]] = {}

        def walk(node: SpanNode, prefix: str) -> None:
            for child in node.children.values():
                path = f"{prefix}/{child.name}" if prefix else child.name
                out[path] = (child.total_s, child.calls)
                walk(child, path)

        walk(self.root, "")
        return out

    # ------------------------------------------------------------------ #
    # rendering

    def tree_table(self) -> str:
        """Indented span tree with totals, call counts and p50/p95/p99."""
        rows: list[tuple[str, SpanNode]] = []

        def walk(node: SpanNode, depth: int) -> None:
            rows.append(("  " * depth + node.name, node))
            for child in sorted(
                node.children.values(), key=lambda n: n.total_s, reverse=True
            ):
                walk(child, depth + 1)

        for top in sorted(
            self.root.children.values(), key=lambda n: n.total_s, reverse=True
        ):
            walk(top, 0)
        if not rows:
            return "(no spans recorded)"

        width = max(len(label) for label, _ in rows)
        header = (
            f"{'span'.ljust(width)}  {'total':>12}  {'calls':>7}  "
            f"{'p50':>9}  {'p95':>9}  {'p99':>9}"
        )
        lines = [header]
        for label, node in rows:
            lines.append(
                f"{label.ljust(width)}  {_fmt_s(node.total_s):>12}  "
                f"{node.calls:>7}  {_fmt_s(node.percentile(0.50)):>9}  "
                f"{_fmt_s(node.percentile(0.95)):>9}  "
                f"{_fmt_s(node.percentile(0.99)):>9}"
            )
        return "\n".join(lines)

    # ------------------------------------------------------------------ #
    # JSON-lines export / import

    def to_jsonl(self) -> str:
        """One JSON object per span node, carrying its path from the root —
        a flat, stream-appendable trace dump."""
        lines: list[str] = []

        def walk(node: SpanNode, path: list[str]) -> None:
            lines.append(json.dumps({
                "path": path,
                "total_s": node.total_s,
                "calls": node.calls,
                "buckets": {str(b): c for b, c in sorted(node.buckets.items())},
            }, sort_keys=True))
            for child in node.children.values():
                walk(child, path + [child.name])

        for top in self.root.children.values():
            walk(top, [top.name])
        return "\n".join(lines) + ("\n" if lines else "")

    @classmethod
    def from_jsonl(cls, text: str) -> "Trace":
        """Rebuilds a trace from `to_jsonl` output (order-insensitive;
        repeated paths accumulate, so concatenated dumps merge)."""
        trace = cls()
        trace.absorb_jsonl(text)
        return trace

    def absorb_jsonl(self, text: str) -> "Trace":
        """Merges a `to_jsonl` dump into THIS trace in place (same
        accumulate-on-repeated-path semantics as ``from_jsonl``). This is
        how a mesh worker's phase totals land in the controller's ambient
        profile: the worker runs its shard dispatch under its own trace,
        ships ``to_jsonl()`` back with the result frame, and the
        controller absorbs it — so ``--watch`` still attributes
        device_dispatch/transcode time per shard even when the shard
        lives in another process."""
        for line in text.splitlines():
            line = line.strip()
            if not line:
                continue
            entry = json.loads(line)
            node = self.root
            for name in entry["path"]:
                node = node.child(name)
            node.total_s += entry["total_s"]
            node.calls += entry["calls"]
            for b, c in entry.get("buckets", {}).items():
                b = int(b)
                node.buckets[b] = node.buckets.get(b, 0) + c
        return self


def _fmt_s(seconds: float | None) -> str:
    if seconds is None:
        return "-"
    if seconds >= 1.0:
        return f"{seconds:.2f} s"
    if seconds >= 1e-3:
        return f"{seconds * 1e3:.2f} ms"
    return f"{seconds * 1e6:.0f} us"


# ---------------------------------------------------------------------- #
# ambient trace: per-context (thread / asyncio task), never a module global

_NULL = Trace(enabled=False)
#: (active trace, current span node) for the running context
_STATE: contextvars.ContextVar[tuple[Trace, SpanNode]] = contextvars.ContextVar(
    "amtrace_state", default=(_NULL, _NULL.root)
)


def get_trace() -> Trace:
    """The ambient trace (a disabled no-op unless one is installed)."""
    return _STATE.get()[0]


@contextlib.contextmanager
def use_trace(trace: Trace) -> Iterator[Trace]:
    """Installs `trace` as the ambient trace for the dynamic extent, in the
    current context only."""
    token = _STATE.set((trace, trace.root))
    try:
        yield trace
    finally:
        _STATE.reset(token)
