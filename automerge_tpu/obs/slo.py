"""amslo: declared service-level objectives evaluated as multi-window
burn rates over the amtrace metrics registry.

ROADMAP item 5 says amserve "has never faced a wall clock": the stack had
latency histograms and shed counters but no notion of *how good is good
enough*. This module closes that gap with the classic SRE shape — an
objective declares a compliance target against an error budget, the
engine samples cumulative good/total counts on an **injected clock**
(`time.monotonic` in real serving, the simulated `ManualClock` in the
load harness — both work identically), and evaluation reports, per
objective, the overall compliance plus a **burn rate** for each
configured window: how many times faster than sustainable the error
budget is being spent. A burn rate of 1.0 exactly exhausts the budget
over the objective's horizon; the multi-window rule (all windows burning
simultaneously) separates a real sustained regression from a one-tick
blip, which a single window cannot.

Three objective kinds cover the serving story:

- ``latency``: fraction of observations at or under ``budget_ms`` in a
  histogram (bucketed compliance on the shared log2 grid) must meet
  ``target`` — e.g. "99% of requests under 250 ms";
- ``availability``: ``good / (good + bad)`` over counters — e.g.
  admission accepts vs backpressure rejections;
- ``ratio``: a gauge read directly as the compliance value — e.g. the
  converged-client ratio the load harness publishes at the end of a run.

Verdicts are exported three ways: as ``slo.*`` gauges in the registry
(so the Prometheus exposition and snapshot stream carry them), as
structured dicts in bench/loadgen reports (the ``--serve`` / ``--mesh``
verdict gates), and as a panel in the ``--watch`` view. The metric-name
catalog for the ``slo.*`` family lives in the README Observability
section (amlint AM304 checks both directions).
"""
# amlint: host-only — pure-host layer: must not import tpu/ or jax
from __future__ import annotations

import time
from dataclasses import dataclass

from .metrics import MetricsRegistry, get_metrics
from .spans import bucket_bounds

#: default burn-rate windows (seconds): a fast window to catch cliffs and
#: a slow one to confirm the budget is really being spent
DEFAULT_WINDOWS = (60.0, 300.0)
#: bounded sample history per objective
MAX_SAMPLES = 512


@dataclass(frozen=True)
class Objective:
    """One declared SLO. ``target`` is the compliance floor in [0, 1].

    ``metric`` names the good-signal instrument (histogram for latency,
    counter for availability, gauge for ratio); ``bad_metrics`` are the
    failure counters an availability objective folds into its
    denominator; ``budget_ms`` is the latency budget on the histogram's
    value axis."""

    name: str
    kind: str  # "latency" | "availability" | "ratio"
    metric: str
    target: float = 0.99
    budget_ms: float | None = None
    bad_metrics: tuple[str, ...] = ()

    def __post_init__(self):
        if self.kind not in ("latency", "availability", "ratio"):
            raise ValueError(f"unknown SLO kind {self.kind!r}")
        if self.kind == "latency" and self.budget_ms is None:
            raise ValueError(f"latency objective {self.name!r} needs budget_ms")
        if not 0.0 < self.target <= 1.0:
            raise ValueError(f"target must be in (0, 1], got {self.target}")


def latency_objective(name: str, metric: str, budget_ms: float,
                      target: float = 0.99) -> Objective:
    return Objective(name, "latency", metric, target, budget_ms=budget_ms)


def availability_objective(name: str, good: str, bad: tuple[str, ...],
                           target: float = 0.999) -> Objective:
    return Objective(name, "availability", good, target,
                     bad_metrics=tuple(bad))


def ratio_objective(name: str, metric: str, target: float) -> Objective:
    return Objective(name, "ratio", metric, target)


class SLOEngine:
    """Samples objectives on an injected clock and renders verdicts.

    ``sample()`` is cheap (a few instrument reads per objective) and is
    meant to be called from the serving loop's tick — the simulated tick
    in the load harness, the asyncio flusher in ``serve_forever``.
    ``evaluate()`` turns the sample history into verdict dicts and
    ``export()`` mirrors them into ``slo.*`` gauges."""

    def __init__(self, objectives, *, clock=None, registry=None,
                 windows=DEFAULT_WINDOWS):
        self.objectives: tuple[Objective, ...] = tuple(objectives)
        self.clock = clock if clock is not None else time.monotonic
        self._registry = registry
        self.windows = tuple(sorted(windows))
        # name -> list[(t, good, total)] cumulative samples, bounded
        self._samples: dict[str, list[tuple]] = {
            o.name: [] for o in self.objectives
        }

    @property
    def registry(self) -> MetricsRegistry:
        return self._registry if self._registry is not None else get_metrics()

    # -------------------------------------------------------------- #
    # sampling

    def _counts(self, o: Objective) -> tuple[float, float]:
        """Cumulative (good, total) for the objective right now."""
        reg = self.registry
        inst = reg.find(o.metric)
        if o.kind == "latency":
            if inst is None or not getattr(inst, "count", 0):
                return (0.0, 0.0)
            good = sum(
                c for b, c in inst.buckets.items()
                if bucket_bounds(b)[1] <= o.budget_ms
            )
            return (float(good), float(inst.count))
        if o.kind == "availability":
            good = float(getattr(inst, "value", 0) or 0)
            bad = sum(
                float(getattr(reg.find(m), "value", 0) or 0)
                for m in o.bad_metrics
            )
            return (good, good + bad)
        # ratio: a gauge IS the compliance; synthesize unit counts so the
        # window algebra below degrades to "latest value"
        value = float(getattr(inst, "value", 0.0) or 0.0)
        return (value, 1.0)

    def sample(self, now: float | None = None) -> None:
        t = self.clock() if now is None else now
        for o in self.objectives:
            ring = self._samples[o.name]
            ring.append((t, *self._counts(o)))
            if len(ring) > MAX_SAMPLES:
                del ring[: len(ring) - MAX_SAMPLES]

    # -------------------------------------------------------------- #
    # evaluation

    @staticmethod
    def _compliance(good: float, total: float) -> float | None:
        return None if total <= 0 else good / total

    def evaluate(self, now: float | None = None) -> list[dict]:
        """One verdict dict per objective: overall compliance vs target,
        per-window burn rates, and the pass/fail bits. Objectives with no
        data yet pass vacuously (``compliance: None``) — an idle service
        has not missed its SLO."""
        t = self.clock() if now is None else now
        self.sample(t)
        verdicts = []
        for o in self.objectives:
            ring = self._samples[o.name]
            t_now, good_now, total_now = ring[-1]
            compliance = self._compliance(good_now, total_now)
            if o.kind == "ratio":
                # gauges are instantaneous; cumulative algebra is moot
                compliance = good_now if total_now else None
            budget = max(1.0 - o.target, 1e-9)
            windows = []
            for w in self.windows:
                base = ring[0]
                for s in ring:
                    if s[0] >= t_now - w:
                        break
                    base = s
                if o.kind == "ratio":
                    w_comp = compliance
                else:
                    w_comp = self._compliance(
                        good_now - base[1], total_now - base[2]
                    )
                burn = None if w_comp is None else (1.0 - w_comp) / budget
                windows.append({
                    "window_s": w,
                    "compliance": w_comp,
                    "burn_rate": burn,
                })
            burns = [w["burn_rate"] for w in windows
                     if w["burn_rate"] is not None]
            burning = bool(burns) and all(b > 1.0 for b in burns)
            ok = compliance is None or compliance >= o.target
            verdicts.append({
                "objective": o.name,
                "kind": o.kind,
                "metric": o.metric,
                "target": o.target,
                "budget_ms": o.budget_ms,
                "compliance": compliance,
                "windows": windows,
                "burn_rate": max(burns) if burns else None,
                "burning": burning,
                "ok": ok,
            })
        return verdicts

    def export(self, verdicts: list[dict] | None = None,
               now: float | None = None) -> list[dict]:
        """Evaluates (unless given verdicts) and mirrors each verdict into
        ``slo.*`` gauges so the exposition/snapshot surfaces carry them:
        per-objective compliance, worst-window burn rate and the pass bit,
        plus the breach count across the whole set."""
        if verdicts is None:
            verdicts = self.evaluate(now)
        reg = self.registry
        breaches = 0
        for v in verdicts:
            name = v["objective"]
            help_ = f"SLO {v['kind']} objective on {v['metric']}"
            if v["compliance"] is not None:
                reg.gauge(f"slo.{name}.compliance", help_).set(v["compliance"])
            if v["burn_rate"] is not None:
                reg.gauge(f"slo.{name}.burn_rate", help_).set(v["burn_rate"])
            reg.gauge(f"slo.{name}.ok", help_).set(1.0 if v["ok"] else 0.0)
            breaches += 0 if v["ok"] else 1
        reg.gauge(
            "slo.breaches",
            "objectives currently out of compliance",
        ).set(float(breaches))
        return verdicts


def verdicts_ok(verdicts: list[dict]) -> bool:
    """The gate predicate benches use: every objective in compliance."""
    return all(v["ok"] for v in verdicts)


def render_verdicts(verdicts: list[dict]) -> str:
    """Human-readable verdict table (the ``--watch`` SLO panel)."""
    if not verdicts:
        return "(no SLOs declared)"
    width = max(len(v["objective"]) for v in verdicts)
    lines = []
    for v in verdicts:
        comp = "-" if v["compliance"] is None else f"{v['compliance']:.4f}"
        burn = "-" if v["burn_rate"] is None else f"{v['burn_rate']:.2f}"
        state = "ok" if v["ok"] else "BREACH"
        if v["burning"] and v["ok"]:
            state = "burning"
        wins = " ".join(
            f"{int(w['window_s'])}s="
            + ("-" if w["burn_rate"] is None else f"{w['burn_rate']:.2f}")
            for w in v["windows"]
        )
        lines.append(
            f"{v['objective'].ljust(width)}  target={v['target']:.3f}  "
            f"compliance={comp}  burn[{wins}]  max_burn={burn}  {state}"
        )
    return "\n".join(lines)


# -------------------------------------------------------------------- #
# canned objective sets

def default_serve_slos(*, budget_ms: float = 250.0,
                       latency_target: float = 0.99,
                       availability_target: float = 0.999,
                       convergence_target: float = 0.999,
                       latency_metric: str = "serve.request.e2e_ms",
                       ) -> list[Objective]:
    """The front door's default SLO set: request latency under budget,
    admission availability (accepts vs backpressure rejections — poison
    sheds are by-design and excluded), and the end-of-run converged-client
    ratio the load harness publishes. ``latency_metric`` defaults to the
    amscope request histogram; the load harness swaps in
    ``serve.sync.latency_ms`` so the objective also has data under the
    metrics-only stack."""
    return [
        latency_objective(
            "serve_latency", latency_metric, budget_ms,
            target=latency_target,
        ),
        availability_objective(
            "serve_availability", "serve.admission.accepted",
            ("serve.admission.rejected_backpressure",),
            target=availability_target,
        ),
        ratio_objective(
            "serve_convergence", "serve.clients.converged_ratio",
            convergence_target,
        ),
    ]


def default_mesh_slos(*, availability_target: float = 0.999
                      ) -> list[Objective]:
    """The mesh bench's machine-independent SLO set: delivery
    availability (changes applied vs docs lost to worker crashes) and
    worker liveness (spawns that stayed up vs crashes)."""
    return [
        availability_objective(
            "mesh_delivery", "farm.changes.applied",
            ("mesh.worker.lost_docs",),
            target=availability_target,
        ),
        availability_objective(
            "mesh_workers", "mesh.worker.spawns",
            ("mesh.worker.crashes",), target=availability_target,
        ),
    ]
