"""amprof perf ledger — append-only JSONL of normalized bench records.

Every ``bench.py --quick`` / ``--mesh --quick`` run appends one record:
config hash, phase table, ops/s, per-program compile/dispatch stats and
(mesh) per-shard pipe bytes. The ledger is the regression memory the
one-shot bench numbers lack — ``python -m automerge_tpu.obs --ledger
ledger.jsonl`` renders the trajectory, ``--diff A B`` diffs two records
by index (negative indices count from the end, so ``--diff -2 -1``
compares the last two runs).

Records are machine-local (wall times differ across hosts); the
regression GATES in bench.py are therefore machine-independent counts
(compiles per program, pipe bytes per round), and the ledger keeps the
wall-clock context those counts were measured in.
"""
# amlint: host-only
from __future__ import annotations

import hashlib
import json
from pathlib import Path


def normalize(value):
    """Recursively converts numpy scalars/arrays and other non-JSON
    leaves into plain Python ints/floats/lists (np.int64 stringifies
    under ``json.dumps(default=str)``; the ledger must stay diffable)."""
    if isinstance(value, dict):
        return {str(k): normalize(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [normalize(v) for v in value]
    if isinstance(value, bool) or value is None:
        return value
    if isinstance(value, (int, float, str)):
        return value
    item = getattr(value, "item", None)
    if callable(item):
        try:
            return normalize(item())
        except (TypeError, ValueError):
            pass
    tolist = getattr(value, "tolist", None)
    if callable(tolist):
        return normalize(tolist())
    return str(value)


def config_hash(config: dict) -> str:
    """Short stable hash of a bench configuration (records with equal
    hashes are comparable runs)."""
    canon = json.dumps(normalize(config), sort_keys=True,
                       separators=(",", ":"))
    return hashlib.sha256(canon.encode()).hexdigest()[:12]


def append_record(path, record: dict) -> dict:
    """Normalizes ``record``, stamps ``config_hash`` from its ``config``
    field, and appends one JSONL line. Returns the normalized record."""
    rec = normalize(record)
    if "config" in rec and "config_hash" not in rec:
        rec["config_hash"] = config_hash(rec["config"])
    out = Path(path)
    out.parent.mkdir(parents=True, exist_ok=True)
    with out.open("a", encoding="utf-8") as fh:
        fh.write(json.dumps(rec, sort_keys=True) + "\n")
    return rec


def load_ledger(path) -> list:
    """All records in the ledger, oldest first. Malformed lines are
    skipped (a crashed bench must not brick the trajectory view)."""
    records = []
    ledger = Path(path)
    if not ledger.exists():
        return records
    for line in ledger.read_text(encoding="utf-8").splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            records.append(json.loads(line))
        except json.JSONDecodeError:
            continue
    return records


def _program_totals(record: dict) -> tuple:
    programs = record.get("programs") or {}
    compiles = sum(int(p.get("compiles", 0)) for p in programs.values())
    dispatches = sum(int(p.get("dispatches", 0)) for p in programs.values())
    return compiles, dispatches


def _pipe_total(record: dict) -> int:
    pipe = record.get("pipe") or {}
    total = 0
    for shard in pipe.values():
        total += int(shard.get("bytes_out", 0)) + int(shard.get("bytes_in", 0))
    return total


def render_trajectory(records: list) -> str:
    """One row per record: index, kind, config hash, ops/s, compile and
    dispatch totals, pipe bytes."""
    if not records:
        return "ledger is empty"
    header = (f"{'#':>4}  {'kind':<12} {'config':<12} {'ops/s':>12} "
              f"{'compiles':>9} {'dispatches':>11} {'pipe_bytes':>11}")
    lines = [header, "-" * len(header)]
    for i, rec in enumerate(records):
        compiles, dispatches = _program_totals(rec)
        ops = rec.get("ops_per_sec")
        ops_s = f"{ops:,.0f}" if isinstance(ops, (int, float)) else "-"
        lines.append(
            f"{i:>4}  {str(rec.get('kind', '?')):<12} "
            f"{str(rec.get('config_hash', '?')):<12} {ops_s:>12} "
            f"{compiles:>9} {dispatches:>11} {_pipe_total(rec):>11}")
    return "\n".join(lines)


def diff_records(a: dict, b: dict) -> dict:
    """Structured diff of two ledger records (b relative to a): ops/s
    delta, per-program compile/dispatch deltas, per-shard pipe deltas."""
    out: dict = {
        "kind": (a.get("kind"), b.get("kind")),
        "config_hash": (a.get("config_hash"), b.get("config_hash")),
        "comparable": a.get("config_hash") == b.get("config_hash"),
    }
    ops_a, ops_b = a.get("ops_per_sec"), b.get("ops_per_sec")
    if isinstance(ops_a, (int, float)) and isinstance(ops_b, (int, float)):
        out["ops_per_sec"] = {
            "a": ops_a, "b": ops_b, "delta": ops_b - ops_a,
            "ratio": (ops_b / ops_a) if ops_a else None,
        }
    programs: dict = {}
    prog_a = a.get("programs") or {}
    prog_b = b.get("programs") or {}
    for name in sorted(set(prog_a) | set(prog_b)):
        pa, pb = prog_a.get(name, {}), prog_b.get(name, {})
        delta = {
            "compiles": int(pb.get("compiles", 0)) - int(pa.get("compiles", 0)),
            "dispatches": (int(pb.get("dispatches", 0))
                           - int(pa.get("dispatches", 0))),
        }
        if delta["compiles"] or delta["dispatches"]:
            programs[name] = delta
    out["programs"] = programs
    pipes: dict = {}
    pipe_a = a.get("pipe") or {}
    pipe_b = b.get("pipe") or {}
    for shard in sorted(set(pipe_a) | set(pipe_b), key=str):
        sa, sb = pipe_a.get(shard, {}), pipe_b.get(shard, {})
        delta = {
            key: int(sb.get(key, 0)) - int(sa.get(key, 0))
            for key in ("bytes_out", "bytes_in", "frames_out", "frames_in")
        }
        if any(delta.values()):
            pipes[shard] = delta
    out["pipe"] = pipes
    return out


def render_diff(a: dict, b: dict) -> str:
    diff = diff_records(a, b)
    lines = [
        f"diff {diff['kind'][0]}/{diff['config_hash'][0]} -> "
        f"{diff['kind'][1]}/{diff['config_hash'][1]}"
        + ("" if diff["comparable"] else "  [configs differ]"),
    ]
    ops = diff.get("ops_per_sec")
    if ops:
        ratio = ops["ratio"]
        lines.append(
            f"  ops/s: {ops['a']:,.0f} -> {ops['b']:,.0f} "
            f"({'x%.3f' % ratio if ratio is not None else 'n/a'})")
    if diff["programs"]:
        lines.append("  programs:")
        for name, delta in diff["programs"].items():
            lines.append(f"    {name}: compiles {delta['compiles']:+d}, "
                         f"dispatches {delta['dispatches']:+d}")
    else:
        lines.append("  programs: no change")
    if diff["pipe"]:
        lines.append("  pipe:")
        for shard, delta in diff["pipe"].items():
            lines.append(
                f"    shard {shard}: bytes_out {delta['bytes_out']:+d}, "
                f"bytes_in {delta['bytes_in']:+d}, "
                f"frames {delta['frames_out'] + delta['frames_in']:+d}")
    return "\n".join(lines)
