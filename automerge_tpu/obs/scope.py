"""amscope request-flow tracing: per-request causal attribution for the
serving stack.

amtrace's metrics are process-wide aggregates and its spans are local
wall-clock trees — neither can answer "where did THIS client's change
spend its 40 ms", because one request's journey crosses the session
multiplexer, a batching window shared with strangers, one batched farm
dispatch serving N requests at once, and the ack fan-out. This module
adds the request dimension on top, with no wire-format changes:

- **RequestScope** — a host-side trace context (trace id, tenant, doc,
  client) attached to each frame at ``AmServer.receive`` and carried
  through admission, ``DynamicBatcher`` window membership and commit.
  Lifecycle marks (``received`` -> ``flush`` -> ``committed`` ->
  ``sent``) are stamped with the *injected* clock, so simulated-time
  harnesses price the batching window exactly as a client feels it.
- **DispatchSpan** — ONE batched farm dispatch linking the N request
  traces it served, carrying the per-phase host durations (decode,
  gate+transcode, pack, device_dispatch, visibility readback, patch
  assembly) captured from the farm's phase profile around the dispatch.
  Every member request shares the span's phase breakdown — that is the
  honest attribution for batched execution.
- **Exemplars** — the request/phase histograms record each observation's
  trace id into its bucket (obs/metrics.py), so a p99 spike is one
  ``exemplar_for(0.99)`` lookup from the request trace behind it.
- **Per-tenant accounting** — requests, changes, bytes, sheds,
  backpressure rejections and a latency histogram per tenant, rendered
  as a table (the ``--watch`` CLI's top panel).

Disabled cost: ``attach`` tests one attribute and returns None; every
propagation point is then an ``is None`` test (asserted by
tests/test_scope.py). The whole layer sits behind the same
disabled-by-default opt-in discipline as the metrics registry.
"""
# amlint: host-only — pure-host layer: must not import tpu/ or jax
from __future__ import annotations

import contextlib
import contextvars
from collections import deque
from typing import Iterator

from .metrics import Histogram, get_metrics

_METRICS = get_metrics()

# request-lifecycle histograms (ms, injected-clock units). Exemplars carry
# the request trace id, so the p99 bucket names a concrete trace.
_M_E2E = _METRICS.histogram(
    "serve.request.e2e_ms",
    "receive -> ack-send per request (injected clock); exemplars carry "
    "trace ids",
)
_M_QUEUE_WAIT = _METRICS.histogram(
    "serve.request.queue_wait_ms",
    "receive -> batching-window flush per request (the window's price)",
)
_M_DISPATCH = _METRICS.histogram(
    "serve.request.dispatch_ms",
    "window flush -> commit per request (the batched farm dispatch)",
)
_M_ACK = _METRICS.histogram(
    "serve.request.ack_ms",
    "commit -> ack-send per request (the pump fan-out)",
)

# per-dispatch phase histograms (ms, host clock): the shared breakdown of
# one batched dispatch, attributed to every member request. Exemplars
# carry dispatch span ids.
PHASE_HISTOGRAMS: dict[str, Histogram] = {
    "decode": _METRICS.histogram(
        "serve.phase.decode_ms", "chunk decode share of serve dispatches"
    ),
    "gate_verdicts": _METRICS.histogram(
        "serve.phase.gate_verdicts_ms",
        "columnar causal-gate verdict share of serve dispatches",
    ),
    "transcode_columns": _METRICS.histogram(
        "serve.phase.transcode_columns_ms",
        "cached-column transcode share of serve dispatches",
    ),
    "gate+transcode": _METRICS.histogram(
        "serve.phase.gate_transcode_ms",
        "scalar-oracle gate + row transcode share of serve dispatches",
    ),
    "pack": _METRICS.histogram(
        "serve.phase.pack_ms", "batch packing share of serve dispatches"
    ),
    "device_dispatch": _METRICS.histogram(
        "serve.phase.device_dispatch_ms",
        "device merge program share of serve dispatches",
    ),
    "visibility": _METRICS.histogram(
        "serve.phase.readback_ms",
        "visibility readback share of serve dispatches",
    ),
    "patch_assembly": _METRICS.histogram(
        "serve.phase.assembly_ms",
        "patch assembly share of serve dispatches",
    ),
    "generate": _METRICS.histogram(
        "serve.phase.generate_ms",
        "batched sync generate share of serve pump sweeps",
    ),
}


class RequestScope:
    """One frame's journey through the front door. Slots only — the hot
    path allocates exactly one of these per admitted frame."""

    __slots__ = ("trace_id", "tenant", "doc", "client_id", "bytes_in",
                 "marks", "phases", "dispatch_id", "changes", "outcome")

    def __init__(self, trace_id, tenant, doc, client_id, bytes_in=0):
        self.trace_id = trace_id
        self.tenant = tenant
        self.doc = doc
        self.client_id = client_id
        self.bytes_in = bytes_in
        self.marks: dict[str, float] = {}
        self.phases: dict[str, float] | None = None  # shared dispatch phases (s)
        self.dispatch_id = None
        self.changes = 0
        self.outcome = None

    def mark(self, name: str, t: float) -> None:
        self.marks[name] = t

    def breakdown(self) -> dict[str, float]:
        """Per-request phase durations in ms: lifecycle segments from the
        injected-clock marks plus the owning dispatch's shared host
        phases. Only segments whose marks exist appear."""
        m = self.marks
        out: dict[str, float] = {}
        if "received" in m and "flush" in m:
            out["queue_wait_ms"] = (m["flush"] - m["received"]) * 1000.0
        if "flush" in m and "committed" in m:
            out["dispatch_ms"] = (m["committed"] - m["flush"]) * 1000.0
        if "committed" in m and "sent" in m:
            out["ack_ms"] = (m["sent"] - m["committed"]) * 1000.0
        if "received" in m and "sent" in m:
            out["e2e_ms"] = (m["sent"] - m["received"]) * 1000.0
        if self.phases:
            for phase, seconds in self.phases.items():
                out[f"phase.{phase}_ms"] = seconds * 1000.0
        return out

    def as_dict(self) -> dict:
        return {
            "trace_id": self.trace_id,
            "tenant": self.tenant,
            "doc": self.doc,
            "client": repr(self.client_id),
            "bytes_in": self.bytes_in,
            "changes": self.changes,
            "outcome": self.outcome,
            "dispatch_id": self.dispatch_id,
            "marks": dict(self.marks),
            "breakdown": self.breakdown(),
        }


class DispatchSpan:
    """One batched farm dispatch and the request traces it served."""

    __slots__ = ("dispatch_id", "trace_ids", "t_start", "t_end", "phases",
                 "docs", "changes")

    def __init__(self, dispatch_id, trace_ids, t_start):
        self.dispatch_id = dispatch_id
        self.trace_ids = list(trace_ids)
        self.t_start = t_start
        self.t_end = None
        self.phases: dict[str, float] = {}
        self.docs = 0
        self.changes = 0

    def as_dict(self) -> dict:
        return {
            "dispatch_id": self.dispatch_id,
            "trace_ids": list(self.trace_ids),
            "t_start": self.t_start,
            "t_end": self.t_end,
            "docs": self.docs,
            "changes": self.changes,
            "phases_s": dict(self.phases),
        }


class TenantStats:
    """Per-tenant accounting row (the --watch table's columns)."""

    __slots__ = ("tenant", "requests", "changes", "bytes_in", "shed",
                 "backpressure", "rejected", "latency")

    def __init__(self, tenant: str):
        self.tenant = tenant
        self.requests = 0
        self.changes = 0
        self.bytes_in = 0
        self.shed = 0
        self.backpressure = 0
        self.rejected = 0
        self.latency = Histogram(f"tenant:{tenant}")
        self.latency.enabled = True  # standalone, lives and dies with amscope

    def as_dict(self) -> dict:
        return {
            "requests": self.requests,
            "changes": self.changes,
            "bytes_in": self.bytes_in,
            "shed": self.shed,
            "backpressure": self.backpressure,
            "rejected": self.rejected,
            "latency_ms": {
                "p50": self.latency.percentile(0.50),
                "p95": self.latency.percentile(0.95),
                "p99": self.latency.percentile(0.99),
                "samples": self.latency.count,
            },
        }


class Amscope:
    """The request-flow tracer: scope factory, dispatch-span registry and
    per-tenant accounting table. Disabled by default — ``attach`` is one
    attribute test when off; every downstream propagation point carries a
    scope of None and costs an identity test."""

    def __init__(self, recent: int = 512, recent_dispatches: int = 128):
        self.enabled = False
        self.recent: deque = deque(maxlen=recent)
        self.dispatches: deque = deque(maxlen=recent_dispatches)
        self.tenants: dict[str, TenantStats] = {}
        self._seq = 0

    # -------------------------------------------------------------- #
    # lifecycle

    def attach(self, tenant, doc, client_id, t, nbytes: int = 0
               ) -> RequestScope | None:
        """Creates the trace context for one received frame (or None when
        disabled). Counts the request and its bytes against the tenant."""
        if not self.enabled:
            return None
        self._seq += 1
        scope = RequestScope(
            f"t{self._seq:08x}", tenant, doc, client_id, nbytes
        )
        scope.mark("received", t)
        stats = self._tenant(tenant)
        stats.requests += 1
        stats.bytes_in += nbytes
        return scope

    def drop(self, scope: RequestScope, reason: str) -> None:
        """Terminal for a frame the front door refused or discarded:
        ``shed`` (quarantine admission / mid-window exclusion),
        ``backpressure`` (tenant budget), ``rejected`` (corrupt/invalid).
        Counted per tenant; no latency sample (nothing completed)."""
        scope.outcome = reason
        stats = self._tenant(scope.tenant)
        if reason == "backpressure":
            stats.backpressure += 1
        elif reason == "rejected":
            stats.rejected += 1
        else:
            stats.shed += 1
        self.recent.append(scope)

    def finish(self, scope: RequestScope, outcome: str = "ok") -> None:
        """Terminal for a frame that ran its course. Observes whichever
        lifecycle segments its marks cover (an envelope-only frame has no
        commit and contributes no dispatch sample) with the trace id as
        the bucket exemplar, and prices the tenant's latency."""
        scope.outcome = outcome
        bd = scope.breakdown()
        tid = scope.trace_id
        if "queue_wait_ms" in bd:
            _M_QUEUE_WAIT.observe(max(bd["queue_wait_ms"], 1e-6), exemplar=tid)
        if "dispatch_ms" in bd:
            _M_DISPATCH.observe(max(bd["dispatch_ms"], 1e-6), exemplar=tid)
        if "ack_ms" in bd:
            _M_ACK.observe(max(bd["ack_ms"], 1e-6), exemplar=tid)
        if "e2e_ms" in bd:
            e2e = max(bd["e2e_ms"], 1e-6)
            _M_E2E.observe(e2e, exemplar=tid)
            stats = self._tenant(scope.tenant)
            stats.changes += scope.changes
            stats.latency.observe(e2e)
        self.recent.append(scope)

    # -------------------------------------------------------------- #
    # dispatch spans (one batched farm dispatch <- N request traces)

    def begin_dispatch(self, trace_ids, t) -> DispatchSpan:
        self._seq += 1
        return DispatchSpan(f"d{self._seq:08x}", trace_ids, t)

    def end_dispatch(self, span: DispatchSpan, t, phases: dict[str, float],
                     docs: int, changes: int) -> None:
        """Closes a dispatch span: stores the farm's per-phase host
        durations and observes them on the serve.phase.* histograms with
        the span id as exemplar."""
        span.t_end = t
        span.phases = dict(phases)
        span.docs = docs
        span.changes = changes
        for phase, seconds in phases.items():
            hist = PHASE_HISTOGRAMS.get(phase)
            if hist is not None:
                hist.observe(max(seconds * 1000.0, 1e-6),
                             exemplar=span.dispatch_id)
        self.dispatches.append(span)

    def observe_phase(self, phase: str, seconds: float, exemplar=None) -> None:
        """Records a standalone phase sample (the server's batched
        generate sweep, which runs outside any dispatch span)."""
        hist = PHASE_HISTOGRAMS.get(phase)
        if hist is not None:
            hist.observe(max(seconds * 1000.0, 1e-6), exemplar=exemplar)

    # -------------------------------------------------------------- #
    # tenant accounting

    def _tenant(self, tenant: str) -> TenantStats:
        stats = self.tenants.get(tenant)
        if stats is None:
            stats = self.tenants[tenant] = TenantStats(tenant)
        return stats

    def tenant_stats(self) -> dict:
        return {
            name: self.tenants[name].as_dict()
            for name in sorted(self.tenants)
        }

    def tenant_table(self) -> str:
        """The per-tenant accounting table: ops (changes), bytes, sheds,
        backpressure, rejects and latency percentiles."""
        if not self.tenants:
            return "(no tenant traffic recorded)"
        header = (
            f"{'tenant':12}  {'requests':>8}  {'changes':>8}  {'bytes':>10}  "
            f"{'shed':>6}  {'backpr':>6}  {'reject':>6}  "
            f"{'p50ms':>8}  {'p95ms':>8}  {'p99ms':>8}"
        )
        lines = [header]
        for name in sorted(self.tenants):
            s = self.tenants[name]
            lines.append(
                f"{name:12}  {s.requests:>8}  {s.changes:>8}  "
                f"{s.bytes_in:>10}  {s.shed:>6}  {s.backpressure:>6}  "
                f"{s.rejected:>6}  {_fmt(s.latency.percentile(0.50)):>8}  "
                f"{_fmt(s.latency.percentile(0.95)):>8}  "
                f"{_fmt(s.latency.percentile(0.99)):>8}"
            )
        return "\n".join(lines)

    # -------------------------------------------------------------- #

    def find(self, trace_id) -> RequestScope | None:
        """Looks a recent trace up by id (the exemplar -> trace jump)."""
        for scope in self.recent:
            if scope.trace_id == trace_id:
                return scope
        return None

    def enable(self) -> None:
        self.enabled = True

    def disable(self) -> None:
        self.enabled = False

    def reset(self) -> None:
        """Drops recent scopes/spans and the tenant table (the enabled
        flag and the id sequence survive)."""
        self.recent.clear()
        self.dispatches.clear()
        self.tenants = {}


def _fmt(v) -> str:
    return "-" if v is None else f"{v:.3g}"


# ---------------------------------------------------------------------- #
# ambient dispatch context: lets the farm's dispatch/readback latency
# histograms carry the owning serve dispatch's span id as their exemplar
# without threading it through every call signature

_CURRENT_DISPATCH: contextvars.ContextVar = contextvars.ContextVar(
    "amscope_dispatch", default=None
)


def current_exemplar():
    """The ambient dispatch span id (None outside a serve dispatch). The
    ambient value is either a full ``DispatchSpan`` (controller side) or a
    bare span-id string restored from the fan-out payload inside a mesh
    worker (``exemplar_context``) — both stamp the same id."""
    span = _CURRENT_DISPATCH.get()
    if span is None:
        return None
    return span if isinstance(span, str) else span.dispatch_id


@contextlib.contextmanager
def dispatch_context(span: DispatchSpan) -> Iterator[DispatchSpan]:
    token = _CURRENT_DISPATCH.set(span)
    try:
        yield span
    finally:
        _CURRENT_DISPATCH.reset(token)


@contextlib.contextmanager
def exemplar_context(dispatch_id: str | None) -> Iterator[str | None]:
    """Worker-side trace propagation: restores a controller span id (as
    shipped in the apply fan-out payload) as the ambient exemplar, so the
    worker farm's ``farm.dispatch.latency_ms``/``farm.readback.latency_ms``
    observations stamp the controller's dispatch id without importing any
    controller state. ``None`` is a clean no-op ambient."""
    token = _CURRENT_DISPATCH.set(dispatch_id)
    try:
        yield dispatch_id
    finally:
        _CURRENT_DISPATCH.reset(token)


# ---------------------------------------------------------------------- #
# the process-wide tracer (disabled until a workload opts in)

_GLOBAL = Amscope()


def get_amscope() -> Amscope:
    """The process-wide request-flow tracer."""
    return _GLOBAL


@contextlib.contextmanager
def enabled_amscope(tracer: Amscope | None = None) -> Iterator[Amscope]:
    """Enables a tracer (the process-wide one by default) for the dynamic
    extent, restoring the previous enabled state on exit."""
    t = tracer if tracer is not None else _GLOBAL
    was_enabled = t.enabled
    t.enabled = True
    try:
        yield t
    finally:
        t.enabled = was_enabled
