"""amscope flight recorder: a bounded ring of structured events for
postmortems that do not require re-running the workload.

Metrics (obs/metrics.py) answer "how much"; spans answer "where did the
time go". Neither answers "what exactly happened, in what order, just
before the service degraded" — that is this module. Subsystems append
compact structured events (session retransmits and backoff, watchdog
escalations, quarantine enter/release with the offending change hashes,
batcher flush decisions, engine recompiles with their shape buckets,
page-slab growth) into one process-wide ring buffer:

- **bounded and allocation-cheap**: a ``collections.deque(maxlen=N)`` of
  small tuples; recording when enabled is one append, recording when
  disabled is a single attribute test (call sites guard kwargs packing
  with ``if _FLIGHT.enabled:``, the same convention as ``_METRICS``);
- **causally ordered**: every event carries a process-global monotonic
  sequence number, so the dump renders a total order even when call sites
  stamp it with different clocks (sessions pass their injected —
  possibly simulated — clock; host layers default to the recorder's);
- **snapshot-dumped on faults**: ``trigger(reason)`` writes the whole
  ring as JSON lines into ``dump_dir`` (``AM_FLIGHT_DIR`` or explicit),
  bounded to ``MAX_AUTO_DUMPS`` files per process. The farm triggers on
  quarantine entry and device faults, the session layer on channel
  quarantine and watchdog resets — so a `DeviceFaultError` at 3am leaves
  a timeline behind, not just counters.

``python -m automerge_tpu.obs --flight <dump.jsonl>`` renders a dump as a
causally-ordered timeline. The event-name catalog lives in the README
"Observability" section and is cross-checked against the code by amlint
rule AM304.
"""
# amlint: host-only — pure-host layer: must not import tpu/ or jax
from __future__ import annotations

import contextlib
import json
import os
import time
from collections import deque
from typing import Iterator

#: ring capacity (events); old events fall off the front
DEFAULT_CAPACITY = 4096
#: auto-dump files per process: a quarantine storm must not fill a disk
MAX_AUTO_DUMPS = 8


class FlightRecorder:
    """One process-wide ring of structured events. See module docstring."""

    __slots__ = ("enabled", "clock", "dump_dir", "dump_paths", "_ring",
                 "_seq")

    def __init__(self, capacity: int = DEFAULT_CAPACITY, clock=None):
        self.enabled = False
        self.clock = clock if clock is not None else time.monotonic
        self.dump_dir = os.environ.get("AM_FLIGHT_DIR") or None
        self.dump_paths: list[str] = []
        self._ring: deque = deque(maxlen=capacity)
        self._seq = 0

    # -------------------------------------------------------------- #
    # recording

    def record(self, event: str, t: float | None = None, **fields) -> None:
        """Appends one event. ``t`` is the caller's clock reading (pass the
        injected clock's value from clocked subsystems so simulated-time
        runs produce simulated-time timelines); None stamps the recorder's
        own clock. Hot call sites guard with ``if recorder.enabled:`` so
        the disabled path never packs kwargs."""
        if not self.enabled:
            return
        self._seq += 1
        self._ring.append(
            (self._seq, self.clock() if t is None else t, event, fields)
        )

    def trigger(self, reason: str, t: float | None = None, **fields
                ) -> str | None:
        """Records a ``flight.trigger`` event and snapshot-dumps the ring
        to ``dump_dir`` (one JSONL file per trigger, bounded by
        ``MAX_AUTO_DUMPS``). Returns the dump path, or None when disabled,
        undumpable (no dump_dir) or over the dump budget."""
        if not self.enabled:
            return None
        self.record("flight.trigger", t=t, reason=reason, **fields)
        if self.dump_dir is None or len(self.dump_paths) >= MAX_AUTO_DUMPS:
            return None
        path = os.path.join(
            self.dump_dir,
            f"amflight-{os.getpid()}-{len(self.dump_paths) + 1:02d}.jsonl",
        )
        with open(path, "w", encoding="utf-8") as fh:
            fh.write(self.to_jsonl())
        self.dump_paths.append(path)
        return path

    # -------------------------------------------------------------- #
    # reading

    def snapshot(self) -> list[dict]:
        """The ring as a list of dicts, oldest first (causal order)."""
        return [
            {"seq": seq, "t": t, "event": kind, "fields": fields}
            for seq, t, kind, fields in self._ring
        ]

    def tail(self, n: int = 16) -> list[dict]:
        """The newest ``n`` events (causal order within the slice)."""
        events = self.snapshot()
        return events[-n:]

    def to_jsonl(self) -> str:
        lines = [
            json.dumps(event, sort_keys=True, default=str)
            for event in self.snapshot()
        ]
        return "\n".join(lines) + ("\n" if lines else "")

    def __len__(self) -> int:
        return len(self._ring)

    def clear(self) -> None:
        """Empties the ring and the per-run dump budget (the sequence
        counter keeps climbing so post-clear events still order after
        pre-clear dumps)."""
        self._ring.clear()
        self.dump_paths = []


# ---------------------------------------------------------------------- #
# dump loading + timeline rendering (the `--flight` CLI path)

def load_jsonl(text: str) -> list[dict]:
    """Parses a dump back into event dicts, sorted causally by seq (so
    concatenated dumps interleave correctly)."""
    events = []
    for line in text.splitlines():
        line = line.strip()
        if line:
            events.append(json.loads(line))
    events.sort(key=lambda e: e.get("seq", 0))
    return events


def render_timeline(events: list[dict]) -> str:
    """Causally-ordered human-readable timeline of a dump."""
    if not events:
        return "(no flight events)"
    width = max(len(e.get("event", "")) for e in events)
    lines = [f"{'seq':>6}  {'t':>12}  {'event'.ljust(width)}  fields"]
    for e in events:
        fields = e.get("fields") or {}
        detail = " ".join(f"{k}={fields[k]}" for k in sorted(fields))
        lines.append(
            f"{e.get('seq', 0):>6}  {e.get('t', 0.0):>12.6f}  "
            f"{e.get('event', '').ljust(width)}  {detail}"
        )
    return "\n".join(lines)


# ---------------------------------------------------------------------- #
# the process-wide recorder (disabled until a workload opts in)

_GLOBAL = FlightRecorder()


def get_flight() -> FlightRecorder:
    """The process-wide flight recorder every instrumented module uses."""
    return _GLOBAL


@contextlib.contextmanager
def enabled_flight(recorder: FlightRecorder | None = None,
                   dump_dir: str | None = None) -> Iterator[FlightRecorder]:
    """Enables a recorder (the process-wide one by default) for the
    dynamic extent, restoring the previous enabled state and dump_dir."""
    rec = recorder if recorder is not None else _GLOBAL
    was_enabled, was_dir = rec.enabled, rec.dump_dir
    rec.enabled = True
    if dump_dir is not None:
        rec.dump_dir = dump_dir
    try:
        yield rec
    finally:
        rec.enabled = was_enabled
        rec.dump_dir = was_dir
