"""amscope flight recorder: a bounded ring of structured events for
postmortems that do not require re-running the workload.

Metrics (obs/metrics.py) answer "how much"; spans answer "where did the
time go". Neither answers "what exactly happened, in what order, just
before the service degraded" — that is this module. Subsystems append
compact structured events (session retransmits and backoff, watchdog
escalations, quarantine enter/release with the offending change hashes,
batcher flush decisions, engine recompiles with their shape buckets,
page-slab growth) into one process-wide ring buffer:

- **bounded and allocation-cheap**: a ``collections.deque(maxlen=N)`` of
  small tuples; recording when enabled is one append, recording when
  disabled is a single attribute test (call sites guard kwargs packing
  with ``if _FLIGHT.enabled:``, the same convention as ``_METRICS``);
- **causally ordered**: every event carries a process-global monotonic
  sequence number, so the dump renders a total order even when call sites
  stamp it with different clocks (sessions pass their injected —
  possibly simulated — clock; host layers default to the recorder's);
- **snapshot-dumped on faults**: ``trigger(reason)`` writes the whole
  ring as JSON lines into ``dump_dir`` (``AM_FLIGHT_DIR`` or explicit),
  bounded to ``MAX_AUTO_DUMPS`` files per process. The farm triggers on
  quarantine entry and device faults, the session layer on channel
  quarantine and watchdog resets — so a `DeviceFaultError` at 3am leaves
  a timeline behind, not just counters.
- **mesh-mergeable**: a recorder can be tagged with a ``(shard, epoch)``
  origin (mesh workers are; ``epoch`` is the spawn generation, so a
  respawned worker's restarted local seq cannot collide with its previous
  life). Workers ``ship()`` their unshipped tail over the result pipe and
  the controller ``absorb()``\\s it into the unified timeline, assigning
  fresh controller seqs while preserving the origin key ``(epoch, shard,
  wseq)``. Merged dumps therefore order deterministically: controller seq
  first, origin key as the tiebreaker when independently-numbered dumps
  are concatenated. Workers also ``write_blackbox()`` a bounded file
  (flight tail + last phase profile) after every delivery, so a
  SIGKILLed worker's final events survive for crash forensics.

``python -m automerge_tpu.obs --flight <dump.jsonl>`` renders a dump as a
causally-ordered timeline (with a shard column once any event carries an
origin). The event-name catalog lives in the README "Observability"
section and is cross-checked against the code by amlint rule AM304.
"""
# amlint: host-only — pure-host layer: must not import tpu/ or jax
from __future__ import annotations

import contextlib
import json
import os
import time
from collections import deque
from typing import Iterator

#: ring capacity (events); old events fall off the front
DEFAULT_CAPACITY = 4096
#: auto-dump files per process: a quarantine storm must not fill a disk
MAX_AUTO_DUMPS = 8
#: events preserved in a worker's black-box file (bounded on disk)
BLACKBOX_TAIL = 64


class FlightRecorder:
    """One process-wide ring of structured events. See module docstring."""

    __slots__ = ("enabled", "clock", "dump_dir", "dump_paths", "shard",
                 "epoch", "_ring", "_seq", "_shipped")

    def __init__(self, capacity: int = DEFAULT_CAPACITY, clock=None):
        self.enabled = False
        self.clock = clock if clock is not None else time.monotonic
        self.dump_dir = os.environ.get("AM_FLIGHT_DIR") or None
        self.dump_paths: list[str] = []
        #: origin tag for mesh workers; None on the controller / solo host
        self.shard: int | None = None
        #: spawn generation of the tagged worker (bumped on respawn)
        self.epoch = 0
        self._ring: deque = deque(maxlen=capacity)
        self._seq = 0
        self._shipped = 0

    # -------------------------------------------------------------- #
    # recording

    def record(self, event: str, t: float | None = None, **fields) -> None:
        """Appends one event. ``t`` is the caller's clock reading (pass the
        injected clock's value from clocked subsystems so simulated-time
        runs produce simulated-time timelines); None stamps the recorder's
        own clock. Hot call sites guard with ``if recorder.enabled:`` so
        the disabled path never packs kwargs."""
        if not self.enabled:
            return
        self._seq += 1
        self._ring.append(
            (self._seq, self.clock() if t is None else t, event, fields)
        )

    def trigger(self, reason: str, t: float | None = None, **fields
                ) -> str | None:
        """Records a ``flight.trigger`` event and snapshot-dumps the ring
        to ``dump_dir`` (one JSONL file per trigger, bounded by
        ``MAX_AUTO_DUMPS``). Returns the dump path, or None when disabled,
        undumpable (no dump_dir) or over the dump budget."""
        if not self.enabled:
            return None
        self.record("flight.trigger", t=t, reason=reason, **fields)
        if self.dump_dir is None or len(self.dump_paths) >= MAX_AUTO_DUMPS:
            return None
        path = os.path.join(
            self.dump_dir,
            f"amflight-{os.getpid()}-{len(self.dump_paths) + 1:02d}.jsonl",
        )
        with open(path, "w", encoding="utf-8") as fh:
            fh.write(self.to_jsonl())
        self.dump_paths.append(path)
        return path

    # -------------------------------------------------------------- #
    # the mesh telemetry channel: worker ship -> controller absorb

    def ship(self) -> list[dict]:
        """The unshipped tail as event dicts, advancing the ship mark.

        This is the flight half of the worker shipping buffer: called once
        per pipe response (result frames and heartbeats alike) and sent
        alongside the ``metrics_delta``. Cheap when idle or disabled: a
        counter compare, no allocation. Events that fell off the bounded
        ring before shipping are lost by design (same budget as dumps)."""
        if self._seq == self._shipped:
            return []
        mark = self._shipped
        self._shipped = self._seq
        return [e for e in self.snapshot() if e["seq"] > mark]

    def absorb(self, events: list[dict], dedup: bool = False) -> int:
        """Merges shipped (or black-box-recovered) worker events into this
        ring, assigning fresh controller seqs so the unified timeline has
        one total order; each event keeps its origin key ``(shard, epoch,
        wseq)`` and the worker's own clock reading. ``dedup=True`` (the
        black-box recovery path) skips events whose origin key is already
        in the ring — the worker may have live-shipped part of its tail
        before dying. No-op when disabled. Returns the absorbed count."""
        if not self.enabled:
            return 0
        seen = (
            {entry[4] for entry in self._ring if len(entry) == 5}
            if dedup else None
        )
        absorbed = 0
        for e in events:
            origin = (e.get("shard"), e.get("epoch", 0),
                      e.get("wseq", e.get("seq", 0)))
            if seen is not None and origin in seen:
                continue
            self._seq += 1
            absorbed += 1
            self._ring.append(
                (self._seq, e.get("t", 0.0), e.get("event", ""),
                 e.get("fields") or {}, origin)
            )
        return absorbed

    # -------------------------------------------------------------- #
    # reading

    def snapshot(self) -> list[dict]:
        """The ring as a list of dicts, oldest first (causal order).

        Untagged recorders (the single-process case) produce exactly the
        pre-mesh shape; shard-tagged recorders and absorbed worker events
        add ``shard``/``epoch``/``wseq`` origin keys."""
        out = []
        for entry in self._ring:
            seq, t, kind, fields = entry[:4]
            e = {"seq": seq, "t": t, "event": kind, "fields": fields}
            if len(entry) == 5:  # absorbed from a worker
                e["shard"], e["epoch"], e["wseq"] = entry[4]
            elif self.shard is not None:  # this recorder IS a worker's
                e["shard"], e["epoch"], e["wseq"] = self.shard, self.epoch, seq
            out.append(e)
        return out

    def tail(self, n: int = 16) -> list[dict]:
        """The newest ``n`` events (causal order within the slice)."""
        events = self.snapshot()
        return events[-n:]

    def to_jsonl(self) -> str:
        lines = [
            json.dumps(event, sort_keys=True, default=str)
            for event in self.snapshot()
        ]
        return "\n".join(lines) + ("\n" if lines else "")

    def __len__(self) -> int:
        return len(self._ring)

    def clear(self) -> None:
        """Empties the ring and the per-run dump budget (the sequence
        counter keeps climbing so post-clear events still order after
        pre-clear dumps)."""
        self._ring.clear()
        self.dump_paths = []


# ---------------------------------------------------------------------- #
# dump loading + timeline rendering (the `--flight` CLI path)

def _merge_key(e: dict) -> tuple:
    """Deterministic order for merged multi-process timelines: primary is
    the (controller) seq — identical to the pre-mesh sort for
    single-process dumps — tie-broken by the origin key ``(epoch, shard,
    local_seq)`` so independently-numbered dumps concatenated together
    (e.g. a controller dump plus a dead worker's black box) interleave
    without per-process seq collisions scrambling the order."""
    shard = e.get("shard")
    return (e.get("seq", 0), e.get("epoch", 0),
            -1 if shard is None else shard, e.get("wseq", 0))


def load_jsonl(text: str) -> list[dict]:
    """Parses a dump back into event dicts, sorted causally (see
    ``_merge_key``; plain single-process dumps sort by seq exactly as
    before)."""
    events = []
    for line in text.splitlines():
        line = line.strip()
        if line:
            events.append(json.loads(line))
    events.sort(key=_merge_key)
    return events


def render_timeline(events: list[dict]) -> str:
    """Causally-ordered human-readable timeline of a dump. A shard column
    appears once any event carries a mesh origin tag (controller-local
    rows show ``-``); untagged dumps render byte-identically to the
    pre-mesh format."""
    if not events:
        return "(no flight events)"
    width = max(len(e.get("event", "")) for e in events)
    tagged = any("shard" in e for e in events)
    header = f"{'seq':>6}  "
    if tagged:
        header += f"{'shard':>5}  "
    header += f"{'t':>12}  {'event'.ljust(width)}  fields"
    lines = [header]
    for e in events:
        fields = e.get("fields") or {}
        detail = " ".join(f"{k}={fields[k]}" for k in sorted(fields))
        row = f"{e.get('seq', 0):>6}  "
        if tagged:
            shard = e.get("shard")
            row += f"{'-' if shard is None else shard:>5}  "
        row += (
            f"{e.get('t', 0.0):>12.6f}  "
            f"{e.get('event', '').ljust(width)}  {detail}"
        )
        lines.append(row)
    return "\n".join(lines)


# ---------------------------------------------------------------------- #
# worker black box: crash forensics that survive a SIGKILL

def write_blackbox(path: str, recorder: FlightRecorder,
                   phases_jsonl: str = "") -> None:
    """Persists a bounded black-box file: the recorder's flight tail
    (shard-tagged) plus the last delivery's phase profile. Written
    atomically (tmp + rename) after every worker delivery and on the
    worker fault path, so the file a crashed worker leaves behind is
    always a complete JSON document — a SIGKILL between deliveries cannot
    tear it. The black box is advisory forensics on a per-delivery hot
    path, so it skips the store tier's fsync (the WAL owns durability)."""
    # Late import: the store package's WAL layer records flight events, so
    # binding its atomic writer at call time keeps the import graph acyclic.
    from ..store.atomic import atomic_write

    payload = {
        "pid": os.getpid(),
        "shard": recorder.shard,
        "epoch": recorder.epoch,
        "events": recorder.tail(BLACKBOX_TAIL),
        "phases": phases_jsonl,
    }
    atomic_write(path, json.dumps(payload, sort_keys=True, default=str),
                 fsync=False)


def read_blackbox(path: str) -> dict | None:
    """Loads a black-box file; None when absent or torn (best-effort by
    contract — the writer may have died before its first delivery)."""
    try:
        with open(path, encoding="utf-8") as fh:
            payload = json.load(fh)
    except (OSError, ValueError):
        return None
    return payload if isinstance(payload, dict) else None


# ---------------------------------------------------------------------- #
# the process-wide recorder (disabled until a workload opts in)

_GLOBAL = FlightRecorder()


def get_flight() -> FlightRecorder:
    """The process-wide flight recorder every instrumented module uses."""
    return _GLOBAL


@contextlib.contextmanager
def enabled_flight(recorder: FlightRecorder | None = None,
                   dump_dir: str | None = None) -> Iterator[FlightRecorder]:
    """Enables a recorder (the process-wide one by default) for the
    dynamic extent, restoring the previous enabled state and dump_dir."""
    rec = recorder if recorder is not None else _GLOBAL
    was_enabled, was_dir = rec.enabled, rec.dump_dir
    rec.enabled = True
    if dump_dir is not None:
        rec.dump_dir = dump_dir
    try:
        yield rec
    finally:
        rec.enabled = was_enabled
        rec.dump_dir = was_dir
