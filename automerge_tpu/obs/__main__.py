"""CLI entry point: ``python -m automerge_tpu.obs``.

Runs a small canned workload — a farm merge (N docs, R change rounds
through `TpuDocFarm.apply_changes`) followed by a batched sync round-trip
between two farms (`SyncFarm` ping-pong until quiescent) — with spans and
metrics enabled, then prints the span tree (p50/p95/p99 latencies) and the
metrics table. Alternatively reads a previously dumped JSON-lines trace
and renders it without running anything.

    python -m automerge_tpu.obs                      # canned workload
    python -m automerge_tpu.obs --docs 4 --rounds 2  # smaller/larger
    python -m automerge_tpu.obs --dump trace.jsonl   # also write the trace
    python -m automerge_tpu.obs --trace trace.jsonl  # render a dump, no run
    python -m automerge_tpu.obs --json               # machine-readable
    python -m automerge_tpu.obs --flight dump.jsonl  # flight timeline
    python -m automerge_tpu.obs --watch snaps.jsonl  # live telemetry view
    python -m automerge_tpu.obs --watch snaps.jsonl --follow
    python -m automerge_tpu.obs --ledger ledger.jsonl           # trajectory
    python -m automerge_tpu.obs --ledger ledger.jsonl --diff -2 -1

``--flight`` renders a flight-recorder dump (obs/flight.py) as a
causally-ordered timeline. ``--watch`` renders the newest line of a
telemetry snapshot file (obs/export.py: tenant table, per-request phase
shares, flight-recorder tail) — once by default (headless/CI friendly),
or refreshing top-style with ``--follow`` against a running server or
load harness.

The workload imports the device layer lazily, so ``--trace``/``--flight``
/``--watch`` rendering works on hosts without jax initialisation. Exit
code 0 on success.
"""
from __future__ import annotations

import argparse
import json
import os
import random
import sys

from .export import program_table, request_breakdown, shard_table
from .flight import load_jsonl, render_timeline
from .metrics import enabled_metrics, get_metrics
from .spans import Trace, use_trace

_SYNC_ROUND_LIMIT = 16


def _change_stream(actor: str, rounds: int, ops_per_round: int, seed: int = 0):
    """One actor's binary change stream: key-set ops through the real wire
    format (the bench's end-to-end workload shape, bench.py)."""
    from ..columnar import decode_change_columns, encode_change

    rng = random.Random(seed)
    buffers, last, max_op, deps = [], {}, 0, []
    for r in range(rounds):
        ops = []
        start_op = max_op + 1
        ctr = start_op
        for _ in range(ops_per_round):
            key = f"k{rng.randrange(16)}"
            ops.append({"action": "set", "obj": "_root", "key": key,
                        "datatype": "uint", "value": rng.randrange(10**6),
                        "pred": [last[key]] if key in last else []})
            last[key] = f"{ctr}@{actor}"
            ctr += 1
        max_op = ctr - 1
        buf = encode_change({"actor": actor, "seq": r + 1, "startOp": start_op,
                             "time": 0, "deps": deps, "ops": ops})
        deps = [decode_change_columns(buf)["hash"]]
        buffers.append(buf)
    return buffers


def _sync_round_trip(trace, farm_a, farm_b):
    """Ping-pongs the batched sync protocol between two farms until both
    sides go quiet (bounded rounds)."""
    from ..tpu.sync_farm import SyncFarm

    sync_a, sync_b = SyncFarm(farm_a), SyncFarm(farm_b)
    n = farm_a.num_docs
    states_a = [SyncFarm.init_state() for _ in range(n)]
    states_b = [SyncFarm.init_state() for _ in range(n)]

    def half_round(sender, states_s, receiver, states_r):
        with trace.span("sync.generate"):
            results = sender.generate_messages(
                [(d, states_s[d]) for d in range(n)]
            )
        outgoing = []
        for d, (state, msg) in enumerate(results):
            states_s[d] = state
            if msg is not None:
                outgoing.append((d, msg))
        if outgoing:
            with trace.span("sync.receive"):
                received = receiver.receive_messages(
                    [(d, states_r[d], msg) for d, msg in outgoing]
                )
            for (d, _), (state, _patch) in zip(outgoing, received):
                states_r[d] = state
        return len(outgoing)

    for _ in range(_SYNC_ROUND_LIMIT):
        sent = half_round(sync_a, states_a, sync_b, states_b)
        sent += half_round(sync_b, states_b, sync_a, states_a)
        if sent == 0:
            break


def run_workload(num_docs: int, rounds: int, ops_per_round: int) -> Trace:
    """Farm merge + sync round-trip under spans and metrics. Returns the
    trace; metrics accumulate into the process-wide registry."""
    from ..tpu.farm import TpuDocFarm

    trace = Trace()
    with use_trace(trace), enabled_metrics():
        with trace.span("merge"):
            farm_a = TpuDocFarm(num_docs, capacity=rounds * ops_per_round)
            farm_b = TpuDocFarm(num_docs, capacity=rounds * ops_per_round)
            streams_a = [
                _change_stream("a" * 8 + f"{d:02x}" * 4, rounds,
                               ops_per_round, seed=d)
                for d in range(num_docs)
            ]
            streams_b = [
                _change_stream("b" * 8 + f"{d:02x}" * 4, rounds,
                               ops_per_round, seed=100 + d)
                for d in range(num_docs)
            ]
            for r in range(rounds):
                farm_a.apply_changes(
                    [[streams_a[d][r]] for d in range(num_docs)]
                )
                farm_b.apply_changes(
                    [[streams_b[d][r]] for d in range(num_docs)]
                )
        with trace.span("sync"):
            _sync_round_trip(trace, farm_a, farm_b)
    return trace


def _render_watch_frame(record: dict) -> str:
    """One --watch frame: header, per-request phase shares, the tenant
    table and the flight-recorder tail, from a snapshot record."""
    lines = [f"== amscope @ t={record.get('t', 0.0):.3f} =="]
    breakdown = record.get("breakdown") or request_breakdown(
        record.get("metrics", {})
    )
    lines.append("")
    lines.append("-- phase shares (per request) --")
    if breakdown.get("requests"):
        shares = breakdown.get("shares", {})
        mean = breakdown.get("mean_ms", {})
        for phase in ("queue_wait", "dispatch", "readback", "assembly", "ack"):
            share = shares.get(phase, 0.0)
            bar = "#" * int(round(share * 40))
            lines.append(
                f"{phase:12} {share * 100:6.1f}%  {mean.get(phase, 0.0):9.3f} ms  {bar}"
            )
        lines.append(f"requests: {breakdown['requests']}")
        if "p99_exemplar" in breakdown:
            ex = breakdown["p99_exemplar"]
            lines.append(
                f"p99 {ex.get('p99_ms')} ms -> trace {ex.get('trace_id')}"
            )
    else:
        lines.append("(no completed requests yet)")
    lines.append("")
    lines.append("-- tenants --")
    tenants = record.get("tenants", {})
    if tenants:
        header = (
            f"{'tenant':12}  {'requests':>8}  {'changes':>8}  {'bytes':>10}  "
            f"{'shed':>6}  {'backpr':>6}  {'p99ms':>9}"
        )
        lines.append(header)
        for name in sorted(tenants):
            s = tenants[name]
            lat = s.get("latency_ms", {})
            p99 = lat.get("p99")
            lines.append(
                f"{name:12}  {s.get('requests', 0):>8}  "
                f"{s.get('changes', 0):>8}  {s.get('bytes_in', 0):>10}  "
                f"{s.get('shed', 0):>6}  {s.get('backpressure', 0):>6}  "
                f"{'-' if p99 is None else format(p99, '.3g'):>9}"
            )
    else:
        lines.append("(no tenant traffic)")
    shards = shard_table(record.get("metrics", {}))
    if shards:
        lines.append("")
        lines.append("-- shards --")
        suffixes = sorted({k for row in shards.values() for k in row})
        lines.append("  ".join([f"{'shard':>5}"] + [f"{s:>18}" for s in suffixes]))
        for shard, row in shards.items():
            cells = []
            for s in suffixes:
                v = row.get(s)
                if isinstance(v, dict):  # histogram: count @ total ms
                    cells.append(f"{v['count']} @ {v['sum']:.1f}ms")
                else:
                    cells.append("-" if v is None else str(v))
            lines.append("  ".join([f"{shard:>5}"] + [f"{c:>18}" for c in cells]))
    programs = program_table(record.get("metrics", {}))
    if programs:
        lines.append("")
        lines.append("-- programs (amprof) --")
        lines.append(
            f"{'program':<28} {'compiles':>9} {'dispatches':>11} "
            f"{'compile_ms':>11} {'dispatch_ms':>12}"
        )
        for name, row in programs.items():
            lines.append(
                f"{name:<28} {row.get('compiles', 0):>9} "
                f"{row.get('dispatches', 0):>11} "
                f"{row.get('compile_ms', 0.0):>11} "
                f"{row.get('dispatch_ms', 0.0):>12}"
            )
    slo = record.get("slo")
    if slo:
        from .slo import render_verdicts

        lines.append("")
        lines.append("-- SLOs --")
        lines.append(render_verdicts(slo))
    lines.append("")
    lines.append("-- flight tail --")
    tail = record.get("flight_tail", [])
    lines.append(render_timeline(tail) if tail else "(no flight events)")
    return "\n".join(lines)


def _watch(path: str, follow: bool, interval: float) -> int:
    """Renders the newest snapshot line of `path`; with --follow, keeps
    re-reading and redrawing until interrupted (or the file vanishes)."""
    import time as _time

    last_rendered = None
    while True:
        try:
            with open(path, "r", encoding="utf-8") as fh:
                lines = [ln for ln in fh.read().splitlines() if ln.strip()]
        except OSError as exc:
            print(f"--watch: cannot read {path}: {exc}", file=sys.stderr)
            return 1
        if not lines:
            print(f"--watch: {path} has no snapshots yet", file=sys.stderr)
            if not follow:
                return 1
        else:
            record = json.loads(lines[-1])
            if lines[-1] != last_rendered:
                last_rendered = lines[-1]
                if follow:
                    print("\033[2J\033[H", end="")
                print(_render_watch_frame(record))
        if not follow:
            return 0
        try:
            _time.sleep(interval)
        except KeyboardInterrupt:
            return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m automerge_tpu.obs",
        description="amtrace/amscope: span tree + metrics report for a "
                    "canned farm merge + sync round-trip, a dumped trace, "
                    "a flight-recorder timeline, or a live telemetry view",
    )
    parser.add_argument("--docs", type=int, default=4,
                        help="documents per farm (default 4)")
    parser.add_argument("--rounds", type=int, default=2,
                        help="change rounds per document (default 2)")
    parser.add_argument("--ops", type=int, default=8,
                        help="ops per change (default 8)")
    parser.add_argument("--trace", metavar="FILE",
                        help="render a JSON-lines trace dump instead of "
                             "running the workload")
    parser.add_argument("--flight", metavar="FILE",
                        help="render a flight-recorder JSONL dump as a "
                             "causally-ordered timeline (no workload)")
    parser.add_argument("--watch", metavar="FILE",
                        help="render the newest telemetry snapshot in FILE "
                             "(tenant table + phase shares + flight tail); "
                             "headless one-frame render unless --follow")
    parser.add_argument("--ledger", metavar="FILE",
                        help="render the perf-ledger trajectory in FILE "
                             "(bench-appended JSONL, obs/ledger.py); "
                             "combine with --diff to compare two records")
    parser.add_argument("--diff", nargs=2, type=int, metavar=("A", "B"),
                        help="with --ledger: diff records A and B by index "
                             "(negative indices count from the end)")
    parser.add_argument("--follow", action="store_true",
                        help="with --watch: keep refreshing top-style")
    parser.add_argument("--interval", type=float, default=1.0,
                        help="with --watch --follow: refresh seconds")
    parser.add_argument("--dump", metavar="FILE",
                        help="also write the span tree as JSON lines")
    parser.add_argument("--json", action="store_true",
                        help="print one JSON object instead of tables")
    args = parser.parse_args(argv)

    if args.ledger:
        from .ledger import (diff_records, load_ledger, render_diff,
                             render_trajectory)

        records = load_ledger(args.ledger)
        if args.diff:
            a_i, b_i = args.diff
            try:
                a, b = records[a_i], records[b_i]
            except IndexError:
                print(
                    f"--ledger: diff indices {a_i},{b_i} out of range "
                    f"({len(records)} record(s))", file=sys.stderr,
                )
                return 1
            if args.json:
                print(json.dumps(diff_records(a, b), sort_keys=True))
            else:
                print(render_diff(a, b))
        elif args.json:
            print(json.dumps(records, sort_keys=True))
        else:
            print(render_trajectory(records))
        return 0

    if args.flight:
        with open(args.flight, "r", encoding="utf-8") as fh:
            events = load_jsonl(fh.read())
        if args.json:
            print(json.dumps({"events": events}, sort_keys=True))
        else:
            print(render_timeline(events))
        return 0

    if args.watch:
        return _watch(args.watch, args.follow, args.interval)

    if args.trace:
        with open(args.trace, "r", encoding="utf-8") as fh:
            trace = Trace.from_jsonl(fh.read())
        metrics = None
    else:
        # the canned workload is a host-shape measurement; keep it off a
        # (possibly cold) accelerator unless the caller overrides
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        get_metrics().reset()
        trace = run_workload(args.docs, args.rounds, args.ops)
        metrics = get_metrics()

    if args.dump:
        with open(args.dump, "w", encoding="utf-8") as fh:
            fh.write(trace.to_jsonl())

    if args.json:
        out = {"spans": [c.as_dict() for c in trace.root.children.values()]}
        if metrics is not None:
            out["metrics"] = metrics.as_dict()
        print(json.dumps(out, sort_keys=True))
        return 0

    print("== spans ==")
    print(trace.tree_table())
    if metrics is not None:
        print()
        print("== metrics ==")
        print(metrics.table(skip_zero=True))
    return 0


if __name__ == "__main__":
    sys.exit(main())
