"""amtrace — observability for the batched merge pipeline (SURVEY §5.1).

The subsystem has two halves plus a CLI:

- **Spans** (`obs.spans`): nested wall-clock span trees with per-span call
  counts and fixed-bucket latency histograms (p50/p95/p99), ambient
  propagation via ``contextvars`` (thread/task safe), JSON-lines export
  and an indented tree-table renderer. ``automerge_tpu/profiling.py`` is a
  thin compatibility shim over this layer — ``PhaseProfile`` /
  ``get_profile`` / ``use_profile`` keep working unchanged.
- **Metrics** (`obs.metrics`): counters/gauges/histograms in one
  process-wide registry — farm batch occupancy and pad waste, engine jit
  cache hits vs recompiles, sync message/byte/Bloom accounting. Disabled
  by default; recording costs one attribute test until a workload enables
  the registry.
- **CLI**: ``python -m automerge_tpu.obs`` runs a canned farm merge + sync
  round-trip (or reads a dumped JSONL trace) and prints the span tree and
  metrics table. See the README "Observability" section for the metric
  catalog.

Everything here is host-side and stdlib-only: importing ``obs`` never
initialises jax, and amlint rule AM303 keeps instrument calls out of
jit/vmap/Pallas-reachable code.
"""
# amlint: host-only — pure-host layer: must not import tpu/ or jax
from __future__ import annotations

from .metrics import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    enabled_metrics,
    get_metrics,
)
from .spans import SpanNode, Trace, get_trace, use_trace

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "SpanNode",
    "Trace",
    "enabled_metrics",
    "get_metrics",
    "get_trace",
    "use_trace",
]
