"""amtrace + amscope — observability for the batched merge pipeline and
the serving stack (SURVEY §5.1).

Five parts plus a CLI:

- **Spans** (`obs.spans`): nested wall-clock span trees with per-span call
  counts and fixed-bucket latency histograms (p50/p95/p99), ambient
  propagation via ``contextvars`` (thread/task safe), JSON-lines export
  and an indented tree-table renderer. ``automerge_tpu/profiling.py`` is a
  thin compatibility shim over this layer — ``PhaseProfile`` /
  ``get_profile`` / ``use_profile`` keep working unchanged.
- **Metrics** (`obs.metrics`): counters/gauges/histograms in one
  process-wide registry — farm batch occupancy, engine jit cache hits vs
  recompiles, sync message/byte/Bloom accounting. Histogram buckets carry
  **exemplars** (recent trace ids), so a p99 spike links to the request
  trace behind it. Disabled by default; recording costs one attribute
  test until a workload enables the registry.
- **Request-flow tracing** (`obs.scope`, "amscope"): per-request trace
  contexts attached at the serving front door and carried through the
  batching window into the batched farm dispatch — one dispatch span
  links the N request traces it served and carries the shared per-phase
  breakdown; per-tenant accounting rides along.
- **Flight recorder** (`obs.flight`): a bounded ring of structured events
  (retransmits, watchdog escalations, quarantine transitions, flush
  decisions, recompiles, slab growth), snapshot-dumped to JSONL on
  faults for postmortems without re-running the workload. Mesh workers
  ship their shard-tagged event tails over the result pipe into the
  controller's unified timeline, and persist a bounded black-box file
  for crash forensics that survive a SIGKILL.
- **Live telemetry** (`obs.export`): Prometheus-style text exposition
  (mounted on the asyncio adapter's telemetry port), periodic JSONL
  snapshots, and the per-request phase-share math.
- **amprof** (`obs.prof`, `obs.ledger`): the compiled-program
  observatory — every tpu-layer jit program registers a named
  ``ProfiledProgram`` wrapper recording per-program compile/dispatch
  tallies, latency histograms and shape buckets, with a recompile-storm
  detector — plus the memory ``Sampler`` (slab pages, DecodeCache and
  change-column bytes as ``prof.mem.*`` gauges) and the append-only
  perf ledger bench runs write their normalized records to.
- **SLOs** (`obs.slo`): declared objectives (latency percentile under
  budget, availability, convergence ratio) evaluated as multi-window
  burn rates on an injected clock — simulated and wall clocks both
  work — exported as ``slo.*`` gauges and verdict dicts that gate the
  serve/mesh benches.
- **CLI**: ``python -m automerge_tpu.obs`` runs a canned farm merge + sync
  round-trip (or reads a dumped JSONL trace); ``--flight`` renders a
  flight-recorder dump as a causal timeline; ``--watch`` renders live
  telemetry snapshots top-style. See the README "Observability" section
  for the metric and event catalogs (cross-checked by amlint AM304).

Everything here is host-side and stdlib-only: importing ``obs`` never
initialises jax, and amlint rule AM303 keeps instrument calls out of
jit/vmap/Pallas-reachable code.
"""
# amlint: host-only — pure-host layer: must not import tpu/ or jax
from __future__ import annotations

import contextlib

from .flight import FlightRecorder, enabled_flight, get_flight
from .metrics import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    enabled_metrics,
    get_metrics,
)
from .prof import (
    Observatory,
    ProfiledProgram,
    Sampler,
    enabled_observatory,
    get_observatory,
)
from .scope import (
    Amscope,
    DispatchSpan,
    RequestScope,
    enabled_amscope,
    get_amscope,
)
from .slo import (
    Objective,
    SLOEngine,
    availability_objective,
    latency_objective,
    ratio_objective,
    verdicts_ok,
)
from .spans import SpanNode, Trace, get_trace, use_trace

__all__ = [
    "Amscope",
    "Counter",
    "DispatchSpan",
    "FlightRecorder",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "Objective",
    "Observatory",
    "ProfiledProgram",
    "RequestScope",
    "SLOEngine",
    "Sampler",
    "SpanNode",
    "Trace",
    "availability_objective",
    "enabled_amscope",
    "enabled_flight",
    "enabled_metrics",
    "enabled_observability",
    "enabled_observatory",
    "get_amscope",
    "get_flight",
    "get_metrics",
    "get_observatory",
    "get_trace",
    "latency_objective",
    "ratio_objective",
    "use_trace",
    "verdicts_ok",
]


@contextlib.contextmanager
def enabled_observability(flight_dir: str | None = None):
    """Enables the whole observability stack — metrics registry, amscope
    request tracing, the flight recorder and the amprof observatory —
    for the dynamic extent, restoring every previous enabled state on
    exit. The one-call opt-in the load harness and bench workloads use."""
    with enabled_metrics(), enabled_amscope(), enabled_flight(
        dump_dir=flight_dir
    ), enabled_observatory():
        yield
