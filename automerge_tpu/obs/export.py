"""amscope live telemetry: text exposition, periodic JSONL snapshots, and
the breakdown math the bench/CLI render.

Three consumers, one data model:

- **Pull-based exposition** (``render_exposition``): the process-wide
  metrics registry and the per-tenant accounting table flattened into a
  Prometheus-style ``text/plain`` page — counters and gauges as plain
  samples, histograms as count/sum/quantile samples with bucket
  exemplars emitted as ``# EXEMPLAR`` comment lines. The asyncio serving
  adapter mounts it on a telemetry port (``serve_exposition``); any
  scraper (or ``curl``) can poll a live server without touching the
  serving data path.
- **Periodic JSONL snapshots** (``SnapshotWriter``): one self-contained
  JSON line per interval — metrics, tenant table, flight-recorder tail —
  appended to a file by ``serve_forever`` or the load harness.
  ``python -m automerge_tpu.obs --watch <file>`` renders the latest line
  as a live top-style view.
- **Phase-share math** (``request_breakdown``): turns the
  ``serve.request.*`` / ``serve.phase.*`` histograms into per-request
  mean milliseconds and normalized phase shares (queue-wait / dispatch /
  readback / assembly / ack), the figure BENCH/SERVE artifacts record so
  the e2e ceiling's location is in the history, not in a lost terminal.

Lifecycle marks use the injected (possibly simulated) clock while farm
phases use the host clock; under a ``ManualClock`` harness the shares are
therefore dominated by the simulated window price — exactly what a client
feels — with the host-side dispatch phases reported alongside.
"""
# amlint: host-only — pure-host layer: must not import tpu/ or jax
from __future__ import annotations

import json
import time

from .flight import get_flight
from .metrics import get_metrics
from .scope import get_amscope

#: serve.phase.* histogram suffix -> breakdown key
_PHASE_KEYS = {
    "serve.phase.decode_ms": "decode",
    "serve.phase.gate_verdicts_ms": "gate_verdicts",
    "serve.phase.transcode_columns_ms": "transcode_columns",
    "serve.phase.gate_transcode_ms": "gate_transcode",
    "serve.phase.pack_ms": "pack",
    "serve.phase.device_dispatch_ms": "device_dispatch",
    "serve.phase.readback_ms": "readback",
    "serve.phase.assembly_ms": "assembly",
    "serve.phase.generate_ms": "generate",
}


def _sanitize(name: str) -> str:
    out = []
    for ch in name:
        out.append(ch if ch.isalnum() or ch == "_" else "_")
    return "".join(out)


def render_exposition(registry=None, scope=None, slo=None) -> str:
    """The pull-based text page: metrics + per-tenant samples. ``slo``
    (a verdict list from ``obs.slo.SLOEngine.evaluate``) adds one
    ``# SLO`` comment line per objective window — the ``slo.*`` gauges
    the engine exports appear as ordinary samples regardless."""
    registry = registry if registry is not None else get_metrics()
    scope = scope if scope is not None else get_amscope()
    lines: list[str] = []
    for v in slo or ():
        for w in v["windows"]:
            burn = w["burn_rate"]
            lines.append(
                f"# SLO {_sanitize(v['objective'])} target={v['target']:.6g}"
                f" window={w['window_s']:.6g}s"
                f" burn={'-' if burn is None else f'{burn:.6g}'}"
                f" {'ok' if v['ok'] else 'BREACH'}"
            )
    for name, snap in registry.as_dict().items():
        n = _sanitize(name)
        if snap["type"] == "histogram":
            lines.append(f"# TYPE {n} summary")
            for q, key in ((0.5, "p50"), (0.95, "p95"), (0.99, "p99")):
                if snap[key] is not None:
                    lines.append(f'{n}{{quantile="{q}"}} {snap[key]:.6g}')
            lines.append(f"{n}_count {snap['count']}")
            lines.append(f"{n}_sum {snap['sum']:.6g}")
            for bucket, exemplar in snap.get("exemplars", {}).items():
                lines.append(f"# EXEMPLAR {n} bucket={bucket} trace={exemplar}")
        else:
            lines.append(f"# TYPE {n} {snap['type']}")
            lines.append(f"{n} {snap['value']:.6g}"
                         if isinstance(snap["value"], float)
                         else f"{n} {snap['value']}")
    for tenant, stats in scope.tenant_stats().items():
        t = _sanitize(tenant)
        for field in ("requests", "changes", "bytes_in", "shed",
                      "backpressure", "rejected"):
            lines.append(f'am_tenant_{field}{{tenant="{t}"}} {stats[field]}')
    return "\n".join(lines) + "\n"


def request_breakdown(metrics_snapshot: dict) -> dict:
    """Per-request phase breakdown from a ``registry.as_dict()`` snapshot.

    Returns ``{"requests": N, "mean_ms": {...}, "shares": {...},
    "p99_exemplar": {...}}``. Shares are normalized over queue_wait /
    dispatch / readback / assembly / ack, where ``dispatch`` is the
    request-measured flush->commit segment minus the host-measured
    readback and assembly phases (floored at zero), so the five shares
    partition the request's journey without double counting."""

    def hist(name):
        return metrics_snapshot.get(name, {})

    requests = hist("serve.request.e2e_ms").get("count", 0)
    if not requests:
        return {"requests": 0, "mean_ms": {}, "shares": {}}
    queue = hist("serve.request.queue_wait_ms").get("sum", 0.0)
    dispatch_total = hist("serve.request.dispatch_ms").get("sum", 0.0)
    ack = hist("serve.request.ack_ms").get("sum", 0.0)
    phases = {
        key: hist(name).get("sum", 0.0) for name, key in _PHASE_KEYS.items()
    }
    readback = phases.get("readback", 0.0)
    assembly = phases.get("assembly", 0.0)
    # dispatch = the merge-side share: the request-measured flush->commit
    # segment net of the host-measured readback/assembly phases. Under a
    # simulated clock that segment is ~0 while the host phases are real —
    # fall back to the host-measured dispatch-side phases so the share
    # still names where the dispatch time went.
    host_dispatch = sum(
        phases.get(k, 0.0)
        for k in ("decode", "gate_verdicts", "transcode_columns",
                  "gate_transcode", "pack", "device_dispatch")
    )
    dispatch = max(dispatch_total - readback - assembly, host_dispatch)
    parts = {
        "queue_wait": queue,
        "dispatch": dispatch,
        "readback": readback,
        "assembly": assembly,
        "ack": ack,
    }
    total = sum(parts.values()) or 1.0
    out = {
        "requests": requests,
        "mean_ms": {
            k: round(v / requests, 4) for k, v in parts.items()
        },
        "shares": {k: round(v / total, 4) for k, v in parts.items()},
        "phase_mean_ms": {
            k: round(v / requests, 4) for k, v in phases.items() if v
        },
    }
    p99 = hist("serve.request.e2e_ms")
    exemplars = p99.get("exemplars", {})
    if exemplars:
        out["p99_exemplar"] = {
            "trace_id": _p99_exemplar(p99),
            "p99_ms": p99.get("p99"),
        }
    return out


def _p99_exemplar(snap: dict):
    """The exemplar of the p99 bucket from a histogram *snapshot* (the
    live-object path is ``Histogram.exemplar_for(0.99)``)."""
    buckets = snap.get("exemplars", {})
    if not buckets:
        return None
    # snapshots carry no per-bucket counts; the p99 value maps back to its
    # bucket via the shared log2 grid
    from .spans import bucket_index

    p99 = snap.get("p99")
    if p99 is None:
        return None
    # p99 is a bucket UPPER bound; the observation lives one bucket down
    b = max(bucket_index(p99) - 1, 0)
    if str(b) in buckets:
        return buckets[str(b)]
    lower = [int(k) for k in buckets if int(k) <= b]
    return buckets[str(max(lower))] if lower else buckets[sorted(buckets)[0]]


def shard_table(metrics_snapshot: dict) -> dict:
    """Per-shard rollup of the mesh's shard-labelled instrument families
    (``mesh.shard.<s>.*``, ``mesh.pipe.<s>.*``, ``mesh.shm.<s>.*`` and
    ``serve.flush.shard.<s>.docs``) from a ``registry.as_dict()``
    snapshot: ``{shard: {suffix: value}}``, shards in ascending order.
    Histograms collapse to their count/sum/p99 (the figures the mesh
    bench reports per shard); counters and gauges pass their value
    through. The serving-side family keeps a ``flush.`` prefix so
    ``serve.flush.shard.<s>.docs`` never shadows the mesh's
    ``mesh.shard.<s>.docs``, and the transport families keep their
    ``pipe.``/``shm.`` prefixes for the same reason
    (``mesh.pipe.<s>.bytes_out`` lands as ``pipe.bytes_out``,
    ``mesh.shm.<s>.bytes_out`` as ``shm.bytes_out`` — the two-transport
    data plane's byte counters stay side by side per shard)."""
    import re

    pattern = re.compile(
        r"^(mesh|serve\.flush)\.(shard|pipe|shm)\.(\d+)\.(.+)$"
    )
    table: dict[int, dict] = {}
    for name, snap in metrics_snapshot.items():
        m = pattern.match(name)
        if m is None:
            continue
        if snap.get("type") == "histogram":
            cell = {
                "count": snap.get("count", 0),
                "sum": round(snap.get("sum", 0.0), 4),
                "p99": snap.get("p99"),
            }
        else:
            cell = snap.get("value")
        suffix = m.group(4)
        if m.group(1) == "serve.flush":
            suffix = f"flush.{suffix}"
        elif m.group(2) in ("pipe", "shm"):
            suffix = f"{m.group(2)}.{suffix}"
        table.setdefault(int(m.group(3)), {})[suffix] = cell
    return {s: table[s] for s in sorted(table)}


def program_table(metrics_snapshot: dict) -> dict:
    """Per-program rollup of the amprof observatory's instrument family
    (``prof.program.<name>.{compiles,dispatches,compile_ms,dispatch_ms}``)
    from a ``registry.as_dict()`` snapshot: ``{program: {suffix: value}}``,
    programs in name order. Histogram suffixes collapse to their sum (the
    total wall ms the ``--watch`` programs panel shows)."""
    import re

    pattern = re.compile(
        r"^prof\.program\.(.+)\.(compiles|dispatches|compile_ms|dispatch_ms)$"
    )
    table: dict[str, dict] = {}
    for name, snap in metrics_snapshot.items():
        m = pattern.match(name)
        if m is None:
            continue
        if snap.get("type") == "histogram":
            cell = round(snap.get("sum", 0.0), 3)
        else:
            cell = snap.get("value")
        table.setdefault(m.group(1), {})[m.group(2)] = cell
    return {p: table[p] for p in sorted(table)}


def snapshot_record(t: float | None = None, registry=None, scope=None,
                    flight=None, tail: int = 16, slo=None) -> dict:
    """One self-contained telemetry snapshot (a JSONL line's payload).
    ``slo`` verdicts (when an engine is wired) ride along for the
    ``--watch`` SLO panel."""
    registry = registry if registry is not None else get_metrics()
    scope = scope if scope is not None else get_amscope()
    flight = flight if flight is not None else get_flight()
    metrics = registry.as_dict()
    record = {
        "t": time.time() if t is None else t,
        "metrics": metrics,
        "tenants": scope.tenant_stats(),
        "breakdown": request_breakdown(metrics),
        "flight_tail": flight.tail(tail),
    }
    if slo is not None:
        record["slo"] = slo
    return record


class SnapshotWriter:
    """Appends periodic JSONL snapshots to a file. Clock-injected so the
    load harness snapshots on simulated time; ``serve_forever`` drives it
    from its flusher task on the real clock. An attached ``slo_engine``
    is evaluated (and its ``slo.*`` gauges exported) at every write, so
    each snapshot line carries the verdicts as of that tick."""

    def __init__(self, path: str, interval: float = 5.0, clock=None,
                 slo_engine=None):
        self.path = path
        self.interval = interval
        self.clock = clock if clock is not None else time.monotonic
        self.slo_engine = slo_engine
        self._last: float | None = None

    def maybe_write(self, now: float | None = None) -> bool:
        now = self.clock() if now is None else now
        if self._last is not None and now - self._last < self.interval:
            return False
        self.write(now)
        return True

    def write(self, now: float | None = None) -> None:
        now = self.clock() if now is None else now
        self._last = now
        verdicts = (
            self.slo_engine.export(now=now)
            if self.slo_engine is not None else None
        )
        record = snapshot_record(t=now, slo=verdicts)
        with open(self.path, "a", encoding="utf-8") as fh:
            fh.write(json.dumps(record, sort_keys=True, default=str) + "\n")


async def serve_exposition(host: str = "127.0.0.1", port: int = 0,
                           registry=None, scope=None):
    """Binds ``render_exposition`` to a minimal HTTP listener (one page,
    any path). Returns the asyncio server; close() to stop. This is the
    serving adapter's telemetry side-car — scraping it never enters the
    serving event loop's data path."""
    import asyncio

    async def _handle(reader: "asyncio.StreamReader",
                      writer: "asyncio.StreamWriter") -> None:
        try:
            # drain the request head (we serve one page whatever the path)
            while True:
                line = await reader.readline()
                if not line or line in (b"\r\n", b"\n"):
                    break
            body = render_exposition(registry, scope).encode("utf-8")
            writer.write(
                b"HTTP/1.0 200 OK\r\n"
                b"Content-Type: text/plain; version=0.0.4\r\n"
                b"Content-Length: " + str(len(body)).encode() + b"\r\n"
                b"\r\n" + body
            )
            await writer.drain()
        except (ConnectionError, asyncio.IncompleteReadError):
            pass
        finally:
            writer.close()

    return await asyncio.start_server(_handle, host, port)
