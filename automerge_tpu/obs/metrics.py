"""amtrace metrics: counters, gauges and histograms in one process-wide
registry.

Spans (obs/spans.py) answer "where did the time go"; metrics answer "what
did the pipeline do": batch occupancy and pad waste in the farm, jit cache
hits vs recompiles in the engine, message/byte/Bloom-probe counts in the
sync layer. Instruments are fetched by name from the registry — two
modules asking for ``counter("sync.messages.generated")`` share one
instrument, so the sequential protocol (sync.py) and the batched farm
(tpu/sync_farm.py) accumulate into the same totals.

Recording is host-side only (amlint AM303 forbids instrument calls inside
jit/vmap/Pallas-reachable code) and near-zero-cost when disabled: every
``inc``/``set``/``observe`` starts with a single attribute test and does
no further work (asserted by tests/test_obs.py). The process-wide registry
starts *disabled*; bench.py and the obs CLI enable it around their
workloads, so library users pay nothing unless they opt in.

Histograms reuse the span layer's log2 bucket grid, which doubles as a
general positive-float grid (e.g. occupancy ratios in (0, 1] land in the
sub-1.0 buckets); quantiles report bucket upper bounds.
"""
# amlint: host-only — pure-host layer: must not import tpu/ or jax
from __future__ import annotations

import contextlib
from typing import Iterator

from .spans import bucket_bounds, bucket_index


class Counter:
    """Monotonic event count."""

    __slots__ = ("name", "help", "enabled", "value")

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self.enabled = False
        self.value = 0

    def inc(self, n: int = 1) -> None:
        if not self.enabled:
            return
        self.value += n

    def reset(self) -> None:
        self.value = 0

    def snapshot(self):
        return {"type": "counter", "value": self.value}


class Gauge:
    """Last-observed value (e.g. the current pad-waste ratio)."""

    __slots__ = ("name", "help", "enabled", "value")

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self.enabled = False
        self.value = 0.0

    def set(self, v: float) -> None:
        if not self.enabled:
            return
        self.value = v

    def reset(self) -> None:
        self.value = 0.0

    def snapshot(self):
        return {"type": "gauge", "value": self.value}


class Histogram:
    """Fixed-bucket distribution of positive floats (log2 grid shared with
    the span layer).

    Each bucket may carry one **exemplar** — an opaque id (an amscope
    trace/dispatch id) of a recent observation that landed in it — so a
    percentile spike is one ``exemplar_for(q)`` lookup away from the
    request trace that produced it."""

    __slots__ = ("name", "help", "enabled", "buckets", "count", "sum",
                 "exemplars")

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self.enabled = False
        self.buckets: dict[int, int] = {}
        self.count = 0
        self.sum = 0.0
        self.exemplars: dict[int, object] = {}

    def observe(self, v: float, exemplar=None) -> None:
        if not self.enabled:
            return
        b = bucket_index(v)
        self.buckets[b] = self.buckets.get(b, 0) + 1
        self.count += 1
        self.sum += v
        if exemplar is not None:
            self.exemplars[b] = exemplar

    def percentile_bucket(self, q: float) -> int | None:
        """Bucket index holding the q-quantile, or None when empty."""
        if self.count == 0:
            return None
        threshold = q * self.count
        cum = 0
        for b in sorted(self.buckets):
            cum += self.buckets[b]
            if cum >= threshold:
                return b
        return max(self.buckets)

    def percentile(self, q: float) -> float | None:
        b = self.percentile_bucket(q)
        return None if b is None else bucket_bounds(b)[1]

    def exemplar_for(self, q: float):
        """The exemplar recorded in the q-quantile's bucket (e.g. the
        trace id behind the p99), or None when that bucket has none."""
        b = self.percentile_bucket(q)
        return None if b is None else self.exemplars.get(b)

    def reset(self) -> None:
        self.buckets = {}
        self.count = 0
        self.sum = 0.0
        self.exemplars = {}

    def snapshot(self):
        out = {
            "type": "histogram",
            "count": self.count,
            "sum": self.sum,
            "p50": self.percentile(0.50),
            "p95": self.percentile(0.95),
            "p99": self.percentile(0.99),
        }
        if self.exemplars:
            out["exemplars"] = {
                str(b): e for b, e in sorted(self.exemplars.items())
            }
        return out


class MetricsRegistry:
    """Name -> instrument table with a single enable switch.

    ``enabled`` is mirrored onto every instrument at creation and on
    enable()/disable(), so the per-record hot path tests one attribute on
    the instrument itself and never chases the registry."""

    def __init__(self, enabled: bool = False):
        self.enabled = enabled
        self._instruments: dict[str, object] = {}

    # ------------------------------------------------------------------ #

    def _get(self, cls, name: str, help: str):
        inst = self._instruments.get(name)
        if inst is None:
            inst = cls(name, help)
            inst.enabled = self.enabled
            self._instruments[name] = inst
        elif not isinstance(inst, cls):
            raise TypeError(
                f"metric {name!r} already registered as "
                f"{type(inst).__name__}, not {cls.__name__}"
            )
        return inst

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get(Gauge, name, help)

    def histogram(self, name: str, help: str = "") -> Histogram:
        return self._get(Histogram, name, help)

    def find(self, name: str):
        """Read-only lookup: the registered instrument, or None. Unlike
        the typed getters this never registers — readers (the SLO engine,
        exposition renderers) must not invent instruments."""
        return self._instruments.get(name)

    # ------------------------------------------------------------------ #

    def enable(self) -> None:
        self.enabled = True
        for inst in self._instruments.values():
            inst.enabled = True

    def disable(self) -> None:
        self.enabled = False
        for inst in self._instruments.values():
            inst.enabled = False

    def reset(self) -> None:
        """Zeroes every instrument (registrations and help text survive).

        Reset semantics are uniform: every instrument class owns its own
        ``reset()`` and the registry only delegates, so a Counter's zero, a
        Gauge's zero, and a Histogram's empty-percentile state (count 0,
        ``percentile`` -> None, exemplars cleared) can never drift apart —
        the reset-consistency bug class where a derived gauge survived a
        reset its source counters did not (pinned by
        tests/test_obs.py::test_reset_is_uniform_across_instrument_types)."""
        for inst in self._instruments.values():
            inst.reset()

    # ------------------------------------------------------------------ #
    # frames: the cross-process shipping format. A mesh worker records
    # into ITS OWN process-wide registry, periodically takes frame(),
    # diffs against the last-shipped frame, and sends the delta with the
    # result; the controller merge_frame()s it into the controller
    # registry. Counters/histograms accumulate (deltas), gauges are
    # last-writer-wins — the same semantics a scrape-and-sum pipeline
    # would apply.

    def frame(self) -> dict:
        """{name: (kind, help, payload)} snapshot of raw instrument state
        (picklable, no instrument objects). Counter/gauge payload is the
        value; histogram payload is (buckets, count, sum, exemplars) —
        exemplars ride along so a worker-stamped trace id survives the
        trip back to the controller registry."""
        out = {}
        for name, inst in self._instruments.items():
            if isinstance(inst, Histogram):
                out[name] = (
                    "histogram", inst.help,
                    (dict(inst.buckets), inst.count, inst.sum,
                     dict(inst.exemplars)),
                )
            elif isinstance(inst, Gauge):
                out[name] = ("gauge", inst.help, inst.value)
            else:
                out[name] = ("counter", inst.help, inst.value)
        return out

    def merge_frame(self, frame: dict) -> None:
        """Accumulates a (delta) frame into this registry: counters are
        inc'd, histogram buckets/count/sum are added (bucket exemplars:
        last writer wins, like gauges), gauges are set. Instruments are
        registered on first sight with the frame's help text. No-op while
        the registry is disabled (instruments drop the records anyway;
        skipping keeps disabled-path cost flat)."""
        if not self.enabled:
            return
        for name, (kind, help, payload) in sorted(frame.items()):
            if kind == "histogram":
                h = self.histogram(name, help)
                buckets, count, sum_, exemplars = payload
                for b, c in buckets.items():
                    h.buckets[b] = h.buckets.get(b, 0) + c
                h.count += count
                h.sum += sum_
                for b, e in exemplars.items():
                    if e is not None:
                        h.exemplars[b] = e
            elif kind == "gauge":
                self.gauge(name, help).set(payload)
            else:
                self.counter(name, help).inc(payload)


    # ------------------------------------------------------------------ #

    def as_dict(self) -> dict:
        return {
            name: self._instruments[name].snapshot()
            for name in sorted(self._instruments)
        }

    def table(self, skip_zero: bool = False) -> str:
        """Human-readable metrics table, sorted by name."""
        rows = []
        for name in sorted(self._instruments):
            snap = self._instruments[name].snapshot()
            if snap["type"] == "histogram":
                if skip_zero and snap["count"] == 0:
                    continue
                detail = (
                    f"count={snap['count']} sum={snap['sum']:.4g} "
                    f"p50={_fmt(snap['p50'])} p95={_fmt(snap['p95'])} "
                    f"p99={_fmt(snap['p99'])}"
                )
            else:
                if skip_zero and not snap["value"]:
                    continue
                detail = _fmt(snap["value"])
            rows.append((name, snap["type"], detail))
        if not rows:
            return "(no metrics recorded)"
        width = max(len(name) for name, _, _ in rows)
        return "\n".join(
            f"{name.ljust(width)}  {type_:9s}  {detail}"
            for name, type_, detail in rows
        )


def _fmt(v) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.4g}"
    return str(v)


def diff_frames(current: dict, previous: dict) -> dict:
    """The delta frame that, ``merge_frame``'d after `previous`, yields
    `current`: counter values subtract, histogram buckets/count/sum
    subtract (unchanged buckets drop), gauges pass through as-is.
    Entries with nothing new are omitted — a quiet worker ships an empty
    dict."""
    out = {}
    for name, (kind, help, payload) in current.items():
        prev = previous.get(name)
        if kind == "counter":
            base = prev[2] if prev else 0
            if payload != base:
                out[name] = (kind, help, payload - base)
        elif kind == "gauge":
            if prev is None or payload != prev[2]:
                out[name] = (kind, help, payload)
        else:
            buckets, count, sum_, exemplars = payload
            pb, pc, ps, pe = prev[2] if prev else ({}, 0, 0.0, {})
            if count != pc:
                delta = {
                    b: c - pb.get(b, 0)
                    for b, c in buckets.items()
                    if c != pb.get(b, 0)
                }
                # ship only exemplars that changed (or are new) since the
                # last frame: the steady-state delta stays small
                ex_delta = {
                    b: e for b, e in exemplars.items() if e != pe.get(b)
                }
                out[name] = (
                    kind, help, (delta, count - pc, sum_ - ps, ex_delta)
                )
    return out


# ---------------------------------------------------------------------- #
# the process-wide registry (disabled until a workload opts in)

_GLOBAL = MetricsRegistry(enabled=False)


def get_metrics() -> MetricsRegistry:
    """The process-wide registry every instrumented module records into."""
    return _GLOBAL


@contextlib.contextmanager
def enabled_metrics(
    registry: MetricsRegistry | None = None,
) -> Iterator[MetricsRegistry]:
    """Enables a registry (the process-wide one by default) for the dynamic
    extent, restoring the previous enabled state on exit."""
    reg = registry if registry is not None else _GLOBAL
    was_enabled = reg.enabled
    reg.enable()
    try:
        yield reg
    finally:
        if not was_enabled:
            reg.disable()
